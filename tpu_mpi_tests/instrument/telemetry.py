"""Comm-layer telemetry: spans, cumulative counters, and a flight recorder.

The reference's observability is NVTX ranges plus printf lines averaged
offline (SURVEY.md §5.5); nothing records what the communication layer —
the thing this suite exists to measure — actually did. This module is the
missing recorder, three pieces sharing one process-wide registry:

* **Spans** (:func:`comm_span` / :func:`span_call`): every public
  collective/halo/ring/alltoall wrapper brackets its dispatch in a span
  that records op kind, payload bytes, mesh axis, wall seconds, and the
  derived bandwidth. Spans are *sync-honest* the same way
  :class:`~tpu_mpi_tests.instrument.timers.PhaseTimer` is: the span blocks
  (:func:`~tpu_mpi_tests.instrument.timers.block`) on the op's result
  before reading the clock, otherwise async dispatch would attribute the
  time to whoever flushes the queue. Recording is OFF by default — a
  disabled span is one attribute check, so instrumented wrappers cost
  nothing in benchmarks that did not opt in (``--telemetry``).

* **Counters**: cumulative ops/bytes/seconds per op kind, queryable by
  drivers and tests (:func:`counters`) and emitted as a
  ``telemetry_summary`` JSONL record when the driver's Reporter closes.

* **Flight recorder**: a bounded ring buffer of recent comm events that
  replaces the watchdog's single sticky ``_last_comm_op`` string. Dispatch
  notes (:func:`note_dispatch` — e.g. the hand-written RDMA ring
  registering itself before a potentially-wedging DMA) are recorded even
  when telemetry is disabled: they are one deque append, and they are
  exactly what a hang dump needs. A watchdog fire or fatal error dumps the
  last N events with ages (:func:`flight_lines`) instead of one string.

Payload-byte conventions are documented per wrapper; bandwidth is
``nbytes / seconds`` — an *algorithmic* rate (like nccl-tests' busbw), not
a per-link measurement.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable

#: default flight-recorder depth; the watchdog dumps up to this many events
#: (the acceptance floor is 8 — keep comfortably above it)
FLIGHT_CAPACITY = 64


@dataclass
class CommEvent:
    """One recorded communication event (a completed span or a dispatch
    note). ``seconds``/``gbps`` are None for dispatch-only notes — the op
    was handed to the device but never synced through a span.

    ``t_start``/``t_end`` are wall-clock (Unix epoch) bounds and
    ``mono_start``/``mono_end`` the matching ``perf_counter`` reads; the
    timeline merger (``instrument/timeline.py``) places the span on a
    cross-rank time axis from the wall pair (clock-offset-corrected) and
    keeps the monotonic pair as the drift-free duration witness. None on
    dispatch-only notes and on records from pre-timeline JSONL."""

    op: str
    nbytes: int = 0
    axis_name: str | None = None
    world: int = 1
    seconds: float | None = None
    gbps: float | None = None
    wall_time: float = 0.0
    note: str | None = None
    t_start: float | None = None
    t_end: float | None = None
    mono_start: float | None = None
    mono_end: float | None = None
    seq: int | None = None
    meta: dict[str, Any] = field(default_factory=dict)

    def describe(self, now: float | None = None) -> str:
        """Human line for hang dumps: op, payload, axis, duration, age."""
        age = (now if now is not None else time.time()) - self.wall_time
        parts = [self.note or self.op]
        if self.nbytes:
            parts.append(f"{self.nbytes}B")
        if self.axis_name:
            parts.append(f"axis={self.axis_name}x{self.world}")
        if self.seconds is not None:
            parts.append(f"{self.seconds * 1e3:.3f}ms")
            if self.gbps is not None:
                parts.append(f"{self.gbps:.2f}GB/s")
        else:
            parts.append("dispatched")
        parts.append(f"{age:.1f}s ago")
        return " ".join(parts)

    def record(self) -> dict[str, Any]:
        """JSONL record shape (``kind: "span"``)."""
        rec: dict[str, Any] = {
            "kind": "span",
            "op": self.op,
            "nbytes": self.nbytes,
            "axis": self.axis_name,
            "world": self.world,
            "seconds": self.seconds,
            "gbps": self.gbps,
            "t_start": self.t_start,
            "t_end": self.t_end,
            "mono_start": self.mono_start,
            "mono_end": self.mono_end,
        }
        if self.seq is not None:
            # per-(op, axis) monotone call number: the k-th allreduce on
            # rank 0 matches the k-th on every sibling, which is what the
            # anatomy layer (instrument/anatomy.py) aligns on. Absent on
            # dispatch notes and pre-seq streams — consumers degrade.
            rec["seq"] = self.seq
        if self.meta:
            rec.update(self.meta)
        return rec


class FlightRecorder:
    """Bounded, thread-safe ring buffer of recent :class:`CommEvent`."""

    def __init__(self, capacity: int = FLIGHT_CAPACITY):
        self._events: deque[CommEvent] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def push(self, event: CommEvent) -> None:
        with self._lock:
            self._events.append(event)

    def recent(self, n: int | None = None) -> list[CommEvent]:
        """Most recent events, oldest first."""
        with self._lock:
            events = list(self._events)
        return events if n is None else events[-n:]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


class Telemetry:
    """Process-wide registry: enable/disable switch, counters, flight
    recorder, and an optional per-event sink (the Reporter's JSONL)."""

    def __init__(self, flight_capacity: int = FLIGHT_CAPACITY):
        self.enabled = False
        self.flight = FlightRecorder(flight_capacity)
        self._sink: Callable[[dict], None] | None = None
        self._lock = threading.Lock()
        # op -> [ops, bytes, seconds]
        self._counters: dict[str, list] = {}
        # (op, axis) -> next call sequence number. Every rank runs the
        # same SPMD program, so the same counter advanced at each span
        # yields matching seq values across ranks — the anatomy layer's
        # whole alignment key.
        self._seq: dict[tuple[str, str | None], int] = {}

    def enable(self, sink: Callable[[dict], None] | None = None) -> None:
        # enable/disable run on the main thread while the watchdog's
        # timer thread may be inside emit(); the races are deliberate
        # best-effort teardown: attribute loads/stores are GIL-atomic,
        # emit re-checks its snapshot, and a sink that disappears
        # mid-emit is swallowed by emit's except — a lock here would
        # put the hang-dump path behind a lock a wedged main thread
        # might hold forever
        self._sink = sink  # tpumt: ignore[TPM1601]
        self.enabled = True  # tpumt: ignore[TPM1601]

    def disable(self) -> None:
        self.enabled = False
        self._sink = None

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._seq.clear()
        self.flight.clear()

    def next_seq(self, op: str, axis_name: str | None) -> int:
        """Allocate the next per-(op, axis) call sequence number.
        0-based; monotone for the registry's lifetime (until reset)."""
        key = (op, axis_name)
        with self._lock:
            n = self._seq.get(key, 0)
            self._seq[key] = n + 1
        return n

    def record(self, event: CommEvent) -> None:
        """Record a completed span: counters + flight recorder + sink."""
        with self._lock:
            c = self._counters.setdefault(event.op, [0, 0, 0.0])
            c[0] += 1
            c[1] += event.nbytes
            c[2] += event.seconds or 0.0
        self.flight.push(event)
        if self._sink is not None:
            self._sink(event.record())

    def emit(self, record: dict[str, Any]) -> None:
        """Best-effort raw record to the sink (no counters, no flight
        entry) — for non-span observability records that belong on the
        timeline: dispatch notes (``kind: "dispatch"``) and watchdog
        fires (``kind: "watchdog"``). Never raises: the callers are hang
        dumps and teardown paths where a sink error must not mask the
        real failure."""
        if not self.enabled or self._sink is None:
            return
        try:
            self._sink(record)
        except Exception:
            pass

    def counters(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {
                op: {"ops": c[0], "bytes": c[1], "seconds": c[2]}
                for op, c in self._counters.items()
            }


_TELEMETRY = Telemetry()

#: chaos arm-point (``tpu_mpi_tests/chaos/inject.py`` rebinds this at
#: arm time; never set by anything else): called as ``hook(op, when)``
#: with ``when`` = "enter" before the span's clock starts and "exit"
#: after the event recorded. Consulted ONLY on the telemetry-enabled
#: span path — the disabled fast path (one attribute check) and every
#: disarmed run are untouched, which is the layer's zero-cost contract.
_CHAOS_SPAN_HOOK: Callable[[str, str], None] | None = None

#: optional cost-model provider (instrument/costs.py registers itself on
#: its first successful compile probe): ``provider(op, seconds)`` returns
#: extra span fields ({} for unknown ops) — cost bytes/flops and roofline
#: utilization, so a span carries achieved-vs-cost-model context
_COST_PROVIDER: Callable[[str, float], dict] | None = None


def set_cost_provider(provider: Callable[[str, float], dict] | None) -> None:
    global _COST_PROVIDER
    _COST_PROVIDER = provider


def _cost_meta(op: str, seconds: float) -> dict:
    """Best-effort cost fields for a closing span — a provider bug must
    never fail the measured op."""
    if _COST_PROVIDER is None:
        return {}
    try:
        return _COST_PROVIDER(op, seconds) or {}
    except Exception:
        return {}


def registry() -> Telemetry:
    """The process-wide telemetry registry."""
    return _TELEMETRY


def enable(sink: Callable[[dict], None] | None = None) -> None:
    _TELEMETRY.enable(sink)


def disable() -> None:
    _TELEMETRY.disable()


def counters() -> dict[str, dict[str, Any]]:
    """Cumulative per-op counters: ``{op: {ops, bytes, seconds}}``."""
    return _TELEMETRY.counters()


def note_dispatch(desc: str, **meta) -> None:
    """Record a dispatch-only event in the flight recorder (always on —
    one deque append). Used for ops that may wedge before any span can
    close, e.g. the hand-written RDMA ring's DMA semaphores. When span
    telemetry is enabled the note also lands in the JSONL sink
    (``kind: "dispatch"``) so the timeline can mark a wedged op's last
    dispatch as an instant event."""
    event = CommEvent(
        op=meta.pop("op", "dispatch"),
        note=desc,
        wall_time=time.time(),
        meta=meta,
    )
    _TELEMETRY.flight.push(event)
    _TELEMETRY.emit(
        {"kind": "dispatch", "note": desc, "op": event.op,
         "t": event.wall_time, **event.meta}
    )


def emit(record: dict[str, Any]) -> None:
    """Raw record to the enabled registry's sink (see
    :meth:`Telemetry.emit`)."""
    _TELEMETRY.emit(record)


def flight_events(n: int | None = None) -> list[CommEvent]:
    return _TELEMETRY.flight.recent(n)


def flight_lines(n: int = 16) -> list[str]:
    """Formatted dump of the last ``n`` comm events, oldest first."""
    now = time.time()
    return [e.describe(now) for e in _TELEMETRY.flight.recent(n)]


class _Span:
    """Mutable handle yielded by :func:`comm_span`; set ``result`` to the
    op's output pytree so the span can sync-honestly block before timing."""

    __slots__ = ("result",)

    def __init__(self):
        self.result = None


def _under_trace() -> bool:
    """True inside a jax trace (jit/scan/fori_loop body). Spans must not
    record there: the wrapper runs ONCE at trace time, so its clock reads
    would fabricate telemetry for the whole compiled loop (ops=1,
    trace-duration seconds), and blocking on a tracer is meaningless or
    worse (the hard-sync path reads device buffers that do not exist).
    Used only on the enabled path — the disabled path never pays it."""
    try:
        from jax import core

        return not core.trace_state_clean()
    except Exception:
        return False


@contextmanager
def comm_span(
    op: str,
    nbytes: int = 0,
    axis_name: str | None = None,
    world: int = 1,
    **meta,
):
    """Record one communication op: wall seconds (sync-honest when the
    body assigns ``span.result``), payload bytes, and derived bandwidth.

    No-ops (yielding an inert span) when telemetry is disabled, so
    instrumented wrappers are free for benchmarks that did not opt in.
    Spans nest: each level records its own event and counter line.
    """
    reg = _TELEMETRY
    if not reg.enabled or _under_trace():
        yield _Span()
        return
    from tpu_mpi_tests.instrument.timers import block

    chaos_hook = _CHAOS_SPAN_HOOK
    if chaos_hook is not None:
        # entry faults (kill/wedge) land here, BEFORE the clock starts,
        # so a killed span never records — dead mid-collective
        chaos_hook(op, "enter")
    span = _Span()
    seq = reg.next_seq(op, axis_name)
    t0_wall = time.time()
    t0 = time.perf_counter()
    try:
        yield span
    finally:
        if span.result is not None:
            block(span.result)
        t1 = time.perf_counter()
        dt = t1 - t0
        gbps = (nbytes / dt / 1e9) if (nbytes and dt > 0) else None
        cost = _cost_meta(op, dt)
        if cost:
            meta = {**cost, **meta}  # explicit caller meta wins
        # wall end is start + the monotonic duration, not a second
        # time.time() read: an NTP step mid-span would otherwise make
        # t_end - t_start disagree with `seconds` on the merged timeline
        reg.record(
            CommEvent(
                op=op,
                nbytes=int(nbytes),
                axis_name=axis_name,
                world=world,
                seconds=dt,
                gbps=gbps,
                wall_time=t0_wall + dt,
                t_start=t0_wall,
                t_end=t0_wall + dt,
                mono_start=t0,
                mono_end=t1,
                seq=seq,
                meta=meta,
            )
        )
        if chaos_hook is not None:
            # exit faults (the op-scoped straggler) sleep here, AFTER
            # the event recorded — outside the measured window, so the
            # culprit's own spans stay honest while its late arrival
            # inflates the siblings' next collective
            chaos_hook(op, "exit")


class AsyncSpan:
    """Dispatch-window span handle for the overlap engine: opened at
    dispatch time, closed by :meth:`done`, which blocks on the op's
    result (the drain point) and records the event.

    The recorded window spans dispatch → observed completion, which is
    WIDER than the op's device time — it includes whatever host/compute
    work rode alongside while the op was in flight. That is the point
    (the window is what overlap_frac measures against the compute
    phase), but it means the ``seconds`` field is NOT a sync-honest op
    duration; records therefore carry ``async: true`` so downstream
    consumers (tpumt-report OP stats, GB/s percentiles) can tell the
    two apart. Inert (no event recorded) when telemetry is disabled or
    under a jax trace, but the mono clock bounds are always tracked —
    the overlap engine derives its measured overlap from them either
    way."""

    __slots__ = ("op", "nbytes", "axis_name", "world", "meta",
                 "t0_wall", "mono_start", "mono_end", "drain_s",
                 "closed", "_armed", "seq")

    def __init__(self, op: str, nbytes: int = 0,
                 axis_name: str | None = None, world: int = 1, **meta):
        self.op = op
        self.nbytes = int(nbytes)
        self.axis_name = axis_name
        self.world = world
        self.meta = meta
        self.closed = False
        self._armed = _TELEMETRY.enabled and not _under_trace()
        # seq at DISPATCH order, not drain order: drains can complete
        # out of order under deep windows, but dispatch order is the
        # SPMD-identical one the cross-rank match needs
        self.seq = (_TELEMETRY.next_seq(op, axis_name)
                    if self._armed else None)
        self.t0_wall = time.time()
        self.mono_start = time.perf_counter()
        self.mono_end = self.mono_start
        #: seconds :meth:`done` spent actually waiting on the result —
        #: the one genuinely *measured* hiding signal: ~0 means the op
        #: completed under whatever ran alongside; large means the
        #: caller's compute finished first and the op was NOT hidden
        self.drain_s = 0.0

    def done(self, result=None) -> None:
        """Block on ``result`` (the op's output pytree) and close the
        span. Idempotent — a drained window may be drained again."""
        if self.closed:
            return
        self.closed = True
        if result is not None:
            from tpu_mpi_tests.instrument.timers import block

            t_wait = time.perf_counter()
            block(result)
            self.drain_s = time.perf_counter() - t_wait
        self.mono_end = time.perf_counter()
        dt = self.mono_end - self.mono_start
        if not self._armed:
            return
        gbps = (self.nbytes / dt / 1e9) if (self.nbytes and dt > 0) else None
        _TELEMETRY.record(
            CommEvent(
                op=self.op,
                nbytes=self.nbytes,
                axis_name=self.axis_name,
                world=self.world,
                seconds=dt,
                gbps=gbps,
                wall_time=self.t0_wall + dt,
                t_start=self.t0_wall,
                t_end=self.t0_wall + dt,
                mono_start=self.mono_start,
                mono_end=self.mono_end,
                seq=self.seq,
                meta={"async": True, "drain_s": self.drain_s,
                      **self.meta},
            )
        )


def async_span(op: str, nbytes: int = 0, axis_name: str | None = None,
               world: int = 1, **meta) -> AsyncSpan:
    """Open a dispatch-window span (see :class:`AsyncSpan`): the comm op
    is dispatched now, the caller computes alongside it, and
    ``handle.done(result)`` is the drain point that closes the window.
    This is the overlap engine's span primitive — the sync-honest
    :func:`comm_span`/:func:`span_call` stay the default for everything
    that syncs per call."""
    return AsyncSpan(op, nbytes=nbytes, axis_name=axis_name, world=world,
                     **meta)


def _maybe_compile_probe(op: str, fn: Callable, args: tuple) -> None:
    """AOT compile-cost probe for jitted fns flowing through
    :func:`span_call` — one probe per (op, arg shapes), only while
    telemetry is enabled, so every instrumented comm wrapper records a
    ``kind: "compile"`` span + cost model without per-wrapper wiring
    (instrument/costs.py). Best-effort by contract."""
    if not hasattr(fn, "lower"):
        return
    try:
        from tpu_mpi_tests.instrument import costs

        costs.compile_probe(fn, args, label=op)
    except Exception:
        pass


def span_call(
    op: str,
    fn: Callable,
    *args,
    nbytes: int = 0,
    axis_name: str | None = None,
    world: int = 1,
    **meta,
):
    """``fn(*args)`` bracketed in a :func:`comm_span`, blocking on the
    result. The disabled path is a single attribute check plus the call;
    under a jax trace the call passes through unrecorded (see
    :func:`_under_trace`)."""
    if not _TELEMETRY.enabled or _under_trace():
        return fn(*args)
    _maybe_compile_probe(op, fn, args)
    with comm_span(
        op, nbytes=nbytes, axis_name=axis_name, world=world, **meta
    ) as span:
        out = fn(*args)
        span.result = out
    return out
