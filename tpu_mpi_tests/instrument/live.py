"""``tpumt-top``: follow-mode console dashboard over the live JSONL
trail, plus the incremental tail engine the online doctor shares.

The post-mortem CLIs (``tpumt-report``/``tpumt-trace``/``tpumt-doctor``)
parse completed files; this module watches files AS THEY ARE WRITTEN:

* :class:`FileTail` — byte-offset incremental JSONL reader: each poll
  reads only the newly appended bytes, consumes complete lines only (a
  partially flushed record waits for its newline), and keeps the
  absolute line numbers ``diagnose`` evidence refs use.
* :class:`RunTail` — the rank-set tailer: re-expands the ``.p<i>``
  sibling set every poll (ranks appear as their files are created) and
  admits only files of the ACTIVE run via the shared ghost-track filter
  (:func:`~tpu_mpi_tests.instrument.timeline.file_in_run` — the same
  ``run_sync_us`` stamp logic the ``--trace-out`` merge uses, one copy):
  a stale ``out.p1.jsonl`` left by an earlier run at the same base path
  never becomes a ghost rank. ``tpumt-doctor --follow`` drives its
  :class:`~tpu_mpi_tests.instrument.diagnose._Stream` digests from this
  same tailer.
* :class:`Dashboard` + :func:`render` — ``tpumt-top`` itself: records
  feed a standalone
  :class:`~tpu_mpi_tests.instrument.metrics.MetricsRegistry` (the same
  aggregation the in-process exporter serves) plus a handful of
  last-value slots, rendered as per-class SLO, per-op rolling GB/s,
  HBM watermarks, overlap fractions, and recent health events.

Without ``--follow`` one frame renders from the files' current contents
and the process exits — the post-mortem snapshot. With ``--follow`` the
frame refreshes every ``--interval`` until ``q`` or Ctrl-C (or
``--frames N`` rendered frames, the scriptable exit).

Pure stdlib, no jax import: a login node can watch files on a shared
filesystem while the pod writes them — the same contract as the other
CLIs, applied to a run that has not ended yet.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import deque
from contextlib import contextmanager
from pathlib import Path

from tpu_mpi_tests.instrument.aggregate import expand_rank_files
from tpu_mpi_tests.instrument.metrics import CommWaitWatch, MetricsRegistry
from tpu_mpi_tests.instrument.timeline import file_in_run

#: stampless files older than this many seconds before the tailer
#: started are treated as leftovers of an earlier run
ADMIT_GRACE_S = 60.0


def _scan_run_ids(path: str) -> tuple[set, object]:
    """``(all run_sync_us stamps, the newest one)`` for one JSONL file
    WITHOUT a full JSON parse: only lines mentioning ``clock_sync`` are
    decoded, so admitting a multi-GB serving log costs one cheap line
    scan instead of the 2 extra full parses
    ``timeline.run_sync_ids``/``newest_run_sync_id`` would spend
    (semantic equivalence is pinned in tests/test_live.py). Appended
    runs land in file order, so the last stamp is the newest
    segment's."""
    ids: set = set()
    newest = None
    try:
        with open(path, "rb") as f:
            for raw in f:
                if b'"clock_sync"' not in raw:
                    continue
                try:
                    rec = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict) \
                        or rec.get("kind") != "clock_sync":
                    continue
                rid = rec.get("run_sync_us")
                if rid is not None:
                    ids.add(rid)
                    newest = rid
    except OSError:
        pass
    return ids, newest


class FileTail:
    """Incremental JSONL reader for one file: ``poll()`` returns the
    ``(line_number, record)`` pairs appended since the last poll,
    consuming complete lines only. A shrunk file (truncate/rotate)
    restarts from the top."""

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._line_no = 0
        self._buf = b""

    def poll(self) -> list[tuple[int, dict]]:
        out: list[tuple[int, dict]] = []
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return out
        if size < self._offset:
            self._offset = 0
            self._line_no = 0
            self._buf = b""
        if size == self._offset and not self._buf:
            return out
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                data = f.read()
                self._offset = f.tell()
        except OSError:
            return out
        self._buf += data
        while True:
            nl = self._buf.find(b"\n")
            if nl < 0:
                break  # a partial line waits for its newline
            line, self._buf = self._buf[:nl], self._buf[nl + 1:]
            self._line_no += 1
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append((self._line_no, rec))
        return out


class RunTail:
    """Tail the live rank set of one run across its ``.p<i>`` files."""

    def __init__(self, paths: list[str], grace_s: float = ADMIT_GRACE_S):
        self._paths = list(paths)
        self._grace = grace_s
        self._started = time.time()
        self._tails: dict[str, FileTail] = {}
        self._order: dict[str, int] = {}
        self._rejected: dict[str, float] = {}  # path -> mtime at verdict
        self._run_id = None

    def files(self) -> list[str]:
        return sorted(self._tails)

    def index(self, path: str) -> int:
        return self._order.get(path, 0)

    def _admit(self) -> None:
        cands = [f for f in expand_rank_files(self._paths)
                 if Path(f).exists()]
        fresh = [f for f in cands
                 if f not in self._tails]
        if not fresh:
            return
        newest = None
        scanned: dict[str, set] = {}
        if self._run_id is None:
            # active run = the newest segment stamp of the most
            # recently written candidate (None when none carries one)
            def mtime(f):
                try:
                    return Path(f).stat().st_mtime
                except OSError:
                    return 0.0

            newest = max(cands, key=mtime, default=None)
            if newest is not None:
                ids, self._run_id = _scan_run_ids(newest)
                scanned[newest] = ids
        cutoff = self._started - self._grace
        for f in fresh:
            try:
                mt = Path(f).stat().st_mtime
            except OSError:
                continue
            prev = self._rejected.get(f)
            if prev is not None and mt <= prev:
                continue  # still the same stale bytes: stay rejected
            ids = scanned.get(f)
            if ids is None:
                ids, _ = _scan_run_ids(f)
            if f == newest or file_in_run(f, self._run_id,
                                          mtime_after=cutoff, ids=ids):
                self._rejected.pop(f, None)
                self._tails[f] = FileTail(f)
                self._order.setdefault(f, len(self._order))
            else:
                self._rejected[f] = mt

    def poll(self) -> list[tuple[str, int, dict]]:
        """All newly appended ``(path, line_number, record)`` across
        the (re-expanded, run-filtered) rank set."""
        self._admit()
        out: list[tuple[str, int, dict]] = []
        for path in sorted(self._tails):
            for ln, rec in self._tails[path].poll():
                if rec.get("kind") == "clock_sync" \
                        and rec.get("run_sync_us") is not None:
                    # a rerun appended to a followed file moves the
                    # active-run identity forward with it
                    self._run_id = rec["run_sync_us"]
                out.append((path, ln, rec))
        return out


class Dashboard:
    """The ``tpumt-top`` model: a standalone metrics registry (same
    aggregation the in-process exporter serves) plus last-value slots
    for the sections the registry does not keep whole records for."""

    def __init__(self):
        self._manifests_seen: set[str] = set()
        self._reset()

    def _reset(self) -> None:
        self.registry = MetricsRegistry()
        self.comm_wait = CommWaitWatch(self.registry)
        self.manifest: dict = {}
        self.slo: dict[str, dict] = {}
        self.mem: dict = {}
        self.overlap: dict[str, dict] = {}
        self.heartbeat: dict = {}   # rank -> last heartbeat record
        self.findings: deque = deque(maxlen=4)
        self.n_records = 0
        self.last_wall: float | None = None
        # per-path rank / clock offset for the cross-rank wait match
        # (the dashboard, unlike the in-process tee, knows which file
        # is which rank — that is what makes live wait_frac possible)
        self._path_rank: dict[str, int] = {}
        self._path_offset: dict[str, float] = {}

    def _rank_of(self, path: str) -> int:
        if path not in self._path_rank:
            # file-order fallback until the path's manifest arrives
            self._path_rank[path] = len(self._path_rank)
        return self._path_rank[path]

    def feed(self, rec: dict, path: str = "") -> None:
        kind = rec.get("kind")
        if kind == "manifest":
            # a SECOND manifest on a path this dashboard already
            # follows = a rerun appended to the same file (the Reporter
            # opens JSONL in append mode): start the model over, like
            # every other consumer's newest-segment selection. The
            # seen-set clears with the reset so the new run's sibling
            # manifests (one per rank) do not re-reset.
            if path in self._manifests_seen:
                self._reset()
                self._manifests_seen.clear()
            self._manifests_seen.add(path)
        self.n_records += 1
        self.registry.observe(rec)
        for key in ("t", "t_end"):
            v = rec.get(key)
            if isinstance(v, (int, float)):
                if self.last_wall is None or v > self.last_wall:
                    self.last_wall = v
        if kind == "manifest":
            if not self.manifest or rec.get("process_index") == 0:
                self.manifest = rec
            if isinstance(rec.get("process_index"), int):
                self._path_rank[path] = rec["process_index"]
            n = rec.get("process_count")
            if isinstance(n, int) and n > self.comm_wait.expected:
                self.comm_wait.expected = n
        elif kind == "clock_sync":
            self._path_offset[path] = float(rec.get("offset_s") or 0.0)
            self.comm_wait.clock_sync(self._rank_of(path), rec)
        elif kind == "span":
            self.comm_wait.span(self._rank_of(path), rec,
                                self._path_offset.get(path, 0.0))
        elif kind == "serve" and rec.get("event") == "window":
            self.slo[rec.get("class", "?")] = rec
        elif kind == "mem":
            self.mem[rec.get("rank", 0)] = rec
        elif kind == "overlap":
            self.overlap[rec.get("op", "?")] = rec
        elif kind == "health" and rec.get("event") == "heartbeat":
            self.heartbeat[rec.get("rank", 0)] = rec
        elif kind == "finding":
            self.findings.append(rec)


def _fmt(v, width: int = 8, digits: int = 3) -> str:
    if v is None:
        return "-".rjust(width)
    if isinstance(v, float):
        return f"{v:.{digits}f}".rjust(width)
    return str(v).rjust(width)


def _sample_map(snap: dict, name: str, label: str) -> dict:
    fam = snap.get(name)
    if not fam:
        return {}
    return {dict(labels).get(label, ""): v
            for labels, v in fam["samples"]}


def render(dash: Dashboard, files: list[str]) -> str:
    """One dashboard frame as text (pure function of the model — the
    golden-render tests call this directly)."""
    snap = dash.registry.snapshot()
    man = dash.manifest
    head = [f"tpumt-top — {len(files)} rank file(s), "
            f"{dash.n_records} records"]
    if man:
        head.append(f"platform={man.get('platform', '?')} "
                    f"procs={man.get('process_count', '?')} "
                    f"devices={man.get('global_device_count', '?')}")
    lines = ["  ".join(head)]

    if dash.heartbeat:
        parts = []
        for rank in sorted(dash.heartbeat):
            hb = dash.heartbeat[rank]
            age = (dash.last_wall - hb.get("t", 0)
                   if dash.last_wall is not None else None)
            state = "closed" if hb.get("final") else (
                f"{age:.1f}s ago" if age is not None else "live")
            parts.append(f"rank {rank}: {state}")
        lines.append("BEAT  " + " | ".join(parts))

    if dash.slo:
        # qd99/svc99: the PR-16 latency decomposition — queue delay vs
        # service share of the tail, live (a climbing qd99 under a flat
        # svc99 is saturation building before the shed cliff)
        lines.append(
            f"SLO   {'class':28s} {'off/s':>8s} {'ach/s':>8s} "
            f"{'p50ms':>8s} {'p95ms':>8s} {'p99ms':>8s} "
            f"{'qd99':>8s} {'svc99':>8s} "
            f"{'err':>5s} {'shed':>5s} {'q':>4s}")
        for cls in sorted(dash.slo):
            w = dash.slo[cls]
            lines.append(
                f"      {cls:28s} {_fmt(w.get('offered_hz'))} "
                f"{_fmt(w.get('achieved_hz'))} {_fmt(w.get('p50_ms'))} "
                f"{_fmt(w.get('p95_ms'))} {_fmt(w.get('p99_ms'))} "
                f"{_fmt(w.get('qd_p99_ms'))} "
                f"{_fmt(w.get('svc_p99_ms'))} "
                f"{_fmt(w.get('errors'), 5)} {_fmt(w.get('shed'), 5)} "
                f"{_fmt(w.get('queue_depth', w.get('queue_max')), 4)}")

    ops = _sample_map(snap, "tpumt_spans", "op")
    if ops:
        # GB/s is the ROLLING-window median (the gauge keeps the last
        # value for the exporter; a dashboard column must not show
        # whichever outlier span landed last)
        gbps = _sample_map(snap, "tpumt_span_gbps_window", "op")
        lat = _sample_map(snap, "tpumt_span_latency_seconds", "op")
        roof = _sample_map(snap, "tpumt_roofline_frac", "op")
        # wait% is the cross-rank anatomy decomposition, live: the
        # share of each op's span time spent waiting for the latest
        # entrant (CommWaitWatch; '-' until calls match across ranks)
        wait = _sample_map(snap, "tpumt_comm_wait_frac", "op")
        lines.append(
            f"OPS   {'op':28s} {'ops':>8s} {'GB/s':>8s} "
            f"{'p50ms':>8s} {'p99ms':>8s} {'roof%':>6s} {'wait%':>6s}")
        for op in sorted(ops):
            q = lat.get(op) or {}
            p50 = q.get("p50")
            p99 = q.get("p99")
            rf = roof.get(op)
            wf = wait.get(op)
            g = gbps.get(op) or {}
            lines.append(
                f"      {op:28s} {_fmt(int(ops[op]))} "
                f"{_fmt(g.get('p50'))} "
                f"{_fmt(p50 * 1e3 if p50 is not None else None)} "
                f"{_fmt(p99 * 1e3 if p99 is not None else None)} "
                f"{_fmt(rf * 100 if rf is not None else None, 6, 1)} "
                f"{_fmt(wf * 100 if wf is not None else None, 6, 1)}")

    # per-link-class bandwidth (comm/topology.py span stamps): intra-
    # vs inter-host traffic live. No stamps (flat topology) → no LINK
    # block, same degrade as every other optional table.
    link_bytes = _sample_map(snap, "tpumt_span_link_bytes", "link")
    if link_bytes:
        lgbps = _sample_map(snap, "tpumt_span_link_gbps_window", "link")
        lsecs = _sample_map(snap, "tpumt_span_link_seconds", "link")
        lines.append(
            f"LINK  {'class':28s} {'bytes':>10s} {'secs':>8s} "
            f"{'GB/s':>8s}")
        for cls in sorted(link_bytes):
            g = lgbps.get(cls) or {}
            lines.append(
                f"      {cls:28s} "
                f"{_human_bytes(link_bytes[cls]):>10s} "
                f"{_fmt(lsecs.get(cls))} {_fmt(g.get('p50'))}")

    if dash.mem:
        parts = []
        for rank in sorted(dash.mem):
            m = dash.mem[rank]
            in_use = m.get("bytes_in_use", m.get("live_bytes"))
            peak = m.get("peak_bytes_in_use")
            txt = f"rank {rank}: {_human_bytes(in_use)}"
            if peak is not None:
                txt += f" (peak {_human_bytes(peak)})"
            parts.append(txt)
        lines.append("MEM   " + " | ".join(parts))

    if dash.overlap:
        parts = [
            f"{op}: depth={o.get('depth')} "
            f"frac={o.get('overlap_frac', 0):.3f} "
            f"drain={o.get('drain_s', 0):.4f}s"
            for op, o in sorted(dash.overlap.items())
        ]
        lines.append("OVLP  " + " | ".join(parts))

    health = list(dash.registry.health_events)
    for f in dash.findings:
        health.append(f)
    if health:
        lines.append("HEALTH")
        for h in health[-5:]:
            if h.get("kind") == "finding":
                lines.append(f"      FINDING {h.get('class')} rank="
                             f"{h.get('rank')} conf="
                             f"{h.get('confidence')}")
            else:
                desc = h.get("event", "?")
                if desc == "tune_stale":
                    desc += (f" op={h.get('op')} signal="
                             f"{h.get('signal')} sag="
                             f"{h.get('sag_pct')}%")
                lines.append(f"      {desc}")
    return "\n".join(lines)


def _human_bytes(v) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024
    return "-"


@contextmanager
def _keyreader():
    """cbreak stdin for the ``q`` key in follow mode; inert when stdin
    is not a tty (piped/CI use)."""
    if not sys.stdin.isatty():
        yield None
        return
    try:
        import termios
        import tty
    except ImportError:  # non-POSIX: no key handling
        yield None
        return
    fd = sys.stdin.fileno()
    old = termios.tcgetattr(fd)
    try:
        tty.setcbreak(fd)
        yield fd
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old)


def _wait_key(fd, seconds: float) -> bool:
    """Sleep up to ``seconds``; True when ``q`` was pressed."""
    if fd is None:
        time.sleep(seconds)
        return False
    import select

    r, _, _ = select.select([sys.stdin], [], [], seconds)
    if r:
        return sys.stdin.read(1).lower() == "q"
    return False


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpumt-top",
        description="live console dashboard over the telemetry JSONL "
        "trail: tails the per-rank out.p<i>.jsonl set while a run "
        "writes it and renders per-class SLO, per-op rolling GB/s, "
        "HBM watermarks, overlap fractions, heartbeats, and health "
        "events (README 'Live observability'); without --follow, one "
        "frame from the files' current contents",
    )
    p.add_argument(
        "files", nargs="+",
        help="per-rank JSONL files; an un-suffixed --jsonl base path "
        "expands to its .p<i> rank set (stale siblings of earlier runs "
        "at the same base path are filtered out by run stamp)",
    )
    p.add_argument(
        "--follow", "-f", action="store_true",
        help="keep refreshing until q or Ctrl-C (default: render one "
        "frame and exit)",
    )
    p.add_argument(
        "--interval", type=float, default=1.0, metavar="S",
        help="refresh period in seconds (default 1.0)",
    )
    p.add_argument(
        "--frames", type=int, default=0, metavar="N",
        help="stop after N rendered frames (scriptable exit for "
        "smokes; >1 implies --follow)",
    )
    args = p.parse_args(argv)

    dash = Dashboard()
    tail = RunTail(args.files)
    follow_mode = args.follow or args.frames > 1
    frames = 0
    try:
        with _keyreader() as fd:
            while True:
                for path, _ln, rec in tail.poll():
                    dash.feed(rec, path)
                if not follow_mode and not tail.files():
                    # one-shot mode on a missing path: the sibling
                    # CLIs' no-input guard, not a clean empty frame
                    # (follow mode keeps waiting — the files may be
                    # about to appear)
                    print("tpumt-top: no input files found",
                          file=sys.stderr)
                    return 2
                if follow_mode and sys.stdout.isatty():
                    sys.stdout.write("\x1b[2J\x1b[H")
                print(render(dash, tail.files()), flush=True)
                frames += 1
                if not follow_mode or (args.frames
                                       and frames >= args.frames):
                    return 0
                if _wait_key(fd, args.interval):
                    return 0
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
