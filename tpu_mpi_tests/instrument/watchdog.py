"""Hang watchdog: deadline-based failure detection for distributed phases.

The reference is fail-fast on *errors* (CHECK aborts,
``cuda_error.h:29-41``) but has nothing for *hangs* — a peer dying mid
``MPI_Allgather`` stalls every rank forever, and only the batch scheduler's
walltime kills the job. Distributed XLA collectives hang the same way when
a process drops out, so the framework provides the missing piece: a
deadline that dumps a diagnosis and hard-exits the process, turning a
silent multi-hour stall into an immediate, attributable failure
(SURVEY.md §5.3 — elastic recovery stays out of scope; detection is in).
"""

from __future__ import annotations

import os
import sys
import threading
from contextlib import contextmanager


class Watchdog:
    """Arms a timer around a named phase; if the phase does not complete in
    time, prints a diagnosis to stderr and hard-exits (``os._exit``) so a
    hung collective cannot keep the process alive."""

    def __init__(self, seconds: float, phase: str = "phase",
                 exit_code: int = 9, _on_timeout=None):
        self.seconds = seconds
        self.phase = phase
        self.exit_code = exit_code
        self._on_timeout = _on_timeout  # test hook
        self._timer: threading.Timer | None = None

    def _fire(self):
        msg = (
            f"WATCHDOG: phase '{self.phase}' exceeded {self.seconds}s — "
            f"likely a hung collective (dead peer / mismatched mesh); "
            f"aborting pid {os.getpid()}\n"
        )
        if self._on_timeout is not None:
            self._on_timeout(msg)
            return
        sys.stderr.write(msg)
        sys.stderr.flush()
        os._exit(self.exit_code)

    def start(self):
        self._timer = threading.Timer(self.seconds, self._fire)
        self._timer.daemon = True
        self._timer.start()
        return self

    def cancel(self):
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None


@contextmanager
def deadline(seconds: float | None, phase: str = "phase"):
    """``with deadline(120, "allgather"): ...`` — no-op when ``seconds`` is
    None/0 so drivers can thread an optional ``--deadline`` flag through."""
    if not seconds:
        yield
        return
    wd = Watchdog(seconds, phase).start()
    try:
        yield
    finally:
        wd.cancel()
