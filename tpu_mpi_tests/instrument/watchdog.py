"""Hang watchdog: deadline-based failure detection for distributed phases.

The reference is fail-fast on *errors* (CHECK aborts,
``cuda_error.h:29-41``) but has nothing for *hangs* — a peer dying mid
``MPI_Allgather`` stalls every rank forever, and only the batch scheduler's
walltime kills the job. Distributed XLA collectives hang the same way when
a process drops out, so the framework provides the missing piece: a
deadline that dumps a diagnosis and hard-exits the process, turning a
silent multi-hour stall into an immediate, attributable failure
(SURVEY.md §5.3 — elastic recovery stays out of scope; detection is in).

Attribution comes from the telemetry flight recorder
(``instrument/telemetry.py``): every comm wrapper's span and every RDMA
dispatch note lands in a bounded ring buffer, and a watchdog fire dumps
the last N events with ages — not just the single most recent op, but the
recent *history*, which is what distinguishes "wedged on the first
collective" from "ran 10k exchanges then stalled".
"""

from __future__ import annotations

import os
import sys
import threading
import time
from contextlib import contextmanager

from tpu_mpi_tests.instrument import telemetry as _telemetry

#: how many flight-recorder events a watchdog fire dumps
DUMP_EVENTS = 16

#: how many live-array census buckets a watchdog fire dumps
MEM_DUMP_TOP_K = 8


def note_comm_op(desc: str) -> None:
    """Record a *dispatched* communication op in the flight recorder.

    Dispatch is async, so a hang surfaces later at a sync point; with
    in-order device queues the recently dispatched comm ops are the best
    available attribution for what wedged. The hand-written RDMA ring
    records itself here because a stuck DMA semaphore/neighborhood barrier
    is otherwise a silent hang with no MPI_ERROR analog (VERDICT r1
    missing #4; ≅ the per-request ``MPI_ERROR`` prints,
    ``mpi_stencil2d_gt.cc:230-247``). Recorded even when span telemetry is
    disabled — one ring-buffer append."""
    _telemetry.note_dispatch(desc)


def last_comm_op() -> str | None:
    """Human-readable most recent comm event, with age."""
    lines = _telemetry.flight_lines(1)
    return lines[-1] if lines else None


def comm_op_history(n: int = DUMP_EVENTS) -> list[str]:
    """The last ``n`` recorded comm events (oldest first), formatted."""
    return _telemetry.flight_lines(n)


def memory_state_lines(top_k: int = MEM_DUMP_TOP_K) -> list[str]:
    """Formatted memory state for a fire dump: per-device
    ``memory_stats`` watermarks plus the top-``top_k`` live-array
    shape·dtype buckets (instrument/memwatch.py). Also mirrors the
    state into the JSONL sink as a ``kind: "mem"`` record
    (``event: "watchdog"``) when telemetry is enabled. Never raises —
    this runs on the watchdog's timer thread mid-hang, where a
    diagnostic failure must not mask the hang itself."""
    try:
        from tpu_mpi_tests.instrument import memwatch

        _telemetry.emit(memwatch.mem_record(event="watchdog",
                                            top_k=top_k))
        return memwatch.watermark_lines(top_k)
    except Exception:
        return []


class Watchdog:
    """Arms a timer around a named phase; if the phase does not complete in
    time, prints a diagnosis to stderr and hard-exits (``os._exit``) so a
    hung collective cannot keep the process alive."""

    def __init__(self, seconds: float, phase: str = "phase",
                 exit_code: int = 9, _on_timeout=None):
        self.seconds = seconds
        self.phase = phase
        self.exit_code = exit_code
        self._on_timeout = _on_timeout  # test hook
        self._timer: threading.Timer | None = None

    def _fire(self):
        # place the fire on the cross-rank timeline before dying: with a
        # JSONL sink enabled this lands a ``kind: "watchdog"`` record the
        # trace merger renders as the marker terminating this rank's flow
        # (telemetry.emit is best-effort — a sink error cannot mask the
        # hang diagnosis below)
        _telemetry.emit({
            "kind": "watchdog",
            "phase": self.phase,
            "deadline_s": self.seconds,
            "t": time.time(),
        })
        history = comm_op_history()
        if history:
            attribution = (
                f" last {len(history)} comm ops (newest last):\n    "
                + "\n    ".join(history)
                + "\n "
            )
        else:
            attribution = ""
        # memory state at fire: per-device watermarks + top live-array
        # census — a hang from an OOM-retrying allocator and a wedged
        # collective look identical without this. Best-effort from this
        # timer thread (allocator stats are local queries; the census
        # reads a host-side registry — neither blocks on device queues),
        # and also emitted as a ``kind: "mem"`` record so the timeline
        # carries the memory state at the fire point.
        mem_lines = memory_state_lines()
        memory = (
            f" memory at fire:\n    " + "\n    ".join(mem_lines) + "\n "
            if mem_lines else ""
        )
        msg = (
            f"WATCHDOG: phase '{self.phase}' exceeded {self.seconds}s — "
            f"likely a hung collective (dead peer / mismatched mesh / "
            f"wedged RDMA semaphore);{attribution}{memory} "
            f"aborting pid {os.getpid()}\n"
        )
        if self._on_timeout is not None:
            self._on_timeout(msg)
            return
        sys.stderr.write(msg)
        sys.stderr.flush()
        os._exit(self.exit_code)

    def start(self):
        self._timer = threading.Timer(self.seconds, self._fire)
        self._timer.daemon = True
        self._timer.start()
        return self

    def cancel(self):
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None


class IdleAwareWatchdog(Watchdog):
    """Watchdog for workloads with idle gaps: the deadline clock runs
    only while ARMED.

    :class:`Watchdog` assumes continuous dispatch — one timer covering a
    whole phase — which is wrong for a serving loop, where open-loop
    Poisson arrivals legitimately leave the process idle for longer than
    any sane batch deadline. This variant makes the active window
    explicit: ``arm()`` (re)starts the timer just before a dispatch,
    ``disarm()`` stops it once the batch completed; while disarmed, no
    amount of idle time can fire. A genuinely wedged batch — armed,
    never disarmed — still dumps and hard-exits exactly like the base
    class. Arm/disarm are called from the single serve-loop thread.

    Each ``arm()`` starts a fresh ``threading.Timer`` — ~100 us next to
    the device round-trip every batch already pays, and the whole
    feature is opt-in (``--batch-deadline``). If a future workload arms
    at kHz rates, the upgrade path is one persistent checker thread
    polling an armed-deadline timestamp; not worth the extra shared
    state at today's batch rates.
    """

    def arm(self, phase: str | None = None) -> "IdleAwareWatchdog":
        """(Re)start the deadline for one active dispatch window."""
        if phase is not None:
            # a firing timer reading phase mid-update can only mislabel
            # its dump (a str rebind is GIL-atomic, never torn), and
            # arm() cancels the old timer before starting the next —
            # the label race is benign by design
            self.phase = phase  # tpumt: ignore[TPM1601]
        self.cancel()
        return self.start()

    def disarm(self) -> None:
        """Back to idle: the deadline clock stops."""
        self.cancel()

    @contextmanager
    def active(self, phase: str | None = None):
        """``with wd.active("serve:daxpy"): dispatch()`` — armed only
        inside the block."""
        self.arm(phase)
        try:
            yield self
        finally:
            self.disarm()


@contextmanager
def deadline(seconds: float | None, phase: str = "phase"):
    """``with deadline(120, "allgather"): ...`` — no-op when ``seconds`` is
    None/0 so drivers can thread an optional ``--deadline`` flag through."""
    if not seconds:
        yield
        return
    wd = Watchdog(seconds, phase).start()
    try:
        yield
    finally:
        wd.cancel()
