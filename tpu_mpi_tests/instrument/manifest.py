"""Run manifest: make every result file self-describing.

The reference identified runs by filename convention (``out-<tag>.txt``)
and tribal knowledge of which node/allocation produced them; nothing in
the file says what hardware, software, or configuration generated the
numbers. The manifest is the first JSONL record of every instrumented run
(``kind: "manifest"``) plus a rank-0 banner line: device topology, process
index/count, jax/jaxlib/libtpu versions, the relevant ``TPU_MPI_*``/JAX
environment flags, argv, and the git sha — enough to re-run or disqualify
a result file months later without asking who produced it.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path

#: env-var prefixes worth capturing — the framework's own knobs plus the
#: JAX/XLA/libtpu switches that change what the numbers mean
ENV_PREFIXES = ("TPU_MPI_", "JAX_", "XLA_", "LIBTPU_", "TPU_")


def _git_sha() -> str | None:
    """Best-effort short sha of the source tree; never raises."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=Path(__file__).resolve().parent,
            capture_output=True,
            text=True,
            timeout=5,
        )
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def run_manifest(argv: list[str] | None = None, **extra) -> dict:
    """Build the manifest record. Requires an initialized JAX backend
    (drivers call it after ``setup_platform``/``bootstrap``); ``extra``
    key/values are merged in (driver-specific config)."""
    import platform as _platform
    import socket

    import jax

    devices = jax.devices()
    env = {
        k: v
        for k, v in sorted(os.environ.items())
        if k.startswith(ENV_PREFIXES)
    }
    record = {
        "kind": "manifest",
        "time_unix": time.time(),
        "time_iso": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "argv": list(sys.argv if argv is None else argv),
        "hostname": socket.gethostname(),
        "python": _platform.python_version(),
        "jax": jax.__version__,
        "jaxlib": _version_of("jaxlib"),
        "libtpu": _version_of("libtpu"),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": len(devices),
        "platform": devices[0].platform,
        "device_kinds": sorted({d.device_kind for d in devices}),
        "env": env,
        "git_sha": _git_sha(),
    }
    record.update(extra)
    return record


def _version_of(module: str) -> str | None:
    try:
        import importlib

        return getattr(importlib.import_module(module), "__version__", None)
    except ImportError:
        return None


def manifest_banner(m: dict) -> str:
    """One-line run identity for the rank-0 banner."""
    kinds = ",".join(m.get("device_kinds", [])) or "?"
    sha = m.get("git_sha") or "unknown"
    return (
        f"MANIFEST {m.get('platform', '?')}x{m.get('global_device_count', 0)}"
        f" ({kinds}) proc {m.get('process_index', 0)}/"
        f"{m.get('process_count', 1)} jax={m.get('jax', '?')} git={sha}"
    )
