"""Run manifest: make every result file self-describing.

The reference identified runs by filename convention (``out-<tag>.txt``)
and tribal knowledge of which node/allocation produced them; nothing in
the file says what hardware, software, or configuration generated the
numbers. The manifest is the first JSONL record of every instrumented run
(``kind: "manifest"``) plus a rank-0 banner line: device topology, process
index/count, jax/jaxlib/libtpu versions, the relevant ``TPU_MPI_*``/JAX
environment flags, argv, and the git sha — enough to re-run or disqualify
a result file months later without asking who produced it.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path

#: env-var prefixes worth capturing — the framework's own knobs plus the
#: JAX/XLA/libtpu switches that change what the numbers mean
ENV_PREFIXES = ("TPU_MPI_", "JAX_", "XLA_", "LIBTPU_", "TPU_")


def _git_sha() -> str | None:
    """Best-effort short sha of the source tree; never raises."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=Path(__file__).resolve().parent,
            capture_output=True,
            text=True,
            timeout=5,
        )
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def run_manifest(argv: list[str] | None = None, **extra) -> dict:
    """Build the manifest record. Requires an initialized JAX backend
    (drivers call it after ``setup_platform``/``bootstrap``); ``extra``
    key/values are merged in (driver-specific config)."""
    import platform as _platform
    import socket

    import jax

    devices = jax.devices()
    env = {
        k: v
        for k, v in sorted(os.environ.items())
        if k.startswith(ENV_PREFIXES)
    }
    record = {
        "kind": "manifest",
        "time_unix": time.time(),
        "time_iso": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "argv": list(sys.argv if argv is None else argv),
        "hostname": socket.gethostname(),
        "python": _platform.python_version(),
        "jax": jax.__version__,
        "jaxlib": _version_of("jaxlib"),
        "libtpu": _version_of("libtpu"),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": len(devices),
        "platform": devices[0].platform,
        "device_kinds": sorted({d.device_kind for d in devices}),
        "env": env,
        "git_sha": _git_sha(),
    }
    # memory identity: whether the backend reports allocator watermarks
    # (CPU/fake devices do not — downstream consumers degrade to
    # census-only) and the per-device HBM capacity when it does, so a
    # result file says what memory the numbers were measured against
    from tpu_mpi_tests.instrument.memwatch import device_memory_stats

    stats = device_memory_stats()
    record["memory_stats_available"] = bool(stats)
    limits = [s["bytes_limit"] for s in stats.values()
              if "bytes_limit" in s]
    if limits:
        record["hbm_bytes_limit"] = max(limits)
    # topology identity (comm/topology.py): host/slice shape summary,
    # stamped ONLY when non-flat — single-host/CPU manifests (and the
    # report header they drive) stay byte-identical
    from tpu_mpi_tests.comm.topology import current as _topology

    topo = _topology()
    if not topo.is_flat:
        record["hosts"] = topo.num_hosts
        if topo.ranks_per_host:
            record["ranks_per_host"] = topo.ranks_per_host
        record["topology"] = topo.label()
    record.update(extra)
    return record


def _split_us(t: float):
    """Epoch seconds -> three base-2^24 digits of integer microseconds
    (float64 array). Each digit < 2^24 is exactly representable in
    float32, so the value survives ``process_allgather``'s device
    round-trip even when x64 is off (jax.device_put canonicalizes
    float64 -> float32, whose ulp at epoch magnitude is ~128 s — a raw
    ``time.time()`` gather would be pure quantization noise)."""
    import numpy as np

    us = int(round(t * 1e6))
    return np.array(
        [(us >> 48) & 0xFFFFFF, (us >> 24) & 0xFFFFFF, us & 0xFFFFFF],
        np.float64,
    )


def _join_us(digits) -> float:
    """Inverse of :func:`_split_us` (exact at 1 us resolution)."""
    d = [int(round(float(v))) for v in digits]
    return ((d[0] << 48) | (d[1] << 24) | d[2]) / 1e6


def clock_sync_record(rounds: int = 5) -> dict:
    """Estimate this rank's wall-clock offset from rank 0 (``kind:
    "clock_sync"``) so per-rank JSONL merges onto one time axis.

    Barrier-echo handshake: every process enters a global barrier, reads
    ``time.time()`` at barrier exit, and all-gathers the readings — at
    each round the exits are simultaneous to within the barrier's own
    skew, so ``t_local − t_rank0`` samples the clock offset plus that
    skew noise. The median over ``rounds`` is the estimate and the
    sample spread is recorded as its quality bound (``spread_s``); the
    timeline merger subtracts ``offset_s`` from every timestamp of the
    rank. Single-process runs (including fake-device meshes — one clock)
    record offset 0 without any collective. Requires an initialized
    backend, like :func:`run_manifest`; never raises — an environment
    where the handshake cannot run yields offset 0 tagged
    ``method: "unavailable"`` (timestamps then merge uncorrected,
    exactly the pre-handshake behavior)."""
    import jax

    now = time.time()
    rec = {
        "kind": "clock_sync",
        "rank": jax.process_index(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "offset_s": 0.0,
        "spread_s": 0.0,
        "rounds": 0,
        "method": "single_process",
        "time_unix": now,
        # run identity: rank 0's first-barrier timestamp, identical on
        # every rank of one handshake — the --trace-out auto-merge uses
        # it to tell this run's sibling rank files from stale ones at
        # the same base path (single-process runs have no same-run
        # siblings, so their own timestamp serves); None when the
        # handshake could not run (merge falls back to an mtime filter)
        "run_sync_us": int(round(now * 1e6)),
    }
    if jax.process_count() <= 1:
        return rec
    # The only raisers below are environmental (import/backend),
    # identical on every rank, so the handler's skip is symmetric —
    # not a partner mismatch.
    try:  # tpumt: ignore[TPM1703] — never-raises contract (docstring)
        import numpy as np
        from jax.experimental import multihost_utils

        # timestamps cross the gather as f32-exact base-2^24 digits
        # (see _split_us — raw epoch float64s would be canonicalized to
        # float32 with ~128 s resolution when x64 is off)
        samples = []
        run_sync_us = None
        for k in range(rounds):
            multihost_utils.sync_global_devices(f"tpumt_clock_sync_{k}")
            t_local = time.time()
            ts = np.asarray(
                multihost_utils.process_allgather(_split_us(t_local))
            ).reshape(-1, 3)
            t_rank0 = _join_us(ts[0])
            if run_sync_us is None:
                run_sync_us = int(round(t_rank0 * 1e6))
            samples.append(t_local - t_rank0)
        samples.sort()
        rec.update(
            offset_s=samples[len(samples) // 2],
            spread_s=samples[-1] - samples[0],
            rounds=len(samples),
            method="barrier_echo",
            run_sync_us=run_sync_us,
        )
    except Exception as e:  # noqa: BLE001 — diagnostic record, not control
        rec.update(method=f"unavailable: {type(e).__name__}",
                   run_sync_us=None)
    return rec


def _version_of(module: str) -> str | None:
    try:
        import importlib

        return getattr(importlib.import_module(module), "__version__", None)
    except ImportError:
        return None


def manifest_banner(m: dict) -> str:
    """One-line run identity for the rank-0 banner."""
    kinds = ",".join(m.get("device_kinds", [])) or "?"
    sha = m.get("git_sha") or "unknown"
    return (
        f"MANIFEST {m.get('platform', '?')}x{m.get('global_device_count', 0)}"
        f" ({kinds}) proc {m.get('process_index', 0)}/"
        f"{m.get('process_count', 1)} jax={m.get('jax', '?')} git={sha}"
    )
