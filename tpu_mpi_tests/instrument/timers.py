"""Phase wall-clock timers with async-dispatch-honest synchronization.

The reference brackets every timed phase with a device sync before reading
the clock (``gt::synchronize`` before ``clock_gettime``,
``mpi_stencil2d_gt.cc:254,520``; ``cudaDeviceSynchronize`` before the
``MPI_Wtime`` reads, ``mpi_daxpy_nvtx.cc:242-249``). JAX dispatch is async,
so the same discipline is mandatory here: every phase boundary calls
``block_until_ready`` on the arrays produced by the phase, otherwise time is
mis-attributed to whichever phase happens to flush the queue
(SURVEY.md §7 hard part 2).
"""

from __future__ import annotations

import functools
import os
import time
from collections import defaultdict
from contextlib import contextmanager

import jax
import numpy as np

#: phase-boundary observers (``hook(name, "begin"|"end")``): the memory
#: watcher (instrument/memwatch.py) snapshots HBM watermarks here so
#: every PhaseTimer phase gets per-phase memory deltas without the
#: drivers threading anything. Empty-list check only when unarmed; hooks
#: fire OUTSIDE the timed window (before the start read, after the end
#: read) so observer cost is never charged to the phase.
_PHASE_HOOKS: list = []


def add_phase_hook(hook) -> None:
    if hook not in _PHASE_HOOKS:
        _PHASE_HOOKS.append(hook)


def remove_phase_hook(hook) -> None:
    try:
        _PHASE_HOOKS.remove(hook)
    except ValueError:
        pass


def _fire_phase_hooks(name: str, event: str) -> None:
    for hook in list(_PHASE_HOOKS):
        try:
            hook(name, event)
        except Exception:
            pass  # observers must never fail the measured phase


@functools.lru_cache(maxsize=None)
def _use_hard_sync() -> bool:
    """Whether ``block_until_ready`` alone is trustworthy on this backend.

    On tunneled/experimental TPU backends (the image's 'axon' plugin, which
    registers as platform ``tpu``), ``block_until_ready`` returns before the
    device work finishes; only a host read truly synchronizes. Measured here:
    a ~1.1 TFLOP matmul "blocks" in 0.13 ms but takes >100 ms to produce a
    byte. Probed empirically once per process: dispatch a ≥1 TFLOP matmul
    and see whether ``block_until_ready`` takes a plausible amount of time;
    if it "completes" faster than any hardware could, the backend is lying
    and every :func:`block` adds a 1-element device→host read per shard.
    Override with ``TPU_MPI_TESTS_HARD_SYNC=0/1``.
    """
    env = os.environ.get("TPU_MPI_TESTS_HARD_SYNC")
    if env is not None:
        return env.lower() not in ("0", "false", "")
    if jax.default_backend() == "cpu":
        return False  # in-process backend; block_until_ready is real
    import jax.numpy as jnp

    # kept deliberately small (2×64 MB HBM, ~137 GFLOP) so the probe doesn't
    # perturb a benchmark mid-run on honest backends; a lying sync returns in
    # ~0.1 ms regardless of op size, so modest work + a scaled threshold
    # discriminates just as well as the original 1.1 TFLOP probe
    a = jnp.ones((4096, 4096), jnp.float32)
    f = jax.jit(lambda a: a @ a)
    f(a).block_until_ready()  # compile + warm
    r = f(a)
    t0 = time.perf_counter()
    r.block_until_ready()
    blocked_s = time.perf_counter() - t0
    del a, r  # release probe HBM before any benchmark allocates
    # 137 GFLOP in under 0.5 ms would exceed 270 TFLOP/s f32 on a single chip
    return blocked_s < 5e-4


def _hard_sync_leaf(x) -> None:
    if not isinstance(x, jax.Array) or x.is_deleted():
        return
    # a 1-element read depends on the whole shard buffer, so its arrival on
    # host proves that shard's producing computation completed; every
    # addressable shard must be read — devices finish independently
    reads = []
    for shard in x.addressable_shards:
        r = shard.data
        # index one axis at a time: a multi-axis dynamic-slice is rejected
        # by the AOT path for host-memory-space (pinned_host) buffers
        # ("Async slice only supports slicing in 1 dimension")
        while r.ndim:
            r = r[0]
        reads.append(r)
    for r in reads:
        np.asarray(r)


def block(*pytrees):
    """Synchronize: wait until every jax.Array in the pytrees is *actually*
    computed (``block_until_ready`` + hard host-read sync where needed).

    Returns the single argument (or tuple) for chaining:
    ``y = block(f(x))`` ≅ kernel-then-``cudaDeviceSynchronize``.
    """
    for t in pytrees:
        jax.block_until_ready(t)
    if _use_hard_sync():
        for t in pytrees:
            for leaf in jax.tree.leaves(t):
                _hard_sync_leaf(leaf)
    return pytrees[0] if len(pytrees) == 1 else pytrees


def dispatch_rate(f, *args, n_iter: int = 2000, n_base: int = 200) -> float:
    """Mean seconds per call of ``f(*args)`` under async dispatch.

    Dispatches ``n_base`` then ``n_base + n_iter`` independent calls, hard-
    syncing once per batch on the last result only (in-order device queues
    make the last result's completion prove the batch drained); the
    difference cancels the fixed controller round-trip (~106 ms on the axon
    tunnel) and dispatch ramp. Use when the op can't be chained
    shape-preservingly (else prefer a device-side ``lax.fori_loop``)."""
    block(f(*args))  # compile + warm

    def run(n):
        t0 = time.perf_counter()
        r = None
        for _ in range(n):
            r = f(*args)
        block(r)
        return time.perf_counter() - t0

    t_base = run(n_base)
    t_full = run(n_base + n_iter)
    return max(t_full - t_base, 1e-12) / n_iter


def chain_rate(run, state, n_short: int = 100, n_long: int = 2100,
               repeats: int = 1):
    """Seconds per iteration of a device-side chained loop.

    ``run(state, n)`` must execute ``n`` data-dependent iterations on device
    (``lax.fori_loop``) and return the new state. Two run lengths are
    differenced to cancel the fixed dispatch + sync cost (≈106 ms controller
    round-trip on the axon tunnel). This is the measurement primitive behind
    every chained row in BASELINE.md — unlike per-dispatch timing it never
    releases the device queue mid-measurement, so it is robust to the shared
    chip's minute-scale contention (round-2 methodology note).

    Returns ``(seconds_per_iter, final_state)``. A non-positive delta
    (possible on a heavily contended host where timer noise exceeds the
    device work) yields NaN rather than a sign-masked absurd rate — an
    invalid measurement must look invalid downstream.

    ``repeats`` > 1 (round 5) measures the pair that many times and
    returns the FINITE minimum — contention only inflates, so the min is
    the robust estimator (the standing BASELINE argument), and a single
    spiked or invalid repeat cannot poison the reading (NaN only when
    EVERY repeat is invalid). Use for fit sweeps whose derived gates
    (linearity checks) a single inflated point would trip.
    """
    state = block(run(state, 3))  # compile + warm
    best = float("nan")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        state = block(run(state, n_short))
        t_short = time.perf_counter() - t0
        t0 = time.perf_counter()
        state = block(run(state, n_long))
        t_long = time.perf_counter() - t0
        delta = t_long - t_short
        if delta > 0:
            per = delta / (n_long - n_short)
            if not (best == best) or per < best:  # best is NaN or worse
                best = per
    return best, state


class PhaseTimer:
    """Accumulating named phase timers (≅ the t_/k_/b_/g_ MPI_Wtime pairs of
    ``mpi_daxpy_nvtx.cc:168,242-291,327`` and the per-iteration
    ``clock_gettime`` loop of ``mpi_stencil2d_gt.cc:511-526``).

    ``skip_first`` implements the warmup convention: the first ``skip_first``
    entries into each phase are timed but not accumulated
    (≅ ``i >= n_warmup`` accumulation guard, ``mpi_stencil2d_gt.cc:521-526``).
    """

    def __init__(self, skip_first: int = 0):
        self.seconds: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.mins: dict[str, float] = {}
        self.maxs: dict[str, float] = {}
        # wall-clock (Unix) + monotonic bounds of each phase's lifetime:
        # first entry's start through last exit's end, warmup entries
        # included — the timeline (instrument/timeline.py) draws the
        # phase as the window it really occupied, while seconds/counts
        # keep the reference's warmup-skipping accumulation semantics
        self.t_starts: dict[str, float] = {}
        self.t_ends: dict[str, float] = {}
        self.mono_starts: dict[str, float] = {}
        self.mono_ends: dict[str, float] = {}
        self._entries: dict[str, int] = defaultdict(int)
        # extra per-phase record fields (``annotate``): the overlap
        # engine attaches its measured overlap_frac here so the phase's
        # JSONL ``time`` record carries it without new record kinds
        self.extras: dict[str, dict] = {}
        self.skip_first = skip_first

    @contextmanager
    def phase(self, name: str, sync=None):
        """Time a phase. ``sync`` (pytree) is blocked on *before* starting so
        queued work from the previous phase is not charged to this one; the
        phase body must return/produce arrays the caller blocks on, or pass
        them via :func:`block` inside the body before exit."""
        if sync is not None:
            block(sync)
        if _PHASE_HOOKS:
            _fire_phase_hooks(name, "begin")
        t0_wall = time.time()
        t0 = time.perf_counter()
        yield
        t1 = time.perf_counter()
        dt = t1 - t0
        if _PHASE_HOOKS:
            _fire_phase_hooks(name, "end")
        self.t_starts.setdefault(name, t0_wall)
        # wall end anchored to the monotonic duration (NTP-step-proof)
        self.t_ends[name] = t0_wall + dt
        self.mono_starts.setdefault(name, t0)
        self.mono_ends[name] = t1
        self._entries[name] += 1
        if self._entries[name] > self.skip_first:
            self.seconds[name] += dt
            self.counts[name] += 1
            self.mins[name] = min(self.mins.get(name, dt), dt)
            self.maxs[name] = max(self.maxs.get(name, dt), dt)

    def timed(self, name: str, fn, *args, **kwargs):
        """Run ``fn`` and block on its result inside the phase bracket."""
        with self.phase(name):
            out = block(fn(*args, **kwargs))
        return out

    def mean(self, name: str) -> float:
        c = self.counts[name]
        return self.seconds[name] / c if c else 0.0

    def annotate(self, name: str, **fields) -> None:
        """Attach extra fields to a phase's JSONL ``time`` record (e.g.
        the overlap engine's ``overlap_frac``). Merged by
        ``Reporter.time_lines``; unknown to the stdout ``TIME`` line,
        whose reference shape stays fixed."""
        self.extras.setdefault(name, {}).update(fields)

    def wall_span(self, name: str) -> tuple[float | None, float | None]:
        """Wall-clock ``(t_start, t_end)`` of the phase's full lifetime
        (first entry to last exit), or ``(None, None)`` if never entered
        — the pair every JSONL ``time`` record carries for the cross-rank
        timeline."""
        return self.t_starts.get(name), self.t_ends.get(name)

    def lines(self, prefix: str = "TIME", stats: bool = False) -> list[str]:
        """Stable per-phase lines (≅ ``TIME <phase> : %0.3f``,
        ``mpi_daxpy_nvtx.cc:333-340``). ``stats`` appends the
        per-entry distribution the timer already accumulates
        (count/mean/min/max — max≫mean exposes a slow link as jitter)
        without disturbing the reference-shaped prefix."""
        out = []
        for name in self.seconds:
            line = f"{prefix} {name} : {self.seconds[name]:0.6f}"
            if stats:
                line += (
                    f" count={self.counts[name]} mean={self.mean(name):e}"
                    f" min={self.mins.get(name, 0.0):e}"
                    f" max={self.maxs.get(name, 0.0):e}"
                )
            out.append(line)
        return out

    def as_dict(self) -> dict[str, float]:
        return dict(self.seconds)
