"""Live metrics plane: a bounded in-process registry fed by the record
stream the spine already emits.

Every observability layer before this one (``tpumt-report``,
``tpumt-trace``, ``tpumt-doctor``) is post-mortem — it reads JSONL after
the run ended. This module is the live half: a
:class:`MetricsRegistry` of counters, gauges, and rolling-window
histograms that is TEE-FED from the Reporter's JSONL chokepoint
(``Reporter.attach_metrics``), so every record the run already writes —
``kind: "span"/"serve"/"mem"/"overlap"/"route"/"decode"/"time"/...`` —
updates named series with ZERO new instrumentation call sites. The
registry is what the OpenMetrics exporter (``instrument/export.py``)
and the ``tpumt-top`` dashboard (``instrument/live.py``) read.

Three design contracts:

* **Bounded**: rolling histograms are a fixed ring of
  :class:`~tpu_mpi_tests.serve.histogram.LatencyHistogram` sub-windows
  (the serve loop's bounded-memory percentile structure, reused) and
  the series table is capped — past :data:`MAX_SERIES` distinct
  (name, labels) pairs new series are dropped and counted in
  ``tpumt_series_dropped``, never grown without bound.
* **Zero-cost when disarmed**: nothing in this module runs unless
  ``--metrics-port`` armed the tee (one ``None`` check on the Reporter
  path); a disarmed run is byte-identical to a build without the
  module (pinned in tests, the PR-9 pattern).
* **Never raises**: :meth:`MetricsRegistry.observe` is on the record
  path of a measured run — a metrics bug must not fail the op that was
  being recorded.

The registry also hosts the ``tune_stale`` watermark rule (ROADMAP
1(c)): once a tuned schedule is active (a ``tune_hit``/``tune_result``
record flowed through), each op's first :data:`STALE_SAMPLES` achieved
GB/s readings (and ``roofline_frac``, where the cost model attached
one) become the cached winner's fresh baseline; a later rolling window
of the same width sagging below the baseline by more than the noise
band emits exactly one ``kind: "health" event: "tune_stale"`` record —
the hook a future re-sweep controller subscribes to.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

from tpu_mpi_tests.serve.histogram import LatencyHistogram

#: rolling-histogram window: percentiles cover the last this-many seconds
ROLLING_WINDOW_S = 60.0

#: sub-windows per rolling histogram (expiry granularity = window/slots)
ROLLING_SLOTS = 6

#: hard cap on distinct (name, labels) series; excess increments
#: ``tpumt_series_dropped`` instead of growing the table
MAX_SERIES = 1024

#: tune_stale window width: baseline = the op's first this-many samples
#: after a tuned schedule went live, rolling = the most recent this-many
STALE_SAMPLES = 8

#: tune_stale noise-band floor: a sag smaller than this fraction of the
#: baseline never fires, however tight the baseline's own spread was
STALE_MIN_SAG = 0.15


class RollingHistogram:
    """Fixed-footprint rolling-window histogram: a ring of
    :class:`LatencyHistogram` sub-windows, one per
    ``window_s / slots`` time slice, expired by slot age on read. The
    merged readout covers at most ``window_s`` (and at least
    ``window_s - window_s/slots``) of trailing samples."""

    __slots__ = ("_slot_s", "_max", "_ring", "_clock")

    def __init__(self, window_s: float = ROLLING_WINDOW_S,
                 slots: int = ROLLING_SLOTS,
                 clock: Callable[[], float] = time.monotonic):
        self._slot_s = float(window_s) / max(1, int(slots))
        self._max = max(1, int(slots))
        self._ring: deque = deque()  # (slot_index, LatencyHistogram)
        self._clock = clock

    def record(self, seconds: float) -> None:
        idx = int(self._clock() / self._slot_s)
        if not self._ring or self._ring[-1][0] != idx:
            self._ring.append((idx, LatencyHistogram()))
            while len(self._ring) > self._max \
                    or self._ring[0][0] <= idx - self._max:
                self._ring.popleft()
        self._ring[-1][1].record(seconds)

    def merged(self) -> LatencyHistogram:
        """One histogram over the non-expired slots (age judged now, so
        a quiet series forgets its stale samples on read)."""
        idx = int(self._clock() / self._slot_s)
        out = LatencyHistogram()
        for slot_idx, sub in self._ring:
            if slot_idx <= idx - self._max or not sub.count:
                continue
            for i, c in enumerate(sub.counts):
                out.counts[i] += c
            out.count += sub.count
            out.total_s += sub.total_s
            out.min_s = min(out.min_s, sub.min_s)
            out.max_s = max(out.max_s, sub.max_s)
        return out


class _Series:
    __slots__ = ("kind", "value", "hist")

    def __init__(self, kind: str, clock):
        self.kind = kind
        self.value = 0.0
        self.hist = RollingHistogram(clock=clock) \
            if kind == "histogram" else None


def _mean(vals) -> float:
    vals = list(vals)
    return sum(vals) / len(vals) if vals else 0.0


class _TuneStaleWatch:
    """The watermark rule: per op, the first :data:`STALE_SAMPLES`
    readings after a tuned schedule went live are the winner's fresh
    baseline; a full rolling window sagging below it by more than
    ``max(STALE_MIN_SAG, baseline spread)`` fires exactly one health
    record (latched per op). Both achieved GB/s and ``roofline_frac``
    feed the same latch — whichever signal sags first convicts."""

    def __init__(self, registry: "MetricsRegistry"):
        self._reg = registry
        self._lock = threading.Lock()
        self._knobs: list[str] = []
        self._ops: dict[str, dict] = {}

    def tuned(self, knob) -> None:
        with self._lock:
            if knob and knob not in self._knobs:
                self._knobs.append(str(knob))

    def reset(self, op: str) -> None:
        """Forget an op's baseline AND its latch: the re-tune controller
        calls this after a ``tune_swap`` so the op re-baselines on the
        NEW schedule's first readings — recovery becomes measurable and
        a future sag of the new winner can fire again."""
        with self._lock:
            self._ops.pop(op, None)

    def span(self, op: str, gbps, roofline_frac) -> None:
        with self._lock:
            if not self._knobs:
                return  # no tuned schedule active: nothing to go stale
            st = self._ops.setdefault(op, {
                "gbps": {"base": [], "roll": deque(maxlen=STALE_SAMPLES)},
                "roofline_frac": {"base": [],
                                  "roll": deque(maxlen=STALE_SAMPLES)},
                "fired": False,
            })
            for signal, v in (("gbps", gbps),
                              ("roofline_frac", roofline_frac)):
                if not isinstance(v, (int, float)) or v != v or v <= 0:
                    continue
                win = st[signal]
                if len(win["base"]) < STALE_SAMPLES:
                    win["base"].append(float(v))
                    continue
                win["roll"].append(float(v))
                if st["fired"] or len(win["roll"]) < STALE_SAMPLES:
                    continue
                base = _mean(win["base"])
                if base <= 0:
                    continue
                band = (max(win["base"]) - min(win["base"])) / base
                threshold = max(STALE_MIN_SAG, band)
                rolling = _mean(win["roll"])
                sag = 1.0 - rolling / base
                if sag <= threshold:
                    continue
                st["fired"] = True
                rec = {
                    "kind": "health", "event": "tune_stale", "op": op,
                    "signal": signal,
                    "baseline": round(base, 6),
                    "rolling": round(rolling, 6),
                    "sag_pct": round(100.0 * sag, 2),
                    "threshold_pct": round(100.0 * threshold, 2),
                    "n": STALE_SAMPLES,
                    "knobs": list(self._knobs),
                    "t": self._reg.wall(),
                }
                break
            else:
                return
        # emit OUTSIDE the lock: the sink is the Reporter's JSONL, whose
        # tee feeds the record straight back into this registry
        self._reg.emit_health(rec)


class MetricsRegistry:
    """Thread-safe named-series table + the record-kind dispatch that
    turns the spine's JSONL records into series updates."""

    def __init__(self, *, wall: Callable[[], float] = time.time,
                 clock: Callable[[], float] = time.monotonic,
                 max_series: int = MAX_SERIES,
                 health_sink: Callable[[dict], None] | None = None):
        self._lock = threading.Lock()
        self._series: dict[tuple, _Series] = {}
        self.wall = wall
        self.clock = clock
        self._max_series = max_series
        self._health_sink = health_sink
        #: recent kind:"health" records (observed or self-fired) for the
        #: dashboard's HEALTH section — bounded by construction
        self.health_events: deque = deque(maxlen=16)
        #: synchronous subscribers to non-heartbeat health events (the
        #: serve-loop re-tune controller latches tune_stale through
        #: this); registered during single-threaded setup, called on the
        #: observing thread, exceptions swallowed like every tee path
        self._health_listeners: list = []
        self.started_wall = wall()
        self._stale = _TuneStaleWatch(self)

    def set_health_sink(self, sink: Callable[[dict], None] | None) -> None:
        self._health_sink = sink

    def add_health_listener(self, cb: Callable[[dict], None]) -> None:
        self._health_listeners.append(cb)

    def reset_stale(self, op: str) -> None:
        """Re-baseline an op's tune_stale watch (post-swap)."""
        self._stale.reset(op)

    # -- series primitives -------------------------------------------------

    def _get(self, name: str, kind: str, labels: tuple) -> _Series | None:
        key = (name, labels)
        s = self._series.get(key)
        if s is not None:
            return s
        if len(self._series) >= self._max_series:
            # the cap is the bounded-memory contract: count the drop
            # (the one series allowed past the cap) instead of growing
            drop_key = ("tpumt_series_dropped", ())
            dropped = self._series.get(drop_key)
            if dropped is None:
                dropped = self._series[drop_key] = _Series(
                    "counter", self.clock)
            dropped.value += 1
            return None
        s = self._series[key] = _Series(kind, self.clock)
        return s

    def inc(self, name: str, labels: tuple = (), v: float = 1) -> None:
        with self._lock:
            s = self._get(name, "counter", labels)
            if s is not None:
                s.value += v

    def set_gauge(self, name: str, labels: tuple = (),
                  v: float = 0.0) -> None:
        with self._lock:
            s = self._get(name, "gauge", labels)
            if s is not None:
                s.value = v

    def observe_sample(self, name: str, labels: tuple = (),
                       value: float = 0.0) -> None:
        """Record into a rolling-window histogram series (latency
        seconds, rates — any positive value the log buckets cover)."""
        with self._lock:
            s = self._get(name, "histogram", labels)
            if s is not None:
                s.hist.record(value)

    def value(self, name: str, labels: tuple = ()):
        """Current counter/gauge value (None for unknown series)."""
        with self._lock:
            s = self._series.get((name, labels))
            return None if s is None or s.kind == "histogram" else s.value

    def snapshot(self) -> dict[str, dict]:
        """``{name: {"type", "samples": [(labels, value-or-quantiles)]}}``
        — the read side the exporter and the dashboard render from.
        Histogram samples resolve to ``{count, sum, p50, p99}`` over the
        rolling window."""
        with self._lock:
            fams: dict[str, dict] = {}
            for (name, labels), s in sorted(
                    self._series.items(), key=lambda kv: kv[0]):
                fam = fams.setdefault(
                    name, {"type": s.kind, "samples": []})
                if s.kind == "histogram":
                    h = s.hist.merged()
                    fam["samples"].append((labels, {
                        "count": h.count, "sum": h.total_s,
                        "p50": h.percentile(50.0),
                        "p99": h.percentile(99.0),
                    }))
                else:
                    fam["samples"].append((labels, s.value))
            return fams

    def emit_health(self, rec: dict) -> None:
        """Route a self-generated health record outward (the Reporter's
        JSONL, whose tee will feed it back here) or, with no sink
        (``tpumt-top``'s standalone registry), absorb it directly."""
        sink = self._health_sink
        if sink is not None:
            try:
                sink(rec)
                return
            except Exception:
                pass
        self.observe(rec)

    # -- the tee entry -----------------------------------------------------

    def observe(self, rec: dict) -> None:
        """Update series from one JSONL record. Never raises — this sits
        on the measured run's record path."""
        try:
            self._observe(rec)
        except Exception:
            pass

    def _observe(self, rec: dict) -> None:
        kind = rec.get("kind")
        if not isinstance(kind, str):
            return
        self.inc("tpumt_records", (("kind", kind),))
        handler = getattr(self, f"_on_{kind}", None)
        if handler is not None:
            handler(rec)

    # one handler per record kind; unknown kinds only count in
    # tpumt_records — forward-compatible by construction

    def _on_span(self, rec: dict) -> None:
        op = str(rec.get("op", "?"))
        if rec.get("async"):
            op += "[async]"
        L = (("op", op),)
        self.inc("tpumt_spans", L)
        self.inc("tpumt_span_bytes", L, int(rec.get("nbytes") or 0))
        secs = rec.get("seconds")
        if isinstance(secs, (int, float)):
            self.inc("tpumt_span_seconds", L, float(secs))
            self.observe_sample("tpumt_span_latency_seconds", L,
                                float(secs))
            self.observe_sample("tpumt_latency_seconds", (),
                                float(secs))
        gbps = rec.get("gbps")
        if isinstance(gbps, (int, float)):
            # last value as a gauge AND a rolling window: the
            # dashboard's "rolling per-op GB/s" promise is the window's
            # median, not whichever span happened to land last
            self.set_gauge("tpumt_span_gbps", L, float(gbps))
            self.observe_sample("tpumt_span_gbps_window", L,
                                float(gbps))
        # per-link-class series (comm/topology.py wrapper stamps):
        # intra- vs inter-host bytes and bandwidth live on tpumt-top.
        # Flat-topology runs carry no ``link`` → no series appear.
        link = rec.get("link")
        if isinstance(link, str):
            LL = (("link", link),)
            self.inc("tpumt_span_link_bytes", LL,
                     int(rec.get("nbytes") or 0))
            if isinstance(secs, (int, float)):
                self.inc("tpumt_span_link_seconds", LL, float(secs))
            if isinstance(gbps, (int, float)):
                self.set_gauge("tpumt_span_link_gbps", LL, float(gbps))
                self.observe_sample("tpumt_span_link_gbps_window", LL,
                                    float(gbps))
        rf = rec.get("roofline_frac")
        if isinstance(rf, (int, float)):
            self.set_gauge("tpumt_roofline_frac", L, float(rf))
        if not rec.get("async"):
            self._stale.span(op, gbps, rf)

    def _on_serve(self, rec: dict) -> None:
        cls = str(rec.get("class", "?"))
        L = (("class", cls),)
        event = rec.get("event")
        if event == "window":
            for field, name in (("arrivals", "tpumt_serve_arrivals"),
                                ("requests", "tpumt_serve_requests"),
                                ("errors", "tpumt_serve_errors"),
                                ("shed", "tpumt_serve_shed")):
                v = rec.get(field)
                if isinstance(v, (int, float)):
                    self.inc(name, L, v)
            depth = rec.get("queue_depth", rec.get("queue_max"))
            if isinstance(depth, (int, float)):
                self.set_gauge("tpumt_serve_queue_depth", L, depth)
            for field in ("p50_ms", "p95_ms", "p99_ms", "offered_hz",
                          "achieved_hz"):
                v = rec.get(field)
                if isinstance(v, (int, float)):
                    self.set_gauge(f"tpumt_serve_{field}", L, v)
            # the latency decomposition as standing gauges: queue-delay
            # and service p99 per class, live on the OpenMetrics
            # endpoint — the saturation early warning (queue-delay
            # share climbing toward the SLO bound) without waiting for
            # the post-mortem table
            for field, name in (
                    ("qd_p99_ms", "tpumt_serve_queue_delay_p99_ms"),
                    ("svc_p99_ms", "tpumt_serve_service_p99_ms")):
                v = rec.get(field)
                if isinstance(v, (int, float)):
                    self.set_gauge(name, L, v)
        elif event == "quarantine":
            self.inc("tpumt_serve_quarantines", L)

    def _on_mem(self, rec: dict) -> None:
        L = ()
        if rec.get("rank") is not None:
            L = (("rank", str(rec["rank"])),)
        for field, name in (
                ("bytes_in_use", "tpumt_hbm_bytes_in_use"),
                ("peak_bytes_in_use", "tpumt_hbm_peak_bytes_in_use"),
                ("live_bytes", "tpumt_live_bytes")):
            v = rec.get(field)
            if isinstance(v, (int, float)):
                self.set_gauge(name, L, v)

    def _on_overlap(self, rec: dict) -> None:
        L = (("op", str(rec.get("op", "?"))),)
        for field, name in (("overlap_frac", "tpumt_overlap_frac"),
                            ("drain_s", "tpumt_overlap_drain_seconds")):
            v = rec.get(field)
            if isinstance(v, (int, float)):
                self.set_gauge(name, L, v)

    def _on_route(self, rec: dict) -> None:
        L = (("op", str(rec.get("op", "?"))),)
        for field, name in (("overflow_pct", "tpumt_route_overflow_pct"),
                            ("occupancy_pct", "tpumt_route_occupancy_pct"),
                            ("imbalance", "tpumt_route_imbalance")):
            v = rec.get(field)
            if isinstance(v, (int, float)):
                self.set_gauge(name, L, v)
        for field, name in (("routed", "tpumt_route_tokens_routed"),
                            ("dropped", "tpumt_route_tokens_dropped")):
            v = rec.get(field)
            if isinstance(v, (int, float)):
                self.inc(name, L, v)

    def _on_decode(self, rec: dict) -> None:
        key = (f"{rec.get('collective', '?')}:"
               f"{rec.get('batch', '?')}x{rec.get('heads', '?')}")
        v = rec.get("us_per_op")
        if isinstance(v, (int, float)):
            self.set_gauge("tpumt_decode_us_per_op", (("key", key),), v)

    def _on_time(self, rec: dict) -> None:
        # cumulative either way: a final PhaseTimer record carries the
        # phase's accumulated seconds, a live event:"progress" snapshot
        # carries the running total — both map to the same gauge
        phase = rec.get("phase")
        v = rec.get("seconds")
        if phase and isinstance(v, (int, float)):
            self.set_gauge("tpumt_phase_seconds",
                           (("phase", str(phase)),), v)

    def _on_watchdog(self, rec: dict) -> None:
        self.inc("tpumt_watchdog_fires", ())

    def _on_finding(self, rec: dict) -> None:
        self.inc("tpumt_findings",
                 (("class", str(rec.get("class", "?"))),))

    def _on_health(self, rec: dict) -> None:
        self.inc("tpumt_health_events",
                 (("event", str(rec.get("event", "?"))),))
        if rec.get("event") != "heartbeat":
            self.health_events.append(dict(rec))
            for cb in self._health_listeners:
                try:
                    cb(rec)
                except Exception:
                    pass

    def _on_control(self, rec: dict) -> None:
        self.inc("tpumt_control_events",
                 (("event", str(rec.get("event", "?"))),))

    def _on_tune_hit(self, rec: dict) -> None:
        self.inc("tpumt_tune_resolutions",
                 (("knob", str(rec.get("knob", "?"))),
                  ("kind", "hit")))
        self._stale.tuned(rec.get("knob"))

    def _on_tune_result(self, rec: dict) -> None:
        self.inc("tpumt_tune_resolutions",
                 (("knob", str(rec.get("knob", "?"))),
                  ("kind", "result")))
        self._stale.tuned(rec.get("knob"))


class CommWaitWatch:
    """Cross-rank live wait_frac: the communication-anatomy match
    (``instrument/anatomy.py`` semantics) run incrementally over the
    multi-rank record stream ``tpumt-top`` already tails.

    The in-process tee sees only its own rank's spans, so it cannot
    decompose wait from wire; the dashboard sees every rank's file and
    knows which file is which rank — it feeds seq-stamped collective
    spans here with their rank and clock offset, and each call matched
    across all expected ranks updates a cumulative per-op
    ``tpumt_comm_wait_frac`` gauge on the registry (rendered as the
    OPS table's WAIT column). Bounded: at most :data:`MAX_PENDING`
    partially-matched calls are held; the oldest are dropped first (a
    dead rank's unmatched calls must not grow the table). Waits below
    the clock-sync uncertainty read as zero — the honesty floor."""

    MAX_PENDING = 2048

    def __init__(self, registry: MetricsRegistry):
        self._reg = registry
        self.expected = 0  # ranks per matched call (manifest count)
        self._pending: dict[tuple, dict[int, tuple[float, float]]] = {}
        self._spread: dict[int, float] = {}
        self._tot: dict[str, list] = {}  # op -> [wait_s, span_s]

    def clock_sync(self, rank: int, rec: dict) -> None:
        self._spread[rank] = float(rec.get("spread_s") or 0.0)

    def reset(self) -> None:
        self._pending.clear()
        self._spread.clear()
        self._tot.clear()

    def span(self, rank: int, rec: dict, offset: float) -> None:
        if (rec.get("seq") is None or rec.get("async")
                or int(rec.get("world") or 1) < 2
                or rec.get("t_start") is None
                or rec.get("t_end") is None
                or self.expected < 2):
            return
        op = str(rec.get("op", "?"))
        key = (op, rec.get("axis"), int(rec["seq"]))
        entries = self._pending.setdefault(key, {})
        entries.setdefault(rank, (float(rec["t_start"]) - offset,
                                  float(rec["t_end"]) - offset))
        if len(entries) < self.expected:
            while len(self._pending) > self.MAX_PENDING:
                self._pending.pop(next(iter(self._pending)))
            return
        del self._pending[key]
        unc = sum(sorted(self._spread.values(), reverse=True)[:2])
        latest = max(e for e, _x in entries.values())
        wait_s = span_s = 0.0
        for entry, end in entries.values():
            span_s += max(end - entry, 0.0)
            w = latest - entry
            if w >= unc:
                wait_s += w
        tot = self._tot.setdefault(op, [0.0, 0.0])
        tot[0] += wait_s
        tot[1] += span_s
        if tot[1] > 0:
            self._reg.set_gauge("tpumt_comm_wait_frac", (("op", op),),
                                tot[0] / tot[1])


class PhaseProgress:
    """Streaming per-phase progress: a ``timers`` phase hook that keeps
    its own cumulative seconds/count per phase and emits throttled
    ``kind: "time" event: "progress"`` snapshots through the sink.

    This is what lets the ONLINE doctor convict a phase straggler while
    the run is still executing: the final ``time`` records land only at
    driver exit, but these cumulative snapshots stream every
    ``interval_s`` — and because they are snapshots (latest wins), not
    deltas, the offline consumers that sum ``time`` records skip them
    (``event: "progress"``) and the doctor's straggler digest lets a
    final record override them, so a completed stream reads identically
    with or without the live trail. Armed only by ``--metrics-port``
    (``drivers/_common.make_reporter``); own accumulation, so warmup-
    skipping in PhaseTimer never skews the live ratio between ranks."""

    def __init__(self, sink: Callable[[dict], None],
                 interval_s: float = 1.0,
                 clock: Callable[[], float] = time.perf_counter,
                 wall: Callable[[], float] = time.time):
        self._sink = sink
        self._interval = float(interval_s)
        self._clock = clock
        self._wall = wall
        self._open: dict[str, float] = {}
        self._tot: dict[str, float] = {}
        self._cnt: dict[str, int] = {}
        self._first_wall: dict[str, float] = {}
        self._last_emit: dict[str, float] = {}

    def __call__(self, name: str, event: str) -> None:
        now = self._clock()
        if event == "begin":
            self._open[name] = now
            return
        t0 = self._open.pop(name, None)
        if t0 is None:
            return
        self._tot[name] = self._tot.get(name, 0.0) + (now - t0)
        self._cnt[name] = self._cnt.get(name, 0) + 1
        w = self._wall()
        self._first_wall.setdefault(name, w)
        if w - self._last_emit.get(name, 0.0) < self._interval:
            return
        self._last_emit[name] = w
        self._emit(name, w)

    def _emit(self, name: str, w: float) -> None:
        try:
            self._sink({
                "kind": "time", "event": "progress", "phase": name,
                "seconds": self._tot[name], "count": self._cnt[name],
                "t_start": self._first_wall[name], "t_end": w, "t": w,
            })
        except Exception:
            pass  # a closing sink must not fail the phase being timed

    def start(self) -> "PhaseProgress":
        from tpu_mpi_tests.instrument import timers

        timers.add_phase_hook(self)
        return self

    def stop(self) -> None:
        from tpu_mpi_tests.instrument import timers

        timers.remove_phase_hook(self)
        w = self._wall()
        for name in list(self._tot):
            self._emit(name, w)  # final cumulative snapshot per phase
