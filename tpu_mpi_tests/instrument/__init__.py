"""Instrumentation: phase timers, trace ranges, structured reporting,
comm-layer telemetry, run manifests, and hang watchdogs.

TPU-native replacement for the reference's L4 (SURVEY.md §5.1, §5.5):
NVTX ranges → XProf trace annotations; cudaProfilerStart/Stop gating →
jax.profiler trace gating; MPI_Wtime/clock_gettime phase timers →
perf_counter with mandatory block_until_ready discipline; printf result
lines → stable formatted lines + JSONL. Beyond parity: telemetry spans +
counters + flight recorder over every comm wrapper (telemetry.py), a
self-describing run manifest (manifest.py), and cross-rank JSONL
aggregation (aggregate.py, the ``tpumt-report`` entry point).
"""

# re-exports resolve lazily (PEP 562): timers.py and trace.py import jax
# at module scope, and the stdlib-only CLIs in this package
# (aggregate.py/timeline.py — tpumt-report/tpumt-trace) must import on
# login nodes that have no jax at all
_EXPORTS = {
    "PhaseTimer": "timers",
    "block": "timers",
    "ProfilerGate": "trace",
    "trace_range": "trace",
    "Reporter": "report",
    "comm_span": "telemetry",
    "span_call": "telemetry",
    "run_manifest": "manifest",
    "MemWatch": "memwatch",
    "mem_record": "memwatch",
    "compile_probe": "costs",
    "MetricsRegistry": "metrics",
    "PhaseProgress": "metrics",
    "MetricsExporter": "export",
    "Heartbeat": "export",
    "render_openmetrics": "export",
}
__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(
            f"tpu_mpi_tests.instrument.{_EXPORTS[name]}"
        )
        return getattr(mod, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
