"""Instrumentation: phase timers, trace ranges, structured reporting,
comm-layer telemetry, run manifests, and hang watchdogs.

TPU-native replacement for the reference's L4 (SURVEY.md §5.1, §5.5):
NVTX ranges → XProf trace annotations; cudaProfilerStart/Stop gating →
jax.profiler trace gating; MPI_Wtime/clock_gettime phase timers →
perf_counter with mandatory block_until_ready discipline; printf result
lines → stable formatted lines + JSONL. Beyond parity: telemetry spans +
counters + flight recorder over every comm wrapper (telemetry.py), a
self-describing run manifest (manifest.py), and cross-rank JSONL
aggregation (aggregate.py, the ``tpumt-report`` entry point).
"""

from tpu_mpi_tests.instrument.timers import PhaseTimer, block  # noqa: F401
from tpu_mpi_tests.instrument.trace import ProfilerGate, trace_range  # noqa: F401
from tpu_mpi_tests.instrument.report import Reporter  # noqa: F401
from tpu_mpi_tests.instrument.telemetry import (  # noqa: F401
    comm_span,
    span_call,
)
from tpu_mpi_tests.instrument.manifest import run_manifest  # noqa: F401
