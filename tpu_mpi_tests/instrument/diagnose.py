"""``tpumt-doctor``: root-cause triage over merged per-rank JSONL.

The observability spine records everything a post-mortem needs — spans,
phases, memory watermarks, watchdog fires, dispatch notes, serve
windows — but until this module a human had to read four tables and a
Perfetto trace to answer "which rank, which op, why". The doctor closes
that loop: given the per-rank file set of one run (the auto-suffixed
``out.p<i>.jsonl`` files, or explicit paths) it applies cross-rank
rules and emits structured ``kind: "finding"`` verdicts — failure
class, culprit rank, last op + phase, evidence record refs, and a
confidence — exactly one per convicted rank.

Failure classes and the signals that convict them:

* ``missing_rank`` — a rank present in the run's manifest whose record
  stream ends without its close markers (the memwatch ``final`` record
  / the ``telemetry_summary`` flush) while siblings kept recording past
  it — the killed-peer signature. A rank file absent from the set
  entirely is the strongest form.
* ``straggler`` — a phase whose per-rank seconds skew past the
  threshold names the SLOW rank; a *collective* op whose span seconds
  skew names the FAST rank — in a sync-honest collective the waiters
  absorb the straggler's lateness, so the rank that never waits is the
  culprit (the inversion is deliberate and documented in the finding).
* ``wedge`` — a dispatch note (``kind: "dispatch"`` — an op handed to
  the device) with no span closing after it, followed by a watchdog
  fire on the same rank: the op never completed.
* ``oom`` — a monotone ``bytes_in_use``/``live_bytes`` ramp in the
  rank's memory records crossing a fraction of ``hbm_bytes_limit``
  (census-only backends: a sustained growth ratio) before the stream
  dies.
* ``shed_storm`` — serve windows with shed ≫ 0 against the offered
  load: the queue bound is doing the dropping, not the handlers.
  Classes under quarantine (serve ``--quarantine-after`` graceful
  degradation, a designed isolation with its own records) are exempt.

The doctor convicts from the ORGANIC telemetry only: ``kind: "chaos"``
injection-audit records are deliberately ignored, so the chaos-smoke
(``make chaos-smoke``) genuinely proves the diagnosis, not the audit
trail. Pure stdlib (no jax import): usable on a login node against
files copied off the pod, same contract as tpumt-report/tpumt-trace.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tpu_mpi_tests.instrument.aggregate import expand_rank_files

#: the classes a finding can carry (the chaos smoke maps injected
#: faults onto them via tpu_mpi_tests.chaos.spec.FINDING_FOR)
FINDING_CLASSES = ("missing_rank", "straggler", "wedge", "oom",
                   "shed_storm")

#: conviction thresholds — deliberately stricter than tpumt-report's
#: reporting bands (1.5x skew): the report flags for a human to read,
#: the doctor CONVICTS, and a clean run must yield zero findings
DEFAULTS = {
    "skew_threshold": 2.0,   # phase/op skew for a straggler verdict
    "margin_s": 0.25,        # absolute seconds behind the fastest rank
    "min_calls": 5,          # phase/op entries per rank before judging
    "gap_s": 1.0,            # seconds siblings progressed past a death
    "ramp_ratio": 3.0,       # census-only oom growth factor
    "limit_frac": 0.5,       # oom: fraction of hbm_bytes_limit crossed
    "shed_min": 10,          # serve sheds before a storm verdict
}


def _rec_t(rec: dict):
    for key in ("t", "t_end", "time_unix", "t_start"):
        v = rec.get(key)
        if isinstance(v, (int, float)):
            return float(v)
    return None


class _Stream:
    """One rank's record stream plus the digests every rule shares."""

    def __init__(self, rank: int, path: str,
                 records: list[tuple[int, dict]]):
        self.rank = rank
        self.path = path
        self.records = records
        self.spans = [(ln, r) for ln, r in records
                      if r.get("kind") == "span"]
        self.dispatches = [(ln, r) for ln, r in records
                           if r.get("kind") == "dispatch"]
        self.watchdogs = [(ln, r) for ln, r in records
                          if r.get("kind") == "watchdog"]
        self.mems = [(ln, r) for ln, r in records
                     if r.get("kind") == "mem"]
        self.serves = [(ln, r) for ln, r in records
                       if r.get("kind") == "serve"]
        self.times = [(ln, r) for ln, r in records
                      if r.get("kind") == "time"]
        ts = [t for _, r in records if (t := _rec_t(r)) is not None]
        self.last_t = max(ts) if ts else None
        # close markers: the memwatch final census and the telemetry
        # counter flush are both emitted by Reporter.close — a stream
        # that recorded through either channel but lacks its marker
        # belongs to a process that never reached a clean close
        has_mem_final = any(r.get("event") == "final"
                            for _, r in self.mems)
        has_summary = any(r.get("kind") == "telemetry_summary"
                          for _, r in records)
        self.died = bool(
            (self.mems and not has_mem_final)
            or (self.spans and not has_summary)
        )

    def ref(self, ln: int, rec: dict) -> str:
        t = _rec_t(rec)
        extra = f" t={t:.3f}" if t is not None else ""
        kind = rec.get("kind")
        name = rec.get("op") or rec.get("phase") or rec.get("note") \
            or rec.get("event") or ""
        return f"{self.path}:{ln}: {kind} {name}{extra}".rstrip()

    def last_activity(self) -> tuple[str | None, str | None]:
        """(last op, last phase) the stream witnessed — the dying
        rank's attribution line."""
        op = None
        for ln, r in reversed(self.records):
            if r.get("kind") in ("span", "dispatch"):
                op = r.get("op") or r.get("note")
                break
        phase = None
        for ln, r in reversed(self.records):
            if r.get("kind") == "mem" and r.get("phase"):
                phase = r["phase"]
                break
            if r.get("kind") == "time" and r.get("phase"):
                phase = r["phase"]
                break
        return op, phase


def load_with_lines(path: str,
                    prog: str = "tpumt-doctor") -> list[tuple[int, dict]]:
    """``[(line_number, record)]`` for one JSONL file — the canonical
    single-parse form: line numbers feed the evidence refs, and
    tpumt-report/tpumt-trace load through this once and hand the result
    to both their own merge and :func:`diagnose_files`, so a report or
    trace never parses its inputs twice."""
    out: list[tuple[int, dict]] = []
    try:
        text = Path(path).read_text()
    except OSError as e:
        print(f"{prog}: cannot open {path}: {e}", file=sys.stderr)
        return out
    for i, line in enumerate(text.splitlines(), start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            out.append((i, rec))
    return out


def _choose_segment(
    records: list[tuple[int, dict]],
    run_sync_us: int | None = None,
) -> list[tuple[int, dict]]:
    """Append-mode JSONL holds several runs back to back; like the
    trace merger, diagnose one run's segment (each run starts with its
    manifest): the one carrying ``run_sync_us``'s ``clock_sync`` stamp
    when given — so trace finding markers land on the SAME run the
    trace renders — else the newest."""
    segments: list[list[tuple[int, dict]]] = [[]]
    for ln, rec in records:
        if rec.get("kind") == "manifest" and segments[-1]:
            segments.append([])
        segments[-1].append((ln, rec))
    if run_sync_us is not None:
        for seg in segments:
            for _ln, rec in seg:
                if rec.get("kind") == "clock_sync":
                    if rec.get("run_sync_us") == run_sync_us:
                        return seg
                    break
    return segments[-1]


def load_streams(
    files: list[str],
    loaded: dict[str, list[tuple[int, dict]]] | None = None,
    run_sync_us: int | None = None,
) -> tuple[list[_Stream], dict]:
    """Per-rank streams (rank = manifest ``process_index``, file order
    fallback) plus the run-level context: the rank-0 manifest and the
    expected process count. ``loaded`` maps paths to already-parsed
    :func:`load_with_lines` output so co-resident CLIs skip a second
    parse; ``run_sync_us`` selects that run's segment in append-mode
    files (newest otherwise)."""
    streams: list[_Stream] = []
    manifest: dict = {}
    expected = 0
    for idx, path in enumerate(files):
        pairs = (loaded or {}).get(path)
        if pairs is None:
            pairs = load_with_lines(path)
        records = _choose_segment(pairs, run_sync_us)
        # the chaos layer's injection-audit records are stripped before
        # any rule sees them: the diagnosis must convict from the
        # organic telemetry alone, or chaos-smoke proves only that the
        # audit trail works
        records = [(ln, r) for ln, r in records
                   if r.get("kind") != "chaos"]
        rank = idx
        for _ln, rec in records:
            if rec.get("kind") == "manifest":
                rank = rec.get("process_index", idx)
                expected = max(expected, int(rec.get("process_count")
                                             or 0))
                if not manifest or rec.get("process_index") == 0:
                    manifest = rec
        streams.append(_Stream(rank, path, records))
    return streams, {"manifest": manifest, "expected": expected}


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _finding(cls: str, rank, confidence: float, detail: str,
             evidence: list[str], last_op=None, phase=None,
             t=None) -> dict:
    return {
        "kind": "finding",
        "class": cls,
        "rank": rank,
        "confidence": round(float(confidence), 2),
        "last_op": last_op,
        "phase": phase,
        "t": t,
        "detail": detail,
        "evidence": evidence[:6],
    }


def _death_finding(s: _Stream, streams: list[_Stream], opts) -> dict | None:
    """Wedge > oom > missing_rank, exactly one verdict for a dead
    rank. Returns None when the stream carries no timestamped evidence
    to judge (pre-timeline JSONL must diagnose as nothing, not as a
    death)."""
    if s.last_t is None:
        return None
    # -- wedge: a dispatched op that never completed, then the watchdog
    if s.dispatches and s.watchdogs:
        ln_d, disp = s.dispatches[-1]
        t_d = _rec_t(disp)
        wd = [(ln, r) for ln, r in s.watchdogs
              if (_rec_t(r) or 0) >= (t_d or 0)]
        progressed = [
            (ln, r) for ln, r in s.spans
            if t_d is not None and (r.get("t_end") or 0) > t_d
        ]
        if wd and not progressed:
            ln_w, wrec = wd[-1]
            op, phase = s.last_activity()
            return _finding(
                "wedge", s.rank, 0.9,
                f"dispatch {disp.get('note') or disp.get('op')!r} never "
                f"completed: no span closed after it and the watchdog "
                f"fired {((_rec_t(wrec) or 0) - (t_d or 0)):.1f}s later "
                f"(phase {wrec.get('phase')!r}, deadline "
                f"{wrec.get('deadline_s')}s)",
                [s.ref(ln_d, disp), s.ref(ln_w, wrec)],
                last_op=disp.get("op") or disp.get("note"), phase=phase,
                t=_rec_t(wrec),
            )
    if not s.died:
        return None
    # -- oom: a monotone memory ramp before death
    series = [
        (ln, r, r.get("bytes_in_use", r.get("live_bytes")))
        for ln, r in s.mems
        if isinstance(r.get("bytes_in_use", r.get("live_bytes")),
                      (int, float))
    ]
    if len(series) >= 4:
        vals = [v for _, _, v in series]
        # the ramp must still be setting NEW HIGHS at death: every
        # process allocates its working set at startup (a "ramp" from
        # ~0), so growth alone convicts every killed rank — genuine
        # OOM pressure is growth that never stopped. Judged on the
        # running-max envelope, not pairwise monotonicity: the series
        # interleaves the sampler thread (live census, which catches
        # transient allocation temporaries) with phase-boundary
        # records, so a terminal dip of a few percent is measurement
        # jitter, not recovery. The peak's FIRST index is what dates
        # the last new high — a plateau held until death repeats the
        # peak value without ever climbing.
        peak = max(vals)
        peak_idx = min(i for i, v in enumerate(vals) if v == peak)
        tail_climbing = (
            peak_idx >= len(vals) - 3           # a new high near death
            and vals[-1] >= 0.75 * peak         # pressure held to the end
            and peak >= vals[max(0, len(vals) - 6)] * 1.1  # tail grew
        )
        growth = peak / max(vals[0], 1)
        limit = (s_manifest_limit(s) or 0)
        crossed = limit and peak >= opts["limit_frac"] * limit
        # the census-only growth fallback (no allocator limit to cross)
        # additionally demands the pressure be DISTINCTIVE: a surviving
        # sibling that reached the same watermark and closed cleanly
        # proves that watermark is the workload's working set, not a
        # runaway — a rank killed the instant its startup ramp tops out
        # must convict as missing_rank, not oom
        sib_peaks = [
            p for o in streams
            if o is not s and not o.died
            and (p := _mem_peak(o)) is not None
        ]
        runaway = not any(p >= 0.9 * peak for p in sib_peaks)
        if tail_climbing and (
            crossed or (growth >= opts["ramp_ratio"] and runaway)
        ):
            op, phase = s.last_activity()
            ln0, r0, v0 = series[0]
            ln1, r1, _v1 = series[peak_idx]
            why = (f"crossed {opts['limit_frac']:g} of hbm_bytes_limit "
                   f"{limit}" if crossed else
                   f"grew {growth:.1f}x (census-only backend, no "
                   f"allocator limit)")
            return _finding(
                "oom", s.rank, 0.9 if crossed else 0.7,
                f"monotone memory ramp {v0} -> {peak} bytes over "
                f"{len(vals)} records {why}, then the stream died "
                f"without its close markers",
                [s.ref(ln0, r0), s.ref(ln1, r1)],
                last_op=op, phase=phase, t=_rec_t(r1),
            )
    # -- missing rank: the stream just stops while siblings progress
    sibs = [o for o in streams if o is not s and o.last_t is not None]
    if sibs:
        latest = max(o.last_t for o in sibs)
        progressed = [
            o for o in sibs
            if sum(1 for _, r in o.records
                   if (_rec_t(r) or 0) > s.last_t) >= 2
        ]
        if latest - s.last_t >= opts["gap_s"] and progressed:
            op, phase = s.last_activity()
            conf = 0.85
            ev = [s.ref(*s.records[-1])]
            for o in progressed[:1]:
                if o.watchdogs:
                    conf = 0.95  # a sibling hung waiting for this rank
                    ev.append(o.ref(*o.watchdogs[-1]))
            return _finding(
                "missing_rank", s.rank, conf,
                f"rank {s.rank} recorded nothing after "
                f"t={s.last_t:.3f} while {len(progressed)} sibling "
                f"rank(s) kept recording {latest - s.last_t:.1f}s "
                f"longer, and its stream has no close markers",
                ev, last_op=op, phase=phase, t=s.last_t,
            )
    # a lone truncated stream stays unconvicted: without siblings (or
    # wedge/oom evidence above) a kill is indistinguishable from a
    # user interrupt — the missing-rank rule is a CROSS-rank rule by
    # definition
    return None


def _mem_peak(s: _Stream) -> int | float | None:
    vals = [
        v for _, r in s.mems
        if isinstance(v := r.get("bytes_in_use", r.get("live_bytes")),
                      (int, float))
    ]
    return max(vals) if vals else None


def s_manifest_limit(s: _Stream) -> int | None:
    for _ln, r in s.records:
        if r.get("kind") == "manifest":
            v = r.get("hbm_bytes_limit")
            if isinstance(v, (int, float)):
                return int(v)
    return None


def _straggler_findings(streams: list[_Stream], opts) -> list[dict]:
    """Cross-rank skew over phases (slowest rank convicts) and
    collective ops (FASTEST rank convicts — sync-honest collective
    spans charge the wait to whoever arrived early, so the rank that
    never waits is the one everyone waited for)."""
    alive = [s for s in streams if not s.died]
    if len(alive) < 2:
        return []
    by_rank: dict = {}

    def judge(table: dict, invert: bool, what: str, conf: float):
        for name, per_rank in table.items():
            if len(per_rank) < len(alive):
                continue
            if any(c < opts["min_calls"] for _s, c in per_rank.values()):
                continue
            secs = {r: v for r, (v, _c) in per_rank.items() if v > 0}
            if len(secs) < 2:
                continue
            worst = max(secs, key=secs.get)
            best = min(secs, key=secs.get)
            skew = secs[worst] / secs[best]
            margin = secs[worst] - secs[best]
            if skew <= opts["skew_threshold"] or margin <= opts["margin_s"]:
                continue
            culprit = best if invert else worst
            entry = by_rank.setdefault(
                culprit, {"conf": conf, "items": [],
                          "first": (what, name)})
            entry["conf"] = max(entry["conf"], conf)
            entry["items"].append(
                f"{what} {name}: rank {worst} spent {secs[worst]:.3g}s "
                f"vs rank {best}'s {secs[best]:.3g}s "
                f"({skew:.2g}x)" + (
                    " — collective spans invert: the fast rank is the "
                    "late arriver" if invert else "")
            )

    phases: dict = {}
    for s in alive:
        for _ln, r in s.times:
            name = r.get("phase")
            if not name:
                continue
            secs = float(r.get("seconds") or 0.0)
            count = int(r.get("count") or 1)
            tot, cnt = phases.setdefault(name, {}).get(s.rank, (0.0, 0))
            phases[name][s.rank] = (tot + secs, cnt + count)
    judge(phases, invert=False, what="phase", conf=0.8)

    ops: dict = {}
    for s in alive:
        for _ln, r in s.spans:
            # collective spans only (world >= 2): a local op's per-rank
            # asymmetry is load, not a straggler, and the inversion
            # argument below only holds where ranks wait on each other
            if int(r.get("world") or 1) < 2 or r.get("async"):
                continue
            name = r.get("op", "?")
            secs = float(r.get("seconds") or 0.0)
            tot, cnt = ops.setdefault(name, {}).get(s.rank, (0.0, 0))
            ops[name][s.rank] = (tot + secs, cnt + 1)
    judge(ops, invert=True, what="collective", conf=0.6)

    by_stream = {s.rank: s for s in alive}
    out = []
    for rank, entry in sorted(by_rank.items()):
        what, name = entry["first"]
        # anchor the verdict at the culprit's last record of the
        # convicting phase/op so tpumt-trace can place the FINDING
        # marker on its track (a skew has no single instant; the last
        # contribution is where a reader should start looking)
        s = by_stream.get(rank)
        anchor = None
        if s is not None:
            if what == "phase":
                ts = [t for _, r in s.times
                      if r.get("phase") == name
                      and (t := _rec_t(r)) is not None]
            else:
                ts = [t for _, r in s.spans
                      if r.get("op") == name
                      and (t := _rec_t(r)) is not None]
            anchor = max(ts) if ts else None
        out.append(_finding(
            "straggler", rank, entry["conf"],
            "; ".join(entry["items"]),
            [],
            # structured attribution, never mined back out of the
            # human-readable message: a phase skew names a phase, a
            # collective-span skew names the op
            last_op=name if what == "collective" else None,
            phase=name if what == "phase" else None,
            t=anchor,
        ))
    return out


def _shed_storm_findings(streams: list[_Stream], opts) -> list[dict]:
    """Serve windows with shed ≫ 0: the queue bound is shedding load.
    One finding per rank, naming the worst class."""
    out = []
    for s in streams:
        # a quarantined class's sheds are graceful degradation working
        # as designed (serve --quarantine-after: targeted isolation,
        # surfaced as its own event:"quarantine" record and SLO
        # accounting, driver exits 0) — convicting them as a
        # queue-bound storm would fail exactly the runs the
        # degradation exists to save. Scoped from the FIRST quarantine
        # entry onward: windows a healthy-handler class shed at the
        # queue bound BEFORE it ever quarantined are a genuine storm.
        # A summary-only signal (episode windows lost) has no entry
        # time, so it exempts the whole stream.
        quar_t: dict = {}
        for _ln, r in s.serves:
            cls = r.get("class")
            if r.get("event") == "quarantine":
                t = _rec_t(r)
                prev = quar_t.get(cls, float("inf"))
                quar_t[cls] = min(prev, t if t is not None
                                  else float("-inf"))
            elif r.get("event") == "summary" and r.get("quarantines"):
                quar_t.setdefault(cls, float("-inf"))
        per_class: dict = {}
        for ln, r in s.serves:
            if r.get("event") != "window":
                continue
            cls_q = quar_t.get(r.get("class"))
            if cls_q is not None and (_rec_t(r) or 0) >= cls_q:
                continue
            cls = r.get("class", "?")
            agg = per_class.setdefault(
                cls, {"shed": 0, "arrivals": 0, "qmax": 0,
                      "windows": [], "t": None})
            agg["shed"] += int(r.get("shed") or 0)
            agg["arrivals"] += int(r.get("arrivals") or 0)
            agg["qmax"] = max(agg["qmax"],
                              int(r.get("queue_max") or 0))
            if r.get("shed"):
                agg["windows"].append((ln, r))
                agg["t"] = _rec_t(r)
        storms = {
            cls: a for cls, a in per_class.items()
            if a["shed"] >= max(opts["shed_min"],
                                0.02 * max(a["arrivals"], 1))
        }
        if not storms:
            continue
        worst = max(storms, key=lambda c: storms[c]["shed"])
        a = storms[worst]
        ev = [s.ref(ln, r) for ln, r in a["windows"][:3]]
        total_shed = sum(x["shed"] for x in storms.values())
        out.append(_finding(
            "shed_storm", s.rank, 0.85,
            f"{total_shed} requests shed across "
            f"{len(storms)} class(es); worst is {worst!r} with "
            f"{a['shed']} shed of {a['arrivals']} arrivals at queue "
            f"depth {a['qmax']} — the queue bound is dropping load",
            ev, last_op=worst, phase="serve", t=a["t"],
        ))
    return out


def diagnose_streams(streams: list[_Stream], ctx: dict | None = None,
                     **overrides) -> list[dict]:
    """Apply every rule; findings sorted most-confident first."""
    opts = dict(DEFAULTS)
    opts.update({k: v for k, v in overrides.items() if v is not None})
    findings: list[dict] = []
    ctx = ctx or {}

    # ranks in the manifest with no file at all — the strongest form
    # of a missing rank (a crashed rank whose JSONL never flushed, or
    # a file lost in transit: either way the run claims n ranks)
    expected = int(ctx.get("expected") or 0)
    seen = {s.rank for s in streams}
    for rank in range(expected):
        if rank not in seen:
            findings.append(_finding(
                "missing_rank", rank, 0.9,
                f"the manifest declares {expected} processes but no "
                f"rank file for rank {rank} exists in the merged set",
                [], t=None,
            ))

    dead_ranks = set()
    for s in streams:
        f = _death_finding(s, streams, opts)
        if f is not None:
            findings.append(f)
            dead_ranks.add(s.rank)

    findings.extend(
        f for f in _straggler_findings(streams, opts)
        if f["rank"] not in dead_ranks
    )
    findings.extend(
        f for f in _shed_storm_findings(streams, opts)
        if f["rank"] not in dead_ranks
    )
    findings.sort(key=lambda f: (-f["confidence"], f["class"],
                                 str(f["rank"])))
    return findings


def diagnose_files(
    files: list[str],
    loaded: dict[str, list[tuple[int, dict]]] | None = None,
    run_sync_us: int | None = None,
    **overrides,
) -> list[dict]:
    """Load + diagnose; the entry point tpumt-report and tpumt-trace
    reuse. Un-suffixed ``--jsonl`` base paths expand to their
    ``.p<i>`` rank set like every other CLI; callers that already
    parsed the files pass :func:`load_with_lines` output as ``loaded``.
    Never raises — a diagnosis bug must not break the report or the
    trace it rides along with."""
    try:
        files = [f for f in expand_rank_files(files)
                 if Path(f).exists()]
        streams, ctx = load_streams(files, loaded=loaded,
                                    run_sync_us=run_sync_us)
        return diagnose_streams(streams, ctx, **overrides)
    except Exception as e:  # noqa: BLE001 — defensive by contract
        print(f"tpumt-doctor: diagnosis failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return []


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def format_finding(f: dict) -> str:
    parts = [f"FINDING {f['class']}: rank={f['rank']} "
             f"confidence={f['confidence']:.2f}"]
    if f.get("last_op"):
        parts.append(f"last_op={f['last_op']}")
    if f.get("phase"):
        parts.append(f"phase={f['phase']}")
    return " ".join(parts) + f" — {f['detail']}"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpumt-doctor",
        description="root-cause triage over per-rank telemetry JSONL: "
        "emits kind:'finding' verdicts (failure class, culprit rank, "
        "last op, evidence, confidence) from cross-rank rules — "
        "missing rank, straggler, wedged dispatch, OOM ramp, serve "
        "shed storm (README 'Chaos & diagnosis')",
    )
    p.add_argument(
        "files", nargs="+",
        help="per-rank JSONL files; an un-suffixed --jsonl base path "
        "expands to its .p<i> rank set",
    )
    p.add_argument(
        "--skew-threshold", type=float, default=None, metavar="X",
        help=f"straggler conviction skew (default "
        f"{DEFAULTS['skew_threshold']}; tpumt-report FLAGS at 1.5, the "
        f"doctor CONVICTS — stricter by design)",
    )
    p.add_argument(
        "--gap", type=float, default=None, metavar="S", dest="gap_s",
        help=f"seconds siblings must outlive a rank before it is "
        f"missing (default {DEFAULTS['gap_s']})",
    )
    p.add_argument(
        "--json", action="store_true",
        help="emit {'findings': [...]} as one JSON document",
    )
    p.add_argument(
        "--expect", default=None, metavar="CLASS:RANK",
        help="CI contract mode: exit 0 iff the diagnosis is EXACTLY "
        "one finding of CLASS convicting RANK (e.g. --expect "
        "missing_rank:1), else exit 2 explaining what was found — "
        "the chaos-smoke assertion primitive",
    )
    args = p.parse_args(argv)

    expect = None
    if args.expect:
        try:
            cls, rank = args.expect.rsplit(":", 1)
            if cls not in FINDING_CLASSES:
                raise ValueError(cls)
            expect = (cls, int(rank))
        except ValueError:
            print(f"tpumt-doctor: bad --expect {args.expect!r}; want "
                  f"CLASS:RANK with CLASS in "
                  f"{','.join(FINDING_CLASSES)}", file=sys.stderr)
            return 2

    files = [f for f in expand_rank_files(args.files) if Path(f).exists()]
    if not files:
        print("tpumt-doctor: no input files found", file=sys.stderr)
        return 2
    streams, ctx = load_streams(files)
    findings = diagnose_streams(
        streams, ctx, skew_threshold=args.skew_threshold,
        gap_s=args.gap_s,
    )

    if args.json:
        json.dump({"files": files, "findings": findings}, sys.stdout,
                  indent=1)
        print()
    else:
        for f in findings:
            print(format_finding(f))
            for ref in f.get("evidence") or []:
                print(f"  evidence: {ref}")
        if not findings:
            n = sum(len(s.records) for s in streams)
            print(f"DOCTOR OK: no findings ({len(streams)} rank "
                  f"file(s), {n} records)")

    if expect is not None:
        cls, rank = expect
        if len(findings) == 1 and findings[0]["class"] == cls \
                and findings[0]["rank"] == rank:
            # stderr under --json: stdout is a JSON document a
            # consumer may be piping into a parser
            print(f"DOCTOR EXPECT OK: {cls}:{rank}",
                  file=sys.stderr if args.json else sys.stdout)
            return 0
        got = [f"{f['class']}:{f['rank']}" for f in findings]
        print(f"DOCTOR EXPECT FAIL: wanted exactly [{cls}:{rank}], "
              f"got {got}", file=sys.stderr)
        return 2
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
