"""``tpumt-doctor``: root-cause triage over merged per-rank JSONL.

The observability spine records everything a post-mortem needs — spans,
phases, memory watermarks, watchdog fires, dispatch notes, serve
windows — but until this module a human had to read four tables and a
Perfetto trace to answer "which rank, which op, why". The doctor closes
that loop: given the per-rank file set of one run (the auto-suffixed
``out.p<i>.jsonl`` files, or explicit paths) it applies cross-rank
rules and emits structured ``kind: "finding"`` verdicts — failure
class, culprit rank, last op + phase, evidence record refs, and a
confidence — exactly one per convicted rank.

Failure classes and the signals that convict them:

* ``missing_rank`` — a rank present in the run's manifest whose record
  stream ends without its close markers (the memwatch ``final`` record
  / the ``telemetry_summary`` flush) while siblings kept recording past
  it — the killed-peer signature. A rank file absent from the set
  entirely is the strongest form.
* ``straggler`` — a phase whose per-rank seconds skew past the
  threshold names the SLOW rank; a *collective* op whose span seconds
  skew names the FAST rank — in a sync-honest collective the waiters
  absorb the straggler's lateness, so the rank that never waits is the
  culprit (the inversion is deliberate and documented in the finding).
* ``wedge`` — a dispatch note (``kind: "dispatch"`` — an op handed to
  the device) with no span closing after it, followed by a watchdog
  fire on the same rank: the op never completed.
* ``oom`` — a monotone ``bytes_in_use``/``live_bytes`` ramp in the
  rank's memory records crossing a fraction of ``hbm_bytes_limit``
  (census-only backends: a sustained growth ratio) before the stream
  dies.
* ``shed_storm`` — serve windows with shed ≫ 0 against the offered
  load: the queue bound is doing the dropping, not the handlers.
  Classes under quarantine (serve ``--quarantine-after`` graceful
  degradation, a designed isolation with its own records) are exempt.
* ``stale_schedule`` — a ``kind:"health" event:"tune_stale"`` latch
  (the metrics plane's achieved-GB/s sag watermark, README "Live
  observability") that no ``kind:"control" event:"tune_swap"``
  answered: the run kept serving a tuned schedule its own telemetry
  says has gone stale. A swap for the same op exonerates — the re-tune
  controller (``--retune``) acting IS the closed loop working.

The doctor convicts from the ORGANIC telemetry only: ``kind: "chaos"``
injection-audit records are deliberately ignored, so the chaos-smoke
(``make chaos-smoke``) genuinely proves the diagnosis, not the audit
trail. Pure stdlib (no jax import): usable on a login node against
files copied off the pod, same contract as tpumt-report/tpumt-trace.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import deque
from pathlib import Path

from tpu_mpi_tests.instrument.aggregate import expand_rank_files

_INF = float("inf")

#: the classes a finding can carry (the chaos smoke maps injected
#: faults onto them via tpu_mpi_tests.chaos.spec.FINDING_FOR)
FINDING_CLASSES = ("missing_rank", "straggler", "wedge", "oom",
                   "shed_storm", "stale_schedule", "queue_ramp")

#: conviction thresholds — deliberately stricter than tpumt-report's
#: reporting bands (1.5x skew): the report flags for a human to read,
#: the doctor CONVICTS, and a clean run must yield zero findings
DEFAULTS = {
    "skew_threshold": 2.0,   # phase/op skew for a straggler verdict
    "margin_s": 0.25,        # absolute seconds behind the fastest rank
    "min_calls": 5,          # phase/op entries per rank before judging
    "gap_s": 1.0,            # seconds siblings progressed past a death
    "ramp_ratio": 3.0,       # census-only oom growth factor
    "limit_frac": 0.5,       # oom: fraction of hbm_bytes_limit crossed
    "shed_min": 10,          # serve sheds before a storm verdict
    "stale_grace_s": 5.0,    # seconds a tune_stale may wait for its
                             # tune_swap before stale_schedule convicts
                             # (mid-follow the controller needs a
                             # window boundary to act)
    "ramp_windows": 3,       # consecutive windows a queue ramp must
                             # sustain before queue_ramp convicts
    "qd_share_min": 0.5,     # queue-delay share of e2e p99 the final
                             # window must reach (past it the tail is
                             # queueing, not service)
    "ramp_depth_min": 8,     # standing queue_depth the final window
                             # must carry — a drained queue is not a
                             # ramp no matter what the shares say
}


def _rec_t(rec: dict):
    for key in ("t", "t_end", "time_unix", "t_start"):
        v = rec.get(key)
        if isinstance(v, (int, float)):
            return float(v)
    return None


#: timestamps retained per stream for the sibling-progress test (the
#: missing-rank rule needs "did ≥2 records land after t?" — a bounded
#: recent-suffix answers it exactly for any realistic record cadence)
RECENT_TS = 128

#: serve windows retained per class for the shed-storm digest: windows
#: arrive once per report interval, so this covers ~40 min of default
#: cadence; older windows age out of the (bounded) online digest
SHED_WINDOWS_KEPT = 512

#: most recent seq-stamped collective calls kept per op for the
#: straggler rule's per-call anatomy evidence (bounded like every
#: digest; a run longer than this judges over the retained tail)
ANAT_CALLS_KEPT = 512

#: --follow floor on the no-files-yet wait (seconds): jax import alone
#: can take tens of seconds before the first record, so the --idle
#: default must not finalize an empty follow that early — but a file
#: that never appears still finalizes instead of hanging forever
NO_FILE_GRACE_S = 60.0


class _Stream:
    """One rank's record stream digested INCREMENTALLY.

    Records are fed one at a time through :meth:`add` — the follow-mode
    doctor feeds them as they are written, the offline constructor
    feeds the whole file — and every rule reads only these bounded
    digests. That is the online/offline agreement contract: both
    doctors run the SAME rule kernels over the SAME digest code, so a
    completed stream diagnoses byte-identically whether it was tailed
    live or read post-mortem (pinned in tests/test_live.py). State is
    bounded by construction: fixed-size deques, per-name aggregate
    dicts, and single last-record slots — never the record list."""

    def __init__(self, rank: int, path: str,
                 records: list[tuple[int, dict]] | None = None):
        self.rank = rank
        self.path = path
        self.n_records = 0
        self.last_t: float | None = None
        self.last_record: tuple[int, dict] | None = None
        self._has_span = self._has_mem = False
        self._has_summary = self._has_mem_final = False
        self.hbm_limit: int | None = None
        self._last_op: str | None = None
        self._last_phase: str | None = None
        # wedge digest: the last dispatch note and what followed it
        self.last_dispatch: tuple[int, dict, float | None] | None = None
        self._span_after_dispatch = False
        self._wd_after_dispatch: tuple[int, dict] | None = None
        self.last_watchdog: tuple[int, dict] | None = None
        # sibling-progress digest (missing_rank)
        self.recent_ts: deque = deque(maxlen=RECENT_TS)
        # oom digest: running-max envelope of the memory series
        self.mem_first: tuple[int, dict, float] | None = None
        self.mem_peak: float | None = None
        self.mem_peak_rec: tuple[int, dict] | None = None
        self.mem_peak_idx: int | None = None
        self.mem_n = 0
        self.mem_tail: deque = deque(maxlen=6)
        # straggler digest: per-phase/per-op totals. Final PhaseTimer
        # records accumulate (`phase_fin`); live cumulative progress
        # snapshots (metrics plane, event:"progress") keep latest-wins
        # (`phase_prog`) and are OVERRIDDEN by finals — so a completed
        # stream reads identically with or without the live trail
        self.phase_fin: dict[str, tuple[float, int]] = {}
        self.phase_prog: dict[str, tuple[float, int]] = {}
        self.phase_last_t: dict[str, float] = {}
        self.op_tot: dict[str, tuple[float, int]] = {}
        self.op_last_t: dict[str, float] = {}
        # link classes seen per collective op (comm/topology.py stamps
        # them at wrapper build time) — straggler evidence: skew whose
        # ops are ALL inter_host points at the cross-host fabric, not
        # the rank. Bounded by the op-name x link-class product.
        self.op_links: dict[str, set] = {}
        # anatomy digest (instrument/anatomy.py semantics): recent
        # seq-stamped collective calls per op — (seq, t_start, t_end,
        # line) on this stream's OWN clock; the straggler judge
        # subtracts clock_offset at match time. Empty on pre-seq
        # streams, which keeps the legacy inversion verdict intact.
        self.op_calls: dict[str, deque] = {}
        self.clock_offset = 0.0
        self.clock_spread = 0.0
        # shed-storm digest: a bounded deque of recent raw windows per
        # class (the exemption boundary can arrive AFTER the windows it
        # exempts, so filtering happens at judge time), windows evicted
        # from it fold into a settled aggregate using the boundary
        # known at eviction time, and the first shed windows are kept
        # separately as evidence — so a storm older than the retention
        # window still convicts with its original evidence refs
        self.quar_t: dict = {}
        self.serve_windows: dict[str, deque] = {}
        self.serve_settled: dict[str, dict] = {}
        self.serve_first_shed: dict[str, list] = {}
        # stale-schedule digest: the FIRST tune_stale latch per op (the
        # registry latches once per op, so first == only, but a rerun
        # segment could repeat) and the latest tune_swap answer per op
        self.stale_ops: dict[str, tuple[int, dict, float]] = {}
        self.swap_t: dict[str, float] = {}
        for ln, rec in (records or []):
            self.add(ln, rec)

    @property
    def died(self) -> bool:
        # close markers: the memwatch final census and the telemetry
        # counter flush are both emitted by Reporter.close — a stream
        # that recorded through either channel but lacks its marker
        # belongs to a process that never reached a clean close
        return bool((self._has_mem and not self._has_mem_final)
                    or (self._has_span and not self._has_summary))

    @property
    def closed(self) -> bool:
        """A clean-close marker was seen — follow mode's signal that
        this rank's run ended on purpose."""
        return self._has_summary or self._has_mem_final

    def add(self, ln: int, rec: dict) -> None:
        self.n_records += 1
        self.last_record = (ln, rec)
        t = _rec_t(rec)
        if t is not None:
            self.recent_ts.append(t)
            if self.last_t is None or t > self.last_t:
                self.last_t = t
        kind = rec.get("kind")
        if kind == "manifest":
            v = rec.get("hbm_bytes_limit")
            if isinstance(v, (int, float)):
                self.hbm_limit = int(v)
        elif kind == "clock_sync":
            # this rank's offset to rank 0 and the barrier-echo sample
            # spread — the anatomy judge's alignment and honesty floor
            self.clock_offset = float(rec.get("offset_s") or 0.0)
            self.clock_spread = float(rec.get("spread_s") or 0.0)
        elif kind == "span":
            self._has_span = True
            self._last_op = rec.get("op") or rec.get("note")
            if self.last_dispatch is not None:
                t_d = self.last_dispatch[2]
                if t_d is not None and (rec.get("t_end") or 0) > t_d:
                    self._span_after_dispatch = True
            name = rec.get("op", "?")
            if t is not None and t > self.op_last_t.get(name, -_INF):
                self.op_last_t[name] = t
            # collective spans only (world >= 2): a local op's per-rank
            # asymmetry is load, not a straggler, and the inversion
            # argument only holds where ranks wait on each other
            if int(rec.get("world") or 1) >= 2 and not rec.get("async"):
                tot, cnt = self.op_tot.get(name, (0.0, 0))
                self.op_tot[name] = (
                    tot + float(rec.get("seconds") or 0.0), cnt + 1)
                link = rec.get("link")
                if isinstance(link, str):
                    self.op_links.setdefault(name, set()).add(link)
                if (rec.get("seq") is not None
                        and rec.get("t_start") is not None):
                    dq = self.op_calls.setdefault(
                        name, deque(maxlen=ANAT_CALLS_KEPT))
                    dq.append((
                        int(rec["seq"]), float(rec["t_start"]),
                        float(rec.get("t_end") or rec["t_start"]), ln,
                    ))
        elif kind == "dispatch":
            self._last_op = rec.get("op") or rec.get("note")
            self.last_dispatch = (ln, rec, t)
            self._span_after_dispatch = False
            self._wd_after_dispatch = None
        elif kind == "watchdog":
            self.last_watchdog = (ln, rec)
            if self.last_dispatch is not None:
                if (t or 0) >= (self.last_dispatch[2] or 0):
                    self._wd_after_dispatch = (ln, rec)
        elif kind == "mem":
            self._has_mem = True
            if rec.get("event") == "final":
                self._has_mem_final = True
            if rec.get("phase"):
                self._last_phase = rec["phase"]
            v = rec.get("bytes_in_use", rec.get("live_bytes"))
            if isinstance(v, (int, float)):
                if self.mem_first is None:
                    self.mem_first = (ln, rec, v)
                if self.mem_peak is None or v > self.mem_peak:
                    # strict > keeps the FIRST index of each new high:
                    # a plateau held until death repeats the peak value
                    # without moving the index
                    self.mem_peak = v
                    self.mem_peak_rec = (ln, rec)
                    self.mem_peak_idx = self.mem_n
                self.mem_tail.append(v)
                self.mem_n += 1
        elif kind == "time":
            name = rec.get("phase")
            if name:
                self._last_phase = name
                secs = float(rec.get("seconds") or 0.0)
                count = int(rec.get("count") or 1)
                if rec.get("event") == "progress":
                    self.phase_prog[name] = (secs, count)
                else:
                    tot, cnt = self.phase_fin.get(name, (0.0, 0))
                    self.phase_fin[name] = (tot + secs, cnt + count)
                if t is not None and t > self.phase_last_t.get(name,
                                                               -_INF):
                    self.phase_last_t[name] = t
        elif kind == "telemetry_summary":
            self._has_summary = True
        elif kind == "health":
            if rec.get("event") == "tune_stale" and rec.get("op"):
                # LATEST latch wins: the --retune controller re-arms the
                # watch after a swap, so an op can latch again — keeping
                # the first latch would let the old swap exonerate the
                # new, unanswered one
                self.stale_ops[str(rec["op"])] = (
                    ln, rec, t if t is not None else 0.0
                )
        elif kind == "control":
            if rec.get("event") == "tune_swap" and rec.get("op"):
                op = str(rec["op"])
                self.swap_t[op] = max(
                    self.swap_t.get(op, -_INF),
                    t if t is not None else _INF,
                )
        elif kind == "serve":
            cls = rec.get("class")
            event = rec.get("event")
            if event == "quarantine":
                prev = self.quar_t.get(cls, _INF)
                self.quar_t[cls] = min(
                    prev, t if t is not None else -_INF)
            elif event == "summary" and rec.get("quarantines"):
                self.quar_t.setdefault(cls, -_INF)
            elif event == "window":
                cls_key = rec.get("class", "?")
                if rec.get("shed"):
                    fs = self.serve_first_shed.setdefault(cls_key, [])
                    if len(fs) < 3:
                        fs.append((ln, rec))
                dq = self.serve_windows.setdefault(cls_key, deque())
                dq.append((ln, rec))
                while len(dq) > SHED_WINDOWS_KEPT:
                    self._settle_window(cls_key, *dq.popleft())

    def _settle_window(self, cls_key: str, ln: int, r: dict) -> None:
        """Fold a window evicted from the bounded recent deque into the
        settled aggregate, applying the exemption boundary known NOW.
        A quarantine boundary arriving more than
        :data:`SHED_WINDOWS_KEPT` windows after the windows it would
        exempt is the one edge the bounded digest gives up — except
        total retro-exemption (the summary-only ``-inf`` boundary),
        which the judge handles by dropping the whole settled range."""
        t = _rec_t(r) or 0
        cls_q = self.quar_t.get(r.get("class"))
        if cls_q is not None and t >= cls_q:
            return
        st = self.serve_settled.setdefault(cls_key, {
            "shed": 0, "arrivals": 0, "qmax": 0, "t": None,
            "t_min": _INF})
        st["shed"] += int(r.get("shed") or 0)
        st["arrivals"] += int(r.get("arrivals") or 0)
        st["qmax"] = max(st["qmax"], int(r.get("queue_max") or 0))
        st["t_min"] = min(st["t_min"], t)
        if r.get("shed"):
            st["t"] = _rec_t(r)

    def phase_totals(self) -> dict[str, tuple[float, int]]:
        """Per-phase (seconds, calls): finals where the stream has
        them, latest live progress snapshot otherwise."""
        out = dict(self.phase_fin)
        for name, pair in self.phase_prog.items():
            out.setdefault(name, pair)
        return out

    def ref(self, ln: int, rec: dict) -> str:
        t = _rec_t(rec)
        extra = f" t={t:.3f}" if t is not None else ""
        kind = rec.get("kind")
        name = rec.get("op") or rec.get("phase") or rec.get("note") \
            or rec.get("event") or ""
        return f"{self.path}:{ln}: {kind} {name}{extra}".rstrip()

    def last_activity(self) -> tuple[str | None, str | None]:
        """(last op, last phase) the stream witnessed — the dying
        rank's attribution line."""
        return self._last_op, self._last_phase


def load_with_lines(path: str,
                    prog: str = "tpumt-doctor") -> list[tuple[int, dict]]:
    """``[(line_number, record)]`` for one JSONL file — the canonical
    single-parse form: line numbers feed the evidence refs, and
    tpumt-report/tpumt-trace load through this once and hand the result
    to both their own merge and :func:`diagnose_files`, so a report or
    trace never parses its inputs twice."""
    out: list[tuple[int, dict]] = []
    try:
        text = Path(path).read_text()
    except OSError as e:
        print(f"{prog}: cannot open {path}: {e}", file=sys.stderr)
        return out
    for i, line in enumerate(text.splitlines(), start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            out.append((i, rec))
    return out


def _choose_segment(
    records: list[tuple[int, dict]],
    run_sync_us: int | None = None,
) -> list[tuple[int, dict]]:
    """Append-mode JSONL holds several runs back to back; like the
    trace merger, diagnose one run's segment (each run starts with its
    manifest): the one carrying ``run_sync_us``'s ``clock_sync`` stamp
    when given — so trace finding markers land on the SAME run the
    trace renders — else the newest."""
    segments: list[list[tuple[int, dict]]] = [[]]
    for ln, rec in records:
        if rec.get("kind") == "manifest" and segments[-1]:
            segments.append([])
        segments[-1].append((ln, rec))
    if run_sync_us is not None:
        for seg in segments:
            for _ln, rec in seg:
                if rec.get("kind") == "clock_sync":
                    if rec.get("run_sync_us") == run_sync_us:
                        return seg
                    break
    return segments[-1]


def load_streams(
    files: list[str],
    loaded: dict[str, list[tuple[int, dict]]] | None = None,
    run_sync_us: int | None = None,
) -> tuple[list[_Stream], dict]:
    """Per-rank streams (rank = manifest ``process_index``, file order
    fallback) plus the run-level context: the rank-0 manifest and the
    expected process count. ``loaded`` maps paths to already-parsed
    :func:`load_with_lines` output so co-resident CLIs skip a second
    parse; ``run_sync_us`` selects that run's segment in append-mode
    files (newest otherwise)."""
    streams: list[_Stream] = []
    manifest: dict = {}
    expected = 0
    for idx, path in enumerate(files):
        pairs = (loaded or {}).get(path)
        if pairs is None:
            pairs = load_with_lines(path)
        records = _choose_segment(pairs, run_sync_us)
        # the chaos layer's injection-audit records are stripped before
        # any rule sees them: the diagnosis must convict from the
        # organic telemetry alone, or chaos-smoke proves only that the
        # audit trail works
        records = [(ln, r) for ln, r in records
                   if r.get("kind") != "chaos"]
        rank = idx
        for _ln, rec in records:
            if rec.get("kind") == "manifest":
                rank = rec.get("process_index", idx)
                expected = max(expected, int(rec.get("process_count")
                                             or 0))
                if not manifest or rec.get("process_index") == 0:
                    manifest = rec
        streams.append(_Stream(rank, path, records))
    return streams, {"manifest": manifest, "expected": expected}


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _finding(cls: str, rank, confidence: float, detail: str,
             evidence: list[str], last_op=None, phase=None,
             t=None, link=None) -> dict:
    return {
        "kind": "finding",
        "class": cls,
        "rank": rank,
        "confidence": round(float(confidence), 2),
        "last_op": last_op,
        "phase": phase,
        "t": t,
        "link": link,
        "detail": detail,
        "evidence": evidence[:6],
    }


def _death_finding(s: _Stream, streams: list[_Stream], opts,
                   followed: bool = False) -> dict | None:
    """Wedge > oom > missing_rank, exactly one verdict for a dead
    rank. Returns None when the stream carries no timestamped evidence
    to judge (pre-timeline JSONL must diagnose as nothing, not as a
    death)."""
    if s.last_t is None:
        return None
    # -- wedge: a dispatched op that never completed, then the watchdog
    if s.last_dispatch is not None and s._wd_after_dispatch is not None \
            and not s._span_after_dispatch:
        ln_d, disp, t_d = s.last_dispatch
        ln_w, wrec = s._wd_after_dispatch
        op, phase = s.last_activity()
        return _finding(
            "wedge", s.rank, 0.9,
            f"dispatch {disp.get('note') or disp.get('op')!r} never "
            f"completed: no span closed after it and the watchdog "
            f"fired {((_rec_t(wrec) or 0) - (t_d or 0)):.1f}s later "
            f"(phase {wrec.get('phase')!r}, deadline "
            f"{wrec.get('deadline_s')}s)",
            [s.ref(ln_d, disp), s.ref(ln_w, wrec)],
            last_op=disp.get("op") or disp.get("note"), phase=phase,
            t=_rec_t(wrec),
        )
    if not s.died:
        return None
    # -- oom: a monotone memory ramp before death
    if s.mem_n >= 4:
        # the ramp must still be setting NEW HIGHS at death: every
        # process allocates its working set at startup (a "ramp" from
        # ~0), so growth alone convicts every killed rank — genuine
        # OOM pressure is growth that never stopped. Judged on the
        # running-max envelope, not pairwise monotonicity: the series
        # interleaves the sampler thread (live census, which catches
        # transient allocation temporaries) with phase-boundary
        # records, so a terminal dip of a few percent is measurement
        # jitter, not recovery. The peak's FIRST index is what dates
        # the last new high — a plateau held until death repeats the
        # peak value without ever climbing.
        peak = s.mem_peak
        tail6_first = (s.mem_tail[0] if s.mem_n >= 6
                       else s.mem_first[2])
        tail_climbing = (
            s.mem_peak_idx >= s.mem_n - 3       # a new high near death
            and s.mem_tail[-1] >= 0.75 * peak   # pressure held to the end
            and peak >= tail6_first * 1.1       # tail grew
        )
        growth = peak / max(s.mem_first[2], 1)
        limit = s.hbm_limit or 0
        crossed = limit and peak >= opts["limit_frac"] * limit
        # the census-only growth fallback (no allocator limit to cross)
        # additionally demands the pressure be DISTINCTIVE: a surviving
        # sibling that reached the same watermark and closed cleanly
        # proves that watermark is the workload's working set, not a
        # runaway — a rank killed the instant its startup ramp tops out
        # must convict as missing_rank, not oom
        # mid-follow every mem-recording stream is still missing its
        # final marker ("died"), which would empty this exoneration
        # set and convict healthy growing ranks — a sibling ACTIVELY
        # recording at the same watermark proves the working set just
        # as well as one that closed cleanly
        sib_peaks = [
            o.mem_peak for o in streams
            if o is not s and (followed or not o.died)
            and o.mem_peak is not None
        ]
        runaway = not any(p >= 0.9 * peak for p in sib_peaks)
        if tail_climbing and (
            crossed or (growth >= opts["ramp_ratio"] and runaway)
        ):
            op, phase = s.last_activity()
            ln0, r0, v0 = s.mem_first
            ln1, r1 = s.mem_peak_rec
            why = (f"crossed {opts['limit_frac']:g} of hbm_bytes_limit "
                   f"{limit}" if crossed else
                   f"grew {growth:.1f}x (census-only backend, no "
                   f"allocator limit)")
            return _finding(
                "oom", s.rank, 0.9 if crossed else 0.7,
                f"monotone memory ramp {v0} -> {peak} bytes over "
                f"{s.mem_n} records {why}, then the stream died "
                f"without its close markers",
                [s.ref(ln0, r0), s.ref(ln1, r1)],
                last_op=op, phase=phase, t=_rec_t(r1),
            )
    # -- missing rank: the stream just stops while siblings progress
    sibs = [o for o in streams if o is not s and o.last_t is not None]
    if sibs:
        latest = max(o.last_t for o in sibs)
        progressed = [
            o for o in sibs
            if sum(1 for t in o.recent_ts if t > s.last_t) >= 2
        ]
        if latest - s.last_t >= opts["gap_s"] and progressed:
            op, phase = s.last_activity()
            conf = 0.85
            ev = [s.ref(*s.last_record)]
            for o in progressed[:1]:
                if o.last_watchdog is not None:
                    conf = 0.95  # a sibling hung waiting for this rank
                    ev.append(o.ref(*o.last_watchdog))
            return _finding(
                "missing_rank", s.rank, conf,
                f"rank {s.rank} recorded nothing after "
                f"t={s.last_t:.3f} while {len(progressed)} sibling "
                f"rank(s) kept recording {latest - s.last_t:.1f}s "
                f"longer, and its stream has no close markers",
                ev, last_op=op, phase=phase, t=s.last_t,
            )
    # a lone truncated stream stays unconvicted: without siblings (or
    # wedge/oom evidence above) a kill is indistinguishable from a
    # user interrupt — the missing-rank rule is a CROSS-rank rule by
    # definition
    return None


def _op_anatomy(alive: list[_Stream], name: str, opts) -> dict | None:
    """Per-call wait attribution for one collective op across the
    alive streams (instrument/anatomy.py semantics over the bounded
    ``op_calls`` digest): match calls by ``seq``, align entries on the
    clock offsets, charge each matched call's total wait to its latest
    entrant, floor waits below the clock-sync uncertainty. None when
    any stream lacks seq-stamped calls (pre-seq streams keep the
    legacy inversion verdict), too few calls match, or every wait is
    under the floor."""
    per: dict[int, dict[int, tuple[float, float, int]]] = {}
    for s in alive:
        dq = s.op_calls.get(name)
        if not dq:
            return None
        per[s.rank] = {
            seq: (t0 - s.clock_offset, t1 - s.clock_offset, ln)
            for seq, t0, t1, ln in dq
        }
    unc = sum(sorted((s.clock_spread for s in alive), reverse=True)[:2])
    common = set.intersection(*(set(m) for m in per.values()))
    if len(common) < opts["min_calls"]:
        return None
    wait_by_rank = {s.rank: 0.0 for s in alive}
    worst_call: dict[int, tuple[float, int, int]] = {}
    total_wait = 0.0
    for seq in sorted(common):
        entries = {r: per[r][seq] for r in per}
        latest = max(e for e, _x, _ln in entries.values())
        late_rank = max(entries, key=lambda r: entries[r][0])
        wait = sum(
            w for e, _x, _ln in entries.values()
            if (w := latest - e) >= unc
        )
        if wait <= 0:
            continue
        wait_by_rank[late_rank] += wait
        total_wait += wait
        cur = worst_call.get(late_rank)
        if cur is None or wait > cur[0]:
            worst_call[late_rank] = (wait, seq, entries[late_rank][2])
    if total_wait <= 0:
        return None
    culprit = max(wait_by_rank, key=wait_by_rank.get)
    return {
        "culprit": culprit,
        "share": wait_by_rank[culprit] / total_wait,
        "wait_s": wait_by_rank[culprit],
        "matched": len(common),
        "worst": worst_call[culprit],
        "unc": unc,
    }


def _straggler_findings(streams: list[_Stream], opts,
                        alive: list[_Stream] | None = None) -> list[dict]:
    """Cross-rank skew over phases (slowest rank convicts) and
    collective ops (FASTEST rank convicts — sync-honest collective
    spans charge the wait to whoever arrived early, so the rank that
    never waits is the one everyone waited for; when the spans carry
    ``seq`` the verdict upgrades to per-call anatomy — the rank
    holding the matched-call wait-share convicts, with call-level
    evidence refs). ``alive`` overrides the default not-died selection
    — follow mode passes the streams that are not death-convicted,
    since mid-run EVERY stream is still missing its close markers."""
    if alive is None:
        alive = [s for s in streams if not s.died]
    if len(alive) < 2:
        return []
    by_rank: dict = {}
    by_stream = {s.rank: s for s in alive}

    def judge(table: dict, invert: bool, what: str, conf: float):
        for name, per_rank in table.items():
            if len(per_rank) < len(alive):
                continue
            if any(c < opts["min_calls"] for _s, c in per_rank.values()):
                continue
            secs = {r: v for r, (v, _c) in per_rank.items() if v > 0}
            if len(secs) < 2:
                continue
            worst = max(secs, key=secs.get)
            best = min(secs, key=secs.get)
            skew = secs[worst] / secs[best]
            margin = secs[worst] - secs[best]
            if skew <= opts["skew_threshold"] or margin <= opts["margin_s"]:
                continue
            culprit = best if invert else worst
            # anatomy upgrade (seq-stamped streams only): replace the
            # inverted totals argument with direct per-call evidence —
            # who the matched calls actually waited for
            anat = _op_anatomy(alive, name, opts) if invert else None
            evidence: list[str] = []
            if anat is not None:
                culprit = anat["culprit"]
                conf = max(conf, 0.75)
                w, seq, ln = anat["worst"]
                cs = by_stream[culprit]
                evidence = [
                    f"anatomy: {anat['matched']} matched {name} calls "
                    f"on {len(alive)} ranks; rank {culprit} held "
                    f"{anat['share'] * 100:.0f}% of the wait "
                    f"({anat['wait_s']:.3g}s, clock_unc="
                    f"{anat['unc'] * 1e3:.3g}ms)",
                    f"{cs.path}:{ln}: span {name} seq={seq} entered "
                    f"{w * 1e3:.1f}ms after the first rank",
                ]
            entry = by_rank.setdefault(
                culprit, {"conf": conf, "items": [], "evidence": [],
                          "links": [], "first": (what, name)})
            entry["conf"] = max(entry["conf"], conf)
            entry["evidence"].extend(evidence)
            if invert:
                # link classes this op ran over, unioned across ranks
                # (topology stamp; empty set when the spans are
                # unstamped — pre-topology streams claim nothing)
                entry["links"].append(
                    set().union(*(by_stream[s.rank].op_links.get(
                        name, set()) for s in alive)))
            entry["items"].append(
                f"{what} {name}: rank {worst} spent {secs[worst]:.3g}s "
                f"vs rank {best}'s {secs[best]:.3g}s "
                f"({skew:.2g}x)" + (
                    (f" — anatomy: rank {culprit} held "
                     f"{anat['share'] * 100:.0f}% of the wait across "
                     f"{anat['matched']} matched calls"
                     if anat is not None else
                     " — collective spans invert: the fast rank is the "
                     "late arriver") if invert else "")
            )

    phases: dict = {}
    for s in alive:
        for name, pair in s.phase_totals().items():
            phases.setdefault(name, {})[s.rank] = pair
    judge(phases, invert=False, what="phase", conf=0.8)

    ops: dict = {}
    for s in alive:
        for name, pair in s.op_tot.items():
            ops.setdefault(name, {})[s.rank] = pair
    judge(ops, invert=True, what="collective", conf=0.6)

    out = []
    for rank, entry in sorted(by_rank.items()):
        what, name = entry["first"]
        # link evidence: when EVERY skewed collective op ran purely
        # over the cross-host fabric, say so — "rank N is slow at
        # inter_host ops only" reads as a host/NIC problem, not a slow
        # chip. Any unstamped or mixed-class op withholds the claim.
        links = entry["links"]
        link = ("inter_host" if links
                and all(ls == {"inter_host"} for ls in links) else None)
        # anchor the verdict at the culprit's last record of the
        # convicting phase/op so tpumt-trace can place the FINDING
        # marker on its track (a skew has no single instant; the last
        # contribution is where a reader should start looking)
        s = by_stream.get(rank)
        anchor = None
        if s is not None:
            anchor = (s.phase_last_t if what == "phase"
                      else s.op_last_t).get(name)
        out.append(_finding(
            "straggler", rank, entry["conf"],
            "; ".join(entry["items"]),
            entry["evidence"],
            # structured attribution, never mined back out of the
            # human-readable message: a phase skew names a phase, a
            # collective-span skew names the op
            last_op=name if what == "collective" else None,
            phase=name if what == "phase" else None,
            t=anchor, link=link,
        ))
    return out


def _shed_storm_findings(streams: list[_Stream], opts) -> list[dict]:
    """Serve windows with shed ≫ 0: the queue bound is shedding load.
    One finding per rank, naming the worst class."""
    out = []
    for s in streams:
        # a quarantined class's sheds are graceful degradation working
        # as designed (serve --quarantine-after: targeted isolation,
        # surfaced as its own event:"quarantine" record and SLO
        # accounting, driver exits 0) — convicting them as a
        # queue-bound storm would fail exactly the runs the
        # degradation exists to save. Scoped from the FIRST quarantine
        # entry onward: windows a healthy-handler class shed at the
        # queue bound BEFORE it ever quarantined are a genuine storm.
        # A summary-only signal (episode windows lost) has no entry
        # time, so it exempts the whole stream. The digest keeps the
        # raw windows per class (bounded deque) precisely because the
        # exemption boundary can arrive AFTER the windows it exempts —
        # the filter runs at judge time, over the retained set.
        per_class: dict = {}
        for cls, dq in s.serve_windows.items():
            agg = None
            settled = s.serve_settled.get(cls)
            if settled:
                # settled windows were exemption-filtered at eviction;
                # a boundary that later moved to (or before) the whole
                # settled range — the summary-only -inf case — drops
                # the aggregate wholesale
                boundary = None
                for _ln, r0 in dq:
                    boundary = s.quar_t.get(r0.get("class"))
                    break
                if not (boundary is not None
                        and boundary <= settled["t_min"]):
                    agg = per_class.setdefault(
                        cls, {"shed": 0, "arrivals": 0, "qmax": 0,
                              "windows": [], "t": None})
                    agg["shed"] += settled["shed"]
                    agg["arrivals"] += settled["arrivals"]
                    agg["qmax"] = max(agg["qmax"], settled["qmax"])
                    agg["t"] = settled["t"]
            for ln, r in dq:
                cls_q = s.quar_t.get(r.get("class"))
                if cls_q is not None and (_rec_t(r) or 0) >= cls_q:
                    continue
                agg = per_class.setdefault(
                    cls, {"shed": 0, "arrivals": 0, "qmax": 0,
                          "windows": [], "t": None})
                agg["shed"] += int(r.get("shed") or 0)
                agg["arrivals"] += int(r.get("arrivals") or 0)
                agg["qmax"] = max(agg["qmax"],
                                  int(r.get("queue_max") or 0))
                if r.get("shed"):
                    agg["t"] = _rec_t(r)
            # evidence = the FIRST shed windows ever seen (kept outside
            # the bounded deque), judge-time exemption-filtered like
            # everything else
            if agg is not None:
                agg["windows"] = [
                    (ln, r) for ln, r in s.serve_first_shed.get(cls, [])
                    if not ((q := s.quar_t.get(r.get("class")))
                            is not None and (_rec_t(r) or 0) >= q)
                ]
        storms = {
            cls: a for cls, a in per_class.items()
            if a["shed"] >= max(opts["shed_min"],
                                0.02 * max(a["arrivals"], 1))
        }
        if not storms:
            continue
        worst = max(storms, key=lambda c: storms[c]["shed"])
        a = storms[worst]
        ev = [s.ref(ln, r) for ln, r in a["windows"][:3]]
        total_shed = sum(x["shed"] for x in storms.values())
        out.append(_finding(
            "shed_storm", s.rank, 0.85,
            f"{total_shed} requests shed across "
            f"{len(storms)} class(es); worst is {worst!r} with "
            f"{a['shed']} shed of {a['arrivals']} arrivals at queue "
            f"depth {a['qmax']} — the queue bound is dropping load",
            ev, last_op=worst, phase="serve", t=a["t"],
        ))
    return out


def _queue_ramp_findings(streams: list[_Stream], opts) -> list[dict]:
    """Saturation as an EARLY WARNING: the queue-delay share of the
    e2e p99 (``qd_p99_ms / p99_ms``, the PR-16 decomposition) held or
    rose across ``ramp_windows`` consecutive windows, ended the run at
    or above ``qd_share_min``, and the run's last window still carried
    a standing backlog of at least ``ramp_depth_min`` — the tail is
    queueing, the queue is not draining, and the shed cliff is where
    that trajectory ends. Scans every consecutive window run (not just
    the stream tail): a flood that eventually drains still convicts
    post-mortem over the windows where it was ramping, so --follow's
    mid-run conviction and the offline doctor agree on the same
    records for free. Suppressed wherever shed_storm already convicted
    the rank: the storm is the verdict once load is actually dropping,
    the ramp is the warning before. One finding per rank, naming the
    class with the worst qualifying share."""
    out = []
    need = int(opts["ramp_windows"])
    for s in streams:
        worst = None
        for cls, dq in s.serve_windows.items():
            wins = [
                (ln, r) for ln, r in dq
                if not ((q := s.quar_t.get(r.get("class"))) is not None
                        and (_rec_t(r) or 0) >= q)
            ]
            if len(wins) < need:
                continue
            for i in range(len(wins) - need + 1):
                run = wins[i:i + need]
                shares = []
                for _ln, r in run:
                    qd, e2e = r.get("qd_p99_ms"), r.get("p99_ms")
                    if not (isinstance(qd, (int, float))
                            and isinstance(e2e, (int, float))
                            and e2e > 0):
                        shares = None  # pre-decomposition records in
                        break          # this run: no verdict from them
                    shares.append(min(qd / e2e, 1.0))
                if not shares:
                    continue
                depth = run[-1][1].get("queue_depth")
                if not isinstance(depth, (int, float)):
                    depth = 0
                sustained = all(b >= a - 0.05
                                for a, b in zip(shares, shares[1:]))
                if not (sustained and shares[-1] >= opts["qd_share_min"]
                        and depth >= opts["ramp_depth_min"]):
                    continue
                if worst is None or shares[-1] > worst[0]:
                    worst = (shares[-1], shares[0], cls, depth, run)
        if worst is None:
            continue
        share_end, share_start, cls, depth, tail = worst
        out.append(_finding(
            "queue_ramp", s.rank, 0.7,
            f"class {cls!r}: queue-delay share of the e2e p99 held at "
            f"{share_start * 100:.0f}%→{share_end * 100:.0f}% across "
            f"{len(tail)} windows with a standing backlog of {depth} "
            f"still queued — the tail is queueing, not service, and "
            f"the queue is not draining; sheds follow if the offered "
            f"load holds (raise capacity, lower --rate, or let "
            f"--max-queue shed earlier)",
            [s.ref(*tail[0]), s.ref(*tail[-1])],
            last_op=cls, phase="serve", t=_rec_t(tail[-1][1]),
        ))
    return out


def _stale_schedule_findings(streams: list[_Stream], opts,
                             followed: bool = False) -> list[dict]:
    """A latched ``tune_stale`` with no ``tune_swap`` answer: the run's
    own telemetry said the tuned schedule sagged below its baseline and
    nothing re-tuned it. A swap at-or-after the latch exonerates (the
    ``--retune`` controller closing the loop is the healthy outcome —
    the doctor must not convict exactly the runs the controller saves).
    Mid-follow a latch fresher than ``stale_grace_s`` stays unconvicted
    — the controller only acts at the next window boundary — while the
    post-mortem pass convicts every unanswered latch: the run ended, no
    swap can come. One finding per rank, naming the first unanswered
    op."""
    out = []
    for s in streams:
        unanswered = []
        for op, (ln, rec, t) in sorted(s.stale_ops.items()):
            if s.swap_t.get(op, -_INF) >= t:
                continue  # the controller answered: loop closed
            if (followed and s.last_t is not None
                    and s.last_t - t < opts["stale_grace_s"]):
                continue  # too fresh to judge live: a swap may come
            unanswered.append((op, ln, rec, t))
        if not unanswered:
            continue
        op, ln, rec, t = unanswered[0]
        sag = rec.get("sag_pct")
        signal = rec.get("signal")
        out.append(_finding(
            "stale_schedule", s.rank, 0.75,
            f"op {op!r} sagged {sag}% below its tuned baseline "
            f"(signal={signal}, knobs={rec.get('knobs')}) and no "
            f"tune_swap followed — the run kept serving a schedule its "
            f"own telemetry convicted; re-sweep (--tune / serve "
            f"--retune) or ship a fresher --tune-pack"
            + (f"; {len(unanswered) - 1} more op(s) stale"
               if len(unanswered) > 1 else ""),
            [s.ref(ln, rec)],
            last_op=op, phase=None, t=t,
        ))
    return out


def diagnose_streams(streams: list[_Stream], ctx: dict | None = None,
                     followed: bool = False, **overrides) -> list[dict]:
    """Apply every rule; findings sorted most-confident first.

    ``followed`` is the ONLINE mode: mid-run every stream is still
    missing its close markers (nothing has closed yet), so the
    straggler rule's alive set becomes "not death-convicted" instead of
    "not died". On a COMPLETED stream the two are identical (closed
    streams are not died; truncated ones get their death finding), so
    the follow-mode doctor's final pass runs with ``followed=False``
    and agrees with the offline doctor byte for byte."""
    opts = dict(DEFAULTS)
    opts.update({k: v for k, v in overrides.items() if v is not None})
    findings: list[dict] = []
    ctx = ctx or {}

    # ranks in the manifest with no file at all — the strongest form
    # of a missing rank (a crashed rank whose JSONL never flushed, or
    # a file lost in transit: either way the run claims n ranks).
    # POST-MORTEM only: while following a live run, a sibling rank
    # that has not opened its file yet (still importing jax) is
    # indistinguishable from one that never will — the follower's
    # FINAL pass (followed=False) applies this rule
    if not followed:
        expected = int(ctx.get("expected") or 0)
        seen = {s.rank for s in streams}
        for rank in range(expected):
            if rank not in seen:
                findings.append(_finding(
                    "missing_rank", rank, 0.9,
                    f"the manifest declares {expected} processes but "
                    f"no rank file for rank {rank} exists in the "
                    f"merged set",
                    [], t=None,
                ))

    dead_ranks = set()
    for s in streams:
        f = _death_finding(s, streams, opts, followed=followed)
        if f is not None:
            findings.append(f)
            dead_ranks.add(s.rank)

    alive = ([s for s in streams if s.rank not in dead_ranks]
             if followed else None)
    findings.extend(
        f for f in _straggler_findings(streams, opts, alive=alive)
        if f["rank"] not in dead_ranks
    )
    storm_findings = [
        f for f in _shed_storm_findings(streams, opts)
        if f["rank"] not in dead_ranks
    ]
    findings.extend(storm_findings)
    storm_ranks = {f["rank"] for f in storm_findings}
    findings.extend(
        # ramp suppressed where the storm already convicted: the storm
        # is the verdict once load is dropping, the ramp the forecast
        # before — double-convicting one saturation event would break
        # every --expect exactly-one-finding contract
        f for f in _queue_ramp_findings(streams, opts)
        if f["rank"] not in dead_ranks | storm_ranks
    )
    findings.extend(
        f for f in _stale_schedule_findings(streams, opts,
                                            followed=followed)
        if f["rank"] not in dead_ranks
    )
    findings.sort(key=lambda f: (-f["confidence"], f["class"],
                                 str(f["rank"])))
    return findings


def diagnose_files(
    files: list[str],
    loaded: dict[str, list[tuple[int, dict]]] | None = None,
    run_sync_us: int | None = None,
    **overrides,
) -> list[dict]:
    """Load + diagnose; the entry point tpumt-report and tpumt-trace
    reuse. Un-suffixed ``--jsonl`` base paths expand to their
    ``.p<i>`` rank set like every other CLI; callers that already
    parsed the files pass :func:`load_with_lines` output as ``loaded``.
    Never raises — a diagnosis bug must not break the report or the
    trace it rides along with."""
    try:
        files = [f for f in expand_rank_files(files)
                 if Path(f).exists()]
        streams, ctx = load_streams(files, loaded=loaded,
                                    run_sync_us=run_sync_us)
        return diagnose_streams(streams, ctx, **overrides)
    except Exception as e:  # noqa: BLE001 — defensive by contract
        print(f"tpumt-doctor: diagnosis failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return []


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def format_finding(f: dict) -> str:
    parts = [f"FINDING {f['class']}: rank={f['rank']} "
             f"confidence={f['confidence']:.2f}"]
    if f.get("last_op"):
        parts.append(f"last_op={f['last_op']}")
    if f.get("phase"):
        parts.append(f"phase={f['phase']}")
    if f.get("link"):
        parts.append(f"link={f['link']}")
    return " ".join(parts) + f" — {f['detail']}"


def _attach_protocol_evidence(findings: list[dict],
                              streams: list[_Stream],
                              cache_arg: str) -> None:
    """ISSUE 18 (``--protocol-model``): for each missing_rank/straggler
    conviction, replay the convicted rank's span stream through the
    schedule automaton rebuilt from tpumt-lint's analysis cache and
    append the statically-expected next collective as one more evidence
    line. Strictly additive and best-effort by contract: a cold/absent
    cache, a pre-seq stream, or a stream outside the model changes
    nothing, and the analysis package (itself stdlib-only) is imported
    lazily only under the flag — without it the doctor's output is
    byte-identical."""
    try:
        from tpu_mpi_tests.analysis.lintcache import default_cache_path
        from tpu_mpi_tests.analysis.protocol import (
            automaton_from_cache,
            expected_after,
        )

        auto = automaton_from_cache(cache_arg or default_cache_path())
    except Exception:
        return
    if auto is None:
        return
    by_rank = {s.rank: s for s in streams}
    for f in findings:
        if f["class"] not in ("missing_rank", "straggler"):
            continue
        s = by_rank.get(f["rank"])
        if s is None:
            continue
        try:
            sibs = [load_with_lines(o.path) for o in streams
                    if o.rank != f["rank"]]
            model = expected_after(load_with_lines(s.path), auto, sibs)
        except Exception:
            continue
        if not model:
            continue
        f.setdefault("evidence", []).append(
            f"protocol-model: after {model['matched']} matched span(s) "
            f"the schedule automaton expects "
            f"{', '.join(model['expected'])} next from rank "
            f"{f['rank']} ({model['states']} automaton state(s); "
            f"source: tpumt-lint analysis cache)"
        )


def _print_findings(findings: list[dict], streams: list[_Stream],
                    as_json: bool, files: list[str]) -> None:
    if as_json:
        json.dump({"files": files, "findings": findings}, sys.stdout,
                  indent=1)
        print()
        return
    for f in findings:
        print(format_finding(f))
        for ref in f.get("evidence") or []:
            print(f"  evidence: {ref}")
    if not findings:
        n = sum(s.n_records for s in streams)
        print(f"DOCTOR OK: no findings ({len(streams)} rank "
              f"file(s), {n} records)")


def _expect_verdict(findings: list[dict], expect, as_json: bool) -> int:
    cls, rank = expect
    if len(findings) == 1 and findings[0]["class"] == cls \
            and findings[0]["rank"] == rank:
        # stderr under --json: stdout is a JSON document a
        # consumer may be piping into a parser
        print(f"DOCTOR EXPECT OK: {cls}:{rank}",
              file=sys.stderr if as_json else sys.stdout)
        return 0
    got = [f"{f['class']}:{f['rank']}" for f in findings]
    print(f"DOCTOR EXPECT FAIL: wanted exactly [{cls}:{rank}], "
          f"got {got}", file=sys.stderr)
    return 2


def follow(args, expect) -> int:
    """The ONLINE doctor: tail the rank files as they are written
    (``instrument/live.py`` tailer — the same incremental reader
    ``tpumt-top`` uses, ghost-sibling-filtered by the shared run-stamp
    helper), feed each new record into the SAME :class:`_Stream`
    digests the offline doctor builds, and re-judge every poll with
    ``followed=True``. New convictions print the moment they land —
    while the run is still executing. With ``--expect`` the process
    exits 0 the instant the diagnosis is exactly the expected finding
    (the live CI primitive ``make live-smoke`` uses against an
    injected chaos straggler).

    Termination without ``--expect`` (or when it never matches): when
    every followed stream saw its clean-close marker, when no file
    grew for ``--idle`` seconds, or at ``--timeout`` — then a FINAL
    pass runs with offline semantics (``followed=False``), so the
    verdicts printed at the end are byte-identical to running the
    post-mortem doctor on the same files (pinned in
    tests/test_live.py)."""
    from tpu_mpi_tests.instrument.live import RunTail

    tail = RunTail(args.files)
    streams: dict[str, _Stream] = {}
    ctx: dict = {"manifest": {}, "expected": 0}
    printed: set = set()
    t0 = time.monotonic()
    last_data = t0
    # --idle applies only once WORKLOAD records flow: a driver writes
    # its manifest/clock_sync header within a second and then spends
    # tens of seconds in jax import + XLA compile before the first
    # span/phase — a header-only quiet gap must not finalize a healthy
    # run as "over"
    saw_body = False
    thresholds = {"skew_threshold": args.skew_threshold,
                  "gap_s": args.gap_s}

    def slist() -> list[_Stream]:
        return list(streams.values())

    def finalize() -> int:
        if not streams:
            # same contract as the offline doctor on a missing path: a
            # typo'd/never-created file must not read as a clean run
            print("tpumt-doctor: no input files found", file=sys.stderr)
            return 2
        findings = diagnose_streams(slist(), ctx, followed=False,
                                    **thresholds)
        _print_findings(findings, slist(), args.json, tail.files())
        if expect is not None:
            return _expect_verdict(findings, expect, args.json)
        return 1 if findings else 0

    try:
        while True:
            grew = False
            for path, ln, rec in tail.poll():
                grew = True
                kind = rec.get("kind")
                if kind == "manifest":
                    # a new segment at a followed path = a rerun
                    # appended to the same file: fresh digest, same as
                    # the offline newest-segment selection — and the
                    # run context restarts with it, or a 2-process
                    # rerun after a 4-process run would inherit
                    # expected=4 and convict phantom missing ranks the
                    # offline (newest-segment) doctor never sees
                    if path in streams:
                        ctx["expected"] = 0
                        ctx["manifest"] = {}
                        # the new run's convictions must print live
                        # even when they repeat the old run's
                        # (class, rank) — the dedup is per run
                        printed.clear()
                    streams[path] = _Stream(
                        rec.get("process_index", tail.index(path)),
                        path)
                    ctx["expected"] = max(
                        ctx["expected"],
                        int(rec.get("process_count") or 0))
                    if not ctx["manifest"] \
                            or rec.get("process_index") == 0:
                        ctx["manifest"] = rec
                if kind not in ("manifest", "clock_sync", "chaos"):
                    saw_body = True
                if kind == "chaos":
                    continue  # organic signals only, like offline load
                s = streams.get(path)
                if s is None:
                    s = streams[path] = _Stream(tail.index(path), path)
                s.add(ln, rec)
            now = time.monotonic()
            if grew:
                last_data = now
                findings = diagnose_streams(slist(), ctx,
                                            followed=True, **thresholds)
                for f in findings:
                    key = (f["class"], f["rank"])
                    if key not in printed and not args.json:
                        printed.add(key)
                        print(format_finding(f), flush=True)
                if expect is not None and len(findings) == 1:
                    f = findings[0]
                    if (f["class"], f["rank"]) == expect:
                        if args.json:
                            # --json keeps stdout a parseable document
                            # on EVERY exit path, this one included
                            _print_findings(findings, slist(), True,
                                            tail.files())
                        print(f"DOCTOR EXPECT OK: "
                              f"{expect[0]}:{expect[1]} (live, "
                              f"{now - t0:.1f}s after follow start)",
                              file=(sys.stderr if args.json
                                    else sys.stdout),
                              flush=True)
                        return 0
            if streams and all(s.closed for s in streams.values()):
                return finalize()
            # the wait is floored well above --idle until the first
            # WORKLOAD record: startup (jax import, XLA compile, a
            # header-only stream) legitimately takes tens of quiet
            # seconds — but a file that never appears, or a run that
            # never produces a body, must finalize, not hang
            wait_limit = (args.idle if saw_body
                          else max(args.idle, NO_FILE_GRACE_S))
            if now - last_data >= wait_limit:
                return finalize()
            if args.timeout is not None and now - t0 >= args.timeout:
                return finalize()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        # Ctrl-C on a live watch ends it like a timeout: the final
        # offline-semantics verdict, not a traceback
        print("", file=sys.stderr)
        return finalize()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpumt-doctor",
        description="root-cause triage over per-rank telemetry JSONL: "
        "emits kind:'finding' verdicts (failure class, culprit rank, "
        "last op, evidence, confidence) from cross-rank rules — "
        "missing rank, straggler, wedged dispatch, OOM ramp, serve "
        "shed storm (README 'Chaos & diagnosis')",
    )
    p.add_argument(
        "files", nargs="+",
        help="per-rank JSONL files; an un-suffixed --jsonl base path "
        "expands to its .p<i> rank set",
    )
    p.add_argument(
        "--skew-threshold", type=float, default=None, metavar="X",
        help=f"straggler conviction skew (default "
        f"{DEFAULTS['skew_threshold']}; tpumt-report FLAGS at 1.5, the "
        f"doctor CONVICTS — stricter by design)",
    )
    p.add_argument(
        "--gap", type=float, default=None, metavar="S", dest="gap_s",
        help=f"seconds siblings must outlive a rank before it is "
        f"missing (default {DEFAULTS['gap_s']})",
    )
    p.add_argument(
        "--json", action="store_true",
        help="emit {'findings': [...]} as one JSON document",
    )
    p.add_argument(
        "--follow", action="store_true",
        help="ONLINE mode: tail the rank files as they are written and "
        "convict WHILE the run executes (same rule kernels as the "
        "post-mortem pass — the final verdict on a completed stream is "
        "byte-identical to running without --follow); with --expect, "
        "exit 0 the moment the diagnosis is exactly the expected "
        "finding (README 'Live observability')",
    )
    p.add_argument(
        "--interval", type=float, default=0.5, metavar="S",
        help="--follow poll period in seconds (default 0.5)",
    )
    p.add_argument(
        "--timeout", type=float, default=None, metavar="S",
        help="--follow: give up and run the final (offline-semantics) "
        "diagnosis after S seconds (default: no limit)",
    )
    p.add_argument(
        "--idle", type=float, default=10.0, metavar="S",
        help="--follow: treat the run as over when no followed file "
        "grew for S seconds (default 10) and run the final diagnosis; "
        "until the first WORKLOAD record (beyond manifest/clock_sync) "
        "the wait is floored at 60 s — driver startup spends tens of "
        "quiet seconds in jax import/compile — after which a "
        "never-appearing run finalizes instead of hanging. NOTE: "
        "--follow replays existing content at the path first, exactly "
        "like the offline doctor would judge it — rotate or remove a "
        "previous run's files when you mean to watch only an upcoming "
        "run",
    )
    p.add_argument(
        "--protocol-model", nargs="?", const="", default=None,
        metavar="CACHE",
        help="cite the statically-expected next collective for each "
        "missing_rank/straggler rank, replayed from tpumt-lint's "
        "analysis cache (optional cache path; default "
        "~/.cache/tpumt/lint.json or $TPU_MPI_LINT_CACHE). Purely "
        "additive evidence — a cold cache or pre-seq stream changes "
        "nothing, and without the flag output is byte-identical",
    )
    p.add_argument(
        "--expect", default=None, metavar="CLASS:RANK",
        help="CI contract mode: exit 0 iff the diagnosis is EXACTLY "
        "one finding of CLASS convicting RANK (e.g. --expect "
        "missing_rank:1), else exit 2 explaining what was found — "
        "the chaos-smoke assertion primitive",
    )
    args = p.parse_args(argv)

    expect = None
    if args.expect:
        try:
            cls, rank = args.expect.rsplit(":", 1)
            if cls not in FINDING_CLASSES:
                raise ValueError(cls)
            expect = (cls, int(rank))
        except ValueError:
            print(f"tpumt-doctor: bad --expect {args.expect!r}; want "
                  f"CLASS:RANK with CLASS in "
                  f"{','.join(FINDING_CLASSES)}", file=sys.stderr)
            return 2

    if args.follow:
        return follow(args, expect)

    files = [f for f in expand_rank_files(args.files) if Path(f).exists()]
    if not files:
        print("tpumt-doctor: no input files found", file=sys.stderr)
        return 2
    streams, ctx = load_streams(files)
    findings = diagnose_streams(
        streams, ctx, skew_threshold=args.skew_threshold,
        gap_s=args.gap_s,
    )
    if args.protocol_model is not None:
        _attach_protocol_evidence(findings, streams,
                                  args.protocol_model)
    _print_findings(findings, streams, args.json, files)
    if expect is not None:
        return _expect_verdict(findings, expect, args.json)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
