"""ctypes bridge to the native phase-timer library (native/phasetimer.cc).

The reference's host-side clock is ``clock_gettime(CLOCK_MONOTONIC)``
(``mpi_stencil_gt.cc:200-204``); libtpumt is the same primitive for this
framework. The library is built on demand (``make -C native``) and cached;
everything degrades to ``time.perf_counter_ns`` when no toolchain is
available, so the native path is an optimization, never a requirement.
"""

from __future__ import annotations

import ctypes
import functools
import os
import subprocess
import time
from pathlib import Path

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_NAME = "libtpumt.so"


@functools.lru_cache(maxsize=None)
def _load() -> ctypes.CDLL | None:
    lib_path = _NATIVE_DIR / _LIB_NAME
    if not lib_path.exists():
        if os.environ.get("TPU_MPI_TESTS_NO_NATIVE"):
            return None
        try:
            subprocess.run(
                ["make", "-C", str(_NATIVE_DIR), _LIB_NAME],
                capture_output=True,
                check=True,
                timeout=120,
            )
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            return None
    try:
        lib = ctypes.CDLL(str(lib_path))
    except OSError:
        return None
    lib.tpumt_monotonic_ns.restype = ctypes.c_int64
    lib.tpumt_phase_seconds.restype = ctypes.c_double
    lib.tpumt_phase_count.restype = ctypes.c_int64
    for fn in (lib.tpumt_phase_start, lib.tpumt_phase_stop,
               lib.tpumt_phase_reset):
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_int]
    lib.tpumt_phase_seconds.argtypes = [ctypes.c_int]
    lib.tpumt_phase_count.argtypes = [ctypes.c_int]
    return lib


def available() -> bool:
    return _load() is not None


def monotonic_ns() -> int:
    """CLOCK_MONOTONIC nanoseconds via the native lib (perf_counter_ns
    fallback)."""
    lib = _load()
    if lib is None:
        return time.perf_counter_ns()
    return lib.tpumt_monotonic_ns()


class NativePhaseSlots:
    """Slot-based accumulating timers backed by libtpumt (Python fallback).

    ≅ the t_/k_/b_/g_ accumulator variables of ``mpi_daxpy_nvtx.cc``,
    kept out of Python arithmetic when native.
    """

    def __init__(self):
        self._lib = _load()
        self._py_accum: dict[int, float] = {}
        self._py_count: dict[int, int] = {}
        self._py_start: dict[int, int] = {}

    def start(self, slot: int) -> None:
        if self._lib is not None:
            self._lib.tpumt_phase_start(slot)
        else:
            self._py_start[slot] = time.perf_counter_ns()

    def stop(self, slot: int) -> None:
        if self._lib is not None:
            self._lib.tpumt_phase_stop(slot)
        else:
            dt = time.perf_counter_ns() - self._py_start.pop(slot)
            self._py_accum[slot] = self._py_accum.get(slot, 0.0) + dt * 1e-9
            self._py_count[slot] = self._py_count.get(slot, 0) + 1

    def seconds(self, slot: int) -> float:
        if self._lib is not None:
            return self._lib.tpumt_phase_seconds(slot)
        return self._py_accum.get(slot, 0.0)

    def count(self, slot: int) -> int:
        if self._lib is not None:
            return self._lib.tpumt_phase_count(slot)
        return self._py_count.get(slot, 0)

    def reset(self, slot: int) -> None:
        if self._lib is not None:
            self._lib.tpumt_phase_reset(slot)
        else:
            self._py_accum.pop(slot, None)
            self._py_count.pop(slot, None)
