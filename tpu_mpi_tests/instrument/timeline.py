"""``tpumt-trace``: merge per-rank telemetry JSONL into one cross-rank
timeline — Chrome trace-event JSON for Perfetto / ``chrome://tracing``.

``tpumt-report`` answers "how much / how skewed"; this module answers
"what happened *when*" — the reference's NVTX + ``nsys`` pillar
(``mpi_daxpy_nvtx.cc:177-325``, ``summit/run.sh:15-19``), rebuilt on the
records the telemetry layer already streams. Given the per-rank file set
of one run (the auto-suffixed ``out.p<i>.jsonl`` files, or explicit
paths), it:

* assigns each stream to its rank (manifest ``process_index``, file
  order fallback) and aligns every timestamp to rank 0's wall clock via
  the ``clock_sync`` record (``instrument/manifest.py`` barrier-echo
  handshake; single-process runs carry offset 0);
* renders one Perfetto process ("track") per rank with two threads —
  ``comm`` (telemetry spans, named by op, annotated with bytes / GB/s /
  mesh axis; flight-recorder dispatch notes as thread-scoped instants,
  so a wedged op's last dispatch is visible at its place on the
  timeline) and ``phases`` (PhaseTimer windows) — as complete events
  (``ph: "X"``) with ``ts``/``dur`` in microseconds;
* marks watchdog fires as process-scoped instant events — the point
  where a rank's flow terminated.

Records without ``t_start`` (pre-timeline JSONL) are counted and
skipped: old files still merge into a *valid* (possibly empty) trace,
and keep aggregating under ``tpumt-report`` unchanged.

Also provides the terminal-only fallback behind ``tpumt-report
--timeline``: a per-phase ASCII swimlane (one lane per rank on a shared
axis) plus per-step start-skew series per comm op — which rank entered
step k late, without leaving the shell.

Pure stdlib (no jax import): usable on a login node against files
copied off the pod.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tpu_mpi_tests.instrument.aggregate import (
    _load_records,
    expand_rank_files,
)

#: thread ids within each rank's trace process
TID_COMM = 0
TID_PHASE = 1
TID_COMPILE = 2
TID_REQ = 3

_US = 1e6  # trace-event ts/dur unit is microseconds


def _run_segments(records: list[dict]) -> list[list[dict]]:
    """Split one file's record stream at manifest boundaries. JSONL
    opens in append mode, so a reused ``--jsonl`` path holds several
    runs back to back; each run starts with its manifest. Records
    before the first manifest (or a file with none) form one leading
    segment, so manifest-less streams pass through whole."""
    segments: list[list[dict]] = [[]]
    for rec in records:
        if rec.get("kind") == "manifest" and segments[-1]:
            segments.append([])
        segments[-1].append(rec)
    return segments


def _segment_run_id(segment: list[dict]):
    for rec in segment:
        if rec.get("kind") == "clock_sync":
            return rec.get("run_sync_us")
    return None


def run_sync_ids(path: str) -> set:
    """All ``run_sync_us`` stamps present in a JSONL file (one per run
    appended to it) — the run-identity probe the ``--trace-out`` merge
    uses to tell sibling rank files of the current run from stale ones."""
    return {
        rid
        for seg in _run_segments(_load_records(path))
        if (rid := _segment_run_id(seg)) is not None
    }


def file_in_run(path: str, run_sync_us, mtime_after=None,
                ids: "set | None" = None) -> bool:
    """Whether ``path`` belongs to the run identified by
    ``run_sync_us`` — THE shared ghost-track filter (one copy of the
    logic): the ``--trace-out`` auto-merge, ``tpumt-top``, and
    ``tpumt-doctor --follow`` all use it to keep stale ``.p<i>``
    sibling files from an earlier run at the same base path out of the
    current run's set. Primary identity is the shared ``clock_sync``
    stamp (a file qualifies when ANY of its appended runs carries it);
    files with no stamp at all (older format / handshake unavailable)
    fall back to the ``mtime_after`` window, and pass when no window
    was given. ``ids`` is the file's precomputed :func:`run_sync_ids`
    set — the follow-mode tailer passes a cheaply scanned one so
    admitting a multi-GB file does not cost a full JSON parse."""
    if ids is None:
        ids = run_sync_ids(path)
    if run_sync_us is not None and ids:
        return run_sync_us in ids
    if mtime_after is None:
        return True
    try:
        return Path(path).stat().st_mtime >= mtime_after
    except OSError:
        return False


def rank_streams(
    files: list[str], run_sync_us: int | None = None,
    loaded: dict[str, list[tuple[int, dict]]] | None = None,
) -> list[tuple[int, float, list[dict]]]:
    """``[(rank, offset_s, records)]`` per file — ONE run's records per
    file. A file reused across runs (append mode) is segmented at its
    manifests: with ``run_sync_us`` the segment carrying that
    ``clock_sync`` stamp is chosen (newest segment when absent), else
    the newest segment — earlier runs' events must not bleed onto the
    merged timeline, where the chosen run's clock offset would misplace
    them. Rank comes from the segment's manifest ``process_index``
    (file order as fallback), the clock offset from its ``clock_sync``
    record (0 when absent — old files merge uncorrected rather than
    erroring). ``loaded`` is pre-parsed ``diagnose.load_with_lines``
    output (line numbers dropped here) so :func:`chrome_trace` parses
    each file once for both the trace and its finding markers."""
    streams = []
    for idx, path in enumerate(files):
        pairs = (loaded or {}).get(path)
        records = ([r for _, r in pairs] if pairs is not None
                   else _load_records(path))
        segments = _run_segments(records)
        chosen = segments[-1]
        if run_sync_us is not None:
            for seg in segments:
                if _segment_run_id(seg) == run_sync_us:
                    chosen = seg
                    break
        rank, offset = idx, 0.0
        for rec in chosen:
            kind = rec.get("kind")
            if kind == "manifest" and "process_index" in rec:
                rank = rec["process_index"]
            elif kind == "clock_sync":
                offset = float(rec.get("offset_s") or 0.0)
        streams.append((rank, offset, chosen))
    return streams


def _collect(streams):
    """Split aligned records into (spans, instants, counters,
    n_unplaced).

    spans:    (rank, tid, name, cat, t_start, dur_s, args)
    instants: (rank, tid, name, cat, t, scope, args)
    counters: (rank, name, t, series_dict) — Perfetto counter samples
              from ``kind: "mem"`` records: per-device ``bytes_in_use``
              where the backend reports watermarks, live-array bytes
              (the census-only CPU/fake-device degrade path) otherwise
    Timestamps are wall-clock seconds already shifted onto rank 0's
    clock (``t - offset``); records with no ``t_start``/``t`` cannot be
    placed and are only counted (pre-timeline JSONL compatibility)."""
    from tpu_mpi_tests.instrument.anatomy import (partner_edges,
                                                  wait_wire_subspans)

    spans, instants, counters, unplaced = [], [], [], 0
    # cross-rank wait/wire split points per matched (op, axis, seq)
    # call (instrument/anatomy.py): empty on pre-seq streams, so the
    # legacy trace document is byte-identical
    splits = wait_wire_subspans(streams)
    # cumulative bytes sent per (src rank → dst rank) edge, sampled at
    # each partner-annotated span's end — the traffic matrix as
    # Perfetto counter tracks
    sent: dict[int, dict[str, int]] = {}
    # cumulative bytes per link class (comm/topology.py partner_link
    # stamps) — its own counter track, present only on non-flat runs
    sent_link: dict[int, dict[str, int]] = {}

    def args_from(rec, keys):
        return {k: rec[k] for k in keys if rec.get(k) is not None}

    for rank, offset, records in streams:
        for rec in records:
            kind = rec.get("kind")
            if kind == "span":
                if rec.get("t_start") is None:
                    unplaced += 1
                    continue
                start = float(rec["t_start"]) - offset
                end = float(rec.get("t_end") or rec["t_start"]) - offset
                op = rec.get("op", "?")
                spans.append((
                    rank, TID_COMM, op, "comm", start,
                    max(end - start, 0.0),
                    args_from(rec, ("nbytes", "gbps", "axis", "world",
                                    "seconds", "cost_bytes",
                                    "model_gbps", "roofline_frac",
                                    "async", "overlap_depth",
                                    "dispatch_depth", "seq", "link")),
                ))
                # wait/wire sub-spans nested under the collective span
                # (appended after the parent, so stable ts-sorting
                # keeps parent-before-child for the nesting renderer):
                # wait = own entry → last arriver, wire = the rest
                split = (splits.get((op, rec.get("axis"), rec["seq"]))
                         if rec.get("seq") is not None
                         and not rec.get("async") else None)
                if split is not None and end > start:
                    sub_args = {"seq": rec["seq"]}
                    if start < split < end:
                        spans.append((rank, TID_COMM, f"wait {op}",
                                      "comm_wait", start, split - start,
                                      sub_args))
                        spans.append((rank, TID_COMM, f"wire {op}",
                                      "comm_wire", split, end - split,
                                      sub_args))
                    else:
                        # this rank IS the last arriver (or the split
                        # clamps outside its span): all wire
                        spans.append((rank, TID_COMM, f"wire {op}",
                                      "comm_wire", start, end - start,
                                      sub_args))
                edges = partner_edges(rec, rank)
                if edges:
                    cum = sent.setdefault(rank, {})
                    for dst, nbytes in edges:
                        key = f"to r{dst}"
                        cum[key] = cum.get(key, 0) + nbytes
                    counters.append((rank, "comm bytes sent", end,
                                     dict(cum)))
                    links = rec.get("partner_link")
                    if links:
                        # align classes with the kept edges — the same
                        # out-of-range drop rule as partner_edges
                        world = int(rec.get("world") or 1)
                        kept = [
                            str(cls)
                            for d, cls in zip(rec.get("partners") or [],
                                              links)
                            if rec.get("periodic")
                            or 0 <= rank + int(d) < world
                        ]
                        lcum = sent_link.setdefault(rank, {})
                        for (_dst, nbytes), cls in zip(edges, kept):
                            lcum[cls] = lcum.get(cls, 0) + nbytes
                        counters.append((rank, "comm bytes by link",
                                         end, dict(lcum)))
            elif kind == "time":
                if rec.get("event") == "progress":
                    # live cumulative snapshots (metrics plane): their
                    # t_start..t_end window is the phase's whole
                    # lifetime so far — rendering each would stack
                    # ever-longer ghost spans over the real phases
                    continue
                if rec.get("t_start") is None:
                    unplaced += 1
                    continue
                start = float(rec["t_start"]) - offset
                end = float(rec.get("t_end") or rec["t_start"]) - offset
                spans.append((
                    rank, TID_PHASE, rec.get("phase", "?"), "phase",
                    start, max(end - start, 0.0),
                    args_from(rec, ("seconds", "count", "mean_s", "min_s",
                                    "max_s")),
                ))
            elif kind == "dispatch":
                if rec.get("t") is None:
                    unplaced += 1
                    continue
                instants.append((
                    rank, TID_COMM,
                    rec.get("note") or rec.get("op", "dispatch"),
                    "dispatch", float(rec["t"]) - offset, "t", {},
                ))
            elif kind == "watchdog":
                if rec.get("t") is None:
                    unplaced += 1
                    continue
                instants.append((
                    rank, TID_COMM,
                    f"WATCHDOG {rec.get('phase', '?')}", "watchdog",
                    float(rec["t"]) - offset, "p",
                    args_from(rec, ("deadline_s",)),
                ))
            elif kind == "control":
                # the re-tune controller acting (tune/controller.py):
                # a process-scoped marker at the hot-swap instant, so
                # the timeline shows the schedule change between the
                # sagging windows and the recovered ones
                if rec.get("t") is None:
                    unplaced += 1
                    continue
                instants.append((
                    rank, TID_COMM,
                    f"CONTROL {rec.get('event', '?')} "
                    f"{rec.get('class') or rec.get('knob', '?')}",
                    "control", float(rec["t"]) - offset, "p",
                    args_from(rec, ("knob", "op", "old", "new",
                                    "sag_pct", "signal", "resweep_s")),
                ))
            elif kind == "compile":
                if rec.get("t_start") is None:
                    unplaced += 1
                    continue
                start = float(rec["t_start"]) - offset
                end = float(rec.get("t_end") or rec["t_start"]) - offset
                spans.append((
                    rank, TID_COMPILE,
                    f"compile {rec.get('label', '?')}", "compile",
                    start, max(end - start, 0.0),
                    args_from(rec, ("seconds", "flops", "bytes_accessed",
                                    "temp_bytes", "output_bytes",
                                    "fingerprint")),
                ))
            elif kind == "req":
                # request lifecycle exemplars (serve loop sampler): the
                # window's p99-worst completion plus shed/error
                # terminals, rendered as a queue span (arrival ->
                # dispatch, or -> death for sheds) and a service span
                # (dispatch -> done) on the owning rank's requests
                # track — per-request latency anatomy on the timeline
                t_arr = rec.get("t_arrival")
                if t_arr is None:
                    unplaced += 1
                    continue
                t_arr = float(t_arr) - offset
                t_disp = rec.get("t_dispatch")
                t_done = rec.get("t_done")
                label = f"{rec.get('event', '?')} {rec.get('class', '?')}"
                q_end = float(t_disp if t_disp is not None
                              else (t_done if t_done is not None
                                    else rec["t_arrival"])) - offset
                spans.append((
                    rank, TID_REQ, f"queue {label}", "req_queue",
                    t_arr, max(q_end - t_arr, 0.0),
                    args_from(rec, ("sampled", "queue_ms", "e2e_ms")),
                ))
                if t_disp is not None and t_done is not None:
                    start = float(t_disp) - offset
                    end = float(t_done) - offset
                    spans.append((
                        rank, TID_REQ, f"service {label}", "req_service",
                        start, max(end - start, 0.0),
                        args_from(rec, ("sampled", "service_ms",
                                        "e2e_ms", "requests")),
                    ))
            elif kind == "mem":
                if rec.get("t") is None:
                    unplaced += 1
                    continue
                t = float(rec["t"]) - offset
                devices = rec.get("devices") or {}
                if devices:
                    counters.append((
                        rank, "HBM bytes_in_use", t,
                        {f"dev{d}": s.get("bytes_in_use", 0)
                         for d, s in sorted(devices.items())},
                    ))
                elif rec.get("live_bytes") is not None:
                    # census-only degrade path (no memory_stats): the
                    # live-array total still draws a counter track
                    counters.append((
                        rank, "live bytes", t,
                        {"bytes": rec["live_bytes"]},
                    ))
    return spans, instants, counters, unplaced


def chrome_trace(
    files: list[str], run_sync_us: int | None = None
) -> dict:
    """Merge the per-rank files into a Chrome trace-event document
    (the JSON-object form: ``{"traceEvents": [...], ...}``). ``ts`` is
    microseconds from the earliest aligned event; the absolute epoch is
    kept in ``otherData.t0_unix_s``. ``run_sync_us`` selects one run's
    segment in files appended to across runs (see
    :func:`rank_streams`)."""
    from tpu_mpi_tests.instrument.diagnose import (diagnose_files,
                                                   load_with_lines)

    loaded = {p: load_with_lines(p, prog="tpumt-trace") for p in files}
    streams = rank_streams(files, run_sync_us, loaded=loaded)
    spans, instants, counters, unplaced = _collect(streams)
    # diagnosis findings as instant markers on the culprit rank's
    # track (instrument/diagnose.py — the tpumt-doctor rules over the
    # same files, parsed once above): the trace shows WHERE the verdict
    # anchors, not just that one exists. Best-effort — a diagnosis bug
    # must never break the trace it rides along with (diagnose_files
    # never raises).
    offsets = {r: off for r, off, _ in streams}
    for f in diagnose_files(files, loaded=loaded,
                            run_sync_us=run_sync_us):
        if f.get("t") is None:
            continue
        rank = f.get("rank") or 0
        instants.append((
            rank, TID_COMM, f"FINDING {f['class']}", "finding",
            float(f["t"]) - offsets.get(rank, 0.0), "p",
            {k: f[k] for k in ("confidence", "last_op", "phase",
                               "detail") if f.get(k) is not None},
        ))
    starts = ([s[4] for s in spans] + [i[4] for i in instants]
              + [c[2] for c in counters])
    t0 = min(starts) if starts else 0.0

    compile_ranks = {s[0] for s in spans if s[1] == TID_COMPILE}
    req_ranks = {s[0] for s in spans if s[1] == TID_REQ}
    events = []
    for rank in sorted({r for r, _, _ in streams}):
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "tid": 0, "args": {"name": f"rank {rank}"}})
        events.append({"ph": "M", "name": "thread_name", "pid": rank,
                       "tid": TID_COMM, "args": {"name": "comm"}})
        events.append({"ph": "M", "name": "thread_name", "pid": rank,
                       "tid": TID_PHASE, "args": {"name": "phases"}})
        if rank in compile_ranks:
            events.append({"ph": "M", "name": "thread_name", "pid": rank,
                           "tid": TID_COMPILE,
                           "args": {"name": "compile"}})
        if rank in req_ranks:
            events.append({"ph": "M", "name": "thread_name", "pid": rank,
                           "tid": TID_REQ,
                           "args": {"name": "requests"}})
    for rank, tid, name, cat, start, dur, args in sorted(
        spans, key=lambda s: s[4]
    ):
        events.append({"ph": "X", "name": name, "cat": cat, "pid": rank,
                       "tid": tid, "ts": (start - t0) * _US,
                       "dur": dur * _US, "args": args})
    for rank, tid, name, cat, t, scope, args in sorted(
        instants, key=lambda s: s[4]
    ):
        events.append({"ph": "i", "name": name, "cat": cat, "pid": rank,
                       "tid": tid, "ts": (t - t0) * _US, "s": scope,
                       "args": args})
    # counter tracks ("C" events): one track per (rank, name) — memory
    # watermarks (one series per device, or the census-only live-bytes
    # series) and the cumulative per-neighbor traffic-matrix bytes
    for rank, name, t, series in sorted(counters, key=lambda c: c[2]):
        cat = ("traffic"
               if name in ("comm bytes sent", "comm bytes by link")
               else "mem")
        events.append({"ph": "C", "name": name, "cat": cat, "pid": rank,
                       "tid": 0, "ts": (t - t0) * _US, "args": series})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "files": list(files),
            "t0_unix_s": t0,
            "unplaced_records": unplaced,
            "clock_offsets_s": {
                str(r): off for r, off, _ in streams
            },
        },
    }


def placed_events(doc: dict) -> int:
    """Placed (non-metadata) event count of a trace document."""
    return sum(1 for e in doc["traceEvents"] if e["ph"] != "M")


def write_trace(
    files: list[str], out_path: str, run_sync_us: int | None = None
) -> int:
    """Merge ``files`` and write the trace document to ``out_path``.
    Returns the number of placed events (metadata excluded)."""
    doc = chrome_trace(files, run_sync_us)
    Path(out_path).write_text(json.dumps(doc))
    return placed_events(doc)


# ---------------------------------------------------------------------------
# terminal fallback: ASCII swimlane + per-step skew (tpumt-report --timeline)
# ---------------------------------------------------------------------------


def _bar(start: float, end: float, lo: float, hi: float,
         width: int) -> str:
    """One swimlane cell: ``#`` over [start, end) on the [lo, hi) axis,
    at least one ``#`` so a short phase never disappears."""
    span = max(hi - lo, 1e-12)
    a = int((start - lo) / span * width)
    b = int((end - lo) / span * width)
    a = min(max(a, 0), width - 1)
    b = min(max(b, a + 1), width)
    return "." * a + "#" * (b - a) + "." * (width - b)


def ascii_swimlane(files: list[str], width: int = 64,
                   max_steps: int = 12) -> list[str]:
    """Compact per-phase swimlane + per-step comm-op start-skew series.

    One lane per rank per phase on the run's shared (offset-corrected)
    time axis; below, for every comm op seen on 2+ ranks, the per-step
    start-time skew (max − min across ranks of the k-th call's
    ``t_start``) — the barrier-skew series that shows *which step*
    desynchronized, not just that some step did."""
    streams = rank_streams(files)
    spans, _, _, unplaced = _collect(streams)
    ranks = sorted({r for r, _, _ in streams})
    phase_spans = [s for s in spans if s[1] == TID_PHASE]
    comm_spans = [s for s in spans if s[1] == TID_COMM]
    if not phase_spans and not comm_spans:
        return [
            "TIMELINE no timestamped records"
            + (f" ({unplaced} pre-timeline records without t_start)"
               if unplaced else "")
            + " — record with --telemetry --jsonl on this version"
        ]
    lo = min(s[4] for s in spans)
    hi = max(s[4] + s[5] for s in spans)
    lines = [
        f"TIMELINE ranks={len(ranks)} window={hi - lo:.6g}s "
        f"axis=[0, {hi - lo:.6g}]s ('#' spans, {width} cols)"
    ]
    if unplaced:
        lines.append(f"NOTE {unplaced} records without timestamps "
                     f"not drawn (pre-timeline JSONL)")

    # phase lanes, ordered by each phase's earliest appearance
    by_phase: dict[str, dict[int, tuple[float, float]]] = {}
    for rank, _, name, _, start, dur, _ in phase_spans:
        cur = by_phase.setdefault(name, {}).get(rank)
        end = start + dur
        if cur is None:
            by_phase[name][rank] = (start, end)
        else:  # several records per phase: draw the covering window
            by_phase[name][rank] = (min(cur[0], start), max(cur[1], end))
    for name in sorted(
        by_phase, key=lambda n: min(v[0] for v in by_phase[n].values())
    ):
        lines.append(f"PHASE {name}")
        for rank in ranks:
            if rank not in by_phase[name]:
                continue
            start, end = by_phase[name][rank]
            lines.append(
                f"  r{rank:<3d} |{_bar(start, end, lo, hi, width)}| "
                f"{end - start:.6g}s"
            )

    # per-step start-skew series per comm op
    op_starts: dict[str, dict[int, list[float]]] = {}
    for rank, _, name, _, start, _, _ in comm_spans:
        op_starts.setdefault(name, {}).setdefault(rank, []).append(start)
    for op in sorted(op_starts):
        per_rank = op_starts[op]
        if len(per_rank) < 2:
            continue
        for starts in per_rank.values():
            starts.sort()
        n_steps = min(len(s) for s in per_rank.values())
        skews = [
            (max(s[k] for s in per_rank.values())
             - min(s[k] for s in per_rank.values())) * 1e3
            for k in range(n_steps)
        ]
        worst = max(range(n_steps), key=skews.__getitem__)
        shown = " ".join(f"{v:.3g}" for v in skews[:max_steps])
        more = (f" ... ({n_steps - max_steps} more)"
                if n_steps > max_steps else "")
        lines.append(
            f"SKEW {op} start-skew ms over {n_steps} steps: {shown}{more}"
            f" | max {skews[worst]:.3g}ms @step {worst}"
        )
    return lines


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpumt-trace",
        description="merge per-rank telemetry JSONL into Chrome "
        "trace-event JSON (one track per rank, clock offsets applied); "
        "open the output in Perfetto (ui.perfetto.dev) or "
        "chrome://tracing",
    )
    p.add_argument(
        "files",
        nargs="+",
        help="per-rank JSONL files; an un-suffixed --jsonl base path "
        "expands to its .p<i> rank set",
    )
    p.add_argument(
        "-o", "--out",
        default="trace.json",
        help="output trace path (default trace.json)",
    )
    p.add_argument(
        "--stdout",
        action="store_true",
        help="write the trace document to stdout instead of --out",
    )
    args = p.parse_args(argv)

    files = [f for f in expand_rank_files(args.files) if Path(f).exists()]
    if not files:
        print("tpumt-trace: no input files found", file=sys.stderr)
        return 1
    if args.stdout:
        doc = chrome_trace(files)
        n = placed_events(doc)
        json.dump(doc, sys.stdout)
        print()
    else:
        n = write_trace(files, args.out)
        print(
            f"tpumt-trace: wrote {args.out}: {n} events from "
            f"{len(files)} files",
            file=sys.stderr,
        )
    if n == 0:
        print(
            "tpumt-trace: no timestamped records (pre-timeline JSONL?) "
            "— trace is valid but empty",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
