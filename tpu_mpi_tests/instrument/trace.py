"""Trace ranges and profiler gating.

NVTX named ranges (``daxpy_nvtx.cu:72-91``, ``mpi_daxpy_nvtx.cc:177-325``)
map to XProf/TensorBoard trace annotations; ``cudaProfilerStart/Stop`` +
``nsys -c cudaProfilerApi`` capture gating (``summit/run.sh:15-19``) maps to
``jax.profiler.start_trace/stop_trace`` around the region of interest.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace_range(name: str):
    """Named range visible in XProf traces (≅ nvtxRangePushA/Pop).

    Works both host-side (TraceAnnotation) and around traced code
    (named_scope names the XLA ops for the compiled trace).
    """
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


class ProfilerGate:
    """Capture gating (≅ cudaProfilerStart/Stop pairing with
    ``nsys profile -c cudaProfilerApi``).

    No-op unless constructed with a log dir, so drivers can leave the calls
    in unconditionally exactly like the reference leaves NVTX in all builds.
    """

    def __init__(self, logdir: str | None = None):
        self.logdir = logdir
        self.active = False

    def start(self):
        if self.logdir and not self.active:
            jax.profiler.start_trace(self.logdir)
            self.active = True

    def stop(self):
        if self.active:
            jax.profiler.stop_trace()
            self.active = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
