"""Structured result reporting: stable stdout lines + JSONL.

The reference's observability is machine-parseable printf lines redirected to
``out-<tag>.txt`` and averaged offline by ``avg.sh`` (SURVEY.md §5.5). The
framework keeps the exact line shapes (so the aggregation workflow survives)
and adds a JSONL sink per record for real tooling.

Line shapes preserved:
  ``<rank>/<size> SUM = <v>``            (``mpi_daxpy.cc:157``)
  ``TIME <phase> : <v>``                 (``mpi_daxpy_nvtx.cc:333-340``)
  ``TEST dim:<d>, <space>, buf:<b>; <t>, err=<e>``
                                         (``mpi_stencil2d_gt.cc:376-383,568``)
  ``<rank>/<size> exchange time <ms> ms`` (``mpi_stencil2d_sycl.cc:530``)
"""

from __future__ import annotations

import json
import sys
import threading
import time
from pathlib import Path
from typing import Any, IO


def rank_suffixed_path(path: str, proc_index: int) -> str:
    """``out.jsonl`` → ``out.p<i>.jsonl`` — one file per process.

    Multiple processes appending to one JSONL path interleave partial
    lines (plain ``open(.., "a")`` writes are not atomic across hosts), so
    multi-process runs write per-rank files; ``tpumt-report`` and
    ``tpu/avg.py`` glob the suffixed set back together."""
    p = Path(path)
    return str(p.with_suffix("")) + f".p{proc_index}" + p.suffix


class Reporter:
    """Rank-aware line + JSONL emitter.

    ``rank``/``size`` default to the process topology; drivers emulating
    multiple ranks in one process pass logical values. Banner lines
    (run-config prints) are rank-0 only, like the reference's
    (``mpi_stencil2d_gt.cc:682-688``).

    A context manager: ``with Reporter(...) as rep`` closes the JSONL
    file handle on exit (and flushes the telemetry summary when
    :meth:`attach_telemetry` opted in). ``proc_index``/``proc_count``
    describe the real process topology (as opposed to the logical
    ``rank``/``size``, which may be emulated): with more than one process
    the JSONL path is auto-suffixed per process so ranks never corrupt a
    shared file.
    """

    def __init__(
        self,
        rank: int = 0,
        size: int = 1,
        jsonl_path: str | None = None,
        stream: IO[str] | None = None,
        proc_index: int = 0,
        proc_count: int = 1,
        trace_out: str | None = None,
    ):
        self.rank = rank
        self.size = size
        self.proc_index = proc_index
        # pre-suffix base path: the trace merge globs the whole rank set
        # from it (the suffixed path would find only this rank's file)
        self._jsonl_base = jsonl_path
        if jsonl_path and proc_count > 1:
            jsonl_path = rank_suffixed_path(jsonl_path, proc_index)
        self.jsonl_path = jsonl_path
        self.trace_out = trace_out
        self.stream = stream or sys.stdout
        self._jsonl_file: IO[str] | None = None
        self._jsonl_lock = threading.Lock()
        self._telemetry = False
        self._memwatch = None
        self._metrics = None
        self._live: list = []
        self._created_at = time.time()  # trace merge excludes older files
        # this run's clock_sync identity (set by make_reporter): the
        # trace merge uses it to recognize same-run sibling rank files
        self.run_sync_us: int | None = None

    def __enter__(self) -> "Reporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def line(self, text: str, record: dict[str, Any] | None = None):
        print(text, file=self.stream, flush=True)
        if record is not None:
            self.jsonl(record)

    def banner(self, text: str):
        if self.rank == 0:
            self.line(text)

    def sum_line(self, value: float, label: str = "SUM", rank=None):
        r = self.rank if rank is None else rank
        self.line(
            f"{r}/{self.size} {label} = {value:f}",
            {"kind": "sum", "label": label, "rank": r, "size": self.size,
             "value": float(value)},
        )

    def time_line(self, phase: str, seconds: float,
                  t_start: float | None = None, t_end: float | None = None):
        """One ``TIME`` line + ``time`` record. ``t_start``/``t_end`` are
        the phase's wall-clock bounds (``PhaseTimer.wall_span``); when the
        caller has none they are synthesized as ``[now - seconds, now]``
        so every ``time`` record is placeable on the merged timeline —
        exact when emitted right after the phase, and never worse than
        the pre-timeline records that carried no placement at all."""
        if t_end is None:
            t_end = time.time()
        if t_start is None:
            t_start = t_end - seconds
        self.line(
            f"TIME {phase} : {seconds:0.6f}",
            {"kind": "time", "phase": phase, "seconds": float(seconds),
             "t_start": t_start, "t_end": t_end, "rank": self.rank},
        )

    def test_line(self, dim: int, space: str, buf, seconds: float, err: float,
                  extra_label: str | None = None, show_err: bool = True):
        space_s = f"{space:7s}"
        if extra_label:
            # labeled variants: `allreduce=<t>` keeps the reference shape
            # (mpi_stencil2d_gt.cc:645-648, show_err=False); `fused=<t>,
            # err=<e>` marks fused exchange+stencil totals so aggregation
            # never conflates them with exchange-only TEST lines
            text = (f"TEST dim:{dim}, {space_s}, buf:{int(buf)}; "
                    f"{extra_label}={seconds:f}")
            if show_err:
                text += f", err={err:e}"
        else:
            text = (f"TEST dim:{dim}, {space_s}, buf:{int(buf)}; "
                    f"{seconds:f}, err={err:e}")
        self.line(
            text,
            {"kind": "test", "dim": dim, "space": space, "buf": int(buf),
             "seconds": float(seconds), "err": float(err),
             "label": extra_label},
        )

    def iter_line(self, dim: int, space: str, buf, phase: str,
                  mean_s: float, min_s: float, max_s: float):
        """Per-iteration timing distribution past warmup (≅ the per-iter
        ``clock_gettime`` accumulation of ``mpi_stencil2d_gt.cc:512-526``,
        extended with min/max so a slow link shows up as jitter)."""
        space_s = f"{space:7s}"
        self.line(
            f"ITER dim:{dim}, {space_s}, buf:{int(buf)}; {phase} "
            f"mean={mean_s:e}, min={min_s:e}, max={max_s:e}",
            {"kind": "iter", "dim": dim, "space": space, "buf": int(buf),
             "phase": phase, "mean_s": float(mean_s),
             "min_s": float(min_s), "max_s": float(max_s)},
        )

    def exchange_line(self, ms_per_iter: float, rank=None):
        r = self.rank if rank is None else rank
        self.line(
            f"{r}/{self.size} exchange time {ms_per_iter:0.8f} ms",
            {"kind": "exchange", "rank": r, "size": self.size,
             "ms_per_iter": float(ms_per_iter)},
        )

    def time_lines(self, timer, stats: bool = False):
        """Emit one ``TIME`` line per accumulated phase of a
        :class:`~tpu_mpi_tests.instrument.timers.PhaseTimer`; with
        ``stats`` the line carries count/mean/min/max (the per-iteration
        distribution the timer already collects), and the JSONL ``time``
        record always carries them — jitter is diagnosable offline even
        when the stdout stays in the reference's terse shape."""
        for text in timer.lines(stats=stats):
            print(text, file=self.stream, flush=True)
        for name in timer.seconds:
            # wall + monotonic phase bounds (getattr: duck-typed timers
            # without the round-2 timestamp fields still report)
            self.jsonl(
                {"kind": "time", "phase": name,
                 "seconds": float(timer.seconds[name]),
                 "count": timer.counts[name],
                 "mean_s": timer.mean(name),
                 "min_s": timer.mins.get(name, 0.0),
                 "max_s": timer.maxs.get(name, 0.0),
                 "t_start": getattr(timer, "t_starts", {}).get(name),
                 "t_end": getattr(timer, "t_ends", {}).get(name),
                 "mono_start": getattr(timer, "mono_starts", {}).get(name),
                 "mono_end": getattr(timer, "mono_ends", {}).get(name),
                 "rank": self.rank,
                 # annotated extras (PhaseTimer.annotate): overlap_frac
                 # and friends ride the phase record they describe
                 **getattr(timer, "extras", {}).get(name, {})}
            )

    def attach_telemetry(self):
        """Opt in to flushing the telemetry registry on close: per-op
        counter lines + ``telemetry_summary`` JSONL records, then the
        registry is disabled (its sink points at this reporter)."""
        self._telemetry = True

    def attach_memwatch(self, memwatch):
        """Own a running :class:`~tpu_mpi_tests.instrument.memwatch.
        MemWatch`: closing the reporter stops its sampler (emitting the
        final census record) before the JSONL file closes."""
        self._memwatch = memwatch

    def attach_metrics(self, registry):
        """Tee every record this reporter emits into a live
        :class:`~tpu_mpi_tests.instrument.metrics.MetricsRegistry` —
        the zero-new-call-sites contract of the live observability
        plane: whatever already flows to JSONL also updates the named
        series. A reporter without a registry pays one ``None`` check."""
        # attached during single-threaded reporter setup, BEFORE any
        # live thread exists (heartbeat/memwatch start later in
        # make_reporter/_arm_metrics); jsonl's unlocked read on a live
        # thread sees either None or the final binding — never a torn
        # value (attribute stores are atomic under the GIL)
        self._metrics = registry  # tpumt: ignore[TPM1601]

    @property
    def metrics(self):
        """The attached live MetricsRegistry, or None — the re-tune
        controller wires its tune_stale subscription through this."""
        return self._metrics

    def attach_live(self, *stoppables):
        """Own live-plane components (heartbeat thread, metrics
        exporter, phase-progress hook): closing the reporter calls
        ``stop()`` on each — in attach order, BEFORE the JSONL file
        closes, so final heartbeats/snapshots still land in the
        stream."""
        self._live.extend(stoppables)

    def jsonl(self, record: dict[str, Any]):
        # the live-metrics tee runs OUTSIDE the lock (observe is
        # internally locked, and a tune_stale health record emitted from
        # inside observe re-enters jsonl — holding the lock here would
        # deadlock that path) and BEFORE the path check, so metrics work
        # even when no JSONL file was configured
        if self._metrics is not None:
            try:
                self._metrics.observe(record)
            except Exception:
                pass
        # serialized under a lock and written as ONE write() call: the
        # watchdog emits its timeline record from a timer thread, and an
        # interleaved json.dump (many small writes) with a main-thread
        # span record would corrupt both lines
        if not self.jsonl_path:
            return
        line = json.dumps(record) + "\n"
        with self._jsonl_lock:
            if self._jsonl_file is None:
                self._jsonl_file = open(self.jsonl_path, "a")
            self._jsonl_file.write(line)
            self._jsonl_file.flush()

    def close(self):
        live, self._live = self._live, []
        for obj in live:
            try:
                obj.stop()  # final heartbeat/snapshot lands before close
            except Exception:
                pass
        if self._memwatch is not None:
            memwatch, self._memwatch = self._memwatch, None
            try:
                memwatch.stop()  # final mem record lands before close
            except Exception:
                pass
        if self._telemetry:
            self._telemetry = False
            from tpu_mpi_tests.instrument import telemetry as T

            for op, c in sorted(T.counters().items()):
                self.line(
                    f"TELEMETRY {op} : ops={c['ops']} bytes={c['bytes']} "
                    f"seconds={c['seconds']:0.6f}",
                    {"kind": "telemetry_summary", "op": op, "rank": self.rank,
                     **c},
                )
            T.disable()
        with self._jsonl_lock:
            if self._jsonl_file is not None:
                self._jsonl_file.close()
                self._jsonl_file = None
        self._write_trace()

    def _write_trace(self):
        """--trace-out auto-merge: after this rank's JSONL is closed,
        process 0 merges the rank set into Chrome trace-event JSON.
        Only THIS run's records are merged — the base-path glob would
        otherwise resurrect stale ``.p<i>`` siblings from an earlier run
        as ghost rank tracks, and append-mode JSONL can hold several
        runs per file. Run identity is the shared ``clock_sync``
        handshake stamp (``run_sync_us``, identical on every rank of one
        run): a file is included when ANY of its runs carries the stamp
        (reruns append — the current run need not be first), and the
        merger then selects exactly that run's segment per file. Files
        without any stamp (older format, or a run whose handshake was
        unavailable) fall back to an mtime window, which cannot
        distinguish a run finished seconds earlier. Still best-effort:
        sibling ranks that have not flushed yet contribute fewer events
        — re-run ``tpumt-trace`` offline for the complete/curated set."""
        if not self.trace_out or self.proc_index != 0:
            return
        self.trace_out, out = None, self.trace_out  # once per close
        if not self._jsonl_base:
            self.line(f"TRACE SKIPPED {out}: --trace-out needs --jsonl "
                      f"records to merge")
            return
        from tpu_mpi_tests.instrument.aggregate import expand_rank_files
        from tpu_mpi_tests.instrument.timeline import (
            file_in_run,
            write_trace,
        )

        def current(f: str) -> bool:
            if self.jsonl_path and Path(f) == Path(self.jsonl_path):
                return True  # this rank's own file
            # the shared ghost-track filter (timeline.file_in_run, also
            # used by tpumt-top / tpumt-doctor --follow): stamp match
            # first, mtime window only for stampless files
            return file_in_run(f, self.run_sync_us,
                               mtime_after=self._created_at - 5.0)

        files = [f for f in expand_rank_files([self._jsonl_base])
                 if Path(f).exists() and current(f)]
        try:
            n = write_trace(files, out, run_sync_us=self.run_sync_us)
        except OSError as e:
            self.line(f"TRACE ERROR {out}: {e}")
            return
        self.line(f"TRACE {out}: {n} events from {len(files)} "
                  f"file{'s' if len(files) != 1 else ''} "
                  f"(open in Perfetto / chrome://tracing)")
