"""``tpumt-report``: merge per-rank telemetry JSONL into one run summary.

The reference's whole aggregation story is ``avg.sh`` — grep a pattern in
``out-*.txt``, average the second field per file (``tpu/avg.py`` keeps that
contract). This CLI is the structured successor for the JSONL the Reporter
and the telemetry registry emit: given the per-rank files of one run (the
auto-suffixed ``base.p<i>.jsonl`` set, or explicit paths), it merges them
into:

* a run header from the rank-0 manifest record;
* per-phase stats across ranks (``kind: "time"`` records): mean/min/max of
  each rank's total seconds, plus the max/min skew;
* per-op stats across ranks (``kind: "span"`` records): op counts, total
  payload bytes, mean seconds, bandwidth percentiles (p10/p50/p90 over all
  ranks' spans), and skew of per-rank totals;
* straggler detection: any phase/op whose slowest rank exceeds the fastest
  by more than ``--skew-threshold`` (default 1.5×) is flagged with the
  offending rank — the cross-rank question avg.sh could never answer;
* a tuning table (``kind: "tune"/"tune_result"/"tune_hit"`` records from
  the autotuner's sweeps — README "Autotuning"): per knob, how many
  candidates were measured/skipped/errored, the persisted winner and its
  measured seconds, and how many later resolutions were pure cache hits.

Pure stdlib (no jax import): usable on a login node against files copied
off the pod. ``--json`` emits the summary as one JSON document instead of
text lines.
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
from pathlib import Path


def expand_rank_files(paths: list[str]) -> list[str]:
    """Resolve CLI paths to the per-rank file set.

    Each path expands to: the literal file if it exists, plus any
    ``<stem>.p<i><suffix>`` siblings the multi-process Reporter suffixing
    produced (so passing the un-suffixed ``--jsonl`` base path finds the
    whole set). Globs pass through. Order is deterministic (sorted)."""
    out: list[str] = []
    for p in paths:
        hits = set(glob.glob(p))
        path = Path(p)
        hits.update(glob.glob(str(path.with_suffix("")) + ".p*" + path.suffix))
        out.extend(sorted(hits) or [p])
    # dedupe, keep order
    seen: set[str] = set()
    return [f for f in out if not (f in seen or seen.add(f))]


def _load_records(path: str) -> list[dict]:
    records = []
    try:
        text = Path(path).read_text()
    except OSError as e:
        print(f"tpumt-report: cannot open {path}: {e}", file=sys.stderr)
        return records
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on pre-sorted values."""
    if not sorted_vals:
        return float("nan")
    idx = min(
        len(sorted_vals) - 1, max(0, round(q / 100 * (len(sorted_vals) - 1)))
    )
    return sorted_vals[idx]


def _skew(per_rank_totals: dict) -> tuple[float, int | None]:
    """(max/min ratio, rank holding the max) over per-rank totals."""
    vals = {r: t for r, t in per_rank_totals.items() if t > 0}
    if len(vals) < 2:
        return 1.0, None
    worst = max(vals, key=vals.get)
    return vals[worst] / min(vals.values()), worst


def summarize(files: list[str]) -> dict:
    """Merge per-rank record streams into the summary structure."""
    manifest = None
    manifests = 0
    phases: dict[str, dict] = {}
    ops: dict[str, dict] = {}
    tuning: dict[str, dict] = {}

    for file_idx, path in enumerate(files):
        file_rank = file_idx
        for rec in _load_records(path):
            kind = rec.get("kind")
            if kind == "manifest":
                manifests += 1
                file_rank = rec.get("process_index", file_rank)
                if manifest is None or rec.get("process_index") == 0:
                    manifest = rec
            elif kind == "time":
                rank = rec.get("rank", file_rank)
                secs = float(rec.get("seconds", 0.0))
                ph = phases.setdefault(
                    rec.get("phase", "?"), {"per_rank": {}, "count": 0}
                )
                ph["per_rank"][rank] = ph["per_rank"].get(rank, 0.0) + secs
                ph["count"] += 1
            elif kind == "span":
                rank = rec.get("rank", file_rank)
                secs = float(rec.get("seconds") or 0.0)
                op = ops.setdefault(
                    rec.get("op", "?"),
                    {"per_rank": {}, "ops": 0, "bytes": 0, "gbps": []},
                )
                op["per_rank"][rank] = op["per_rank"].get(rank, 0.0) + secs
                op["ops"] += 1
                op["bytes"] += int(rec.get("nbytes") or 0)
                if rec.get("gbps"):
                    op["gbps"].append(float(rec["gbps"]))
            elif kind in ("tune", "tune_result", "tune_hit"):
                t = tuning.setdefault(
                    rec.get("knob", "?"),
                    {"measured": 0, "skipped": 0, "errors": 0,
                     "invalid": 0, "hits": 0,
                     "winner": None, "winner_seconds": None},
                )
                if kind == "tune":
                    if rec.get("skipped"):
                        t["skipped"] += 1
                    elif rec.get("error") is not None:
                        t["errors"] += 1
                    elif rec.get("seconds") is not None:
                        t["measured"] += 1
                    else:
                        # NaN measurement: seconds=null with no error —
                        # invalid, never countable as measured
                        t["invalid"] += 1
                elif kind == "tune_result":
                    t["winner"] = rec.get("value")
                    t["winner_seconds"] = rec.get("seconds")
                else:  # tune_hit: a resolution served from the cache
                    t["hits"] += 1
                    if t["winner"] is None:
                        t["winner"] = rec.get("value")

    def _stats(per_rank: dict) -> dict:
        vals = list(per_rank.values())
        skew, worst = _skew(per_rank)
        return {
            "ranks": len(per_rank),
            "mean_s": sum(vals) / len(vals) if vals else 0.0,
            "min_s": min(vals) if vals else 0.0,
            "max_s": max(vals) if vals else 0.0,
            "skew": skew,
            "straggler_rank": worst,
            "per_rank_s": {str(r): per_rank[r] for r in sorted(per_rank)},
        }

    summary = {
        "files": list(files),
        "manifest": manifest,
        "manifest_count": manifests,
        "phases": {},
        "ops": {},
        "tuning": {name: tuning[name] for name in sorted(tuning)},
    }
    for name in sorted(phases):
        summary["phases"][name] = {
            "count": phases[name]["count"],
            **_stats(phases[name]["per_rank"]),
        }
    for name in sorted(ops):
        o = ops[name]
        gbps = sorted(o["gbps"])
        summary["ops"][name] = {
            "ops": o["ops"],
            "bytes": o["bytes"],
            "gbps_p10": _percentile(gbps, 10),
            "gbps_p50": _percentile(gbps, 50),
            "gbps_p90": _percentile(gbps, 90),
            **_stats(o["per_rank"]),
        }
    return summary


def _print_text(summary: dict, skew_threshold: float) -> None:
    m = summary["manifest"]
    if m:
        kinds = ",".join(m.get("device_kinds", []))
        print(
            f"RUN {m.get('platform', '?')}x{m.get('global_device_count', 0)}"
            f" ({kinds}) procs={m.get('process_count', 1)}"
            f" jax={m.get('jax', '?')} git={m.get('git_sha') or 'unknown'}"
        )
        print(f"ARGV {' '.join(m.get('argv', []))}")
    print(f"FILES {len(summary['files'])}: {' '.join(summary['files'])}")

    for name, ph in summary["phases"].items():
        print(
            f"PHASE {name}: ranks={ph['ranks']} n={ph['count']} "
            f"mean={ph['mean_s']:.6g} min={ph['min_s']:.6g} "
            f"max={ph['max_s']:.6g} skew={ph['skew']:.3g}"
        )
    for name, op in summary["ops"].items():
        gb = (
            f" gbps p10/p50/p90={op['gbps_p10']:.4g}/"
            f"{op['gbps_p50']:.4g}/{op['gbps_p90']:.4g}"
            if op["gbps_p50"] == op["gbps_p50"]  # not NaN
            else ""
        )
        print(
            f"OP {name}: ranks={op['ranks']} ops={op['ops']} "
            f"bytes={op['bytes']} mean={op['mean_s']:.6g} "
            f"min={op['min_s']:.6g} max={op['max_s']:.6g} "
            f"skew={op['skew']:.3g}{gb}"
        )

    for name, t in summary.get("tuning", {}).items():
        sec = t["winner_seconds"]
        print(
            f"TUNE {name}: winner={json.dumps(t['winner'])} "
            f"seconds={'-' if sec is None else format(sec, '.6g')} "
            f"measured={t['measured']} skipped={t['skipped']} "
            f"errors={t['errors']} invalid={t['invalid']} "
            f"cache_hits={t['hits']}"
        )

    stragglers = 0
    for label, table in (("PHASE", summary["phases"]),
                         ("OP", summary["ops"])):
        for name, st in table.items():
            if st["skew"] > skew_threshold and st["straggler_rank"] is not None:
                stragglers += 1
                print(
                    f"STRAGGLER {label} {name}: rank "
                    f"{st['straggler_rank']} is {st['skew']:.3g}x the "
                    f"fastest rank ({st['max_s']:.6g}s vs {st['min_s']:.6g}s)"
                )
    if not stragglers:
        print(f"OK no stragglers above {skew_threshold:g}x")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpumt-report",
        description="merge per-rank telemetry JSONL into a run summary "
        "(per-phase/per-op cross-rank stats + straggler detection)",
    )
    p.add_argument(
        "files",
        nargs="+",
        help="per-rank JSONL files; an un-suffixed --jsonl base path "
        "expands to its .p<i> rank set",
    )
    p.add_argument(
        "--skew-threshold",
        type=float,
        default=1.5,
        metavar="X",
        help="flag a phase/op when max rank time > X * min (default 1.5)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="emit the summary as one JSON document instead of text",
    )
    p.add_argument(
        "--timeline",
        action="store_true",
        help="render a per-phase ASCII swimlane (one lane per rank, "
        "clock offsets applied) plus per-step comm-op start-skew "
        "series instead of the stats summary; needs the timestamped "
        "records new runs emit (instrument/timeline.py renders; "
        "tpumt-trace exports the same merge for Perfetto)",
    )
    p.add_argument(
        "--width",
        type=int,
        default=64,
        metavar="COLS",
        help="swimlane width in columns for --timeline (default 64)",
    )
    args = p.parse_args(argv)

    files = [f for f in expand_rank_files(args.files) if Path(f).exists()]
    if not files:
        print("tpumt-report: no input files found", file=sys.stderr)
        return 1
    if args.timeline:
        from tpu_mpi_tests.instrument.timeline import ascii_swimlane

        for line in ascii_swimlane(files, width=max(args.width, 8)):
            print(line)
        return 0
    summary = summarize(files)
    if args.json:
        json.dump(summary, sys.stdout, indent=1)
        print()
    else:
        _print_text(summary, args.skew_threshold)
    return 0


if __name__ == "__main__":
    sys.exit(main())
