"""``tpumt-report``: merge per-rank telemetry JSONL into one run summary.

The reference's whole aggregation story is ``avg.sh`` — grep a pattern in
``out-*.txt``, average the second field per file (``tpu/avg.py`` keeps that
contract). This CLI is the structured successor for the JSONL the Reporter
and the telemetry registry emit: given the per-rank files of one run (the
auto-suffixed ``base.p<i>.jsonl`` set, or explicit paths), it merges them
into:

* a run header from the rank-0 manifest record;
* per-phase stats across ranks (``kind: "time"`` records): mean/min/max of
  each rank's total seconds, plus the max/min skew;
* per-op stats across ranks (``kind: "span"`` records): op counts, total
  payload bytes, mean seconds, bandwidth percentiles (p10/p50/p90 over all
  ranks' spans), and skew of per-rank totals;
* straggler detection: any phase/op whose slowest rank exceeds the fastest
  by more than ``--skew-threshold`` (default 1.5×) is flagged with the
  offending rank — the cross-rank question avg.sh could never answer;
* a tuning table (``kind: "tune"/"tune_result"/"tune_hit"`` records from
  the autotuner's sweeps — README "Autotuning"): per knob, how many
  candidates were measured/skipped/errored, the persisted winner and its
  measured seconds, and how many later resolutions were pure cache hits;
* a MEMORY table (``kind: "mem"`` records from ``--memwatch`` —
  instrument/memwatch.py): per-phase peak/delta HBM watermarks, the
  run-wide peak, and the top live shape·dtype buffer buckets;
* a COMPILE table (``kind: "compile"`` records from the AOT probe —
  instrument/costs.py): per-fn compile wall time, the compiler's
  flops/bytes-accessed/temp-allocation model, and — joined against the
  measured span/phase seconds — the model-implied achieved GB/s plus
  roofline utilization where the device's peak bandwidth is known;
* a VMEM table (``kind: "vmem"`` records from ``tpu/vmemprobe.py``):
  model-vs-actual scoped-VMEM per kernel config, under-estimates
  flagged UNSAFE;
* an OVERLAP table (``kind: "overlap"`` records + annotated phase
  records from the overlap engine — ``comm/halo.py`` OverlapRunner,
  README "Overlap engine"): per pipelined op, the resolved depth and
  the measured wall overlap between in-flight comm spans and the
  interior-compute phase (``overlap_frac`` — 0.000 on a depth-1 run,
  rendered either way); the driver bench rows (``kind: "attn"``/
  ``"heat"``) aggregate alongside as BENCH lines so ``--diff`` can
  gate them;
* an SLO table (``kind: "serve"`` records from the serving loop —
  ``drivers/serve.py`` / ``tpu_mpi_tests/serve/``): per workload class,
  offered vs achieved request rate, p50/p95/p99 latency, queue depth,
  and error/shed counts; the cross-window spread of the per-window
  records doubles as the ``--diff`` noise band for the percentiles;
* a ROUTE table (``kind: "route"`` records from the MoE routing
  collective — ``comm/moe.py``): per routed op, token/capacity
  accounting — occupancy %, overflow (dropped) %, per-expert imbalance
  — with ``--diff`` gating overflow and imbalance lower-is-better
  (README "Reading the ROUTE table");
* DECODE rows (``kind: "decode"`` records from the decode-collective
  pillar — ``workloads/decode.py``): µs/op latency per (collective,
  batch×heads), gated lower-is-better by ``--diff`` — the
  latency-bound regime where GB/s tables are blind;
* WORKLOAD rows (``kind: "workload"`` records — the spec runner's
  stable bench row, ``workloads/runner.py``): one headline metric per
  workload spec, regression direction carried by the record itself;
* a CONTROL table (``kind: "control"`` records from the serve loop's
  online re-tune controller — ``tune/controller.py``, README "Fleet
  tuning"): per re-tuned class, how many ``tune_swap``s fired, the
  old/new winner, the sag that triggered each, and the re-sweep
  seconds — the controller's actions made auditable post-mortem.

``--diff A B`` compares two runs instead: two JSONL sets (per-phase /
per-op / memory metrics) or two bench JSON files (``bench.py`` output or
the driver-captured ``BENCH_r*.json`` wrappers), flagging changes beyond
the cross-sample noise band and exiting 1 when a regression is found.

Pure stdlib (no jax import): usable on a login node against files copied
off the pod. ``--json`` emits the summary as one JSON document instead of
text lines.
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
from pathlib import Path


def expand_rank_files(paths: list[str]) -> list[str]:
    """Resolve CLI paths to the per-rank file set.

    Each path expands to: the literal file if it exists, plus any
    ``<stem>.p<i><suffix>`` siblings the multi-process Reporter suffixing
    produced (so passing the un-suffixed ``--jsonl`` base path finds the
    whole set). Globs pass through. Order is deterministic (sorted)."""
    out: list[str] = []
    for p in paths:
        hits = set(glob.glob(p))
        path = Path(p)
        hits.update(glob.glob(str(path.with_suffix("")) + ".p*" + path.suffix))
        out.extend(sorted(hits) or [p])
    # dedupe, keep order
    seen: set[str] = set()
    return [f for f in out if not (f in seen or seen.add(f))]


def _load_records(path: str) -> list[dict]:
    """One parser for the record format repo-wide: delegates to
    diagnose.load_with_lines (lazy import — diagnose imports this
    module) and drops the line numbers."""
    from tpu_mpi_tests.instrument.diagnose import load_with_lines

    return [r for _, r in load_with_lines(path, prog="tpumt-report")]


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on pre-sorted values."""
    if not sorted_vals:
        return float("nan")
    idx = min(
        len(sorted_vals) - 1, max(0, round(q / 100 * (len(sorted_vals) - 1)))
    )
    return sorted_vals[idx]


def _skew(per_rank_totals: dict) -> tuple[float, int | None]:
    """(max/min ratio, rank holding the max) over per-rank totals."""
    vals = {r: t for r, t in per_rank_totals.items() if t > 0}
    if len(vals) < 2:
        return 1.0, None
    worst = max(vals, key=vals.get)
    return vals[worst] / min(vals.values()), worst


def _merge_mem(memory: dict, rec: dict, rank) -> None:
    """Fold one ``kind: "mem"`` record into the MEMORY accumulator:
    run-wide watermark maxima (with the holding rank), per-phase
    peak/delta from the phase-boundary records, and the live-buffer
    bucket maxima from the censuses."""
    memory["records"] += 1
    for key in ("bytes_in_use", "peak_bytes_in_use", "live_bytes"):
        v = rec.get(key)
        if isinstance(v, (int, float)):
            cur = memory["peak"].get(key)
            if cur is None or v > cur["bytes"]:
                memory["peak"][key] = {"bytes": int(v), "rank": rank}
    if rec.get("event") == "phase" and rec.get("phase"):
        ph = memory["phases"].setdefault(
            rec["phase"],
            {"peak_bytes": None, "delta_bytes": None, "peak_delta": None,
             "records": 0, "_ranks": set()},
        )
        ph["records"] += 1
        ph["_ranks"].add(rank)
        peak = rec.get("peak_bytes_in_use", rec.get("live_bytes"))
        if isinstance(peak, (int, float)):
            ph["peak_bytes"] = max(ph["peak_bytes"] or 0, int(peak))
        for key, field in (("delta_bytes", "delta_bytes"),
                           ("peak_delta", "peak_delta")):
            v = rec.get(field)
            if isinstance(v, (int, float)):
                cur = ph[key]
                ph[key] = int(v) if cur is None else max(cur, int(v))
    census = rec.get("census") or {}
    for entry in census.get("top", []):
        key = entry.get("key")
        b = entry.get("bytes")
        if not key or not isinstance(b, (int, float)):
            continue
        cur = memory["top"].get(key)
        if cur is None or b > cur["bytes"]:
            memory["top"][key] = {
                "bytes": int(b),
                "count": int(entry.get("count") or 0),
                "rank": rank,
            }


def summarize(
    files: list[str],
    loaded: dict[str, list[tuple[int, dict]]] | None = None,
) -> dict:
    """Merge per-rank record streams into the summary structure.
    ``loaded`` is pre-parsed ``diagnose.load_with_lines`` output so
    ``main`` parses each file once for both the report and the
    DIAGNOSIS table."""
    manifest = None
    manifests = 0
    rank_indices: set = set()
    expected_ranks = 0
    phases: dict[str, dict] = {}
    ops: dict[str, dict] = {}
    tuning: dict[str, dict] = {}
    memory: dict = {"phases": {}, "peak": {}, "top": {}, "records": 0}
    compiles: dict[str, dict] = {}
    vmem: dict[str, dict] = {}
    serve: dict[str, dict] = {}
    overlap: dict[str, dict] = {}
    bench_rows: dict[str, list] = {}
    route: dict[str, dict] = {}
    decode: dict[str, dict] = {}
    workload: dict[str, dict] = {}
    control: dict[str, dict] = {}
    traffic: dict | None = None
    topo: dict | None = None

    for file_idx, path in enumerate(files):
        file_rank = file_idx
        pairs = (loaded or {}).get(path)
        records = ([r for _, r in pairs] if pairs is not None
                   else _load_records(path))
        for rec in records:
            kind = rec.get("kind")
            if kind == "manifest":
                manifests += 1
                file_rank = rec.get("process_index", file_rank)
                rank_indices.add(file_rank)
                expected_ranks = max(
                    expected_ranks, int(rec.get("process_count") or 0)
                )
                if manifest is None or rec.get("process_index") == 0:
                    manifest = rec
            elif kind == "topo":
                # manifest-adjacent topology audit record
                # (comm/topology.py): one per rank, SPMD-identical —
                # first wins; absent entirely on pre-topo files
                if topo is None:
                    topo = rec
            elif kind == "time":
                if rec.get("event") == "progress":
                    # live cumulative snapshots (metrics plane) repeat
                    # the running total every interval — summing them
                    # with the final PhaseTimer record would multiply
                    # every phase total; the final record is the truth
                    continue
                rank = rec.get("rank", file_rank)
                secs = float(rec.get("seconds", 0.0))
                ph = phases.setdefault(
                    rec.get("phase", "?"),
                    {"per_rank": {}, "count": 0,
                     "call_count": 0, "call_seconds": 0.0},
                )
                ph["per_rank"][rank] = ph["per_rank"].get(rank, 0.0) + secs
                ph["count"] += 1
                # per-call denominator for the COMPILE roofline join: a
                # PhaseTimer record's `count` is its iteration count
                ph["call_count"] += int(rec.get("count") or 1)
                ph["call_seconds"] += secs
                # overlap-engine annotations (PhaseTimer.annotate):
                # carried per rank so the phase summary can report the
                # measured comm/compute overlap of the pipelined phase
                if isinstance(rec.get("overlap_frac"), (int, float)):
                    ph.setdefault("ov_frac", {})[rank] = float(
                        rec["overlap_frac"]
                    )
                if rec.get("overlap_depth") is not None:
                    ph["ov_depth"] = rec["overlap_depth"]
            elif kind == "span":
                rank = rec.get("rank", file_rank)
                secs = float(rec.get("seconds") or 0.0)
                # dispatch-window spans (AsyncSpan: dispatch → drain,
                # NOT a sync-honest op duration) aggregate under their
                # own [async] row — merging them with sync spans would
                # corrupt the op's seconds and GB/s percentiles
                op_name = rec.get("op", "?") + (
                    "[async]" if rec.get("async") else ""
                )
                op = ops.setdefault(
                    op_name,
                    {"per_rank": {}, "ops": 0, "bytes": 0, "gbps": []},
                )
                op["per_rank"][rank] = op["per_rank"].get(rank, 0.0) + secs
                op["ops"] += 1
                op["bytes"] += int(rec.get("nbytes") or 0)
                if rec.get("gbps"):
                    op["gbps"].append(float(rec["gbps"]))
            elif kind in ("tune", "tune_result", "tune_hit"):
                t = tuning.setdefault(
                    rec.get("knob", "?"),
                    {"measured": 0, "skipped": 0, "errors": 0,
                     "invalid": 0, "hits": 0,
                     "winner": None, "winner_seconds": None},
                )
                if kind == "tune":
                    if rec.get("skipped"):
                        t["skipped"] += 1
                    elif rec.get("error") is not None:
                        t["errors"] += 1
                    elif rec.get("seconds") is not None:
                        t["measured"] += 1
                    else:
                        # NaN measurement: seconds=null with no error —
                        # invalid, never countable as measured
                        t["invalid"] += 1
                elif kind == "tune_result":
                    t["winner"] = rec.get("value")
                    t["winner_seconds"] = rec.get("seconds")
                else:  # tune_hit: a resolution served from the cache
                    t["hits"] += 1
                    if t["winner"] is None:
                        t["winner"] = rec.get("value")
            elif kind == "mem":
                _merge_mem(memory, rec, rec.get("rank", file_rank))
            elif kind == "compile":
                c = compiles.setdefault(
                    rec.get("label", "?"),
                    {"compiles": 0, "seconds": 0.0, "phase": None,
                     "flops": None, "bytes_accessed": None,
                     "temp_bytes": None, "output_bytes": None,
                     "peak_gbps": None, "fingerprint": None,
                     "_ba_seen": set()},
                )
                c["compiles"] += 1
                c["seconds"] += float(rec.get("seconds") or 0.0)
                for k in ("phase", "flops", "bytes_accessed",
                          "temp_bytes", "output_bytes", "peak_gbps",
                          "fingerprint"):
                    if rec.get(k) is not None:
                        c[k] = rec[k]
                if rec.get("bytes_accessed") is not None:
                    c["_ba_seen"].add(float(rec["bytes_accessed"]))
            elif kind == "vmem":
                v = vmem.setdefault(rec.get("config", "?"), {})
                for k in ("model_bytes", "actual_bytes", "ratio",
                          "error"):
                    if rec.get(k) is not None:
                        v[k] = rec[k]
            elif kind == "overlap":
                rank = rec.get("rank", file_rank)
                ov = overlap.setdefault(
                    rec.get("op", "?"),
                    {"depth": None, "frac": {}, "rate": {},
                     "rate_unit": None, "comm_s": 0.0, "compute_s": 0.0,
                     "drain_s": 0.0, "steps": 0, "tier": None},
                )
                if rec.get("depth") is not None:
                    ov["depth"] = rec["depth"]
                if rec.get("tier") is not None:
                    # ISSUE 15: the fused tier's kernel-level records
                    # name their tier; the row keeps it so OVERLAP
                    # numbers stay attributable to a kernel schedule
                    ov["tier"] = rec["tier"]
                if isinstance(rec.get("overlap_frac"), (int, float)):
                    ov["frac"][rank] = float(rec["overlap_frac"])
                for key, unit in (("it_per_s", "it/s"),
                                  ("steps_per_s", "steps/s")):
                    if isinstance(rec.get(key), (int, float)):
                        ov["rate"][rank] = float(rec[key])
                        ov["rate_unit"] = unit
                for key in ("comm_s", "compute_s", "drain_s"):
                    if isinstance(rec.get(key), (int, float)):
                        ov[key] += float(rec[key])
                ov["steps"] += int(rec.get("steps") or 0)
            elif kind == "attn":
                # driver bench rows become gated --diff series: a
                # schedule change that silently slows a tier must trip
                # the noise-band gate, not pass unobserved
                if isinstance(rec.get("tflops"), (int, float)):
                    key = (
                        f"attn:{rec.get('tier', '?')}"
                        + ("[striped]" if rec.get("stripe") else "")
                        + ":tflops"
                    )
                    bench_rows.setdefault(key, []).append(
                        float(rec["tflops"])
                    )
            elif kind == "heat":
                if isinstance(rec.get("steps_per_s"), (int, float)):
                    bench_rows.setdefault("heat:steps_per_s", []).append(
                        float(rec["steps_per_s"])
                    )
            elif kind == "route":
                rt = route.setdefault(
                    rec.get("op", "?"),
                    {"calls": 0, "tokens": 0, "routed": 0, "dropped": 0,
                     "overflow": [], "occupancy": [], "imbalance": [],
                     "capacity": None, "world": None, "combine": None},
                )
                rt["calls"] += 1
                for k in ("tokens", "routed", "dropped"):
                    rt[k] += int(rec.get(k) or 0)
                for k, dst in (("overflow_pct", "overflow"),
                               ("occupancy_pct", "occupancy"),
                               ("imbalance", "imbalance")):
                    if isinstance(rec.get(k), (int, float)):
                        rt[dst].append(float(rec[k]))
                for k in ("capacity", "world", "combine"):
                    if rec.get(k) is not None:
                        rt[k] = rec[k]
            elif kind == "decode":
                key = (f"{rec.get('collective', '?')}:"
                       f"{rec.get('batch', '?')}x{rec.get('heads', '?')}")
                d = decode.setdefault(
                    key, {"us": [], "shard_bytes": None, "world": None},
                )
                if isinstance(rec.get("us_per_op"), (int, float)):
                    d["us"].append(float(rec["us_per_op"]))
                for k in ("shard_bytes", "world"):
                    if rec.get(k) is not None:
                        d[k] = rec[k]
            elif kind == "workload":
                key = (f"{rec.get('workload', '?')}:"
                       f"{rec.get('metric', '?')}")
                wl = workload.setdefault(
                    key, {"vals": [], "unit": "", "higher_better": True},
                )
                if isinstance(rec.get("value"), (int, float)):
                    wl["vals"].append(float(rec["value"]))
                if rec.get("unit"):
                    wl["unit"] = rec["unit"]
                if rec.get("higher_better") is not None:
                    wl["higher_better"] = bool(rec["higher_better"])
            elif kind == "control":
                key = (f"{rec.get('class', '?')}|"
                       f"{rec.get('knob', '?')}")
                c = control.setdefault(
                    key, {"class": rec.get("class"),
                          "knob": rec.get("knob"),
                          "event": rec.get("event"),
                          "swaps": 0, "old": None, "new": None,
                          "signal": None, "sag_pct": [],
                          "resweep_s": 0.0},
                )
                c["swaps"] += 1
                if c["old"] is None:
                    c["old"] = rec.get("old")
                c["new"] = rec.get("new")
                if rec.get("signal") is not None:
                    c["signal"] = rec.get("signal")
                if isinstance(rec.get("sag_pct"), (int, float)):
                    c["sag_pct"].append(float(rec["sag_pct"]))
                if isinstance(rec.get("resweep_s"), (int, float)):
                    c["resweep_s"] += float(rec["resweep_s"])
            elif kind == "traffic":
                # the run's traffic identity (serve --record/--replay):
                # one per run — last wins, which is also correct for
                # append-mode reruns
                traffic = {
                    "event": rec.get("event"),
                    "fingerprint": rec.get("fingerprint"),
                    "count": rec.get("count"),
                    "duration_s": rec.get("duration_s"),
                    "classes": rec.get("classes"),
                    "path": rec.get("path"),
                }
            elif kind == "serve":
                sv = serve.setdefault(
                    rec.get("class", "?"),
                    {"workload": rec.get("workload"),
                     "dtype": rec.get("dtype"),
                     "summaries": {}, "windows": []},
                )
                rank = rec.get("rank", file_rank)
                if rec.get("event") == "summary":
                    # last summary per rank wins (append-mode reruns)
                    sv["summaries"][rank] = rec
                elif rec.get("event") == "window":
                    # quarantine/recover event records are lifecycle
                    # markers, not traffic windows — counting them
                    # here would inflate windows= and pollute the
                    # crashed-rank synthesis path
                    sv["windows"].append(dict(rec, rank=rank))

    def _stats(per_rank: dict) -> dict:
        vals = list(per_rank.values())
        skew, worst = _skew(per_rank)
        return {
            "ranks": len(per_rank),
            "mean_s": sum(vals) / len(vals) if vals else 0.0,
            "min_s": min(vals) if vals else 0.0,
            "max_s": max(vals) if vals else 0.0,
            "skew": skew,
            "straggler_rank": worst,
            "per_rank_s": {str(r): per_rank[r] for r in sorted(per_rank)},
        }

    for name, ph in memory["phases"].items():
        ph["ranks"] = len(ph.pop("_ranks"))
    memory["top"] = dict(sorted(
        memory["top"].items(), key=lambda kv: -kv[1]["bytes"]
    )[:8])

    summary = {
        "files": list(files),
        "manifest": manifest,
        "manifest_count": manifests,
        # topology audit record — key present ONLY when the run emitted
        # one (pre-topo files keep their exact --json shape)
        **({"topo": topo} if topo else {}),
        # rank-set completeness: which manifest ranks the merged file
        # set actually covers — a crashed rank whose file is missing
        # must be a visible NOTE (and a refused --diff baseline), not
        # a silently shrunk noise band
        "rank_set": {
            "expected": expected_ranks,
            "seen": sorted(rank_indices),
            "missing": sorted(
                set(range(expected_ranks)) - rank_indices
            ),
        },
        "phases": {},
        "ops": {},
        "tuning": {name: tuning[name] for name in sorted(tuning)},
        "memory": memory,
        "compile": {},
        "vmem": {name: vmem[name] for name in sorted(vmem)},
        "serve": {cls: _serve_row(serve[cls]) for cls in sorted(serve)},
        "traffic": traffic,
        "route": {op: _route_row(route[op]) for op in sorted(route)},
        "decode": {
            key: {"us_per_op": sum(d["us"]) / len(d["us"]),
                  "band": _noise_band(d["us"]), "n": len(d["us"]),
                  "shard_bytes": d["shard_bytes"], "world": d["world"]}
            for key, d in sorted(decode.items()) if d["us"]
        },
        "workload": {
            key: {"value": sum(w["vals"]) / len(w["vals"]),
                  "band": _noise_band(w["vals"]), "n": len(w["vals"]),
                  "unit": w["unit"], "higher_better": w["higher_better"]}
            for key, w in sorted(workload.items()) if w["vals"]
        },
        "overlap": {op: _overlap_row(overlap[op])
                    for op in sorted(overlap)},
        "control": {
            key: {**{f: c[f] for f in ("class", "knob", "event",
                                       "swaps", "old", "new",
                                       "signal", "resweep_s")},
                  "sag_pct": (sum(c["sag_pct"]) / len(c["sag_pct"])
                              if c["sag_pct"] else None)}
            for key, c in sorted(control.items())
        },
        "bench": {
            key: {"value": sum(vals) / len(vals),
                  "band": _noise_band(vals), "n": len(vals)}
            for key, vals in sorted(bench_rows.items())
        },
    }
    for name in sorted(phases):
        ph = phases[name]
        summary["phases"][name] = {
            "count": ph["count"],
            "mean_call_s": (ph["call_seconds"] / ph["call_count"]
                            if ph["call_count"] else 0.0),
            **_stats(ph["per_rank"]),
        }
        if "ov_frac" in ph:
            fracs = list(ph["ov_frac"].values())
            summary["phases"][name]["overlap_frac"] = (
                sum(fracs) / len(fracs)
            )
            if ph.get("ov_depth") is not None:
                summary["phases"][name]["overlap_depth"] = ph["ov_depth"]
    for name in sorted(ops):
        o = ops[name]
        gbps = sorted(o["gbps"])
        summary["ops"][name] = {
            "ops": o["ops"],
            "bytes": o["bytes"],
            "gbps_p10": _percentile(gbps, 10),
            "gbps_p50": _percentile(gbps, 50),
            "gbps_p90": _percentile(gbps, 90),
            **_stats(o["per_rank"]),
        }
    for label in sorted(compiles):
        c = dict(compiles[label])
        c["cost_models"] = len(c.pop("_ba_seen"))
        summary["compile"][label] = dict(
            c, **_roofline_join(c, label, summary["ops"],
                                summary["phases"])
        )
    # communication anatomy (instrument/anatomy.py): wait/wire
    # decomposition + rank-pair traffic matrix over the same files,
    # aligned per run by the timeline merger. The key exists ONLY when
    # the streams carry seq-stamped collective spans on 2+ ranks or
    # partner metadata — pre-seq files keep the exact summary shape
    # (and --json document) they always had. Lazy imports: timeline
    # imports this module at its top level.
    from tpu_mpi_tests.instrument.anatomy import anatomize
    from tpu_mpi_tests.instrument.timeline import rank_streams

    anatomy = anatomize(rank_streams(files, loaded=loaded))
    if anatomy is not None:
        summary["anatomy"] = anatomy
    return summary


def _noise_band(vals: list) -> float:
    """Half-spread of the finite samples over their median — the same
    cross-sample band the bench diff uses."""
    vals = [float(v) for v in vals
            if isinstance(v, (int, float)) and v == v]
    if len(vals) < 2:
        return 0.0
    mid = sorted(vals)[len(vals) // 2]
    return (max(vals) - min(vals)) / 2 / abs(mid) if mid else 0.0


def _overlap_row(ov: dict) -> dict:
    """One OVERLAP-table row from a run's ``kind:"overlap"`` records:
    per-rank fracs/rates averaged, their cross-rank spread kept as the
    ``--diff`` noise band. ``overlap_frac`` is reported even at 0.0 —
    a depth-1 (serialized) run must RENDER its zero, that is half of
    the acceptance contract."""
    fracs = list(ov["frac"].values())
    rates = list(ov["rate"].values())
    return {
        "depth": ov["depth"],
        "tier": ov.get("tier"),
        "ranks": max(len(fracs), len(rates), 1),
        "steps": ov["steps"],
        "overlap_frac": sum(fracs) / len(fracs) if fracs else 0.0,
        "frac_band": _noise_band(fracs),
        "comm_s": ov["comm_s"],
        "compute_s": ov["compute_s"],
        "drain_s": ov["drain_s"],
        "rate": sum(rates) / len(rates) if rates else None,
        "rate_unit": ov["rate_unit"],
        "rate_band": _noise_band(rates),
    }


def _route_row(rt: dict) -> dict:
    """One ROUTE-table row from a run's ``kind: "route"`` records:
    token/drop counts summed across calls, the distribution metrics
    (overflow %, occupancy %, imbalance) averaged with their
    cross-record spread kept as the ``--diff`` noise band. A routing
    change that raises overflow or imbalance beyond the run's own
    variation is a regression — dropped tokens are lost quality, a hot
    expert is the tail."""

    def mean(vals):
        return sum(vals) / len(vals) if vals else 0.0

    return {
        "calls": rt["calls"],
        "world": rt["world"],
        "capacity": rt["capacity"],
        "combine": rt["combine"],
        "tokens": rt["tokens"],
        "routed": rt["routed"],
        "dropped": rt["dropped"],
        "overflow_pct": mean(rt["overflow"]),
        "overflow_band": _noise_band(rt["overflow"]),
        "occupancy_pct": mean(rt["occupancy"]),
        "imbalance": mean(rt["imbalance"]),
        "imbalance_band": _noise_band(rt["imbalance"]),
    }


#: the serve latency metrics (worst-rank maxima in the SLO row; the
#: qd_/svc_ pair is the PR-16 decomposition — queue delay + service
#: ≈ e2e) and, with achieved_hz appended, the metrics whose
#: cross-window spread becomes a --diff band
_SERVE_LAT_METRICS = ("p50_ms", "p95_ms", "p99_ms", "mean_ms",
                      "qd_p99_ms", "svc_p99_ms")
_SERVE_METRICS = _SERVE_LAT_METRICS + ("achieved_hz",)


def _serve_row(sv: dict) -> dict:
    """One SLO-table row from a class's serve records: rank summaries
    combined as sums for counts/rates and worst-rank maxima for the
    latency percentiles (an SLO is a tail guarantee — the slowest
    rank's tail is the honest number). A rank that died before its
    summary is synthesized from its window records — per rank, so one
    crashed rank cannot vanish from the row just because its siblings
    finished cleanly. Like every other ``summarize`` table (phases,
    ops, memory), append-mode files merge ALL runs they hold — point
    the CLI at one run's files (or fresh ``--jsonl`` paths, as the
    smoke does) when diffing; per-run segmentation is the trace
    merger's job, not this table's."""
    per_rank: dict = dict(sv["summaries"])
    synth: dict = {}
    for w in sv["windows"]:
        rank = w.get("rank", 0)
        if rank in per_rank:
            continue  # that rank's summary is authoritative
        agg = synth.setdefault(rank, {
            "arrivals": 0, "requests": 0, "errors": 0, "shed": 0,
            "batches": 0, "queue_max": 0,
            "_t_lo": None, "_t_hi": None,
        })
        for k in ("arrivals", "requests", "errors", "shed",
                  "batches"):
            agg[k] = agg.get(k, 0) + int(w.get(k) or 0)
        # wall span, not summed active durations: idle windows are
        # never emitted, and dividing by active time alone would
        # overstate a sparse class's rates by the idle fraction
        for key, fn in (("_t_lo", min), ("_t_hi", max)):
            bound = w.get("t_start" if key == "_t_lo" else "t_end")
            if isinstance(bound, (int, float)):
                cur = agg[key]
                agg[key] = bound if cur is None else fn(cur, bound)
        agg["queue_max"] = max(agg["queue_max"],
                               int(w.get("queue_max") or 0))
        for k in _SERVE_LAT_METRICS:
            if isinstance(w.get(k), (int, float)):
                agg[k] = max(agg.get(k) or 0.0, float(w[k]))
    for agg in synth.values():
        lo, hi = agg.pop("_t_lo"), agg.pop("_t_hi")
        dur = (hi - lo) if (lo is not None and hi is not None
                           and hi > lo) else 1e-9
        agg["duration_s"] = dur
        agg["offered_hz"] = agg["arrivals"] / dur
        agg["achieved_hz"] = agg["requests"] / dur
    per_rank.update(synth)
    rows = list(per_rank.values())
    row = {
        "workload": sv.get("workload"),
        "dtype": sv.get("dtype"),
        "ranks": len(rows),
        "windows": len(sv["windows"]),
    }
    for k in ("arrivals", "requests", "errors", "shed", "batches"):
        row[k] = sum(int(r.get(k) or 0) for r in rows)
    # graceful-degradation accounting (serve --quarantine-after): how
    # many episodes the class spent quarantined and for how long —
    # keys absent on pre-quarantine streams so old rows keep shape
    quarantines = sum(int(r.get("quarantines") or 0) for r in rows)
    if quarantines:
        row["quarantines"] = quarantines
        row["quarantine_s"] = sum(
            float(r.get("quarantine_s") or 0.0) for r in rows
        )
    for k in ("offered_hz", "achieved_hz"):
        row[k] = sum(float(r.get(k) or 0.0) for r in rows)
    for k in _SERVE_LAT_METRICS:
        vals = [float(r[k]) for r in rows
                if isinstance(r.get(k), (int, float))]
        if vals:
            row[k] = max(vals)
    row["queue_max"] = max(
        (int(r.get("queue_max") or 0) for r in rows), default=0
    )
    row["bands"] = {
        k: _noise_band([w.get(k) for w in sv["windows"]])
        for k in _SERVE_METRICS
    }
    return row


def _roofline_join(c: dict, label: str, ops: dict, phases: dict) -> dict:
    """Join a compile record's cost model against the measured runtime
    of the same fn: the mean per-call seconds come from the span table
    (op named like the label) or, failing that, from the PhaseTimer
    phase the record named. Yields the model-implied achieved GB/s and
    the roofline fraction when the probing rank knew its peak.

    A label probed at several shapes (``cost_models`` > 1 — e.g. a
    collbench op swept over payload sizes) gets NO model join: mixing
    one shape's bytes with every shape's mean seconds would fabricate
    the number this table exists to make trustworthy."""
    mean_call = None
    op = ops.get(label)
    if op and op["ops"]:
        mean_call = sum(
            float(v) for v in op["per_rank_s"].values()
        ) / op["ops"]
    elif c.get("phase") in phases:
        mean_call = phases[c["phase"]].get("mean_call_s")
    out: dict = {"mean_call_s": mean_call}
    ba = c.get("bytes_accessed")
    if mean_call and ba and c.get("cost_models", 1) <= 1:
        out["model_gbps"] = ba / mean_call / 1e9
        if c.get("peak_gbps"):
            out["roofline_frac"] = out["model_gbps"] / c["peak_gbps"]
    return out


def _print_text(summary: dict, skew_threshold: float,
                findings: list | None = None) -> None:
    m = summary["manifest"]
    if m:
        kinds = ",".join(m.get("device_kinds", []))
        # hosts suffix only when the manifest carries the (non-flat)
        # topology stamp — flat/CPU runs keep the exact header
        hosts = (
            f" hosts={m['hosts']}"
            + (f"x{m['ranks_per_host']}" if m.get("ranks_per_host")
               else "")
            if m.get("hosts") else ""
        )
        print(
            f"RUN {m.get('platform', '?')}x{m.get('global_device_count', 0)}"
            f" ({kinds}) procs={m.get('process_count', 1)}{hosts}"
            f" jax={m.get('jax', '?')} git={m.get('git_sha') or 'unknown'}"
        )
        print(f"ARGV {' '.join(m.get('argv', []))}")
    print(f"FILES {len(summary['files'])}: {' '.join(summary['files'])}")
    rank_set = summary.get("rank_set") or {}
    if rank_set.get("missing"):
        missing = ",".join(str(r) for r in rank_set["missing"])
        print(
            f"NOTE incomplete rank set ({len(rank_set['seen'])} of "
            f"{rank_set['expected']} from manifest): missing rank(s) "
            f"{missing} — cross-rank stats and noise bands cover the "
            f"survivors only"
        )

    for name, ph in summary["phases"].items():
        print(
            f"PHASE {name}: ranks={ph['ranks']} n={ph['count']} "
            f"mean={ph['mean_s']:.6g} min={ph['min_s']:.6g} "
            f"max={ph['max_s']:.6g} skew={ph['skew']:.3g}"
        )
    for name, op in summary["ops"].items():
        gb = (
            f" gbps p10/p50/p90={op['gbps_p10']:.4g}/"
            f"{op['gbps_p50']:.4g}/{op['gbps_p90']:.4g}"
            if op["gbps_p50"] == op["gbps_p50"]  # not NaN
            else ""
        )
        print(
            f"OP {name}: ranks={op['ranks']} ops={op['ops']} "
            f"bytes={op['bytes']} mean={op['mean_s']:.6g} "
            f"min={op['min_s']:.6g} max={op['max_s']:.6g} "
            f"skew={op['skew']:.3g}{gb}"
        )

    _print_topology(summary.get("topo"), summary.get("anatomy"))
    _print_anatomy(summary.get("anatomy"))

    for cls, sv in summary.get("serve", {}).items():
        def ms(key, sv=sv):
            v = sv.get(key)
            return "-" if v is None else format(v, ".4g")

        quar = (
            f" quarantines={sv['quarantines']}"
            f" quar_s={sv['quarantine_s']:.4g}"
            if sv.get("quarantines") else ""
        )
        print(
            f"SLO {cls}: ranks={sv['ranks']} "
            f"offered={sv['offered_hz']:.4g}/s "
            f"achieved={sv['achieved_hz']:.4g}/s "
            f"n={sv['requests']} err={sv['errors']} shed={sv['shed']} "
            f"p50={ms('p50_ms')}ms p95={ms('p95_ms')}ms "
            f"p99={ms('p99_ms')}ms qd99={ms('qd_p99_ms')}ms "
            f"svc99={ms('svc_p99_ms')}ms qmax={sv['queue_max']} "
            f"windows={sv['windows']}{quar}"
        )

    tf = summary.get("traffic")
    if tf:
        dur = tf.get("duration_s")
        dur_s = (format(dur, ".4g")
                 if isinstance(dur, (int, float)) else "?")
        print(
            f"TRAFFIC {tf.get('event', '?')}: "
            f"fingerprint={tf.get('fingerprint')} "
            f"count={tf.get('count')} duration={dur_s}s "
            f"path={tf.get('path')}"
        )

    for op, rt in summary.get("route", {}).items():
        print(
            f"ROUTE {op}: calls={rt['calls']} world={rt['world']} "
            f"capacity={rt['capacity']} tokens={rt['tokens']} "
            f"routed={rt['routed']} dropped={rt['dropped']} "
            f"overflow={rt['overflow_pct']:.2f}% "
            f"occupancy={rt['occupancy_pct']:.1f}% "
            f"imbalance={rt['imbalance']:.3f}"
            + (f" combine={rt['combine']}" if rt.get("combine") else "")
        )
    for key, d in summary.get("decode", {}).items():
        print(
            f"DECODE {key}: us_per_op={d['us_per_op']:.4g} "
            f"bytes={d['shard_bytes']} n={d['n']} "
            f"band=±{d['band'] * 100:.2f}%"
        )
    for key, w in summary.get("workload", {}).items():
        unit = f" {w['unit']}" if w["unit"] else ""
        print(
            f"WORKLOAD {key}: value={w['value']:.6g}{unit} n={w['n']} "
            f"band=±{w['band'] * 100:.2f}%"
        )
    for op, ov in summary.get("overlap", {}).items():
        rate = ""
        if ov.get("rate") is not None:
            rate = f" {ov['rate']:.4g} {ov['rate_unit'] or 'it/s'}"
        tier = f" tier={ov['tier']}" if ov.get("tier") else ""
        print(
            f"OVERLAP {op}: depth={ov['depth']} "
            f"frac={ov['overlap_frac']:.3f} "
            f"comm={ov['comm_s']:.6g}s compute={ov['compute_s']:.6g}s "
            f"drain={ov['drain_s']:.6g}s "
            f"steps={ov['steps']} ranks={ov['ranks']}{tier}{rate}"
        )
    for name, ph in summary["phases"].items():
        if "overlap_frac" in ph:
            print(
                f"OVERLAP phase={name}: frac={ph['overlap_frac']:.3f}"
                f" depth={ph.get('overlap_depth', '-')}"
            )
    for key, b in summary.get("bench", {}).items():
        print(
            f"BENCH {key}: value={b['value']:.6g} n={b['n']} "
            f"band=±{b['band'] * 100:.2f}%"
        )

    for _key, c in summary.get("control", {}).items():
        sag = ("-" if c.get("sag_pct") is None
               else format(c["sag_pct"], ".1f") + "%")
        print(
            f"CONTROL {c.get('event', '?')} {c.get('class', '?')}: "
            f"knob={c.get('knob')} n={c['swaps']} "
            f"old={json.dumps(c.get('old'))} "
            f"new={json.dumps(c.get('new'))} sag={sag} "
            f"signal={c.get('signal') or '-'} "
            f"resweep={c.get('resweep_s', 0.0):.3g}s"
        )

    for name, t in summary.get("tuning", {}).items():
        sec = t["winner_seconds"]
        print(
            f"TUNE {name}: winner={json.dumps(t['winner'])} "
            f"seconds={'-' if sec is None else format(sec, '.6g')} "
            f"measured={t['measured']} skipped={t['skipped']} "
            f"errors={t['errors']} invalid={t['invalid']} "
            f"cache_hits={t['hits']}"
        )

    _print_memory(summary.get("memory") or {})
    _print_compile(summary.get("compile") or {})
    for name, v in summary.get("vmem", {}).items():
        if v.get("error") is not None:
            print(f"VMEM {name}: ERROR {v['error']}")
            continue
        ratio = v.get("ratio")
        unsafe = " UNSAFE" if (ratio is not None and ratio < 0.95) else ""
        print(
            f"VMEM {name}: model={v.get('model_bytes')} "
            f"actual={v.get('actual_bytes')} "
            f"model/actual={'-' if ratio is None else format(ratio, '.3g')}"
            f"{unsafe}"
        )

    stragglers = 0
    for label, table in (("PHASE", summary["phases"]),
                         ("OP", summary["ops"])):
        for name, st in table.items():
            if st["skew"] > skew_threshold and st["straggler_rank"] is not None:
                stragglers += 1
                print(
                    f"STRAGGLER {label} {name}: rank "
                    f"{st['straggler_rank']} is {st['skew']:.3g}x the "
                    f"fastest rank ({st['max_s']:.6g}s vs {st['min_s']:.6g}s)"
                )
    if not stragglers:
        print(f"OK no stragglers above {skew_threshold:g}x")

    # DIAGNOSIS table (instrument/diagnose.py — the tpumt-doctor
    # rules over the same merged records): printed only when a rule
    # convicted, so clean runs and pre-chaos streams keep their exact
    # report shape
    for f in findings or []:
        print(
            f"DIAGNOSIS {f['class']}: rank={f['rank']} "
            f"confidence={f['confidence']:.2f}"
            + (f" last_op={f['last_op']}" if f.get("last_op") else "")
            + (f" phase={f['phase']}" if f.get("phase") else "")
            + (f" link={f['link']}" if f.get("link") else "")
            + f" — {f['detail']}"
        )


def _print_topology(topo: dict | None, anat: dict | None) -> None:
    """TOPOLOGY table: the discovered shape (``kind:"topo"`` record)
    plus per-link-class aggregate GB/s (anatomy ``by_link``). Silent on
    flat topologies AND on pre-topo files — a single-host/CPU run's
    report grows no lines (the same degrade contract as ANATOMY)."""
    shape = topo if topo and (
        int(topo.get("hosts") or 1) > 1 or int(topo.get("slices") or 1) > 1
    ) else None
    if shape:
        hosts = f" hosts={shape.get('hosts')}"
        if shape.get("ranks_per_host"):
            hosts += f"x{shape['ranks_per_host']}"
        slices = (f" slices={shape['slices']}"
                  if int(shape.get("slices") or 1) > 1 else "")
        links = ",".join(shape.get("link_classes") or []) or "-"
        print(
            f"TOPOLOGY {shape.get('topology', '?')}: "
            f"world={shape.get('world')}{hosts}{slices} links={links}"
        )
    from tpu_mpi_tests.instrument.anatomy import LINK_ORDER

    by_link = (anat or {}).get("by_link") or {}
    for cls in sorted(by_link, key=lambda c: (
            LINK_ORDER.index(c) if c in LINK_ORDER else len(LINK_ORDER), c)):
        agg = by_link[cls]
        pure = ("-" if agg.get("pure_gbps") is None
                else format(agg["pure_gbps"], ".4g"))
        eff = ("-" if agg.get("eff_gbps") is None
               else format(agg["eff_gbps"], ".4g"))
        print(
            f"TOPOLOGY {cls}: calls={agg['calls']} bytes={agg['bytes']} "
            f"wait_frac={agg['wait_frac']:.3f} "
            f"pure={pure}GB/s eff={eff}GB/s"
        )


def _print_anatomy(anat: dict | None) -> None:
    """ANATOMY + COMMGRAPH tables (instrument/anatomy.py): silent when
    the run carries no seq-stamped collective spans on 2+ ranks and no
    partner metadata — pre-anatomy files keep their exact report shape.

    Reading guide (README "Communication anatomy"): ``wait_frac`` is
    the fraction of all ranks' in-collective seconds spent waiting for
    the LAST arriver; the wait-share ranking names who that was
    (sync-honest spans charge the wait to the early ranks — this table
    un-inverts it). ``pure`` is bytes over wire time (what the fabric
    sustained once everyone arrived), ``eff`` bytes over the whole span
    (what the program felt); decompositions finer than the clock-sync
    uncertainty (``unc``) are counted ``unresolved``, not split."""
    if not anat:
        return
    for op in sorted(anat.get("ops", {})):
        row = anat["ops"][op]
        pure = ("-" if row.get("pure_gbps") is None
                else format(row["pure_gbps"], ".4g"))
        eff = ("-" if row.get("eff_gbps") is None
               else format(row["eff_gbps"], ".4g"))
        share = " ".join(
            f"r{r}={frac * 100:.0f}%" for r, frac in row["wait_share"][:4]
        )
        print(
            f"ANATOMY {op}: calls={row['calls']} "
            f"ranks={len(row['ranks'])} "
            f"wait_frac={row['wait_frac']:.3f} "
            f"wait={row['wait_s']:.6g}s wire={row['wire_s']:.6g}s "
            f"pure={pure}GB/s eff={eff}GB/s "
            f"unresolved={row['unresolved']} "
            f"unmatched={row['unmatched']} "
            f"unc=±{anat['clock_unc_s'] * 1e3:.3g}ms"
            + (f" wait_share {share}" if share else "")
        )
        # link-class split rows (comm/topology.py stamps): present
        # only when the op's spans carried a link class — flat runs
        # render the exact legacy table
        for cls in sorted(row.get("by_link") or {}):
            sub = row["by_link"][cls]
            spure = ("-" if sub.get("pure_gbps") is None
                     else format(sub["pure_gbps"], ".4g"))
            seff = ("-" if sub.get("eff_gbps") is None
                    else format(sub["eff_gbps"], ".4g"))
            print(
                f"ANATOMY {op}[{cls}]: calls={sub['calls']} "
                f"wait_frac={sub['wait_frac']:.3f} "
                f"wait={sub['wait_s']:.6g}s wire={sub['wire_s']:.6g}s "
                f"pure={spure}GB/s eff={seff}GB/s"
            )
    path = anat.get("critical_path") or []
    if path and anat.get("ops"):
        total = sum(seg["seconds"] for seg in path)
        shown = " -> ".join(
            f"r{seg['rank']} {seg['kind']} {seg['name']} "
            f"{seg['seconds']:.4g}s"
            for seg in path[:6]
        )
        more = f" ... ({len(path) - 6} more)" if len(path) > 6 else ""
        print(
            f"ANATOMY critpath: {len(path)} segments "
            f"{total:.6g}s: {shown}{more}"
        )
    for edge in sorted(anat.get("matrix", {})):
        by_op = anat["matrix"][edge]
        ops = " ".join(
            f"{op}={by_op[op]}" for op in sorted(by_op)
            if op not in ("total", "link")
        )
        link = f" link={by_op['link']}" if by_op.get("link") else ""
        print(f"COMMGRAPH {edge}: bytes={by_op['total']} {ops}".rstrip()
              + link)


def _print_memory(memory: dict) -> None:
    """MEMORY table: per-phase watermarks, run peak, top live buffers.
    Silent when the run recorded no ``mem`` records (no --memwatch) —
    old files keep their exact report shape."""
    if not memory.get("records"):
        return
    for name, ph in memory.get("phases", {}).items():
        parts = [f"MEM phase={name}:"]
        for key in ("peak_bytes", "delta_bytes", "peak_delta"):
            if ph.get(key) is not None:
                parts.append(f"{key.replace('_bytes', '')}={ph[key]}")
        parts.append(f"ranks={ph['ranks']} n={ph['records']}")
        print(" ".join(parts))
    peak = memory.get("peak", {})
    parts = ["MEM peak:"]
    for key in ("bytes_in_use", "peak_bytes_in_use", "live_bytes"):
        if key in peak:
            parts.append(
                f"{key}={peak[key]['bytes']} (r{peak[key]['rank']})"
            )
    if len(parts) > 1:
        print(" ".join(parts))
    if not any(k in peak for k in ("bytes_in_use", "peak_bytes_in_use")):
        # census-only run (CPU / fake devices report no allocator
        # stats): say why there are no watermark numbers instead of the
        # live-array totals silently reading as real HBM
        print(f"MEM census-only: {memory['records']} records, no "
              f"device memory_stats (CPU/fake devices)")
    for key, e in memory.get("top", {}).items():
        print(f"MEMTOP {key}: bytes={e['bytes']} count={e['count']} "
              f"(r{e['rank']})")


def _print_compile(compiles: dict) -> None:
    for label, c in compiles.items():
        parts = [
            f"COMPILE {label}: n={c['compiles']} "
            f"compile={c['seconds']:.6g}s"
        ]
        for key in ("flops", "bytes_accessed", "temp_bytes",
                    "output_bytes"):
            if c.get(key) is not None:
                parts.append(f"{key}={c[key]:.6g}")
        if c.get("mean_call_s"):
            parts.append(f"mean_call={c['mean_call_s']:.6g}s")
        if c.get("model_gbps"):
            parts.append(f"model_gbps={c['model_gbps']:.4g}")
        if c.get("roofline_frac") is not None:
            parts.append(f"roofline={c['roofline_frac'] * 100:.1f}%")
        if c.get("cost_models", 1) > 1:
            # several shapes under one label: last-seen flops/bytes are
            # shown but no model join (see _roofline_join)
            parts.append(f"cost_models={c['cost_models']}")
        print(" ".join(parts))


# ---------------------------------------------------------------------------
# --diff: compare two runs (JSONL sets or bench JSON files)
# ---------------------------------------------------------------------------


def _load_bench_doc(path: str) -> dict | None:
    """The bench result object from a path holding either bench.py's one
    JSON line or a driver-captured ``BENCH_r*.json`` wrapper (the result
    line is the last JSON object inside its ``tail``). None when the
    file is not a single JSON document (then it is treated as JSONL)."""
    try:
        doc = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(doc, dict):
        return None
    if "metric" in doc:
        return doc
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and "metric" in d:
                return d
    return None


def _bench_metrics(doc: dict, prefix: str = "") -> dict[str, dict]:
    """``{metric_name: {value, band, higher_better}}`` from a bench
    result object, sub-dtype objects included (``bfloat16.iter/s``).
    The noise band is the half-spread of the finite samples over their
    median — the run's own cross-sample noise."""
    out: dict[str, dict] = {}
    if isinstance(doc.get("value"), (int, float)):
        out[prefix + (doc.get("unit") or "value")] = {
            "value": float(doc["value"]),
            "band": _noise_band(doc.get("samples") or []),
            "higher_better": True,
        }
    if isinstance(doc.get("hbm_peak_bytes"), (int, float)):
        out[prefix + "hbm_peak_bytes"] = {
            "value": float(doc["hbm_peak_bytes"]), "band": 0.0,
            "higher_better": False,
        }
    for sub in ("float32", "bfloat16"):
        if isinstance(doc.get(sub), dict):
            out.update(_bench_metrics(doc[sub], prefix=f"{sub}."))
    return out


def _jsonl_metrics(files: list[str]) -> dict[str, dict]:
    """Per-phase / per-op / memory metrics of one JSONL run. The noise
    band of a phase/op is its cross-rank spread (half the max−min over
    the mean); bandwidth uses the p10–p90 spread over p50."""
    return _metrics_from_summary(summarize(files))


def _metrics_from_summary(s: dict) -> dict[str, dict]:
    out: dict[str, dict] = {}

    def rank_band(st) -> float:
        return ((st["max_s"] - st["min_s"]) / (2 * st["mean_s"])
                if st["mean_s"] else 0.0)

    for name, st in s["phases"].items():
        out[f"phase:{name}"] = {
            "value": st["mean_s"], "band": rank_band(st),
            "higher_better": False,
        }
    for name, st in s["ops"].items():
        out[f"op:{name}"] = {
            "value": st["mean_s"], "band": rank_band(st),
            "higher_better": False,
        }
        p50 = st["gbps_p50"]
        if p50 == p50 and p50:  # not NaN, non-zero
            out[f"op:{name}:gbps"] = {
                "value": p50,
                "band": (st["gbps_p90"] - st["gbps_p10"]) / (2 * p50),
                "higher_better": True,
            }
    # communication-anatomy series (ISSUE 17): wait_frac gates lower-
    # is-better (a change that makes ranks arrive more skewed is a
    # regression even when the op's mean seconds hide it) and pure GB/s
    # higher-is-better (the fabric's own rate, wait removed). Bands are
    # each op's per-call spread. Absent entirely on pre-seq runs.
    for op, row in ((s.get("anatomy") or {}).get("ops") or {}).items():
        if isinstance(row.get("wait_frac"), (int, float)):
            out[f"anatomy:{op}:wait_frac"] = {
                "value": float(row["wait_frac"]),
                "band": row.get("wait_frac_band", 0.0),
                "higher_better": False,
            }
        if isinstance(row.get("pure_gbps"), (int, float)):
            out[f"anatomy:{op}:pure_gbps"] = {
                "value": float(row["pure_gbps"]),
                "band": row.get("pure_gbps_band", 0.0),
                "higher_better": True,
            }
        # per-link-class fabric rate (ISSUE 20): a regression confined
        # to the inter_host edges must flag even when the intra_host
        # majority keeps the op-level pure GB/s flat. Absent on
        # flat-topology runs (no stamps → no series).
        for cls, sub in (row.get("by_link") or {}).items():
            if isinstance(sub.get("pure_gbps"), (int, float)):
                out[f"anatomy:{op}:{cls}:pure_gbps"] = {
                    "value": float(sub["pure_gbps"]),
                    "band": sub.get("pure_gbps_band", 0.0),
                    "higher_better": True,
                }
    peak = (s.get("memory") or {}).get("peak") or {}
    if "peak_bytes_in_use" in peak:
        out["mem:peak_bytes_in_use"] = {
            "value": float(peak["peak_bytes_in_use"]["bytes"]),
            "band": 0.0, "higher_better": False,
        }
    # serve SLO metrics: latency percentiles (lower better) + achieved
    # throughput-under-load (higher better); the band is each class's
    # own cross-window spread, so a noisy run demands a bigger change
    # before its tail flags (same contract as the bench samples)
    for cls, sv in s.get("serve", {}).items():
        bands = sv.get("bands") or {}
        # the qd_/svc_ decomposition series gate alongside the e2e
        # percentiles: a queue-side regression can't hide inside a
        # flat e2e p99 (service got faster, queueing got worse)
        for met in ("p50_ms", "p95_ms", "p99_ms",
                    "qd_p99_ms", "svc_p99_ms"):
            v = sv.get(met)
            if isinstance(v, (int, float)):
                out[f"serve:{cls}:{met}"] = {
                    "value": float(v),
                    "band": bands.get(met, 0.0),
                    "higher_better": False,
                }
        # isinstance, not truthiness: a run whose throughput collapsed
        # to 0 must emit the metric, or the -100% regression would
        # degrade to a present-on-one-side NOTE and the gate exits 0
        if isinstance(sv.get("achieved_hz"), (int, float)):
            out[f"serve:{cls}:achieved_hz"] = {
                "value": float(sv["achieved_hz"]),
                "band": bands.get("achieved_hz", 0.0),
                "higher_better": True,
            }
    # overlap-engine series (ISSUE 7 satellite): a future change that
    # silently re-serializes the pipeline drops overlap_frac from ~1
    # to 0 — a -100% regression beyond any noise band, so the gate
    # exits 1 instead of the de-pipelining passing unobserved. The
    # rate (it/s / steps/s) and the driver bench rows gate alongside.
    for op, ov in s.get("overlap", {}).items():
        if isinstance(ov.get("overlap_frac"), (int, float)):
            out[f"overlap:{op}:frac"] = {
                "value": float(ov["overlap_frac"]),
                "band": ov.get("frac_band", 0.0),
                "higher_better": True,
            }
        if isinstance(ov.get("rate"), (int, float)):
            out[f"overlap:{op}:rate"] = {
                "value": float(ov["rate"]),
                "band": ov.get("rate_band", 0.0),
                "higher_better": True,
            }
    # routing-quality series (ISSUE 8): overflow % is dropped tokens
    # (lost quality under load) and imbalance is the hot-expert tail —
    # both gate lower-is-better against the run's own cross-call spread
    for op, rt in s.get("route", {}).items():
        if isinstance(rt.get("overflow_pct"), (int, float)):
            out[f"route:{op}:overflow_pct"] = {
                "value": float(rt["overflow_pct"]),
                "band": rt.get("overflow_band", 0.0),
                "higher_better": False,
            }
        if isinstance(rt.get("imbalance"), (int, float)):
            out[f"route:{op}:imbalance"] = {
                "value": float(rt["imbalance"]),
                "band": rt.get("imbalance_band", 0.0),
                "higher_better": False,
            }
    # decode-latency rows: µs/op per (collective, batch×heads), lower
    # better — the per-op fixed cost the GB/s tables are blind to
    for key, d in s.get("decode", {}).items():
        out[f"decode:{key}:us_per_op"] = {
            "value": d["us_per_op"], "band": d["band"],
            "higher_better": False,
        }
    # spec bench rows: regression direction recorded by the runner
    for key, w in s.get("workload", {}).items():
        out[f"workload:{key}"] = {
            "value": w["value"], "band": w["band"],
            "higher_better": w["higher_better"],
        }
    for key, b in s.get("bench", {}).items():
        out[f"bench:{key}"] = {
            "value": b["value"], "band": b["band"],
            "higher_better": True,
        }
    return out


def _side_metrics(
    path: str,
) -> tuple[str, dict[str, dict], dict | None, dict | None]:
    bench = _load_bench_doc(path)
    if bench is not None:
        return "bench", _bench_metrics(bench), None, None
    files = [f for f in expand_rank_files([path]) if Path(f).exists()]
    s = summarize(files)
    return ("jsonl", _metrics_from_summary(s), s.get("rank_set"),
            s.get("traffic"))


def diff_main(path_a: str, path_b: str, threshold: float = 0.05,
              allow_traffic_mismatch: bool = False) -> int:
    """Compare two runs per metric. A change is flagged only beyond the
    noise band — the larger of either side's cross-sample/cross-rank
    band and the ``--diff-threshold`` floor. Returns 1 when any flagged
    change is a *regression* (slower / less bandwidth / more memory),
    0 otherwise; 2 when the baseline is a partial-rank run (a crashed
    rank must not silently shrink the noise band a gate trusts) or the
    two serve runs carry different traffic fingerprints (an SLO diff
    across different traffic is not a comparison — record once, replay
    twice; ``--allow-traffic-mismatch`` downgrades this to a NOTE)."""
    kind_a, a, ranks_a, traffic_a = _side_metrics(path_a)
    kind_b, b, ranks_b, traffic_b = _side_metrics(path_b)
    fp_a = (traffic_a or {}).get("fingerprint")
    fp_b = (traffic_b or {}).get("fingerprint")
    if fp_a and fp_b and fp_a != fp_b:
        if not allow_traffic_mismatch:
            print(
                f"DIFF ERROR traffic fingerprints differ: A={fp_a} "
                f"B={fp_b} — these serve runs saw different request "
                f"streams, so their SLO deltas conflate the change "
                f"under test with the load change; replay one recorded "
                f"artifact on both sides (tpumt-serve --record/"
                f"--replay) or pass --allow-traffic-mismatch to "
                f"compare anyway",
                file=sys.stderr,
            )
            return 2
        print(f"DIFF NOTE traffic fingerprints differ (A={fp_a} "
              f"B={fp_b}); comparing anyway (--allow-traffic-mismatch)")
    elif (fp_a or fp_b) and not (fp_a and fp_b):
        # one side recorded/replayed, the other ran synthetic traffic:
        # not refusable (pre-PR-16 baselines have no fingerprint) but
        # never silent
        have, lack = (path_a, path_b) if fp_a else (path_b, path_a)
        print(f"DIFF NOTE only {have} carries a traffic fingerprint; "
              f"{lack} ran unrecorded traffic — the comparison cannot "
              f"verify identical load")
    elif fp_a and fp_b:
        print(f"DIFF traffic fingerprints match ({fp_a})")
    if ranks_a and ranks_a.get("missing"):
        print(
            f"DIFF ERROR baseline {path_a} is a partial-rank run "
            f"({len(ranks_a['seen'])} of {ranks_a['expected']} rank "
            f"files; missing "
            f"{','.join(str(r) for r in ranks_a['missing'])}) — a "
            f"crashed rank's survivors are not a baseline; re-run or "
            f"pick a complete run",
            file=sys.stderr,
        )
        return 2
    if ranks_b and ranks_b.get("missing"):
        # a partial CANDIDATE is still worth diffing (what regressed
        # before the crash?) but never silently: its bands cover the
        # survivors only
        print(
            f"DIFF NOTE candidate {path_b} is a partial-rank run "
            f"({len(ranks_b['seen'])} of {ranks_b['expected']} rank "
            f"files; missing "
            f"{','.join(str(r) for r in ranks_b['missing'])}) — "
            f"metrics and noise bands cover the surviving ranks only"
        )
    print(f"DIFF A={path_a} ({kind_a}) B={path_b} ({kind_b})")
    if kind_a != kind_b:
        print("DIFF NOTE comparing different input kinds; only shared "
              "metric names are compared")
    shared = sorted(set(a) & set(b))
    if not shared:
        print("DIFF no shared metrics", file=sys.stderr)
        return 1
    regressions = 0
    for name in shared:
        ma, mb = a[name], b[name]
        if not ma["value"]:
            continue
        change = (mb["value"] - ma["value"]) / abs(ma["value"])
        band = max(ma["band"], mb["band"], threshold)
        worse = (-change if ma["higher_better"] else change) > band
        better = (change if ma["higher_better"] else -change) > band
        tag = ""
        if worse:
            regressions += 1
            tag = " REGRESSION"
        elif better:
            tag = " improved"
        print(
            f"DIFF {name}: A={ma['value']:.6g} B={mb['value']:.6g} "
            f"change={change * 100:+.2f}% band=±{band * 100:.2f}%{tag}"
        )
    skipped = (set(a) | set(b)) - set(shared)
    if skipped:
        print(f"DIFF NOTE {len(skipped)} metrics present on one side "
              f"only: {' '.join(sorted(skipped))}")
    if regressions:
        print(f"DIFF REGRESSIONS {regressions} beyond the noise band")
        return 1
    print("DIFF OK within noise")
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpumt-report",
        description="merge per-rank telemetry JSONL into a run summary "
        "(per-phase/per-op cross-rank stats + straggler detection)",
    )
    p.add_argument(
        "files",
        nargs="+",
        help="per-rank JSONL files; an un-suffixed --jsonl base path "
        "expands to its .p<i> rank set",
    )
    p.add_argument(
        "--skew-threshold",
        type=float,
        default=1.5,
        metavar="X",
        help="flag a phase/op when max rank time > X * min (default 1.5)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="emit the summary as one JSON document instead of text",
    )
    p.add_argument(
        "--timeline",
        action="store_true",
        help="render a per-phase ASCII swimlane (one lane per rank, "
        "clock offsets applied) plus per-step comm-op start-skew "
        "series instead of the stats summary; needs the timestamped "
        "records new runs emit (instrument/timeline.py renders; "
        "tpumt-trace exports the same merge for Perfetto)",
    )
    p.add_argument(
        "--width",
        type=int,
        default=64,
        metavar="COLS",
        help="swimlane width in columns for --timeline (default 64)",
    )
    p.add_argument(
        "--diff",
        action="store_true",
        help="compare exactly two runs instead of summarizing: each "
        "path is a JSONL set (base path expands its rank files) or a "
        "bench JSON file (bench.py output / BENCH_r*.json wrapper); "
        "changes beyond the cross-sample noise band are flagged and a "
        "regression exits 1",
    )
    p.add_argument(
        "--diff-threshold",
        type=float,
        default=0.05,
        metavar="FRAC",
        help="minimum relative-change floor for --diff flags when the "
        "runs' own noise bands are tighter (default 0.05)",
    )
    p.add_argument(
        "--allow-traffic-mismatch",
        action="store_true",
        help="let --diff compare two serve runs whose traffic "
        "fingerprints differ (normally refused with exit 2: an SLO "
        "delta across different request streams conflates the change "
        "under test with the load change — record once, replay twice)",
    )
    args = p.parse_args(argv)

    if args.diff:
        if len(args.files) != 2:
            print("tpumt-report: --diff needs exactly two paths",
                  file=sys.stderr)
            return 1
        for f in args.files:
            if not Path(f).exists() and not (
                expand_rank_files([f]) and
                any(Path(x).exists() for x in expand_rank_files([f]))
            ):
                print(f"tpumt-report: cannot open {f}", file=sys.stderr)
                return 1
        return diff_main(
            args.files[0], args.files[1],
            threshold=args.diff_threshold,
            allow_traffic_mismatch=args.allow_traffic_mismatch,
        )

    files = [f for f in expand_rank_files(args.files) if Path(f).exists()]
    if not files:
        print("tpumt-report: no input files found", file=sys.stderr)
        return 1
    if args.timeline:
        from tpu_mpi_tests.instrument.timeline import ascii_swimlane

        for line in ascii_swimlane(files, width=max(args.width, 8)):
            print(line)
        return 0
    # DIAGNOSIS table: the tpumt-doctor rules over the same files
    # (lazy import; diagnose imports this module). One parse feeds
    # both consumers. Best-effort by contract — diagnose_files never
    # raises.
    from tpu_mpi_tests.instrument.diagnose import (diagnose_files,
                                                   load_with_lines)

    loaded = {p: load_with_lines(p, prog="tpumt-report") for p in files}
    summary = summarize(files, loaded=loaded)
    findings = diagnose_files(files, loaded=loaded)
    if args.json:
        json.dump(dict(summary, findings=findings), sys.stdout, indent=1)
        print()
    else:
        _print_text(summary, args.skew_threshold, findings)
    return 0


if __name__ == "__main__":
    sys.exit(main())
