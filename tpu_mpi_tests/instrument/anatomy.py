"""Cross-rank communication anatomy: wait/wire decomposition, per-op
skew attribution, and the rank-pair traffic matrix.

The telemetry layer's collective spans are *sync-honest* — each rank's
span covers its own entry to its own exit — which means a straggler's
lateness is charged to the EARLY ranks (they sit in the collective
waiting), exactly inverted from where the cause lives. The reference
suite prints per-rank bandwidths for the same reason: one fused number
cannot distinguish "the network is slow" from "a rank is late". This
module splits the two, using facts the spine already records:

* every comm span carries a per-(op, axis) monotone ``seq``
  (``instrument/telemetry.py``), so the k-th allreduce on rank 0 is the
  k-th on every sibling — the cross-rank alignment key;
* every rank's clock offset to rank 0 (``clock_sync`` barrier-echo,
  PR-2) puts all entries on one axis, and its sample ``spread_s`` bounds
  how finely two ranks' entries can honestly be compared.

For each matched call the decomposition is::

    wait = latest_entry − own_entry     (sitting in the collective)
    wire = own_end − latest_entry       (everyone arrived; data moving)

and the *cause* of a call's total wait is the latest entrant. Rollups:
per-op ``wait_frac`` (wait / span), the per-rank wait-share ranking
("rank 2 caused 71% of allreduce wait"), *pure* GB/s (bytes over wire —
what the fabric did) vs *effective* GB/s (bytes over span — what the
program felt), the per-step critical path (the chain of slowest
phase/op segments across ranks), and the rank-pair traffic matrix
(bytes per directed (src, dst) edge from the halo/ppermute ``partners``
span metadata).

Honesty floor: a wait smaller than the measured clock-sync uncertainty
(the worst two ranks' ``spread_s`` summed) is reported ``unresolved`` —
counted, never decomposed — because the clocks cannot support the
claim. Streams without ``seq`` (pre-anatomy JSONL) yield ``None`` from
:func:`anatomize`, so every consumer keeps its legacy output
byte-identical.

Pure stdlib (no jax import): usable on a login node against files
copied off the pod, like the sibling consumers.
"""

from __future__ import annotations

from typing import Any

from tpu_mpi_tests.instrument.aggregate import _noise_band

#: most critical-path segments kept in a rollup — the chain is a
#: reading aid, not a database
CRITPATH_MAX_SEGMENTS = 32

#: link classes weakest→strongest, kept in lockstep with
#: ``comm/topology.LINK_CLASSES`` (tests assert the match) — anatomy
#: is stdlib-only and cannot import the comm package, whose __init__
#: pulls jax
LINK_ORDER = ("self", "intra_host", "inter_host", "inter_slice")


def _stronger(a: str, b: str) -> str:
    """The stronger of two link classes; unknown classes (a newer
    producer's vocabulary) sort strongest — never silently weakened."""

    def rank(c):
        return LINK_ORDER.index(c) if c in LINK_ORDER else len(LINK_ORDER)

    return a if rank(a) >= rank(b) else b


def _eligible(rec: dict) -> bool:
    """Spans the cross-rank match may align: sync-honest collective
    spans (``seq`` stamped, 2+ participants, not an async dispatch
    window — a drain-bounded window is not an arrival time)."""
    return (
        rec.get("kind") == "span"
        and rec.get("seq") is not None
        and int(rec.get("world") or 1) >= 2
        and not rec.get("async")
        and rec.get("t_start") is not None
        and rec.get("t_end") is not None
    )


def clock_uncertainty(spreads: dict[int, float]) -> float:
    """The floor under any cross-rank time comparison: the two worst
    ranks' barrier-echo sample spreads summed (an entry-vs-entry delta
    subtracts two offsets, each good to its own ``spread_s``)."""
    worst = sorted((float(s) for s in spreads.values()), reverse=True)
    return sum(worst[:2])


def _clock_spreads(streams) -> dict[int, float]:
    """Per-rank ``clock_sync`` sample spread (0.0 when a stream carries
    none — old files compare uncorrected AND unbounded, which the
    caller's floor then treats as perfectly synced; matching the
    timeline merger's 0-offset degrade)."""
    spreads: dict[int, float] = {}
    for rank, _offset, records in streams:
        spreads.setdefault(rank, 0.0)
        for rec in records:
            if rec.get("kind") == "clock_sync":
                spreads[rank] = float(rec.get("spread_s") or 0.0)
    return spreads


def matched_calls(
    streams,
) -> dict[tuple[str, Any], dict[int, list[tuple[int, float, float]]]]:
    """``{(op, axis): {rank: [(seq, t_entry, t_end)]}}`` over the
    eligible spans, entry/end already shifted onto rank 0's clock.
    The caller decides which seqs count as matched (present on every
    participating rank)."""
    table: dict[tuple[str, Any], dict[int, list]] = {}
    for rank, offset, records in streams:
        for rec in records:
            if not _eligible(rec):
                continue
            key = (rec.get("op", "?"), rec.get("axis"))
            table.setdefault(key, {}).setdefault(rank, []).append((
                int(rec["seq"]),
                float(rec["t_start"]) - offset,
                float(rec["t_end"]) - offset,
            ))
    return table


def partner_edges(rec: dict, rank: int) -> list[tuple[int, int]]:
    """``[(dst, bytes)]`` sent by ``rank`` for one span record carrying
    ``partners`` ring-offset metadata: ``partner_nbytes`` flows to each
    ``(rank+d) % world`` on a periodic ring, out-of-range neighbors
    dropped at the edges otherwise. Empty for spans without the
    metadata — the shared edge enumeration for the traffic matrix and
    the trace counter tracks."""
    if rec.get("kind") != "span" or not rec.get("partners"):
        return []
    world = int(rec.get("world") or 1)
    per_edge = int(rec.get("partner_nbytes") or 0)
    if world < 2 or not per_edge:
        return []
    edges = []
    for d in rec["partners"]:
        dst = rank + int(d)
        if rec.get("periodic"):
            dst %= world
        elif not (0 <= dst < world):
            continue
        edges.append((dst, per_edge))
    return edges


def traffic_matrix(streams) -> dict[tuple[int, int], dict[str, int]]:
    """``{(src, dst): {op: bytes}}`` from the ``partners`` span
    metadata (halo/ppermute wrappers — see :func:`partner_edges`).
    Needs no seq matching (bytes are bytes); spans without partner
    metadata simply contribute no edges."""
    matrix: dict[tuple[int, int], dict[str, int]] = {}
    for rank, _offset, records in streams:
        for rec in records:
            op = rec.get("op", "?")
            for dst, nbytes in partner_edges(rec, rank):
                edge = matrix.setdefault((rank, dst), {})
                edge[op] = edge.get(op, 0) + nbytes
    return matrix


def edge_link_classes(streams) -> dict[tuple[int, int], str]:
    """``{(src, dst): link class}`` from spans carrying both
    ``partners`` and the parallel ``partner_link`` stamp
    (``comm/topology.py`` — resolved at wrapper-build time); conflicting
    stamps keep the stronger class. Empty on flat-topology runs (no
    stamps), which is the COMMGRAPH link-suffix degrade gate. Offset →
    dst mapping mirrors :func:`partner_edges` exactly, including the
    non-periodic edge drops."""
    out: dict[tuple[int, int], str] = {}
    for rank, _offset, records in streams:
        for rec in records:
            links = rec.get("partner_link")
            if (rec.get("kind") != "span" or not links
                    or not rec.get("partners")):
                continue
            world = int(rec.get("world") or 1)
            if world < 2:
                continue
            for d, cls in zip(rec["partners"], links):
                dst = rank + int(d)
                if rec.get("periodic"):
                    dst %= world
                elif not (0 <= dst < world):
                    continue
                prev = out.get((rank, dst))
                out[(rank, dst)] = (str(cls) if prev is None
                                    else _stronger(prev, str(cls)))
    return out


def critical_path(streams) -> list[dict]:
    """The chain of slowest segments across ranks: starting from the
    globally last-ending phase/op segment, repeatedly step to the
    latest-ending segment that starts strictly before the current one —
    the backward walk over "what was the run waiting on just before
    this". Segments are placed phase windows and comm spans on the
    offset-corrected axis; oldest first in the result."""
    segs: list[tuple[float, float, int, str, str]] = []
    for rank, offset, records in streams:
        for rec in records:
            kind = rec.get("kind")
            if kind == "span" and rec.get("t_start") is not None:
                name, cat = rec.get("op", "?"), "op"
            elif (kind == "time" and rec.get("event") != "progress"
                  and rec.get("t_start") is not None):
                name, cat = rec.get("phase", "?"), "phase"
            else:
                continue
            start = float(rec["t_start"]) - offset
            end = float(rec.get("t_end") or rec["t_start"]) - offset
            if end > start:
                segs.append((start, end, rank, name, cat))
    if not segs:
        return []
    chain: list[tuple[float, float, int, str, str]] = [
        max(segs, key=lambda s: s[1])
    ]
    while len(chain) < CRITPATH_MAX_SEGMENTS:
        cur_start = chain[-1][0]
        prev = [s for s in segs if s[0] < cur_start]
        if not prev:
            break
        chain.append(max(prev, key=lambda s: s[1]))
    return [
        {"rank": rank, "kind": cat, "name": name,
         "t_start": start, "seconds": end - start}
        for start, end, rank, name, cat in reversed(chain)
    ]


def anatomize(streams) -> dict | None:
    """The full anatomy rollup over one run's aligned rank streams
    (``timeline.rank_streams`` shape: ``[(rank, offset_s, records)]``).

    Returns ``None`` when no op has seq-stamped collective spans on 2+
    ranks AND no span carries partner metadata — the pre-anatomy
    degrade gate every consumer keys its legacy byte-identity on."""
    spreads = _clock_spreads(streams)
    unc = clock_uncertainty(spreads)
    table = matched_calls(streams)
    ops: dict[str, dict] = {}
    for (op, axis), per_rank in sorted(
        table.items(), key=lambda kv: (kv[0][0], str(kv[0][1]))
    ):
        if len(per_rank) < 2:
            continue
        by_seq: dict[int, dict[int, tuple[float, float]]] = {}
        for rank, calls in per_rank.items():
            for seq, entry, end in calls:
                # duplicate (append-mode double record): first wins
                by_seq.setdefault(seq, {}).setdefault(rank, (entry, end))
        ranks = set(per_rank)
        row = ops.setdefault(op, {
            "calls": 0, "unmatched": 0, "unresolved": 0,
            "ranks": [],
            "span_s": 0.0, "wait_s": 0.0, "wire_s": 0.0, "bytes": 0,
            "wait_by_rank": {},
            "_wait_fracs": [], "_pure_gbps": [],
            "by_link": {},
        })
        row["ranks"] = sorted(set(row["ranks"]) | ranks)
        for r in sorted(ranks):
            row["wait_by_rank"].setdefault(r, 0.0)
        nbytes_by_seq = _bytes_by_seq(streams, op, axis)
        link_by_seq = _link_by_seq(streams, op, axis)
        for seq in sorted(by_seq):
            entries = by_seq[seq]
            if set(entries) != ranks:
                # a rank died (or its file is missing) before this call:
                # no honest latest-entry exists — count, never fabricate
                row["unmatched"] += len(entries)
                continue
            latest_entry = max(e for e, _ in entries.values())
            culprit = max(entries, key=lambda r: entries[r][0])
            span_s = sum(max(x - e, 0.0) for e, x in entries.values())
            wait_s = wire_s = 0.0
            for rank, (entry, end) in entries.items():
                wait = latest_entry - entry
                if 0.0 < wait < unc:
                    # below the clock floor: the split is not supported
                    # by the measurement — whole span reads as wire
                    row["unresolved"] += 1
                    wait = 0.0
                wait_s += wait
                wire_s += max((end - entry) - wait, 0.0)
            row["calls"] += 1
            row["span_s"] += span_s
            row["wait_s"] += wait_s
            row["wire_s"] += wire_s
            row["wait_by_rank"][culprit] += wait_s
            nb = nbytes_by_seq.get(seq, 0) * len(entries)
            row["bytes"] += nb
            if span_s > 0:
                row["_wait_fracs"].append(wait_s / span_s)
            if nb and wire_s > unc:
                row["_pure_gbps"].append(nb / wire_s / 1e9)
            # per-link-class split (comm/topology.py wrapper stamps):
            # the same accumulators keyed by the call's link class —
            # present ONLY when spans carry ``link``, so flat-topology
            # runs keep the exact per-op row shape
            cls = link_by_seq.get(seq)
            if cls is not None:
                sub = row["by_link"].setdefault(cls, {
                    "calls": 0, "span_s": 0.0, "wait_s": 0.0,
                    "wire_s": 0.0, "bytes": 0, "_pure_gbps": [],
                })
                sub["calls"] += 1
                sub["span_s"] += span_s
                sub["wait_s"] += wait_s
                sub["wire_s"] += wire_s
                sub["bytes"] += nb
                if nb and wire_s > unc:
                    sub["_pure_gbps"].append(nb / wire_s / 1e9)
    for op, row in list(ops.items()):
        if not row["calls"] and not row["unmatched"]:
            del ops[op]
            continue
        row["wait_frac"] = (row["wait_s"] / row["span_s"]
                            if row["span_s"] > 0 else 0.0)
        row["eff_gbps"] = (row["bytes"] / row["span_s"] / 1e9
                           if row["bytes"] and row["span_s"] > 0 else None)
        # pure GB/s only when the wire residual clears the clock floor:
        # an all-wait call's "wire rate" would be fabricated bandwidth
        row["pure_gbps"] = (row["bytes"] / row["wire_s"] / 1e9
                            if row["bytes"] and row["wire_s"] > unc
                            else None)
        total_wait = sum(row["wait_by_rank"].values())
        row["wait_share"] = sorted(
            ((r, w / total_wait) for r, w in row["wait_by_rank"].items()
             if total_wait > 0 and w > 0),
            key=lambda rw: -rw[1],
        )
        # per-call spreads become the --diff noise bands: a run whose
        # wait_frac jitters call to call demands a bigger delta to flag
        row["wait_frac_band"] = _noise_band(row.pop("_wait_fracs"))
        row["pure_gbps_band"] = _noise_band(row.pop("_pure_gbps"))
        for sub in row["by_link"].values():
            sub["wait_frac"] = (sub["wait_s"] / sub["span_s"]
                                if sub["span_s"] > 0 else 0.0)
            sub["eff_gbps"] = (sub["bytes"] / sub["span_s"] / 1e9
                               if sub["bytes"] and sub["span_s"] > 0
                               else None)
            sub["pure_gbps"] = (sub["bytes"] / sub["wire_s"] / 1e9
                                if sub["bytes"] and sub["wire_s"] > unc
                                else None)
            sub["pure_gbps_band"] = _noise_band(sub.pop("_pure_gbps"))
        if not row["by_link"]:
            del row["by_link"]
    # per-class aggregate across ops — the TOPOLOGY table's GB/s rows;
    # absent (like every link surface) when no span carried a stamp
    by_link: dict[str, dict] = {}
    for row in ops.values():
        for cls, sub in (row.get("by_link") or {}).items():
            agg = by_link.setdefault(cls, {
                "calls": 0, "span_s": 0.0, "wait_s": 0.0,
                "wire_s": 0.0, "bytes": 0,
            })
            for k in ("calls", "span_s", "wait_s", "wire_s", "bytes"):
                agg[k] += sub[k]
    for agg in by_link.values():
        agg["wait_frac"] = (agg["wait_s"] / agg["span_s"]
                            if agg["span_s"] > 0 else 0.0)
        agg["eff_gbps"] = (agg["bytes"] / agg["span_s"] / 1e9
                           if agg["bytes"] and agg["span_s"] > 0
                           else None)
        agg["pure_gbps"] = (agg["bytes"] / agg["wire_s"] / 1e9
                            if agg["bytes"] and agg["wire_s"] > unc
                            else None)
    matrix = traffic_matrix(streams)
    links = edge_link_classes(streams)
    if not ops and not matrix:
        return None
    return {
        "clock_unc_s": unc,
        "clock_spread_s": {str(r): s for r, s in sorted(spreads.items())},
        "ops": ops,
        "matrix": {
            f"{src}->{dst}": dict(
                sorted(by_op.items()),
                total=sum(by_op.values()),
                **({"link": links[(src, dst)]}
                   if (src, dst) in links else {}),
            )
            for (src, dst), by_op in sorted(matrix.items())
        },
        "critical_path": critical_path(streams),
        **({"by_link": by_link} if by_link else {}),
    }


def _bytes_by_seq(streams, op: str, axis) -> dict[int, int]:
    """Per-seq payload bytes for one (op, axis) (any rank's record —
    SPMD payloads match; per-call so a size sweep prices each call
    right)."""
    out: dict[int, int] = {}
    for _rank, _offset, records in streams:
        for rec in records:
            if (_eligible(rec) and rec.get("op", "?") == op
                    and rec.get("axis") == axis and rec.get("nbytes")):
                out.setdefault(int(rec["seq"]), int(rec["nbytes"]))
    return out


def _link_by_seq(streams, op: str, axis) -> dict[int, str]:
    """Per-seq link class for one (op, axis) from the wrapper-build
    ``link`` stamp (``comm/topology.py``; first record wins — SPMD
    stamps match). Empty on flat-topology runs, which is the by_link
    degrade gate."""
    out: dict[int, str] = {}
    for _rank, _offset, records in streams:
        for rec in records:
            if (_eligible(rec) and rec.get("op", "?") == op
                    and rec.get("axis") == axis and rec.get("link")):
                out.setdefault(int(rec["seq"]), str(rec["link"]))
    return out


def wait_wire_subspans(streams) -> dict[tuple[str, Any, int], float]:
    """``{(op, axis, seq): latest_entry}`` for every fully matched call
    whose latest entry clears the clock floor — the timeline renderer's
    split points for wait/wire sub-spans (times on rank 0's clock)."""
    spreads = _clock_spreads(streams)
    unc = clock_uncertainty(spreads)
    out: dict[tuple[str, Any, int], float] = {}
    for (op, axis), per_rank in matched_calls(streams).items():
        if len(per_rank) < 2:
            continue
        by_seq: dict[int, dict[int, float]] = {}
        for rank, calls in per_rank.items():
            for seq, entry, _end in calls:
                by_seq.setdefault(seq, {}).setdefault(rank, entry)
        ranks = set(per_rank)
        for seq, entries in by_seq.items():
            if set(entries) != ranks:
                continue
            latest = max(entries.values())
            if latest - min(entries.values()) >= unc:
                out[(op, axis, seq)] = latest
    return out
