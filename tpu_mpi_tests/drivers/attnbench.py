"""Attention-tier benchmark driver: flash vs XLA local attention, plus the
sequence-parallel flavors, at CLI-selectable shapes.

Nothing attention-shaped exists in the reference (SURVEY.md §5.7) — this
driver benchmarks the capability its communication skeleton was built to
carry: ``softmax(q·kᵀ/√d)·v`` locally (the building block), and the ring /
Ulysses distributed flavors across the mesh. Output per configuration::

    ATTN <tier> L=<L> d=<D> <dtype> <tflops> TFLOP/s

Tiers: ``xla`` (materialized scores), ``flash`` (Pallas VMEM-tiled,
``kernels.pallas_kernels.flash_attention_pallas``), ``ring``/``ulysses``
(distributed; flash local compute, sequence sharded over the mesh axis).
Iterations chain device-side with the output fed back as the next query
(data-dependent, contention-robust; ``instrument.timers.chain_rate``).
FLOP accounting is the standard 4·L²·d per attention (2 matmuls), counted
globally for the distributed tiers. Correctness of every tier is gated by
``tests/test_ring.py`` against exact references; this driver measures.
"""

from __future__ import annotations

import functools
import sys

from tpu_mpi_tests.drivers import _common

TIERS = ("xla", "flash", "ring", "ulysses")


def run(args) -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_mpi_tests.comm.alltoall import ulysses_attention_fn
    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh, topology
    from tpu_mpi_tests.comm.ring import ring_attention_fn
    from tpu_mpi_tests.instrument.timers import chain_rate
    from tpu_mpi_tests.kernels.pallas_kernels import flash_attention_pallas
    from tpu_mpi_tests.utils import check_divisible

    dtype = _common.jnp_dtype(args)
    bootstrap()
    topo = topology()
    world = topo.global_device_count
    mesh = make_mesh()
    axis_name = mesh.axis_names[0]

    from tpu_mpi_tests.comm.ring import (
        _resolve_k_tile,
        _resolve_pipeline_depth,
        _resolve_ring_tier,
    )
    from tpu_mpi_tests.kernels.collectives_pallas import (
        fused_ring_feasible,
    )

    # stripe only affects the RING tier's layout; flash/ulysses always
    # run the contig defaults — the banner shows the REQUEST (None =
    # measured-best table) and each flash-kernel tier's JSONL row
    # carries its resolved tile CEILINGS (they still auto-shrink to
    # divisors at trace time; the xla tier records neither — never
    # mis-attribute a schedule)
    rep = _common.make_reporter(args, rank=topo.process_index, size=world)
    with rep:
        rep.banner(
            f"attnbench: L={args.seq_len} d={args.head_dim} tiers={args.tiers} "
            f"dtype={args.dtype} causal={args.causal} stripe={args.stripe} "
            f"k_tile={args.k_tile} skip_tile={args.skip_tile} "
            f"ring_tier={args.ring_tier} n_iter={args.n_iter} world={world}"
        )
        if args.stripe and args.dtype == "bfloat16":
            # measured regression, not an error: the striped balance win is
            # dtype-dependent (BASELINE round-5 stripebalance dtype note —
            # 1.42-1.51x at f32, 0.79-0.83x at bf16 where per-cell fixed
            # cost dominates the halved matmul work). Benchmarking the
            # combination is the point of this driver, so note, don't
            # block; banner = rank-0 only, like the config line above
            rep.banner(
                "NOTE --stripe at bfloat16: the striped layout measured "
                "SLOWER than contiguous at 16-bit (0.79-0.83x paced, "
                "BASELINE round-5) — it pays at float32 only"
            )

        L, d = args.seq_len, args.head_dim
        # causal computes only the lower triangle — half the matmul work
        # (flash-attn benchmark convention)
        flops = (2.0 if args.causal else 4.0) * L * L * d
        tiers = _common.parse_choice_list(args.tiers, TIERS, "tier")
        if tiers is None:
            return 2

        prec = lax.Precision.DEFAULT if args.fast else lax.Precision.HIGHEST

        def xla_attn(q, k, v):
            s = jnp.matmul(q, k.T, precision=prec) / (d**0.5)
            if args.causal:
                s = jnp.where(
                    jnp.tril(jnp.ones((L, L), bool)), s, -jnp.inf
                )
            return jnp.matmul(jax.nn.softmax(s, axis=-1), v, precision=prec)

        rc = 0
        tuned_layouts: set = set()
        for tier in tiers:
            striped = tier == "ring" and args.stripe

            def make_qkv(tier=tier):
                key = jax.random.PRNGKey(0)
                if tier in ("ring", "ulysses"):
                    check_divisible(L, world, "sequence over mesh axis")
                    shape = (L, world, d) if tier == "ulysses" else (L, d)
                    q, k, v = (
                        jax.random.normal(kk, shape, dtype)
                        for kk in jax.random.split(key, 3)
                    )
                    if tier == "ring" and args.stripe:
                        # striped causal layout (comm.ring.to_striped):
                        # balanced ring — every rank ~half-live at every
                        # step; the chained output stays in the striped
                        # layout, position-consistent with the next query
                        from tpu_mpi_tests.comm.ring import to_striped

                        q, k, v = (
                            to_striped(t, world) for t in (q, k, v)
                        )
                    return tuple(shard_1d(t, mesh) for t in (q, k, v))
                return tuple(
                    jax.random.normal(kk, (L, d), dtype)
                    for kk in jax.random.split(key, 3)
                )

            def make_attn(kt, st, tier=tier, depth=None, rtier=None):
                if tier == "ring":
                    return ring_attention_fn(
                        mesh, axis_name, causal=args.causal, flash=True,
                        precision=prec, stripe=args.stripe,
                        k_tile=kt, skip_tile=st,
                        depth=depth if depth is not None
                        else args.ring_depth,
                        tier=rtier if rtier is not None
                        else args.ring_tier,
                    )
                if tier == "ulysses":
                    return ulysses_attention_fn(
                        mesh, axis_name, causal=args.causal, flash=True,
                        precision=prec, k_tile=kt, skip_tile=st,
                    )
                if tier == "flash":
                    return functools.partial(
                        flash_attention_pallas, causal=args.causal,
                        precision=prec, k_tile=kt, skip_tile=st,
                    )
                return xla_attn

            def make_loop(attn):
                @functools.partial(jax.jit, donate_argnums=0)
                def loop(state, n):
                    def body(_, st):
                        qq, kk, vv = st
                        return attn(qq, kk, vv), kk, vv

                    return lax.fori_loop(
                        0, jnp.asarray(n, jnp.int32), body, state
                    )

                return loop

            # the flash-kernel tiers' local block length: what the tile
            # fit (and therefore the tuned optimum) actually sees
            lq_local = L // world if tier == "ring" else L
            if (
                args.tune and tier != "xla"
                and args.k_tile is None and args.skip_tile is None
            ):
                # measured tile sweep (cache miss only): each candidate
                # runs the REAL tier pipeline at a shortened chain, so
                # the winner prices ring pacing/skip behavior, not just
                # the local kernel. Explicit --k-tile/--skip-tile skip
                # the sweep entirely — explicit > cached > prior.
                from tpu_mpi_tests.tune.sweep import ensure_tuned

                layout = "striped" if striped else "contig"
                if (layout, lq_local) not in tuned_layouts:
                    tuned_layouts.add((layout, lq_local))
                    n_long = max(11, args.n_iter // 10)

                    def measure(cand):
                        # tile knobs parameterize the per-step flash
                        # kernel — pin the ring rotation to pipelined so
                        # a cached fused winner (which has no tile
                        # knobs) cannot flatten this sweep
                        loop = make_loop(
                            make_attn(cand["k_tile"], cand["skip_tile"],
                                      rtier="pipelined")
                        )
                        sec, st = chain_rate(
                            loop, make_qkv(),
                            n_short=n_long // 10 or 1, n_long=n_long,
                        )
                        del st
                        return sec

                    ensure_tuned(
                        f"flash_tiles/{layout}", measure,
                        dtype=args.dtype, lq=lq_local,
                    )

            if (
                args.tune and tier == "ring"
                and args.ring_depth is None
                and ("depth", lq_local) not in tuned_layouts
            ):
                # ring pipeline-depth sweep (ISSUE 7): each candidate
                # runs the REAL ring tier at a shortened chain, so the
                # winner prices the prefetch pipeline against the
                # matmul it hides under — results are depth-invariant
                # bit for bit, only the schedule changes
                from tpu_mpi_tests.tune.sweep import ensure_tuned

                tuned_layouts.add(("depth", lq_local))
                n_long = max(11, args.n_iter // 10)

                def measure_depth(cand):
                    # depth parameterizes the PIPELINED rotation only —
                    # pin the tier so a cached fused winner cannot turn
                    # this sweep into w identical fused measurements
                    loop = make_loop(
                        make_attn(args.k_tile, args.skip_tile,
                                  depth=int(cand), rtier="pipelined")
                    )
                    sec, st = chain_rate(
                        loop, make_qkv(),
                        n_short=n_long // 10 or 1, n_long=n_long,
                    )
                    del st
                    return sec

                ensure_tuned(
                    "ring/pipeline_depth", measure_depth,
                    dtype=args.dtype, lq=lq_local,
                )

            if (
                args.tune and tier == "ring"
                and args.ring_tier is None
                and ("tier", lq_local) not in tuned_layouts
            ):
                # ring rotation-tier sweep (ISSUE 19): price the
                # one-launch fused-RDMA kernel against the pipelined
                # ppermute ring on the REAL tier pipeline, after the
                # tile/depth sweeps so pipelined competes at its tuned
                # schedule. Infeasible geometry declines the sweep
                # outright — resolution then falls to the prior.
                from tpu_mpi_tests.tune.sweep import ensure_tuned

                tuned_layouts.add(("tier", lq_local))
                if not fused_ring_feasible(lq_local, lq_local, d, dtype):
                    _common.decline_note(
                        f"ring/tier sweep: fused candidate "
                        f"infeasible at lq={lq_local} d={d} "
                        f"{args.dtype} (live block set exceeds VMEM); "
                        f"keeping the pipelined tier"
                    )
                else:
                    n_long = max(11, args.n_iter // 10)

                    def measure_tier(cand):
                        loop = make_loop(
                            make_attn(args.k_tile, args.skip_tile,
                                      rtier=str(cand))
                        )
                        sec, st = chain_rate(
                            loop, make_qkv(),
                            n_short=n_long // 10 or 1, n_long=n_long,
                        )
                        del st
                        return sec

                    ensure_tuned(
                        "ring/tier", measure_tier,
                        dtype=args.dtype, lq=lq_local,
                    )

            # effective rotation tier for this row (ring only):
            # explicit > cached > prior, then the driver-level decline —
            # a fused request/winner at a geometry whose live set
            # exceeds VMEM runs the pipelined tier with a NOTE instead
            # of crashing mid-benchmark (the bench.py tier idiom)
            ring_tier_eff = None
            if tier == "ring":
                ring_tier_eff = _resolve_ring_tier(
                    args.ring_tier, dtype=args.dtype, lq=lq_local
                )
                if ring_tier_eff == "fused" and not fused_ring_feasible(
                    lq_local, lq_local, d, dtype
                ):
                    # same voice as bench.py's stencil-tier decline:
                    # stderr NOTE + the row/line stamp below names what
                    # actually ran — never a mislabeled headline
                    _common.decline_note(
                        f"ring tier fused infeasible at "
                        f"lq={lq_local} d={d} {args.dtype} (live block "
                        f"set exceeds VMEM); running the pipelined tier"
                    )
                    ring_tier_eff = "pipelined"

            attn = make_attn(args.k_tile, args.skip_tile,
                             rtier=ring_tier_eff)
            loop = make_loop(attn)
            state0 = make_qkv()
            # compile-cost probe (telemetry runs only): the chained loop
            # is THE hot fn of this tier — record its compile wall time
            # + cost model before chain_rate donates the state away
            # (lower/compile never execute, so the buffers survive)
            from tpu_mpi_tests.instrument import costs

            costs.compile_probe(
                loop, (state0, args.n_iter),
                label=f"attn_{tier}{'[striped]' if striped else ''}"
                      f"{'[fused]' if ring_tier_eff == 'fused' else ''}",
                dtype=args.dtype, lq=lq_local, world=world,
            )
            sec, state = chain_rate(
                loop, state0,
                n_short=args.n_iter // 10 or 1,
                n_long=args.n_iter,
            )
            del state
            tflops = flops / sec / 1e12
            heads = world if tier == "ulysses" else 1
            striped = tier == "ring" and args.stripe
            # schedule stamp (ISSUE 19 satellite, the bench.py _ov/_tier
            # idiom): the ring line names the EFFECTIVE rotation tier —
            # "[fused]" only when the one-launch kernel actually ran, so
            # the default pipelined line stays byte-identical
            tag = ("[striped]" if striped else "") + (
                "[fused]" if ring_tier_eff == "fused" else ""
            )
            row = {"kind": "attn", "tier": tier, "L": L, "d": d,
                   "dtype": args.dtype, "causal": args.causal,
                   "stripe": striped,
                   "tflops": tflops * heads, "us_per_iter": sec * 1e6,
                   "world": world}
            if tier == "ring":
                # schedule attribution (ISSUE 7 satellite): the
                # resolved prefetch pipeline depth this row ran with
                row["ring_depth"] = _resolve_pipeline_depth(
                    args.ring_depth, dtype=args.dtype, lq=lq_local
                )
                # rotation-tier attribution (ISSUE 19): the EFFECTIVE
                # tier after the feasibility decline above — never the
                # request, which may have been declined
                row["ring_tier"] = ring_tier_eff
            if tier != "xla":  # flash-kernel tiers only
                row["k_tile_ceiling"] = _resolve_k_tile(
                    args.k_tile, striped, dtype=args.dtype, lq=lq_local
                )
                if args.skip_tile is not None:
                    # explicit request: operative on both kernel paths
                    # (modulo the divisor snap)
                    row["skip_tile_ceiling"] = args.skip_tile
                else:
                    # None resolves PER PATH inside the kernel (layout table
                    # for resident, _STREAM_SKIP_TILE_DEFAULT for streaming)
                    # and the driver cannot know which path the fit takes —
                    # record the request, never a possibly-wrong constant
                    row["skip_tile_req"] = None
            rep.line(
                f"ATTN {tier}{tag} L={L} d={d} "
                f"{args.dtype} {tflops * heads:0.1f} TFLOP/s",
                row,
            )
            if not (tflops > 0):
                rep.line(f"ATTN FAIL {tier}: non-positive rate {tflops}")
                rc = 1
        return rc


def _serve_step_factory(mesh, shape, dtype):
    """Serve-mode handler: ``step_fn(n)`` runs ``n`` ring-attention
    blocks (sequence sharded over the mesh axis — the driver's ``ring``
    tier, XLA local blocks) with the output fed back as the next query.
    Batched as ``n`` dispatches of the persistent jitted step with one
    sync at the end — wrapping the shard_map ring in an *outer* jitted
    ``fori_loop`` trips the jax-0.4.x PartitionId SPMD limitation the
    attnbench ring tier already documents on CPU meshes. Shape is
    ``(L, head_dim)`` with L divisible by the mesh world.

    The ring's K/V prefetch pipeline depth resolves inside
    ``ring_attention`` like any other knob (``ring/pipeline_depth``,
    cached winner > prior 1 — README "Overlap engine"), so
    ``tpumt-serve`` steady-state traffic exercises the tuned pipelined
    ring without serve-side wiring."""
    import jax
    import jax.numpy as jnp

    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.ring import ring_attention_fn
    from tpu_mpi_tests.instrument.timers import block
    from tpu_mpi_tests.utils import check_divisible

    if len(shape) != 2:
        raise ValueError(f"attn wants an (L, head_dim) shape, got {shape}")
    L, d = shape
    world = mesh.devices.size
    check_divisible(L, world, "sequence over mesh axis")
    axis_name = mesh.axis_names[0]
    dt = jnp.dtype(dtype)
    attn = ring_attention_fn(mesh, axis_name, causal=False, flash=False)
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(kk, (L, d), dt)
        for kk in jax.random.split(key, 3)
    )
    state = {"s": tuple(shard_1d(t, mesh) for t in (q, k, v))}

    def step(n: int):
        qq, kk, vv = state["s"]
        for _ in range(n):
            qq = attn(qq, kk, vv)
        state["s"] = block((qq, kk, vv))

    step(1)  # compile + warm before traffic opens
    return step


_common.register_workload("attn", _serve_step_factory)


def main(argv=None) -> int:
    p = _common.base_parser(__doc__)
    p.add_argument("--seq-len", type=int, default=8192)
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--tiers", default="xla,flash",
                   help=f"comma list from {','.join(TIERS)}")
    p.add_argument("--causal", action="store_true")
    p.add_argument(
        "--stripe", action="store_true",
        help="striped causal layout for the ring tier (balanced: every "
        "rank ~half-live per step; requires --causal)",
    )
    p.add_argument(
        "--k-tile", type=int, default=None,
        help="flash kernel key-tile ceiling (auto-shrinks to fit). "
        "Default: the schedule cache's tuned winner for this topology, "
        "else the measured-best prior for the layout "
        "(comm.ring.MEASURED_BEST_K_TILE, pinned to BASELINE.md by "
        "tests/test_ring.py); an explicit value always wins over the "
        "cache. Since round 5's skip/rescale decoupling the causal "
        "skip granularity is the separate --skip-tile knob",
    )
    p.add_argument(
        "--skip-tile", type=int, default=None,
        help="causal sub-span skip granularity for the diagonal band "
        "(round 5, VERDICT r4 #1); 0 = coupled path (full-width "
        "masking). Default: the schedule cache's tuned winner, else "
        "the measured-best prior per layout "
        "(comm.ring.MEASURED_BEST_SKIP_TILE - striped wants 256-wide "
        "sub-span skipping, contiguous/self-causal runs best coupled); "
        "an explicit value always wins over the cache",
    )
    p.add_argument(
        "--ring-depth", type=int, default=None,
        help="ring K/V prefetch pipeline depth (ISSUE 7; README "
        "'Overlap engine'): 1 = rotate after compute (the historical "
        "schedule), d>=2 keeps d-1 rotations in flight ahead of the "
        "consuming matmul. Default: the schedule cache's tuned winner "
        "for this topology, else the prior (1); results are "
        "depth-invariant bit for bit. With --tune, a cache miss "
        "sweeps the candidates on the real ring tier first",
    )
    p.add_argument(
        "--ring-tier", default=None,
        help="ring K/V rotation tier (ISSUE 19; README 'Pallas "
        "collective tier'): 'pipelined' = the host-scheduled ppermute "
        "ring (paced by --ring-depth), 'fused' = the one-launch "
        "fused-RDMA Pallas kernel (whole rotation+compute loop in one "
        "dispatch; requires the local block set to fit VMEM — an "
        "infeasible geometry declines to pipelined with a NOTE). "
        "Default: the schedule cache's tuned winner for this topology, "
        "else the prior (pipelined). With --tune, a cache miss sweeps "
        "both tiers on the real ring pipeline",
    )
    p.add_argument(
        "--fast", action="store_true",
        help="MXU-native (DEFAULT) matmul precision instead of HIGHEST "
        "(the throughput configuration BASELINE.md quotes)",
    )
    p.add_argument("--n-iter", type=int, default=1100,
                   help="chained iterations (delta = n_iter - n_iter/10)")
    args = p.parse_args(argv)
    if args.seq_len < 8 or args.head_dim < 1:
        p.error("--seq-len must be >= 8 and --head-dim >= 1")
    if args.n_iter < 10:
        p.error("--n-iter must be >= 10")
    if args.ring_depth is not None and args.ring_depth < 1:
        p.error("--ring-depth must be >= 1")
    if args.ring_tier is not None and args.ring_tier not in (
        "pipelined", "fused"
    ):
        p.error("--ring-tier must be 'pipelined' or 'fused'")
    if args.k_tile is not None and args.k_tile < 8:
        p.error("--k-tile must be >= 8")
    if args.skip_tile is not None and args.skip_tile != 0 \
            and args.skip_tile < 8:
        p.error("--skip-tile must be 0 (legacy coupled path) or >= 8; "
                "the kernel snaps it down to a divisor of the fitted "
                "k_tile at trace time")
    if args.stripe and not args.causal:
        p.error("--stripe requires --causal (non-causal rings are "
                "already balanced)")
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


if __name__ == "__main__":
    sys.exit(main())
