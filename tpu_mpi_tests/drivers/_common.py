"""Shared driver plumbing: config flags and platform selection.

The reference's four config surfaces (argv positionals, compile-time defines,
build options, env — SURVEY.md §5.6) are unified here into one argparse layer
per driver; runtime flags replace the ``-DMANAGED``-style twin binaries.
"""

from __future__ import annotations

import argparse
import os
from typing import Callable

# ---------------------------------------------------------------------------
# Workload-handler registry (serve mode; first slice of the ROADMAP-4
# driver-boilerplate factor-out)
# ---------------------------------------------------------------------------

#: name -> factory ``(mesh, shape, dtype) -> step_fn``; ``step_fn(n)``
#: executes ``n`` coalesced requests against persistent state and returns
#: only after device completion (it blocks), so serve-mode latency reads
#: are sync-honest by contract, not by caller discipline
_WORKLOAD_FACTORIES: dict[str, Callable] = {}


def register_workload(name: str, factory: Callable) -> Callable:
    """Register a serve-mode workload handler under ``name``.

    Drivers register the step their benchmark already exercises (daxpy
    step, stencil1d halo step, attnbench ring block, collbench small
    allreduce) at import time, so serve mode dispatches them
    declaratively instead of copying driver bodies. Idempotent per name
    (test runners re-import driver modules); returns the factory so it
    can be used as a decorator."""
    _WORKLOAD_FACTORIES.setdefault(name, factory)
    return factory


def decline_note(msg: str) -> None:
    """Print a schedule-decline ``NOTE`` to stderr (flushed).

    The shared voice of every "requested schedule cannot run here"
    message (bench.py's tier/blocks/overlap declines, the fused
    ring/collective tier declines — ISSUE 19 satellite): stderr so the
    headline stdout stays parseable (bench.py's one-JSON-line contract),
    prefixed ``NOTE `` so log scrapers find every decline with one
    grep. Callers pass the message WITHOUT the prefix."""
    import sys

    print(f"NOTE {msg}", file=sys.stderr, flush=True)


def workload_names() -> tuple[str, ...]:
    _import_workload_owners()
    return tuple(sorted(_WORKLOAD_FACTORIES))


def workload_factory(name: str) -> Callable:
    """The registered factory for ``name``. Imports the owning driver
    modules on demand (like ``tune.registry._import_knob_owners``) so
    lookups never depend on who imported what first."""
    _import_workload_owners()
    try:
        return _WORKLOAD_FACTORIES[name]
    except KeyError:
        raise KeyError(
            f"no workload handler {name!r}; registered: "
            f"{','.join(sorted(_WORKLOAD_FACTORIES))}"
        ) from None


def _import_workload_owners() -> None:
    """Import every module that registers a handler. Lazy so the
    registry stays importable without jax (driver/spec modules only
    import jax inside their run/factory bodies). The workload-spec
    subsystem registers its pillars (daxpy, halo, moe, decode,
    embedding) through ``register_spec``; attnbench/collbench still
    register directly."""
    import tpu_mpi_tests.drivers.attnbench  # noqa: F401
    import tpu_mpi_tests.drivers.collbench  # noqa: F401
    from tpu_mpi_tests import workloads

    workloads.load_specs()


def base_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument(
        "--fake-devices",
        type=int,
        default=0,
        metavar="N",
        help="run on N fake CPU devices (distributed-on-CPU test mode; "
        "≅ running the reference under mpirun -np N on one box)",
    )
    p.add_argument(
        "--dtype",
        default="float32",
        choices=["float32", "float64", "bfloat16"],
        help="element type; reference is float64 (MPI_DOUBLE) — TPU default "
        "is float32, float64 enables the x64 software path",
    )
    p.add_argument(
        "--jsonl",
        default=None,
        help="append JSONL records here (multi-process runs auto-suffix "
        "the path per process: out.jsonl -> out.p<i>.jsonl; merge with "
        "tpumt-report)",
    )
    p.add_argument(
        "--telemetry",
        action="store_true",
        help="record a span (op/bytes/axis/seconds/GB/s) for every comm "
        "wrapper call into the JSONL sink plus cumulative per-op counters "
        "(instrument/telemetry.py); spans sync-honestly block on their op, "
        "so leave this off for pure-throughput timing runs",
    )
    p.add_argument(
        "--memwatch",
        action="store_true",
        help="record HBM watermarks + live-array census as kind:'mem' "
        "JSONL records (instrument/memwatch.py): a low-rate sampler "
        "thread plus per-phase begin/end snapshots; needs --jsonl. "
        "tpumt-trace renders them as per-device counter tracks, "
        "tpumt-report as the MEMORY table; degrades to census-only "
        "where device.memory_stats() is unavailable (CPU/fake devices)",
    )
    p.add_argument(
        "--mem-interval",
        type=float,
        default=0.5,
        metavar="S",
        help="memwatch sampler period in seconds (default 0.5; the "
        "sampler exists to draw a counter track, not to profile "
        "allocation churn)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="arm the live observability plane (instrument/metrics.py): "
        "tee every JSONL record into an in-process metrics registry, "
        "serve it as OpenMetrics text at http://<host>:PORT/metrics "
        "(rank 0 only unless --metrics-all-ranks; 0 = ephemeral port), "
        "emit periodic kind:'health' heartbeat records, and stream "
        "per-phase progress snapshots so tpumt-top / tpumt-doctor "
        "--follow can watch the run live (README 'Live observability'); "
        "disarmed runs install nothing",
    )
    p.add_argument(
        "--metrics-interval",
        type=float,
        default=1.0,
        metavar="S",
        help="heartbeat + phase-progress emission period in seconds "
        "(default 1.0); only meaningful with --metrics-port",
    )
    p.add_argument(
        "--metrics-all-ranks",
        action="store_true",
        help="serve the /metrics endpoint on every rank at "
        "PORT + process_index instead of rank 0 only (the registry, "
        "heartbeats, and progress records are per-rank either way)",
    )
    p.add_argument(
        "--profile-dir",
        default=None,
        help="capture an XProf trace to this dir (≅ nsys -c cudaProfilerApi)",
    )
    p.add_argument(
        "--trace-out",
        default=None,
        metavar="TRACE.json",
        help="on driver exit, merge the --jsonl record stream(s) into "
        "Chrome trace-event JSON here (rank 0 only; one track per rank, "
        "clock offsets applied) — open in Perfetto/chrome://tracing, or "
        "run tpumt-trace offline for the same merge",
    )
    p.add_argument(
        "--tune",
        action="store_true",
        help="arm the measured autotuner: a hot-path knob with no cache "
        "entry for this topology runs an on-device candidate sweep and "
        "persists the winner (README 'Autotuning'); without this flag "
        "cached winners still apply but misses fall back to the shipped "
        "priors",
    )
    p.add_argument(
        "--tune-cache",
        default=None,
        metavar="PATH",
        help="schedule cache file (default: $TPU_MPI_TUNE_CACHE, else "
        "~/.cache/tpumt/tune.json); corrupted/stale files fall back to "
        "priors",
    )
    p.add_argument(
        "--tune-pack",
        default=None,
        metavar="PACK",
        help="preload a portable schedule pack (tpumt-tune pack/merge "
        "— README 'Fleet tuning') into the in-memory cache before any "
        "knob resolves: a fleet of identical topologies tunes once and "
        "ships the artifact with the deployment; fingerprints still "
        "gate which entries apply, and a corrupted pack degrades to "
        "empty (priors) rather than failing the run",
    )
    p.add_argument(
        "--tune-budget",
        type=float,
        default=60.0,
        metavar="S",
        help="wall-clock budget per sweep in seconds: the prior is "
        "always measured, later candidates are dropped (and reported "
        "as skipped) once the budget is spent (default 60)",
    )
    p.add_argument(
        "--compile-cache",
        default=None,
        metavar="DIR",
        help="enable jax's persistent compilation cache in DIR "
        "($TPU_MPI_COMPILE_CACHE) so repeat runs skip XLA recompiles — "
        "measured warmup delta in README 'Autotuning'",
    )
    p.add_argument(
        "--chaos",
        default=None,
        metavar="SPEC",
        help="arm deterministic fault injection ($TPU_MPI_CHAOS when "
        "absent): comma list of class[:key=value]* faults — kill / "
        "straggler / wedge / oom / flood, e.g. "
        "'kill:rank=1:op=halo_exchange:after=3' (grammar in README "
        "'Chaos & diagnosis'); disarmed runs install zero chaos state "
        "by construction",
    )
    p.add_argument(
        "--verbose", action="store_true", help="extra per-device reporting"
    )
    p.add_argument(
        "--debug-nans",
        action="store_true",
        help="abort on NaN production (the framework's sanitizer axis, "
        "SURVEY §5.2 — ≅ the correctness-by-construction DEBUG builds)",
    )
    p.add_argument(
        "--deadline",
        type=float,
        default=None,
        metavar="S",
        help="hang watchdog: hard-exit if the driver exceeds S seconds "
        "(detects hung collectives from dead peers; ≅ the scheduler "
        "walltime the reference relied on, made first-class)",
    )
    return p


def run_guarded(run, args) -> int:
    """Run a driver body under the optional hang watchdog."""
    from tpu_mpi_tests.instrument.watchdog import deadline

    with deadline(args.deadline, "driver"):
        return run(args)


def make_reporter(args, rank: int = 0, size: int = 1,
                  manifest_extra: dict | None = None):
    """Build the driver's Reporter with the full observability wiring —
    one call so every driver gets it without per-driver plumbing:

    * per-process JSONL suffixing (multi-process runs never append to one
      shared file — ``tpumt-report`` merges the suffixed set);
    * a run-manifest record (``kind: "manifest"``) as the first JSONL
      line whenever a sink is configured, so every result file is
      self-describing;
    * a clock-alignment record (``kind: "clock_sync"``): multi-process
      runs estimate each rank's wall-clock offset from rank 0 via the
      barrier-echo handshake so ``tpumt-trace``/``--trace-out`` can
      merge the per-rank streams onto one time axis (single-process
      runs record offset 0);
    * with ``--telemetry``: the telemetry registry is enabled with the
      reporter's JSONL as its span sink, a rank-0 manifest banner is
      printed, and closing the reporter (drivers hold it in a ``with``
      block) flushes per-op counter lines and disables the registry.
      ``--trace-out`` makes that close also merge the run's JSONL into
      a Perfetto-loadable trace (rank 0).
    """
    import jax

    from tpu_mpi_tests.instrument.report import Reporter

    trace_out = getattr(args, "trace_out", None)
    if trace_out and not args.jsonl:
        print("NOTE --trace-out needs --jsonl (the trace is merged from "
              "the JSONL record stream); no trace will be written")
        trace_out = None
    rep = Reporter(
        rank=rank,
        size=size,
        jsonl_path=args.jsonl,
        proc_index=jax.process_index(),
        proc_count=jax.process_count(),
        trace_out=trace_out,
    )
    _arm_metrics(args, rep)
    telemetry_on = getattr(args, "telemetry", False)
    if rep.jsonl_path or telemetry_on:
        from tpu_mpi_tests.instrument.manifest import (
            clock_sync_record,
            manifest_banner,
            run_manifest,
        )

        # manifest_extra: driver-known run identity (e.g. the serve
        # driver's replay traffic fingerprint) folded into the
        # kind:"manifest" record — the manifest schema is open by
        # design (run_manifest merges **extra)
        m = run_manifest(**(manifest_extra or {}))
        rep.jsonl(m)
        # manifest-adjacent topology audit record (comm/topology.py):
        # world/host/slice structure + link classes, per run — emitted
        # unconditionally (a flat run records its declared flatness;
        # the REPORT surfaces stay silent on it)
        from tpu_mpi_tests.comm.topology import topo_record

        rep.jsonl(topo_record())
        if rep.rank == 0:
            _check_pack_topology(args)
        if rep.jsonl_path:
            cs = clock_sync_record()
            rep.jsonl(cs)
            # run identity for the --trace-out merge's stale-file filter
            rep.run_sync_us = cs.get("run_sync_us")
        if telemetry_on:
            rep.banner(manifest_banner(m))
    if telemetry_on:
        from tpu_mpi_tests.instrument import telemetry as T

        T.enable(sink=lambda rec: rep.jsonl({**rec, "rank": rep.rank}))
        rep.attach_telemetry()
    if getattr(args, "memwatch", False):
        if rep.jsonl_path:
            from tpu_mpi_tests.instrument.memwatch import MemWatch

            rep.attach_memwatch(
                MemWatch(
                    sink=lambda rec: rep.jsonl({**rec, "rank": rep.rank}),
                    interval_s=getattr(args, "mem_interval", 0.5),
                ).start()
            )
        else:
            print("NOTE --memwatch needs --jsonl (mem records stream to "
                  "the JSONL sink); no memory records will be written")
    _attach_tune_sink(rep)
    _arm_chaos(args, rep)
    return rep


def _arm_metrics(args, rep) -> None:
    """The ONE live-plane arm-point: with ``--metrics-port`` set, tee
    the Reporter's record stream into a
    :class:`~tpu_mpi_tests.instrument.metrics.MetricsRegistry`, start
    the heartbeat thread + per-phase progress hook, and (rank 0 by
    default, every rank with ``--metrics-all-ranks``) serve the
    registry as OpenMetrics at ``--metrics-port``. Without the flag
    nothing is imported and nothing is installed — the disarmed run is
    byte-identical to a build without the live modules (the PR-9
    zero-cost pattern, pinned in tests/test_metrics.py)."""
    port = getattr(args, "metrics_port", None)
    if port is None:
        return
    from tpu_mpi_tests.instrument.export import Heartbeat, MetricsExporter
    from tpu_mpi_tests.instrument.metrics import (
        MetricsRegistry,
        PhaseProgress,
    )

    def sink(rec):
        # stamp the TRUE process index, not rep.rank: meshless specs
        # pass rank=0 to make_reporter in every process (the _arm_chaos
        # lesson below), and the heartbeat trail exists precisely to
        # tell per-RANK liveness apart in multi-process runs
        rep.jsonl({**rec, "rank": rep.proc_index})

    interval = getattr(args, "metrics_interval", 1.0)
    reg = MetricsRegistry(health_sink=sink)
    rep.attach_metrics(reg)
    rep.attach_live(
        PhaseProgress(sink, interval_s=interval).start(),
        Heartbeat(reg, sink, interval_s=interval).start(),
    )
    all_ranks = getattr(args, "metrics_all_ranks", False)
    if rep.proc_index == 0 or all_ranks:
        bind = int(port) + (rep.proc_index if all_ranks and port else 0)
        try:
            exporter = MetricsExporter(reg, bind).start()
        except OSError as e:
            rep.line(f"METRICS ERROR: cannot bind port {bind}: {e}")
        else:
            rep.attach_live(exporter)
            rep.line(f"METRICS rank {rep.proc_index}: OpenMetrics at "
                     f"http://0.0.0.0:{exporter.port}/metrics")


def _arm_chaos(args, rep) -> None:
    """The ONE sanctioned chaos arm-point (lint rule TPM1001 fails any
    other import of the chaos package outside tests): with ``--chaos``
    or ``$TPU_MPI_CHAOS`` set, install the faults targeting this
    process rank and audit them to the JSONL sink. Without a spec,
    nothing is imported and nothing is installed — the disarmed run is
    byte-identical to a build without the chaos layer."""
    spec_text = getattr(args, "chaos", None) or os.environ.get(
        "TPU_MPI_CHAOS"
    )
    if not spec_text:
        return
    import jax

    from tpu_mpi_tests import chaos

    try:
        # fault targeting AND the audit records key on the TRUE
        # process index, not rep.rank: meshless specs (daxpy) pass
        # rank=0 to make_reporter in every process, which would make
        # `rank=1` faults unarmable there — and would stamp rank 1's
        # armed/fire records as rank 0 in the merged post-mortem
        proc = jax.process_index()
        mine = chaos.arm_from_spec(
            spec_text, rank=proc,
            emit=lambda rec: rep.jsonl({**rec, "rank": proc}),
        )
    except ValueError as e:
        print(f"ERROR bad --chaos spec: {e}")
        raise SystemExit(2) from None
    for s in mine:
        rep.line(f"CHAOS armed: {s.describe()}")
        if s.op and not getattr(args, "telemetry", False):
            rep.line(f"NOTE chaos fault {s.raw!r} triggers on telemetry "
                     f"spans but --telemetry is off; it will never fire")


def _attach_tune_sink(rep) -> None:
    """Point the autotuner's sweep records at this run's Reporter: every
    ``tune``/``tune_result``/``tune_hit`` record lands in the JSONL
    stream (``tpumt-report`` renders the tuning table from them) and
    winners/hits get a stable ``TUNE`` stdout line."""
    from tpu_mpi_tests.tune import registry as tr

    # single-writer contract: setup_tuning configured the cache BEFORE
    # bootstrap initialized jax.distributed, so the non-zero-rank
    # read-only marking must be applied now that the rank is known
    tr.mark_fleet_rank()
    if tr.configured_cache() is None:
        return

    import json as _json

    def emit(rec):
        # stamp the TRUE process index, not rep.rank: meshless specs
        # (daxpy) pass rank=0 to make_reporter in every process, and a
        # fleet sweep's per-rank tune records exist precisely to show
        # which rank measured and which applied the broadcast winner
        rep.jsonl({**rec, "rank": rep.proc_index})
        kind = rec.get("kind")
        if kind == "tune_result":
            sec = rec.get("seconds")
            rep.line(
                f"TUNE {rec['knob']} winner={_json.dumps(rec['value'])} "
                f"seconds={'-' if sec is None else f'{sec:.6g}'} "
                f"measured={rec.get('measured', 0)} "
                f"skipped={rec.get('skipped', 0)}"
            )
        elif kind == "tune_hit":
            rep.line(
                f"TUNE {rec['knob']} cache-hit "
                f"value={_json.dumps(rec['value'])}"
            )

    tr.set_emit(emit)


def force_cpu_devices(n: int) -> None:
    """Force the CPU backend with ``n`` fake devices.

    The image's sitecustomize registers the TPU plugin programmatically, so
    this must go through jax.config, not just the env var. XLA_FLAGS is read
    only at first backend init — call before any JAX backend use; a live
    backend keeps its device count (callers must fail-fast on too few).
    """
    import jax

    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized; device check happens downstream


def enable_compile_cache(path: str) -> None:
    """Point jax's persistent compilation cache at ``path`` (created if
    missing) with the thresholds floored so even CPU-fast compiles
    cache. Unknown config names on older jax are skipped — the cache is
    an accelerant, never a hard dependency."""
    import jax

    os.makedirs(path, exist_ok=True)
    for key, val in (
        ("jax_compilation_cache_dir", path),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(key, val)
        except (AttributeError, ValueError):
            pass


def setup_platform(args) -> None:
    """Apply platform/dtype config. Must run before any JAX backend use."""
    import jax

    if args.fake_devices:
        force_cpu_devices(args.fake_devices)
    if args.dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    if getattr(args, "debug_nans", False):
        jax.config.update("jax_debug_nans", True)
    compile_cache = getattr(args, "compile_cache", None) or os.environ.get(
        "TPU_MPI_COMPILE_CACHE"
    )
    if compile_cache:
        enable_compile_cache(compile_cache)
    setup_tuning(args)


def _check_pack_topology(args) -> None:
    """Topology-portability visibility for ``--tune-pack``: the
    fingerprints already guarantee a mismatched-shape entry never
    resolves (hosts/ranks-per-host are key fields — tune/fingerprint),
    so a pack tuned on a different slice shape silently contributes
    nothing. This note names that at run start instead of leaving the
    user to wonder why the pack "didn't take". Same-shape packs (or
    flat-on-flat) say nothing. Never raises — observability only."""
    pack_path = getattr(args, "tune_pack", None)
    if not pack_path:
        return
    try:
        from tpu_mpi_tests.comm.topology import current
        from tpu_mpi_tests.tune import pack as tp

        doc = tp.load_pack(pack_path)
        packed = tp.provenance(
            doc.get("entries") or []
        ).get("topologies") or []
        live = current().label()
        if packed and live not in packed:
            decline_note(
                f"--tune-pack {pack_path}: pack topology "
                f"{','.join(packed)} does not match this run's "
                f"{live}; its schedule entries will not resolve here"
            )
    except Exception:
        pass


def setup_tuning(args) -> None:
    """Configure the schedule-cache registry for this run (idempotent;
    ``make_reporter`` re-configures with the reporter's JSONL sink).

    The cache loads when the run asked for tuning (``--tune`` /
    ``--tune-cache`` / ``--tune-pack``) or when the default cache file
    already exists — so a warmed machine benefits without flags, while
    a pristine machine (no cache, no ``--tune``) resolves every
    schedule from the shipped priors, byte-identical to the
    pre-autotuner behavior. A ``--tune-pack`` artifact is absorbed into
    the in-memory cache (newer-measurement-wins against local entries)
    so every later resolution sees the shipped schedules."""
    from tpu_mpi_tests.tune import cache as tc
    from tpu_mpi_tests.tune import registry as tr

    path = getattr(args, "tune_cache", None) or tc.default_cache_path()
    pack_path = getattr(args, "tune_pack", None)
    wants = (getattr(args, "tune", False)
             or getattr(args, "tune_cache", None) or pack_path)
    if not wants and not os.path.exists(path):
        return
    cache = tr.configure(
        cache_path=path,
        enabled=getattr(args, "tune", False),
        budget_s=getattr(args, "tune_budget", None),
    )
    if pack_path:
        from tpu_mpi_tests.tune import pack as tp

        doc = tp.load_pack(pack_path)
        if not doc["entries"]:
            print(f"NOTE --tune-pack {pack_path}: empty or unreadable "
                  f"pack; resolving from the local cache/priors")
        else:
            n = tp.absorb(cache, doc)
            print(f"TUNE PACK {pack_path}: {n} of "
                  f"{len(doc['entries'])} schedule entries preloaded")


def jnp_dtype(args):
    import jax.numpy as jnp

    return {
        "float32": jnp.float32,
        "float64": jnp.float64,
        "bfloat16": jnp.bfloat16,
    }[args.dtype]


def parse_grid_mesh(spec: "str | None", n_dev: int):
    """Resolve a 'PX,PY' process-grid spec (or auto-factor ``n_dev`` into
    the squarest grid when None) → ``(px, py)``. Returns None after
    printing an ERROR line when the spec is malformed, non-positive, or
    does not multiply to the device count — shared by every 2-D-grid
    driver so a hardening fix cannot miss one of them."""
    if spec:
        try:
            px, py = (int(v) for v in spec.split(","))
        except ValueError:
            print(f"ERROR --mesh must be 'PX,PY', got {spec!r}")
            return None
        if px < 1 or py < 1:
            print(f"ERROR --mesh factors must be positive, got {px},{py}")
            return None
    else:
        px = 1
        for cand in range(int(n_dev**0.5), 0, -1):
            if n_dev % cand == 0:
                px = cand
                break
        py = n_dev // px
    if px * py != n_dev:
        print(f"ERROR --mesh {px},{py} needs {px * py} devices, "
              f"have {n_dev}")
        return None
    return px, py


def parse_choice_list(spec: str, valid, what: str = "entries"):
    """Split a comma list and validate each entry against ``valid``.
    Returns the list, or None after printing an ERROR line — shared by the
    sweep drivers (collbench, attnbench) so a hardening fix cannot miss
    one of them."""
    names = [s.strip() for s in spec.split(",") if s.strip()]
    bad = [n for n in names if n not in valid]
    if bad or not names:
        print(f"ERROR unknown {what} {bad or [spec]}; "
              f"valid: {','.join(valid)}")
        return None
    return names


def resolve_kernel_auto(dtype: str, n: int, world: int, rep) -> str:
    """Map the ``stencil/tier`` cache winner onto a driver's xla/pallas
    update-body choice (``--kernel auto``, ISSUE 15): the "xla" tier
    keeps the expression form, every hand tier maps to the in-place
    pallas body. ONE copy of the policy for every tiered driver
    (stencil2d, heat2d), with the resolution NOTE'd so the run's
    provenance is visible (README "Kernel tiers")."""
    from tpu_mpi_tests.comm.halo import resolve_stencil_tier

    tier = resolve_stencil_tier(None, dtype=dtype, n=n, world=world)
    kernel = "xla" if tier == "xla" else "pallas"
    rep.line(f"NOTE --kernel auto -> {kernel} (stencil/tier {tier})")
    return kernel


def pick_kernel_tier(build, probe_args, kernel: str, rep, label: str = "step"):
    """Return ``(step, effective_kernel)`` for drivers with an XLA/pallas
    update-body choice. The pallas tier is probed at trace time (no
    execution); only the documented "VMEM budget" width limit falls back
    to XLA — with a visible NOTE, never silently — and the probed step is
    reused, not rebuilt. Any other trace error still raises.

    With telemetry enabled the chosen step is also AOT compile-probed
    (instrument/costs.py): compile wall time + the compiler's
    flops/bytes model land as a ``kind: "compile"`` record under
    ``label``, the shared wrap point for every tiered driver."""
    import jax

    from tpu_mpi_tests.instrument import costs

    if kernel == "pallas":
        step = build("pallas")
        try:
            jax.eval_shape(step, *probe_args)
            costs.compile_probe(step, tuple(probe_args), label=label,
                                kernel="pallas")
            return step, "pallas"
        except ValueError as e:
            if "VMEM budget" not in str(e):
                raise
            rep.line(f"NOTE pallas kernel unavailable, using xla ({e})")
    step = build("xla")
    costs.compile_probe(step, tuple(probe_args), label=label, kernel="xla")
    return step, "xla"
