"""Multi-rank DAXPY with device + managed allocation pairs.

≅ ``mpi_daxpy.cc`` / ``mpi_daxpy_gt.cc``: every rank runs the same DAXPY on
its block; both an explicit-device pair and a "managed" pair are allocated
and introspected (MEMINFO), the kernel runs on the **managed** pair
(``mpi_daxpy.cc:140-141``) and the checksum is read host-side from managed
memory (``:152-156``); each rank prints ``rank/size SUM = <v>``. The
``MEMORY_PER_CORE`` env probe (``:99-108``) is preserved.

Ranks are mesh devices; run with ``--fake-devices N`` for the reference's
``mpirun -np N`` shape on one box.
"""

from __future__ import annotations

import os
import sys

import numpy as np

from tpu_mpi_tests.drivers import _common


def run(args) -> int:
    import jax
    import jax.numpy as jnp

    import tpu_mpi_tests.kernels.daxpy as kd
    from tpu_mpi_tests.comm import collectives as C
    from tpu_mpi_tests.comm.mesh import (
        bootstrap,
        check_divisible,
        device_report,
        make_mesh,
        topology,
    )
    from tpu_mpi_tests.arrays.spaces import Space, ensure_device, meminfo, place
    from tpu_mpi_tests.comm.mesh import ranks_per_device
    from tpu_mpi_tests.utils import TpuMtError
    from tpu_mpi_tests.instrument.timers import block

    dtype = _common.jnp_dtype(args)
    bootstrap()
    topo = topology()
    mesh = make_mesh()
    n_dev = topo.global_device_count
    # oversubscription: logical world may exceed the device count
    # (≅ ranks_per_device, mpi_daxpy.cc:49-51; each chip carries k logical
    # ranks inside one program — SURVEY §7 hard part 5)
    world = args.ranks or n_dev
    if world < n_dev:
        raise TpuMtError(
            f"--ranks {world} < device count {n_dev}: undersubscription is "
            "not emulated (shards must cover every device)"
        )
    k = ranks_per_device(world)
    n = check_divisible(args.n_total, world, "n_total over ranks")

    rep = _common.make_reporter(args, rank=topo.process_index, size=world)
    with rep:
        if k > 1:
            rep.banner(f"{world} logical ranks over {n_dev} devices "
                       f"({k} ranks/device)")

        # env probe (mpi_daxpy.cc:99-108)
        mb_per_core = os.environ.get("MEMORY_PER_CORE")
        if mb_per_core is None:
            rep.banner("MEMORY_PER_CORE is not set")
        else:
            rep.banner(f"MEMORY_PER_CORE={mb_per_core}")
        rep.banner(device_report(verbose=args.verbose))

        # every rank initializes the same local values x=i+1, y=-(i+1)
        # (mpi_daxpy.cc:94-97) — globally that's the per-rank pattern tiled
        lx, ly = kd.init_xy_np(n, dtype)
        h_x = np.tile(lx, world)
        h_y = np.tile(ly, world)

        # explicit-device pair AND managed pair (mpi_daxpy.cc:115-119)
        d_x = C.shard_1d(jnp.asarray(h_x), mesh)
        d_y = C.shard_1d(jnp.asarray(h_y), mesh)
        m_x = place(h_x, Space.MANAGED, d_x.sharding)
        m_y = place(h_y, Space.MANAGED, d_y.sharding)
        if args.verbose:
            for name, a in [("d_x", d_x), ("d_y", d_y), ("m_x", m_x),
                            ("m_y", m_y)]:
                rep.line(f"MEMINFO {name}: {meminfo(a)}")

        # kernel runs on the managed pair (mpi_daxpy.cc:140-141); managed
        # arrays migrate to HBM on first device touch (arrays/spaces.py)
        m_x, m_y = ensure_device(m_x), ensure_device(m_y)
        m_y = block(kd.daxpy(jnp.asarray(args.a, dtype), m_x, m_y))

        # per-rank checksums of the managed result (mpi_daxpy.cc:152-156);
        # computed as a collective so multi-host processes can all read them
        sums = (
            C.per_rank_sums(m_y, mesh, groups_per_shard=k)
            .astype(np.float64)
            .reshape(-1)
        )
        for r in range(world):
            rep.sum_line(sums[r], rank=r)

        expected = kd.expected_checksum(n)
        tol = 0 if args.dtype == "float64" else max(1e-5 * expected, 1.0)
        ok = all(abs(s - expected) <= tol for s in sums)
        if not ok:
            rep.line(f"CHECKSUM FAIL: {sums} != {expected}")
            return 1
        del d_x, d_y
        return 0


def main(argv=None) -> int:
    p = _common.base_parser(__doc__)
    p.add_argument(
        "--n-total",
        type=int,
        default=1 << 20,
        help="total elements across ranks (split evenly)",
    )
    p.add_argument("--a", type=float, default=2.0)
    p.add_argument(
        "--ranks",
        type=int,
        default=None,
        help="logical rank count; > device count emulates oversubscription "
        "(≅ more MPI ranks than GPUs, mpi_daxpy.cc:49-51)",
    )
    args = p.parse_args(argv)
    if args.n_total < 1:
        p.error(f"--n-total must be positive, got {args.n_total}")
    if args.ranks is not None and args.ranks < 1:
        p.error(f"--ranks must be positive, got {args.ranks}")
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


if __name__ == "__main__":
    sys.exit(main())
