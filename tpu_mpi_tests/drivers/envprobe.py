"""Environment propagation probe.

≅ ``mpienv.f90``: every rank reads ``MEMORY_PER_CORE`` (or a flag-chosen
variable) and prints what it sees — debugging env propagation through the
launch stack (the reference chased Spectrum-MPI eating this variable,
``mpi_daxpy.cc:99-101``). In the JAX model env propagates per *process*, so
one line is printed per process and one per local device row.
"""

from __future__ import annotations

import os
import sys

from tpu_mpi_tests.drivers import _common


def run(args) -> int:
    import jax

    from tpu_mpi_tests.comm.mesh import bootstrap, topology

    bootstrap()
    topo = topology()
    rep = _common.make_reporter(
        args, rank=topo.process_index, size=topo.process_count
    )
    with rep:
        val = os.environ.get(args.var)
        shown = val if val is not None else "<not set>"
        rep.line(
            f"{topo.process_index}/{topo.process_count} {args.var}={shown}",
            {"kind": "envprobe", "var": args.var, "value": val,
             "rank": topo.process_index},
        )
        if args.verbose:
            for d in jax.local_devices():
                rep.line(
                    f"{topo.process_index}/{topo.process_count} "
                    f"device {d.id} ({d.device_kind}) sees {args.var}={shown}"
                )
        return 0


def main(argv=None) -> int:
    p = _common.base_parser(__doc__)
    p.add_argument(
        "--var",
        default="MEMORY_PER_CORE",
        help="environment variable to probe (reference: MEMORY_PER_CORE)",
    )
    args = p.parse_args(argv)
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


if __name__ == "__main__":
    sys.exit(main())
