"""Flagship 2-D stencil benchmark: full dim × space × staging test matrix.

≅ ``mpi_stencil2d_gt.cc`` (call stack SURVEY.md §3.2). A 2-D array is
decomposed along the derivative dim (0 or 1); each test runs ``n_warmup``
untimed + ``n_iter`` timed halo exchanges, applies the 5-point stencil, and
reports the rank-summed exchange time plus the rank-summed error norm vs the
analytic derivative of z = x³ + y²::

    TEST dim:<d>, <device|managed>, buf:<b>; <seconds>, err=<e>

followed by the axis-reduction + in-place-allreduce benchmark
(``test_sum``, ``mpi_stencil2d_gt.cc:574-649``)::

    TEST dim:<d>, <device|managed>; allreduce=<seconds>

Matrix semantics (staging ↔ the reference's ``buf`` flag):

* dim 0 (non-contiguous in the reference): device staging is mandatory
  there, so ``buf:0`` → DEVICE_STAGED, ``buf:1`` → HOST_STAGED
  (``stage_host``, ``mpi_stencil2d_gt.cc:148-156``).
* dim 1 (contiguous): ``buf:0`` → DIRECT (MPI straight on device views),
  ``buf:1`` → DEVICE_STAGED (``stage_device``, ``:258-373``).
* ``--managed`` adds the managed-space twins (``TEST_MANAGED`` matrix,
  ``:696-728``): arrays start host-resident (pinned host memory kind) and
  migrate on first device use.

Timing discipline (≅ the reference hot loop ``mpi_stencil2d_gt.cc:511-535``):
each iteration hard-syncs, reads the clock around the exchange alone, then
runs the 5-point stencil (untimed but executing, preserving the reference's
exchange/compute iteration structure — note the end-of-iteration sync means
the exchange starts from a drained device, exactly as the reference's
``gt::synchronize`` at :534 drains before the next ``clock_gettime`` at
:512). Warmup iterations run identically but are not accumulated. Per-iteration mean/min/max past warmup are reported on
``ITER`` lines (a slow link shows up as max≫mean jitter); ``--fused`` times
exchange+stencil as one compiled program instead, for the split-vs-fused A/B.
The reported total seconds are multiplied by the logical world size to match
the reference's ``MPI_Reduce(MPI_SUM)`` of per-rank times (``:562-566``).

Over a high-latency controller link (the axon tunnel adds ~106 ms per hard
sync) the per-iteration sync floor dominates; reduce ``--n-iter`` there, or
use ``bench.py`` (device-side ``lax.fori_loop`` chaining) for throughput.
"""

from __future__ import annotations

import sys
import time

import numpy as np

from tpu_mpi_tests.drivers import _common


def _deriv_test(args, mesh, topo, rep, dim: int, space: str, buf: bool) -> int:
    import jax

    from tpu_mpi_tests.arrays.domain import Domain2D
    from tpu_mpi_tests.arrays.spaces import Space
    from tpu_mpi_tests.comm import collectives as C
    from tpu_mpi_tests.comm import halo as H
    from tpu_mpi_tests.instrument.timers import PhaseTimer, block
    from tpu_mpi_tests.kernels.stencil import analytic_pairs

    dtype = _common.jnp_dtype(args)
    world = topo.global_device_count
    axis_name = mesh.axis_names[0]
    d = Domain2D(
        n_local_deriv=args.n_local,
        n_global_other=args.n_other,
        n_shards=world,
        dim=dim,
    )
    f, df = analytic_pairs()[f"2d_dim{dim}"]

    if args.rdma:
        # hand-written remote-DMA ring kernel replaces every staged path
        staging = H.Staging.PALLAS_RDMA
    elif dim == 0:
        staging = H.Staging.HOST_STAGED if buf else H.Staging.DEVICE_STAGED
    else:
        staging = H.Staging.DEVICE_STAGED if buf else H.Staging.DIRECT
    if staging is H.Staging.HOST_STAGED and topo.is_multi_host:
        # host staging needs fully-addressable arrays (single-controller
        # measurement mode); skip rather than abort the rest of the matrix
        rep.line(
            f"SKIP dim:{dim}, {space}, buf:{int(buf)}: host staging "
            "unavailable on multi-host meshes"
        )
        return 0

    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_mpi_tests.arrays.spaces import host_sharding

    spec = [None, None]
    spec[dim] = axis_name
    sharding = NamedSharding(mesh, P(*spec))
    if Space.parse(space) is not Space.DEVICE:
        sharding = host_sharding(sharding, context=str(space))
    if args.init == "device":
        # compute the analytic field on chip; for managed space, land it in
        # host memory afterwards (the managed twin starts host-resident)
        zg = C.device_init(
            mesh,
            lambda r: d.init_shard_jax(f, r, dtype),
            axis=dim,
            sharding=sharding
            if Space.parse(space) is not Space.DEVICE
            else None,
        )
    else:
        zg = C.shard_blocks(
            mesh,
            d.global_ghosted_shape,
            dtype,
            lambda r: d.init_shard(f, r, dtype),
            axis=dim,
            sharding=sharding,
        )

    # Hot loop ≅ mpi_stencil2d_gt.cc:511-535: per-iteration clock reads
    # around the exchange (:512-526), the stencil eval every iteration
    # (untimed but executing, :529-533), and a device sync closing each
    # iteration (:534). Warmup iterations run the same code but are not
    # accumulated (skip_first ≅ the i >= n_warmup guard, :521-526).
    fused = stencil = None
    if args.fused:
        if staging not in (H.Staging.DIRECT, H.Staging.DEVICE_STAGED):
            rep.line(
                f"SKIP dim:{dim}, {space}, buf:{int(buf)}: --fused supports "
                "only DIRECT/DEVICE_STAGED exchanges"
            )
            return 0
        fused = H.exchange_stencil_fused_fn(
            mesh, axis_name, dim, 2, d.n_bnd, d.scale,
            staged=staging is H.Staging.DEVICE_STAGED,
        )
    else:
        stencil = H.stencil_fn(mesh, axis_name, dim, 2, d.scale,
                               kernel=args.kernel)
    timer = PhaseTimer(skip_first=args.n_warmup)
    phase_name = "fused" if args.fused else "exchange"
    zg = block(zg)
    dz = None
    for _ in range(args.n_warmup + args.n_iter):
        if fused is not None:
            # split-vs-fused A/B (SURVEY §7 hard part 2): exchange + stencil
            # compiled as ONE program, so the timed phase includes the
            # overlapped compute XLA schedules against the ppermute DMA
            dz = timer.timed(phase_name, fused, zg)
        else:
            zg = timer.timed(phase_name, H.halo_exchange, zg, mesh,
                             axis=dim, staging=staging)
            dz = stencil(zg)
            block(dz)
    seconds = timer.seconds[phase_name]
    if args.fused and args.debug_dump:
        # the fused program never materializes exchanged ghosts; run one
        # standalone exchange so the dump below has them
        zg = block(H.halo_exchange(zg, mesh, axis=dim, staging=staging))

    if args.debug_dump and zg.is_fully_addressable:
        # ≅ the DEBUG halo dumps of mpi_stencil2d_sycl_oo.cc:636-659: print
        # each logical rank's ghost rows and adjacent interior edge rows
        zh = np.asarray(C.host_value(zg))
        for r in range(world):
            blk = np.split(zh, world, axis=dim)[r]
            sl = [slice(None), slice(None)]
            for label, lohi in (("lo", slice(0, 2 * d.n_bnd)),
                                ("hi", slice(-2 * d.n_bnd, None))):
                sl[dim] = lohi
                edge = blk[tuple(sl)]
                flat = np.array2string(
                    edge[:, :4] if dim == 0 else edge[:4, :].T,
                    precision=4, max_line_width=120,
                )
                rep.line(f"DEBUG rank {r} {label} ghost+edge:\n{flat}")

    if args.init == "device":
        actual = C.device_init(
            mesh, lambda r: d.interior_shard_jax(df, r, dtype), axis=dim
        )
    else:
        actual = C.shard_blocks(
            mesh,
            d.global_interior_shape,
            dtype,
            lambda r: d.interior_shard(df, r, np.float64),
            axis=dim,
        )
    per_rank = C.per_rank_err_norms(dz, actual, mesh, axis=dim)
    err_sum = float(per_rank.sum())
    # rank-summed time: every logical rank experiences the same wall clock
    rep.test_line(dim, space, buf, seconds * world, err_sum,
                  extra_label="fused" if args.fused else None)
    rep.iter_line(
        dim, space, buf, phase_name,
        timer.mean(phase_name),
        timer.mins.get(phase_name, 0.0),
        timer.maxs.get(phase_name, 0.0),
    )

    tol = args.tol if args.tol is not None else _default_tol(args, d)
    if per_rank.max() > tol:
        rep.line(
            f"ERR_NORM FAIL dim:{dim} {space} buf:{int(buf)}: "
            f"max {per_rank.max():.8g} > tol {tol:.8g}"
        )
        return 1
    return 0


def _default_tol(args, d) -> float:
    if args.dtype == "float64":
        return 1e-5
    eps = 7.8e-3 if args.dtype == "bfloat16" else 1.2e-7
    # both axes use the same grid spacing (like the reference's shared dx,
    # mpi_stencil2d_gt.cc:445-456), so the non-decomposed axis spans
    # length·n_other/n_deriv — z = x³ + y² must be bounded by the REAL
    # coordinate extents or the f32 cancellation estimate is far too small
    other_extent = d.length * d.n_global_other / d.n_global_deriv
    x_max = d.length if d.dim == 0 else other_extent
    y_max = other_extent if d.dim == 0 else d.length
    zmax = x_max**3 + y_max**2
    n_pts = d.n_global_deriv * d.n_global_other
    return 8 * eps * zmax * d.scale * np.sqrt(n_pts / d.n_shards)


def _sum_test(args, mesh, topo, rep, dim: int, space: str) -> int:
    """Axis reduction + timed allreduce (≅ test_sum, :574-649): local sum
    along the decomposed dim, then psum across ranks; the allreduce is timed
    by differencing loops with and without it."""
    import jax

    from tpu_mpi_tests.arrays.domain import Domain2D
    from tpu_mpi_tests.arrays.spaces import Space, ensure_device
    from tpu_mpi_tests.comm import collectives as C
    from tpu_mpi_tests.instrument.timers import block
    from tpu_mpi_tests.kernels.reductions import sum_axis

    import functools

    import jax.numpy as jnp
    from tpu_mpi_tests.compat import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    dtype = _common.jnp_dtype(args)
    world = topo.global_device_count
    axis_name = mesh.axis_names[0]
    d = Domain2D(
        n_local_deriv=args.n_local,
        n_global_other=args.n_other,
        n_shards=world,
        dim=dim,
    )

    spec = [None, None]
    spec[dim] = axis_name
    fill = np.pi / world
    sharding = NamedSharding(mesh, P(*spec))
    if Space.parse(space) is not Space.DEVICE:
        from tpu_mpi_tests.arrays.spaces import host_sharding

        sharding = host_sharding(sharding, context=str(space))
    z = C.shard_blocks(
        mesh,
        d.global_interior_shape,
        dtype,
        lambda r: np.full(d.local_shape, fill, dtype),
        axis=dim,
        sharding=sharding,
    )
    # managed migration on first device touch (see arrays/spaces.py)
    z = ensure_device(z)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P(*spec),
        out_specs=P(axis_name),
        check_vma=False,
    )
    def local_sum(zz):
        return sum_axis(zz, axis=dim).reshape(1, -1)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis_name),
        out_specs=P(axis_name),
        check_vma=False,
    )
    def psum_allreduce(s):
        from jax import lax

        return lax.psum(s, axis_name)

    allreduce = psum_allreduce
    if args.rdma:
        # hand tier: explicit-RDMA ring reduce-scatter + all-gather instead
        # of lax.psum (≅ hand-writing the in-place MPI_Allreduce the
        # reference times, mpi_stencil2d_gt.cc:615-625). The ring kernels
        # have a lane-alignment floor (w·128·sublane elements); below it
        # fall back to the XLA tier with a visible NOTE, never silently.
        def rdma_allreduce(s):
            return C.allreduce_rdma(s, mesh, axis_name)

        try:
            jax.eval_shape(rdma_allreduce, jax.ShapeDtypeStruct(
                (world, d.n_global_other), dtype))
            allreduce = rdma_allreduce
        except ValueError as e:
            rep.line(
                f"NOTE dim:{dim} {space}: rdma allreduce below alignment "
                f"floor, using psum ({e})"
            )

    expected = np.full(d.n_global_other, np.pi * args.n_local)

    # warmup + correctness
    s = block(allreduce(local_sum(z)))
    got = C.host_value(s.addressable_shards[0].data).reshape(-1) if s.is_fully_addressable else None
    if got is not None and not np.allclose(
        got, expected, rtol=1e-3 if args.dtype == "bfloat16" else 1e-5
    ):
        rep.line(f"ALLREDUCE FAIL dim:{dim} {space}: {got[:3]} != {expected[:3]}")
        return 1

    t0 = time.perf_counter()
    for _ in range(args.n_iter):
        s = allreduce(local_sum(z))
    block(s)
    t_with = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(args.n_iter):
        s = local_sum(z)
    block(s)
    t_without = time.perf_counter() - t0

    # the headline keeps the reference's "allreduce cost" semantics, but the
    # raw loop timings are reported too: the difference of two noisy loops
    # can clamp to zero, and a clamped value is only diagnosable from the
    # components (VERDICT r1 weak #7)
    seconds = max(t_with - t_without, 0.0)
    if t_with < t_without:
        rep.line(
            f"NOTE dim:{dim} {space}: allreduce difference clamped to 0 "
            f"(t_with={t_with:.6f} < t_without={t_without:.6f}; "
            "loop noise exceeds the allreduce cost at this size)"
        )
    rep.test_line(dim, space, 0, seconds * world, 0.0,
                  extra_label="allreduce", show_err=False)
    rep.jsonl(
        {"kind": "allreduce_raw", "dim": dim, "space": space,
         "n_iter": args.n_iter, "t_with_s": t_with,
         "t_without_s": t_without, "world": world}
    )
    return 0


def _iterate_tiers(args, mesh, topo):
    """Tier-runner builders for the iterate leg, on ONE shared dim-0
    periodic geometry (rows decomposed, sin eigenfield — see
    :func:`_iterate_tier_test`). Returns ``(build, make_state,
    timesteps_per_call, geom)`` where ``build(tier) -> run`` may raise
    on an infeasible tier (recorded by the sweep, never fatal)."""
    import jax.numpy as jnp
    import numpy as np

    from tpu_mpi_tests.comm import collectives as C, halo as H
    from tpu_mpi_tests.kernels.stencil import N_BND
    from tpu_mpi_tests.tune import priors as _priors, registry as _tr

    dtype = _common.jnp_dtype(args)
    world = topo.global_device_count
    axis_name = mesh.axis_names[0]
    steps = args.iterate_steps
    K = N_BND * steps
    nloc, cols = args.n_local, args.n_other
    n_glob = world * nloc
    se = 0.01  # scale_eps of the iterate update (ITER line records it)

    # per-shard ghosted blocks: interior rows hold the global eigenfield
    # sin(2π·m·i/n), ghosts start zero (the first fused exchange fills
    # every exchange-fed band before any read — periodic ring)
    m = 2
    phase = 2.0 * np.pi * m / n_glob

    def ghost_width(tier):
        # the XLA iterate exchanges EVERY timestep over radius-wide
        # ghosts (its own geometry); the k-step tiers carry deep halos
        return N_BND if tier == "xla" else K

    def make_state(gw=K):
        blocks = []
        for r in range(world):
            b = np.zeros((nloc + 2 * gw, cols), np.float64)
            rows = np.arange(r * nloc, (r + 1) * nloc)
            b[gw:gw + nloc] = np.sin(phase * rows)[:, None]
            blocks.append(b.astype(dtype))
        return C.shard_1d(
            jnp.asarray(np.concatenate(blocks, axis=0)), mesh, axis=0
        )

    # the blocks tier's sub-knob, resolved ONCE per leg and replicated
    # from rank 0 on a fleet: per-rank caches can diverge (rank 0 is
    # the only writer), and a per-rank resolve inside a fleet-swept
    # candidate would let two ranks build DIFFERENT collective programs
    # mid-sweep — the PR-14 one-sided-binding hazard, one knob removed
    n_blocks = int(_tr.resolve(
        "stencil/blocks",
        prior=_priors.BENCH_BLOCKS.get(
            args.dtype, _priors.BENCH_BLOCKS["float32"]),
        device_fallback=False, dtype=args.dtype, n=n_glob,
        world=world,
    ))
    from tpu_mpi_tests.tune.sweep import _process_count

    if _process_count() > 1:
        from tpu_mpi_tests.tune import fleet as _fleet

        try:
            n_blocks = int(_fleet.bcast(
                n_blocks if _fleet.process_index() == 0 else None,
                "stencil2d/iterate_blocks",
            ))
        except _fleet.FleetUnavailable:
            pass  # no transport: local resolution, pre-fleet behavior

    def build(tier):
        if tier == "xla":
            return H.iterate_fused_fn(
                mesh, axis_name, 0, 2, N_BND, 1.0, se, periodic=True
            )
        if tier == "rdma-chained":
            return H.iterate_pallas_fn(
                mesh, axis_name, K, se, axis=0, steps=steps,
                periodic=True, rdma=True,
            )
        if tier == "rdma-fused":
            return H.iterate_fused_rdma_fn(
                mesh, axis_name, K, se, steps=steps, periodic=True,
            )
        # "blocks": the ppermute hand tier, block count resolved above
        if n_blocks >= 2 and nloc % n_blocks == 0:
            inner = H.iterate_pallas_blocks_fn(
                n_blocks, K, se, steps=steps,
                mesh=None if world == 1 else mesh, axis_name=axis_name,
                periodic=True,
            )
            bmesh = None if world == 1 else mesh

            def run_blocks(z, n):
                st = H.split_blocks(z, n_blocks, K, mesh=bmesh)
                return H.merge_blocks(inner(st, n), K, mesh=bmesh)

            return run_blocks
        return H.iterate_pallas_fn(
            mesh, axis_name, K, se, axis=0, steps=steps, periodic=True,
        )

    geom = {"steps": steps, "K": K, "n_glob": n_glob, "cols": cols,
            "se": se, "m": m, "phase": phase, "world": world,
            "ghost_width": ghost_width}
    return build, make_state, (lambda t: 1 if t == "xla" else steps), geom


def _iterate_tier_test(args, mesh, topo, rep) -> int:
    """The kernel-tier iterate leg (ISSUE 15): resolve ``stencil/tier``
    (sweeping it under ``--tune`` — the PR-4 engine prices the fused
    tier against blocks / chained RDMA / XLA and records a declined
    tier visibly), time the winner, and run the honesty checks:

    * fused-vs-chained interiors BITWISE-identical (the two tiers share
      the update functions by construction — a seam bug breaks this
      immediately);
    * the analytic err-norm gate: on the periodic ring the eigenfield
      sin(m·x) rotates through (sin, cos) with an exactly-known 2×2 map
      per timestep (the 5-point first-difference analog of heat2d's
      eigen gate), so the timed field is checked against a closed form
      — a broken exchange or seam destroys it at once;
    * the kernel-level ``overlap_frac`` record: the fused runner
      host-bracketed against its compute-only twin
      (``local_only=True``), seam-wait vs total step time, feeding the
      existing OVERLAP table.
    """
    import time

    import numpy as np

    from tpu_mpi_tests.comm import halo as H
    from tpu_mpi_tests.instrument import costs
    from tpu_mpi_tests.instrument.timers import block, chain_rate
    from tpu_mpi_tests.tune.sweep import ensure_tuned

    world = topo.global_device_count
    build, make_state, steps_per_call, g = _iterate_tiers(args, mesh, topo)
    steps, K, n_glob = g["steps"], g["K"], g["n_glob"]
    ctx = {"dtype": args.dtype, "n": n_glob, "world": world}
    explicit = None if args.iterate_tier == "auto" else args.iterate_tier

    def measure(cand):
        run_c = build(str(cand))
        sec, st = chain_rate(
            run_c, make_state(g["ghost_width"](str(cand))),
            n_short=2, n_long=6,
        )
        del st
        return sec / steps_per_call(str(cand))  # per-timestep seconds

    try:
        tier = str(ensure_tuned(
            "stencil/tier", measure, explicit=explicit,
            device_fallback=False, **ctx,
        ))
        tier = tier if tier in H.STENCIL_TIERS else "blocks"

        gw = g["ghost_width"](tier)
        run = build(tier)
        costs.compile_probe(
            run, (make_state(gw), 1), label="stencil2d_iterate",
            kernel=tier,
        )
        z = block(run(make_state(gw), 1))  # compile + warm
        t0 = time.perf_counter()
        z = block(run(z, args.iterate_iters))
        seconds = time.perf_counter() - t0
        # the warm call advanced the field too: the eigen gate below
        # checks the TOTAL evolution, the rate only the timed window
        timesteps = (1 + args.iterate_iters) * steps_per_call(tier)
        rate = (args.iterate_iters * steps_per_call(tier) / seconds
                if seconds > 0 else float("inf"))
        rep.line(
            f"ITER tier={tier} steps={steps} n={n_glob}x{g['cols']} "
            f"world={world}: {rate:0.1f} steps/s"
        )
    except Exception as e:
        # scoped to FLEETS: a multi-process backend without cross-
        # process collectives (this image's CPU) cannot run any tier —
        # the sweep already recorded the per-candidate errors, so the
        # leg degrades with a visible NOTE. Single-process failures are
        # genuine kernel breakage and must fail loudly, not skip the
        # honesty gates.
        from tpu_mpi_tests.tune.sweep import _process_count as _pc

        if _pc() <= 1:
            raise
        rep.line(
            f"NOTE iterate tier leg unavailable on this backend "
            f"({type(e).__name__}: {e}); gates skipped"
        )
        return 0

    rc = 0
    # honesty check 1: fused-vs-chained interiors bitwise-identical
    try:
        fused = build("rdma-fused")
        chained = build("rdma-chained")
        ja = block(fused(make_state(), args.iterate_iters))
        jb = block(chained(make_state(), args.iterate_iters))
        if not (getattr(ja, "is_fully_addressable", True)
                and getattr(jb, "is_fully_addressable", True)):
            raise ValueError(
                "multi-host shards not addressable; compare per-host "
                "with --jsonl + tpumt-report instead"
            )
        za = np.asarray(ja)
        zb = np.asarray(jb)
        if np.array_equal(za, zb):
            rep.line(f"ITER BITWISE fused==chained over "
                     f"{args.iterate_iters} calls: OK")
        else:
            rep.line(
                f"ITER BITWISE FAIL: fused and chained tiers diverge "
                f"(max |d|={np.abs(za - zb).max():.8g})"
            )
            rc = 1
    except ValueError as e:
        rep.line(f"NOTE fused/chained bitwise gate skipped ({e})")

    # honesty check 2: analytic eigen gate on the timed field — the
    # (sin, cos) pair rotates by [[1, -a], [a, 1]] per timestep with
    # a = se·(2c1·sin(mΔ) + 2c2·sin(2mΔ))
    if hasattr(z, "is_fully_addressable") and z.is_fully_addressable:
        from tpu_mpi_tests.kernels.pallas_kernels import _C1, _C2

        a = g["se"] * (2.0 * _C1 * np.sin(g["phase"])
                       + 2.0 * _C2 * np.sin(2.0 * g["phase"]))
        sc = np.array([1.0, 0.0])
        step_m = np.array([[1.0, -a], [a, 1.0]])
        for _ in range(timesteps):
            sc = step_m @ sc
        rows = np.arange(n_glob)
        want = (sc[0] * np.sin(g["phase"] * rows)
                + sc[1] * np.cos(g["phase"] * rows))
        zh = np.asarray(z, np.float64).reshape(world, -1, g["cols"])
        got = zh[:, gw:gw + n_glob // world, 0].reshape(-1)
        denom = max(float(np.sqrt(np.mean(want**2))), 1e-300)
        rel = float(np.sqrt(np.mean((got - want) ** 2))) / denom
        eps = {"float64": 2.3e-16, "float32": 1.2e-7,
               "bfloat16": 7.8e-3}.get(args.dtype, 1.2e-7)
        tol = min(0.5, 50.0 * eps * max(timesteps, 1) ** 0.5 + 10.0 * eps)
        rep.line(f"ITER ERR rel={rel:e} (gate {tol:e})")
        if not np.isfinite(rel) or rel > tol:
            rep.line(f"ITER FAIL rel={rel:.8g} > tol {tol:.8g}")
            rc = 1

    # kernel-level overlap record: host-bracket the fused runner vs its
    # compute-only twin (same kernel, communication compiled out)
    try:
        fused = build("rdma-fused")
        comp = H.iterate_fused_rdma_fn(
            mesh, mesh.axis_names[0], K, g["se"], steps=steps,
            periodic=True, local_only=True,
        )
        zf = block(fused(make_state(), 1))  # warm
        zc = block(comp(make_state(), 1))
        t0 = time.perf_counter()
        zf = block(fused(zf, args.iterate_iters))
        fused_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        zc = block(comp(zc, args.iterate_iters))
        compute_s = time.perf_counter() - t0
        del zf, zc
        ov = H.fused_overlap_record(
            "stencil2d_fused_rdma", steps=args.iterate_iters,
            fused_s=fused_s, compute_s=compute_s, world=world,
            dtype=args.dtype,
        )
        rep.line(
            f"OVERLAP stencil2d_fused_rdma "
            f"overlap_frac={ov['overlap_frac']:0.3f} "
            f"seam_wait_s={ov['drain_s']:0.6f}",
            ov,
        )
    except ValueError as e:
        rep.line(f"NOTE fused overlap probe skipped ({e})")
    return rc


def run(args) -> int:
    from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh, topology
    from tpu_mpi_tests.instrument import ProfilerGate

    bootstrap()
    topo = topology()
    mesh = make_mesh()
    world = topo.global_device_count

    rep = _common.make_reporter(args, rank=topo.process_index, size=world)
    with rep:
        rep.banner(
            f"stencil2d: n_local={args.n_local} n_other={args.n_other} "
            f"world={world} n_iter={args.n_iter} n_warmup={args.n_warmup} "
            f"dtype={args.dtype} managed={args.managed}"
        )

        rc = 0
        if args.iterate_tier != "off":
            rc |= _iterate_tier_test(args, mesh, topo, rep)
            if args.iterate_only:
                return rc

        if args.kernel == "auto":
            # resolved AFTER the iterate leg so a same-run --tune
            # sweep's freshly persisted winner is what the matrix legs
            # actually apply
            args.kernel = _common.resolve_kernel_auto(
                args.dtype, args.n_local * world, world, rep
            )

        spaces = ["device"] + (["managed"] if args.managed else [])
        only = None
        if args.only:
            only = {
                (int(d), int(b))
                for d, b in (pair.split(":") for pair in args.only.split(","))
            }
        with ProfilerGate(args.profile_dir):
            for dim in (0, 1):
                for buf in (True, False):
                    if only is not None and (dim, int(buf)) not in only:
                        continue
                    for space in spaces:
                        rc |= _deriv_test(args, mesh, topo, rep, dim, space, buf)
            for dim in (0, 1):
                if only is not None and not any(d == dim for d, _ in only):
                    continue
                for space in spaces:
                    rc |= _sum_test(args, mesh, topo, rep, dim, space)
        return rc


def main(argv=None) -> int:
    p = _common.base_parser(__doc__)
    p.add_argument(
        "--n-local",
        type=int,
        default=1024,
        help="per-shard size along the derivative dim "
        "(≅ n_local_deriv argv, default 1024, mpi_stencil2d_gt.cc:656)",
    )
    p.add_argument(
        "--n-other",
        type=int,
        default=512 * 1024,
        help="global size of the non-decomposed dim "
        "(≅ n_global_other = 512Ki, mpi_stencil2d_gt.cc:676)",
    )
    p.add_argument(
        "--n-iter", type=int, default=1000, help="timed iterations (≅ :657)"
    )
    p.add_argument(
        "--n-warmup", type=int, default=5, help="untimed warmup (≅ :658)"
    )
    p.add_argument(
        "--managed",
        action="store_true",
        help="add managed-space twins to the matrix (≅ -DTEST_MANAGED)",
    )
    p.add_argument(
        "--rdma",
        action="store_true",
        help="use the hand-written pallas remote-DMA ring for every "
        "exchange (≅ running the SYCL hand-kernel variant of the matrix)",
    )
    p.add_argument(
        "--fused",
        action="store_true",
        help="time exchange+stencil compiled as ONE program per iteration "
        "(the fused side of the split-vs-fused A/B, SURVEY §7 hard part 2); "
        "default times the exchange alone with the stencil executing "
        "untimed between iterations (≅ mpi_stencil2d_gt.cc:511-535)",
    )
    p.add_argument(
        "--kernel",
        default="xla",
        choices=["xla", "pallas", "auto"],
        help="stencil compute implementation: XLA expression (≅ gtensor), "
        "hand-written pallas strips (≅ the SYCL kernel), or auto — the "
        "stencil/tier schedule cache's winner mapped onto the two bodies "
        "(README 'Kernel tiers')",
    )
    p.add_argument(
        "--iterate-tier",
        default="off",
        choices=["off", "auto", "blocks", "rdma-chained", "rdma-fused",
                 "xla"],
        help="run the kernel-tier ITERATE leg (ISSUE 15): time the "
        "exchange+update hot loop under the named tier (auto = the "
        "stencil/tier cache winner; --tune sweeps the space), with the "
        "fused-vs-chained bitwise gate, the analytic eigen err-norm "
        "gate, and the fused tier's seam-wait OVERLAP record",
    )
    p.add_argument(
        "--iterate-steps", type=int, default=1,
        help="temporal-blocking depth of the iterate leg (k timesteps "
        "per deep-ghost exchange)",
    )
    p.add_argument(
        "--iterate-iters", type=int, default=4,
        help="timed outer iterations of the iterate leg",
    )
    p.add_argument(
        "--iterate-only",
        action="store_true",
        help="run ONLY the iterate leg, skipping the exchange matrix "
        "(the fleet-smoke tier leg's mode)",
    )
    p.add_argument(
        "--debug-dump",
        action="store_true",
        help="print per-rank ghost+edge rows after the exchange "
        "(≅ the DEBUG halo dumps, mpi_stencil2d_sycl_oo.cc:636-659)",
    )
    p.add_argument(
        "--init",
        default="device",
        choices=["device", "host"],
        help="compute initial fields on chip (default; host→device "
        "transfer of multi-GB analytic data is the wrong tool) or on host "
        "(≅ the reference's host init + H2D copy, mpi_stencil2d_gt.cc:508)",
    )
    p.add_argument(
        "--only",
        default=None,
        help="run a subset of the matrix as 'dim:buf' pairs, e.g. "
        "'0:0,1:0' (the reference edits main() for this; host-staged "
        "buf:1 configs move whole shards through the host and can be "
        "impractical at full size over a tunneled controller)",
    )
    p.add_argument(
        "--tol",
        type=float,
        default=None,
        help="per-rank err_norm gate (default dtype-dependent)",
    )
    args = p.parse_args(argv)
    for name in ("n_local", "n_other", "n_iter", "iterate_steps",
                 "iterate_iters"):
        if getattr(args, name) < 1:
            p.error(f"--{name.replace('_', '-')} must be positive")
    if args.n_local < 5:
        p.error("--n-local must be >= 5 (stencil width)")
    if args.iterate_only and args.iterate_tier == "off":
        p.error("--iterate-only needs an --iterate-tier selection")
    if args.fused and args.kernel != "xla":
        p.error("--fused compiles the XLA stencil into the exchange program; "
                "it does not support --kernel pallas")
    if args.fused and args.rdma:
        p.error("--fused supports only DIRECT/DEVICE_STAGED exchanges; "
                "combining it with --rdma would skip the whole matrix")
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


if __name__ == "__main__":
    sys.exit(main())
