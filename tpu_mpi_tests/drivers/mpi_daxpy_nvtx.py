"""Flagship DAXPY benchmark: weak-scaled, phase-timed, with device allgather.

≅ ``mpi_daxpy_nvtx.cc`` (call stack in SURVEY.md §3.1). Semantics preserved:

* weak scaling by node count: ``nall = n_per_node * nodes``, ``n = nall /
  world_size`` (``:121-132``; node ≙ JAX process, SURVEY §7 hard part 7);
* per-rank init ``x[i] = (i+1)/n``, ``y = -x``, ``a = 2`` → ``y = x``,
  local SUM ``(n+1)/2`` (``:207-217``);
* managed vs pinned-host+explicit-copy allocation twins — a runtime
  ``--space`` flag here instead of the ``-DMANAGED`` twin binaries;
* ``MPI_Allgather(MPI_IN_PLACE)`` of x + regular allgather of y on device
  buffers (``:282-291``) → donated/plain all_gather over the mesh axis;
* global checksum ALLSUM (``:293-310``), phase timers total/kernel/barrier/
  gather printed as ``TIME <phase> : <s>`` (``:333-340``), trace ranges for
  every phase (NVTX names preserved), profiler gating via ``--profile-dir``.
"""

from __future__ import annotations

import os
import sys

import numpy as np

from tpu_mpi_tests.drivers import _common


def run(args) -> int:
    import jax
    import jax.numpy as jnp

    import tpu_mpi_tests.kernels.daxpy as kd
    from tpu_mpi_tests.comm import collectives as C
    from tpu_mpi_tests.comm.mesh import (
        bootstrap,
        check_divisible,
        device_report,
        make_mesh,
        topology,
    )
    from tpu_mpi_tests.arrays.spaces import Space, meminfo, place
    from tpu_mpi_tests.instrument import PhaseTimer, ProfilerGate
    from tpu_mpi_tests.instrument.timers import block
    from tpu_mpi_tests.instrument.trace import trace_range

    dtype = _common.jnp_dtype(args)
    bootstrap()
    topo = topology()
    mesh = make_mesh()
    world = topo.global_device_count
    managed = args.space == "managed"

    # weak scaling by "node" (process) count, mpi_daxpy_nvtx.cc:121-132
    nodes = topo.process_count
    nall = args.n_per_node * nodes
    n = check_divisible(nall, world, "nall over ranks")

    rep = _common.make_reporter(args, rank=topo.process_index, size=world)
    with rep:
        rep.banner(
            f"{nodes} nodes, {world} ranks, {n} elements each, total {nall}"
        )
        mb_per_core = os.environ.get("MEMORY_PER_CORE")
        rep.banner(
            f"MEMORY_PER_CORE={mb_per_core}"
            if mb_per_core
            else "MEMORY_PER_CORE is not set"
        )
        rep.banner(device_report(verbose=args.verbose))

        timer = PhaseTimer()
        gate = ProfilerGate(args.profile_dir)
        gate.start()

        if args.warmup:
            # compile outside EVERY timed phase (including total): the
            # reference's binaries carry no JIT cost, so charging trace+compile
            # (~1 s) to any phase would measure the compiler, not the op.
            # Device-created dummies of the real shapes/shardings hit the same
            # compilation cache; the real (possibly managed) arrays are
            # untouched so their timed first-touch migration is preserved.
            with trace_range("compileWarmup"):
                wx = C.device_init(mesh, lambda r: jnp.zeros(n, dtype), ndim=1)
                wy = C.device_init(mesh, lambda r: jnp.zeros(n, dtype), ndim=1)
                block(kd.daxpy(jnp.asarray(args.a, dtype), wx, wy))
                block(C.all_gather_inplace(jnp.copy(wx), mesh))
                block(C.all_gather(wy, mesh))
                del wx, wy

        with timer.phase("total"):
            # ── allocateArrays / initializeArrays (+ copyInput if unmanaged) ──
            if args.init == "device":
                # on-chip init: every shard computes its own (i+1)/n pattern
                # (no host staging phases; for tunnel-bound controllers where
                # H2D of 48Mi/node is slower than the whole benchmark)
                with trace_range("initializeArrays"), timer.phase("init"):
                    d_x = block(
                        C.device_init(
                            mesh,
                            lambda r: kd.init_xy_scaled_jax(n, dtype)[0],
                            ndim=1,
                        )
                    )
                    d_y = block(
                        C.device_init(
                            mesh,
                            lambda r: kd.init_xy_scaled_jax(n, dtype)[1],
                            ndim=1,
                        )
                    )
                h_x = h_y = None
            else:
                with trace_range("initializeArrays"), timer.phase("init"):
                    # per-rank pattern (i+1)/n tiled across ranks (:207-217)
                    lx, ly = kd.init_xy_scaled_np(n, dtype)
                    h_x = np.tile(lx, world)
                    h_y = np.tile(ly, world)
            if args.init == "device":
                pass
            elif managed:
                # managed ≈ host-resident, device reads it implicitly (SURVEY
                # §2.3 memory-space row): place sharded into host memory kind
                from jax.sharding import NamedSharding, PartitionSpec as P

                sh = NamedSharding(mesh, P(mesh.axis_names[0]))
                with trace_range("allocateArrays"), timer.phase("alloc"):
                    d_x = block(place(h_x, Space.MANAGED, sh))
                    d_y = block(place(h_y, Space.MANAGED, sh))
            else:
                with trace_range("copyInput"), timer.phase("copyInput"):
                    d_x = block(C.shard_1d(jnp.asarray(h_x), mesh))
                    d_y = block(C.shard_1d(jnp.asarray(h_y), mesh))
            if args.verbose:
                rep.line(f"MEMINFO d_x: {meminfo(d_x)}")
                rep.line(f"MEMINFO d_y: {meminfo(d_y)}")

            # ── kernel (:242-249) ──
            with trace_range("daxpy"), timer.phase("kernel"):
                # managed arrays migrate to HBM on first device touch (TPU has
                # no page-migrating UVM; see arrays/spaces.ensure_device), so
                # the migration cost lands in kernel time like UVM page faults
                from tpu_mpi_tests.arrays.spaces import ensure_device

                d_x = ensure_device(d_x)
                d_y = ensure_device(d_y)
                d_y = block(kd.daxpy(jnp.asarray(args.a, dtype), d_x, d_y))

            # ── localSum (+ copyOutput if unmanaged) (:251-268) ──
            # computed as a collective so multi-host processes can all read it
            with trace_range("localSum"), timer.phase("localSum"):
                local_sums = C.per_rank_sums(d_y, mesh).astype(np.float64)
            local_sums = local_sums.reshape(-1)
            for r in range(world):
                rep.sum_line(local_sums[r], rank=r)

            # ── copyPrepAllxInplace (:270-272): own slice into the gather buf ──
            with trace_range("copyPrepAllxInplace"), timer.phase("copyPrep"):
                d_allx = block(jnp.copy(d_x))

            # ── optional barrier (:274-280) ──
            if args.barrier:
                with trace_range("mpiBarrier"), timer.phase("barrier"):
                    C.barrier(mesh)

            # ── allgather x (IN_PLACE) + y (:282-291) ──
            with trace_range("mpiAllGather"), timer.phase("gather"):
                with trace_range("x"):
                    g_allx = C.all_gather_inplace(d_allx, mesh)
                with trace_range("y"):
                    g_ally = C.all_gather(d_y, mesh)
                block(g_allx, g_ally)

            # ── allSum global checksum (:293-310) ──
            # device reductions accumulate at the run's precision: f64 runs are
            # gated with tol=0 below, which an f32-accumulated sum of 48Mi+
            # elements cannot meet (x64 is enabled iff --dtype float64)
            acc_dtype = jnp.float64 if args.dtype == "float64" else jnp.float32
            with trace_range("allSum"), timer.phase("allSum"):
                if args.init == "device":
                    # device reduction (the gathered array never moves to host)
                    all_sum = float(jnp.sum(g_ally.astype(acc_dtype)))
                else:
                    all_sum = float(
                        C.host_value(g_ally).astype(np.float64).sum()
                    )
            rep.sum_line(all_sum, label="ALLSUM")

        gate.stop()
        for phase in ("total", "kernel", "barrier", "gather"):
            if timer.counts[phase]:
                rep.time_line(phase, timer.seconds[phase],
                              *timer.wall_span(phase))

        # verification: y = x elementwise → ALLSUM = world*(n+1)/2; gathered x
        # must equal the original global x (in-place parity)
        expected_all = world * (n + 1) / 2
        if args.dtype == "float64":
            # host np.float64 sums reproduce the reference's exact checksums;
            # device-side f64 reductions may differ by reduction-order rounding
            tol = 0 if args.init == "host" else 1e-12 * abs(expected_all)
        else:
            tol = max(1e-5 * abs(expected_all), 1.0)
        ok = abs(all_sum - expected_all) <= tol
        if h_x is not None:
            if not np.array_equal(C.host_value(g_allx), h_x):
                rep.line("GATHER PARITY FAIL: gathered x != filled buffer")
                ok = False
        else:
            # device-init path: in-place-gather parity via the x checksum
            # (x sums to (n+1)/2 per rank, like y)
            gx_sum = float(jnp.sum(g_allx.astype(acc_dtype)))
            if abs(gx_sum - expected_all) > tol:
                rep.line(
                    f"GATHER PARITY FAIL: x sum {gx_sum} != {expected_all}"
                )
                ok = False
        if not ok:
            rep.line(f"CHECKSUM FAIL: ALLSUM {all_sum} != {expected_all}")
            return 1
        return 0


def main(argv=None) -> int:
    p = _common.base_parser(__doc__)
    p.add_argument(
        "--n-per-node",
        type=int,
        default=48 * 1024 * 1024,
        help="elements per node for weak scaling (reference: 48Mi doubles)",
    )
    p.add_argument("--a", type=float, default=2.0)
    p.add_argument(
        "--space",
        default="device",
        choices=["device", "managed"],
        help="allocation mode (≅ the -DMANAGED twin binaries)",
    )
    p.add_argument(
        "--barrier",
        action="store_true",
        help="time an explicit barrier before the gather (≅ -DBARRIER)",
    )
    p.add_argument(
        "--init",
        default="host",
        choices=["host", "device"],
        help="host init + copy (reference phase semantics, the default) or "
        "on-chip init + device reductions (for tunnel-bound controllers "
        "at 48Mi+/node scale)",
    )
    p.add_argument(
        "--no-warmup",
        dest="warmup",
        action="store_false",
        help="charge XLA trace+compile to the timed phases (raw behavior; "
        "default warms the compiled fns untimed first)",
    )
    args = p.parse_args(argv)
    if args.n_per_node < 1:
        p.error(f"--n-per-node must be positive, got {args.n_per_node}")
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


if __name__ == "__main__":
    sys.exit(main())
