"""Distributed 1-D 5-point stencil with halo exchange and error-norm gate.

≅ ``mpi_stencil_gt.cc`` (call stack SURVEY.md §3.3): y = x³ over n_global
points (default 32Mi, ``--n-global-mi`` in Mi units like the reference argv),
decomposed across ranks with ghost width 2; one timed halo exchange; stencil
derivative; per-rank ``err_norm`` vs the analytic 3x², exact to rounding for
a cubic. Output lines preserved::

    <rank>/<size> exchange time <s>
    <rank>/<size> [<device>] err_norm = <v>
"""

from __future__ import annotations

import sys
import time

import numpy as np

from tpu_mpi_tests.drivers import _common


def run(args) -> int:
    import jax
    import jax.numpy as jnp

    from tpu_mpi_tests.arrays.domain import Domain1D
    from tpu_mpi_tests.comm import collectives as C
    from tpu_mpi_tests.comm import halo as H
    from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh, topology
    from tpu_mpi_tests.instrument import ProfilerGate
    from tpu_mpi_tests.instrument.timers import block
    from tpu_mpi_tests.kernels.stencil import analytic_pairs
    from tpu_mpi_tests.utils import TpuMtError

    dtype = _common.jnp_dtype(args)
    bootstrap()
    topo = topology()
    mesh = make_mesh()
    world = topo.global_device_count
    axis_name = mesh.axis_names[0]

    n_global = args.n_global
    d = Domain1D(n_global=n_global, n_shards=world, n_bnd=2)
    f, df = analytic_pairs()["1d"]

    rep = _common.make_reporter(args, rank=topo.process_index, size=world)
    with rep:
        rep.banner(
            f"stencil1d: n_global={n_global} world={world} "
            f"n_local={d.n_local} dtype={args.dtype} staging={args.staging}"
        )

        # shards materialize on their own devices (multi-GB host→device init
        # transfer is the wrong tool at 32Mi+ scale — see collectives.device_init)
        zg = block(
            C.device_init(
                mesh, lambda r: d.init_shard_jax(f, r, dtype), ndim=1
            )
        )

        staging = H.Staging.parse(args.staging)
        if staging is H.Staging.AUTO:
            if args.tune:
                # measured sweep over the halo schedule space (staging
                # strategy + ppermute-vs-RDMA flavor) on this exact
                # buffer: each candidate prices a donated feedback chain
                # (state = exchange(state)), sync-honest via block();
                # the winner persists to the schedule cache and a rerun
                # is a pure cache hit (make tune-smoke gates this)
                from tpu_mpi_tests.tune.sweep import (
                    ensure_tuned,
                    feedback_rate,
                )

                def measure(cand):
                    sec, _ = feedback_rate(
                        lambda z: H.halo_exchange(z, mesh, staging=cand),
                        zg + 0,  # fresh copy: the exchange donates
                    )
                    return sec

                ensure_tuned(
                    "halo/staging", measure, device_fallback=False,
                    **H._staging_context(zg, 0, world),
                )
            staging = H.resolve_staging("auto", zg, 0, world)
            rep.banner(f"TUNE halo/staging resolved -> {staging.value}")
        with ProfilerGate(args.profile_dir):
            # untimed warmup so the timed exchange measures communication, not
            # trace+compile (exchange is idempotent: ghosts are rewritten with
            # identical values) — async-dispatch discipline, SURVEY §7 part 2
            zg = block(H.halo_exchange(zg, mesh, staging=staging))
            # one timed exchange (mpi_stencil_gt.cc:200-205)
            t0 = time.perf_counter()
            zg = block(H.halo_exchange(zg, mesh, staging=staging))
            seconds = time.perf_counter() - t0
            if topo.process_index == 0:
                for r in range(world):
                    rep.line(
                        f"{r}/{world} exchange time {seconds:0.8f}",
                        {"kind": "exchange1d", "rank": r, "seconds": seconds},
                    )

            # compile-cost probe on the derivative kernel (the halo
            # exchange is probed automatically through span_call); the
            # fingerprint context keys the record to this layout
            from tpu_mpi_tests.instrument import costs

            deriv_fn = H.stencil_fn(mesh, axis_name, 0, 1, d.scale)
            costs.compile_probe(
                deriv_fn, (zg,), label="stencil1d_deriv",
                dtype=args.dtype, n=n_global, world=world,
            )
            deriv = block(deriv_fn(zg))

        # per-rank err norms vs analytic derivative, computed shard-local on
        # device (the full global field never moves to host)
        actual = C.device_init(
            mesh, lambda r: d.interior_shard_jax(df, r, dtype), ndim=1
        )
        per_rank_err = C.per_rank_err_norms(deriv, actual, mesh)
        kind = jax.devices()[0].device_kind
        if topo.process_index == 0:
            for r in range(world):
                rep.line(
                    f"{r}/{world} [{kind}] err_norm = {per_rank_err[r]:.8f}",
                    {"kind": "err_norm", "rank": r, "err": float(per_rank_err[r])},
                )

        if args.tol is not None:
            tol = args.tol
        elif args.dtype == "float64":
            # rounding error grows with scale·√n like the f32 case (coordinate
            # ulps amplified by 1/delta); a broken halo exceeds this by >10⁴
            eps64 = 2.2e-16
            tol = max(
                128 * eps64 * d.length**3 * d.scale * np.sqrt(n_global), 1e-6
            )
        else:
            # f32/bf16: cancellation error ≈ eps·max|y|·scale per point
            # (SURVEY §7 hard part 1); a broken halo exceeds this by >10³
            eps = float(np.finfo(np.dtype(args.dtype).newbyteorder("=")).eps) if args.dtype != "bfloat16" else 7.8e-3
            ymax = d.length**3
            tol = 8 * eps * ymax * d.scale * np.sqrt(n_global)
        if per_rank_err.max() > tol:
            rep.line(
                f"ERR_NORM FAIL: max {per_rank_err.max():.8g} > tol {tol:.8g}"
            )
            return 1
        return 0


def _serve_step_factory(mesh, shape, dtype):
    """Serve-mode handler: ``step_fn(n)`` performs ``n`` halo exchanges
    on a persistent ghosted shard set (the exchange is idempotent —
    ghosts are rewritten with identical values — so chained requests are
    exactly the driver's timed step). Each exchange goes through
    :func:`~tpu_mpi_tests.comm.halo.halo_exchange`, so with telemetry on
    every request also lands its own comm span, and the staging schedule
    resolves through the tune cache like any other run."""
    import jax.numpy as jnp

    from tpu_mpi_tests.arrays.domain import Domain1D
    from tpu_mpi_tests.comm import collectives as C
    from tpu_mpi_tests.comm import halo as H
    from tpu_mpi_tests.instrument.timers import block
    from tpu_mpi_tests.kernels.stencil import analytic_pairs

    if len(shape) != 1:
        raise ValueError(f"halo wants a 1-d shape, got {shape}")
    (n,) = shape
    world = mesh.devices.size
    d = Domain1D(n_global=n, n_shards=world, n_bnd=2)
    f, _ = analytic_pairs()["1d"]
    dt = jnp.dtype(dtype)

    def init():
        return block(C.device_init(
            mesh, lambda r: d.init_shard_jax(f, r, dt), ndim=1
        ))

    state = {"z": init()}

    def step(k: int):
        try:
            z = state["z"]
            for _ in range(k):
                # AUTO staging: the tune cache's winner for this
                # topology when one is warmed, the shipped prior
                # (direct) otherwise — the schedule preload at serve
                # start is consumed here
                z = H.halo_exchange(z, mesh, staging=H.Staging.AUTO)
            state["z"] = block(z)
        except Exception:
            # the exchange donates its input: after a mid-batch failure
            # the held buffer may already be consumed, and keeping it
            # would poison every later batch of this class with
            # buffer-deleted errors for the rest of a long run —
            # rebuild, then let the loop count the error
            state["z"] = init()
            raise

    step(1)  # compile + warm before traffic opens
    return step


_common.register_workload("halo", _serve_step_factory)


def main(argv=None) -> int:
    p = _common.base_parser(__doc__)
    p.add_argument(
        "--n-global-mi",
        type=int,
        default=None,
        help="global size in Mi elements (reference argv unit; default 32)",
    )
    p.add_argument(
        "--n-global",
        type=int,
        default=32 * 1024 * 1024,
        help="global size in elements (exact; overridden by --n-global-mi)",
    )
    p.add_argument(
        "--staging",
        default="direct",
        choices=["direct", "device", "host", "pallas", "auto"],
        help="halo staging mode (≅ reference stage_host/device variants; "
        "'pallas' = hand-written inter-chip RDMA ring kernel; 'auto' = "
        "the schedule cache's tuned winner for this topology — with "
        "--tune a cache miss runs the measured sweep first)",
    )
    p.add_argument(
        "--tol",
        type=float,
        default=None,
        help="err_norm gate (default: dtype-dependent)",
    )
    args = p.parse_args(argv)
    if args.n_global_mi is not None:
        args.n_global = args.n_global_mi * 1024 * 1024
    if args.n_global < 1:
        p.error(f"global size must be positive, got {args.n_global}")
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


if __name__ == "__main__":
    sys.exit(main())
