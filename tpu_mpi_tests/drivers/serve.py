"""Serving-mode harness: steady-state traffic against a persistent mesh.

Every other driver is a one-shot benchmark; this one is the ROADMAP
north star's missing regime — a long-running service loop (``serve/``)
that keeps one mesh and the warmed compile/tune caches alive, generates
requests via a configurable arrival process (open-loop Poisson or
closed-loop at a target concurrency), draws each request from a mixed
workload table (``--workloads``: daxpy step, stencil1d halo step,
ring-attention block, small-payload allreduce — the registered handlers
of ``drivers/_common.py``), coalesces compatible requests into batches,
and records per-request latency into bounded-memory histograms.

Output per workload class (stable line + ``kind: "serve"`` JSONL)::

    SERVE <class>: offered=<hz>/s achieved=<hz>/s n=<done> err=<e> \
shed=<s> p50=<ms>ms p95=<ms>ms p99=<ms>ms qmax=<depth>

``tpumt-report`` renders the merged records as the SLO table and
``tpumt-report --diff`` gates the percentiles against the cross-window
noise band; with ``--telemetry --trace-out`` every batch appears as a
``serve:<class>`` request span on the Perfetto timeline. Pair long runs
with ``--memwatch`` to watch HBM over hours (README "Serving mode").

Single-process only (fake-device meshes included): mixed-traffic batch
composition depends on real-time arrival/service interleaving, which
would diverge across ranks and deadlock collectives — the rank-
coordinated variant is ROADMAP work, like the tune sweeps before it.
"""

from __future__ import annotations

import sys

from tpu_mpi_tests.drivers import _common


def run(args) -> int:
    from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh, topology
    from tpu_mpi_tests.instrument.watchdog import IdleAwareWatchdog
    from tpu_mpi_tests.serve.arrival import ClosedLoop, OpenLoopPoisson
    from tpu_mpi_tests.serve.loop import ServeLoop
    from tpu_mpi_tests.serve.workloads import parse_workload_table
    from tpu_mpi_tests.tune import registry as tr
    from tpu_mpi_tests.utils import TpuMtError

    bootstrap()
    topo = topology()
    if topo.process_count > 1:
        print("ERROR serve mode is single-process only: batch "
              "composition depends on arrival/service timing and would "
              "diverge across ranks mid-collective (run one process, "
              "fake or real devices)")
        return 2
    mesh = make_mesh()
    world = topo.global_device_count

    try:
        classes = parse_workload_table(args.workloads)
    except ValueError as e:
        print(f"ERROR {e}")
        return 2

    # --replay: load + validate the traffic artifact BEFORE any mesh or
    # reporter work — a refused artifact is a visible NOTE + exit 2
    # (never a crash, never a silent partial replay), and an accepted
    # one stamps its fingerprint into the run manifest so the JSONL is
    # self-describing about what traffic drove it
    replay_artifact = None
    manifest_extra = None
    if args.replay:
        from tpu_mpi_tests.serve.replay import (
            TrafficFormatError,
            load_traffic,
        )

        try:
            replay_artifact = load_traffic(args.replay)
        except TrafficFormatError as e:
            print(f"NOTE traffic artifact refused: {e}")
            return 2
        unknown = sorted(set(replay_artifact.get("classes") or ())
                         - {c.key for c in classes})
        if unknown:
            print(f"NOTE replay traffic names workload classes absent "
                  f"from --workloads: {', '.join(unknown)} (re-run "
                  f"with the recording's workload table)")
            return 2
        if args.duration != replay_artifact["duration_s"]:
            print(f"NOTE --replay pins --duration to the artifact's "
                  f"{replay_artifact['duration_s']:g}s (byte-identical "
                  f"replay needs the recorded horizon)")
        args.duration = float(replay_artifact["duration_s"])
        manifest_extra = {
            "traffic_fingerprint": replay_artifact["fingerprint"],
            "traffic_count": replay_artifact["count"],
            "traffic_path": args.replay,
        }

    rep = _common.make_reporter(args, rank=topo.process_index,
                                size=world, manifest_extra=manifest_extra)
    with rep:
        if args.retune and rep.metrics is None:
            # --retune without --metrics-port: attach a sink-only
            # registry NOW, before the handlers warm — their tune_hit
            # records are what arm the stale watch, so the tee must be
            # live before the first resolution flows (no exporter, no
            # heartbeat threads: just the record tee)
            from tpu_mpi_tests.instrument.metrics import MetricsRegistry

            rep.attach_metrics(MetricsRegistry(
                health_sink=lambda rec: rep.jsonl(
                    {**rec, "rank": rep.proc_index})))
        if replay_artifact is not None:
            load = (f"replay={args.replay} "
                    f"fingerprint={replay_artifact['fingerprint']}")
            arrival_name = "replay"
        elif args.arrival == "poisson":
            load = f"rate={args.rate:g}/s"
            arrival_name = args.arrival
        else:
            load = f"concurrency={args.concurrency}"
            arrival_name = args.arrival
        rep.banner(
            f"serve: arrival={arrival_name} {load} "
            f"duration={args.duration:g}s world={world} "
            f"max_batch={args.max_batch} seed={args.seed} "
            f"classes={','.join(c.key for c in classes)}"
        )

        # warm-cache preload: knob owners imported, schedule cache
        # fingerprints resolved BEFORE traffic opens — no first request
        # pays a cold resolution inside its measured latency
        warm = tr.preload()
        if tr.configured_cache() is not None:
            rep.banner(f"serve: tune preload resolved {len(warm)} "
                       f"schedule knobs")

        # build + warm one persistent handler per workload class (the
        # factories compile and run one step — serve latency then
        # measures the steady state, not compilation)
        handlers = {}
        for cls in classes:
            try:
                factory = _common.workload_factory(cls.workload)
                handlers[cls.key] = factory(mesh, cls.shape, cls.dtype)
            except (TpuMtError, ValueError, KeyError) as e:
                rep.line(f"ERROR workload {cls.key}: {e}")
                return 2
        rep.banner(f"serve: {len(handlers)} handlers warmed, "
                   f"opening traffic")

        if replay_artifact is not None:
            from tpu_mpi_tests.serve.replay import ReplayArrivals

            arrival = ReplayArrivals(replay_artifact)
        elif args.arrival == "poisson":
            arrival = OpenLoopPoisson(args.rate, seed=args.seed)
        else:
            arrival = ClosedLoop(args.concurrency)
        recorder = None
        if args.record:
            from tpu_mpi_tests.serve.replay import TrafficRecorder

            recorder = TrafficRecorder(arrival=args.arrival, load=load)
        wd = (IdleAwareWatchdog(args.batch_deadline, "serve")
              if args.batch_deadline else None)
        loop = ServeLoop(
            classes, handlers, arrival,
            duration_s=args.duration,
            max_batch=args.max_batch,
            window_s=args.report_interval,
            max_queue=args.max_queue,
            seed=args.seed,
            sink=lambda rec: rep.jsonl({**rec, "rank": rep.rank}),
            watchdog=wd,
            quarantine_after=args.quarantine_after,
            recorder=recorder,
        )
        if args.retune:
            # the closed loop: tune_stale (metrics tee, attached above
            # before the handlers warmed) → bounded between-windows
            # re-sweep → hot swap via registry.resolve → kind:"control"
            # tune_swap records (tune/controller.py). The stale watch
            # reads span GB/s, so telemetry must be on. Bound to the
            # LOOP's handler dict (the loop copies the caller's) so a
            # hot swap lands in the dict batches actually dispatch from.
            from tpu_mpi_tests.tune.controller import TuneController

            if not args.telemetry:
                rep.line("NOTE --retune needs --telemetry (tune_stale "
                         "watches span GB/s); the controller will "
                         "never fire")
            loop.controller = TuneController(
                rep.metrics, loop.handlers,
                sink=lambda rec: rep.jsonl({**rec, "rank": rep.rank}),
                line=rep.line,
                budget_s=args.batch_deadline or args.tune_budget,
                watchdog=wd,
            )
        summaries = loop.run()

        if recorder is not None:
            from tpu_mpi_tests.serve.replay import save_traffic

            artifact = recorder.finalize(args.duration)
            save_traffic(args.record, artifact)
            rep.jsonl({
                "kind": "traffic", "event": "record", "rank": rep.rank,
                "path": args.record,
                "fingerprint": artifact["fingerprint"],
                "count": artifact["count"],
                "duration_s": artifact["duration_s"],
                "classes": artifact["classes"],
                "version": artifact["version"],
            })
            rep.line(
                f"SERVE TRAFFIC recorded: path={args.record} "
                f"fingerprint={artifact['fingerprint']} "
                f"count={artifact['count']}"
            )
        if replay_artifact is not None:
            rep.jsonl({
                "kind": "traffic", "event": "replay", "rank": rep.rank,
                "path": args.replay,
                "fingerprint": replay_artifact["fingerprint"],
                "count": replay_artifact["count"],
                "duration_s": replay_artifact["duration_s"],
                "classes": replay_artifact["classes"],
                "version": replay_artifact["version"],
            })
            rep.line(
                f"SERVE TRAFFIC replayed: path={args.replay} "
                f"fingerprint={replay_artifact['fingerprint']} "
                f"count={replay_artifact['count']}"
            )

        rc = 0
        for rec in summaries:
            def ms(field, rec=rec):
                v = rec.get(field)
                return "-" if v is None else format(v, ".4g")

            quar = (f" quarantines={rec['quarantines']} "
                    f"quar_s={rec['quarantine_s']:.4g}"
                    if rec.get("quarantines") else "")
            rep.line(
                f"SERVE {rec['class']}: "
                f"offered={rec['offered_hz']:.4g}/s "
                f"achieved={rec['achieved_hz']:.4g}/s "
                f"n={rec['requests']} err={rec['errors']} "
                f"shed={rec['shed']} p50={ms('p50_ms')}ms "
                f"p95={ms('p95_ms')}ms p99={ms('p99_ms')}ms "
                f"qmax={rec['queue_max']}{quar}"
            )
            if rec.get("quarantines"):
                # graceful degradation worked as designed: the dead
                # class was isolated and accounted instead of failing
                # the whole run — surface it loudly, and forgive
                # exactly the errors/sheds the quarantine accounts
                # for (the triggering streaks + quarantine-dropped
                # load). Failures OUTSIDE those episodes still rc-1:
                # one recovered quarantine is not amnesty for a class
                # that kept failing afterwards.
                rep.line(
                    f"SERVE QUARANTINE {rec['class']}: "
                    f"{rec['quarantines']} episode(s), "
                    f"{rec['quarantine_s']:.4g}s quarantined "
                    f"(err={rec['errors']} shed={rec['shed']} "
                    f"survived by the other classes)"
                )
                if (rec["errors"] > rec.get("quar_errors", 0)
                        or rec["shed"] > rec.get("quar_shed", 0)):
                    rc = 1
                continue
            if rec["errors"] or rec["shed"]:
                rc = 1
            if rec["arrivals"] and not rec["requests"]:
                rep.line(f"SERVE FAIL {rec['class']}: {rec['arrivals']} "
                         f"arrivals, zero completed")
                rc = 1
        if not sum(r["requests"] for r in summaries):
            rep.line("SERVE FAIL: no requests completed (duration too "
                     "short for the configured rate?)")
            rc = 1
        return rc


def main(argv=None) -> int:
    from tpu_mpi_tests.serve.workloads import DEFAULT_TABLE

    p = _common.base_parser(__doc__)
    p.add_argument(
        "--duration", type=float, default=10.0, metavar="S",
        help="traffic window in seconds (the queue drains afterwards); "
        "serving runs are open-ended by design — pair long runs with "
        "--memwatch to watch HBM over hours",
    )
    p.add_argument(
        "--arrival", default="poisson", choices=["poisson", "closed"],
        help="arrival process: 'poisson' = open loop at --rate (latency "
        "includes queue wait from the scheduled arrival — coordinated "
        "omission impossible); 'closed' = fixed population of "
        "--concurrency clients, each re-issuing on completion",
    )
    p.add_argument(
        "--rate", type=float, default=20.0, metavar="HZ",
        help="open-loop offered rate, requests/second (default 20)",
    )
    p.add_argument(
        "--concurrency", type=int, default=4, metavar="N",
        help="closed-loop client population (default 4)",
    )
    p.add_argument(
        "--workloads", default=DEFAULT_TABLE, metavar="TABLE",
        help="comma list of name[:shape[:dtype[:weight]]] entries "
        "(shape dims 'x'-separated, e.g. attn:256x64:bfloat16:2); "
        "handlers: daxpy (vector step), halo (stencil1d exchange), "
        "attn (ring-attention block), allreduce (small-payload "
        "collective), moe (tokensxd_model capacity-bucketed routing), "
        "decode (batchxheads latency-bound allreduce), embedding "
        f"(vocabxbatchxd_model sharded lookup). Default: {DEFAULT_TABLE}",
    )
    p.add_argument(
        "--seed", type=int, default=0,
        help="RNG seed for the arrival schedule and workload mix "
        "(deterministic request sequences across runs)",
    )
    p.add_argument(
        "--max-batch", type=int, default=8, metavar="N",
        help="coalescing cap: at most N compatible (same shape x dtype "
        "x op) queued requests execute as one batch (default 8)",
    )
    p.add_argument(
        "--max-queue", type=int, default=10000, metavar="N",
        help="queue bound: arrivals beyond N waiting requests are shed "
        "and counted in the SLO table (default 10000)",
    )
    p.add_argument(
        "--report-interval", type=float, default=5.0, metavar="S",
        help="SLO window length: per-class kind:'serve' records emit "
        "every S seconds plus one run summary (default 5); the "
        "cross-window spread is the --diff noise band",
    )
    p.add_argument(
        "--quarantine-after", type=int, default=None, metavar="N",
        help="graceful degradation: a class whose handler fails N "
        "consecutive batches is quarantined (arrivals shed, backlog "
        "dropped, the other classes keep serving) and probed for "
        "recovery at each window boundary; quarantine/recovery time "
        "lands in the SLO table instead of the whole run exiting 1 "
        "(closed-loop note: requests shed during quarantine thin the "
        "client population like any shed). Default: off",
    )
    p.add_argument(
        "--retune", action="store_true",
        help="online closed-loop tuning: when a class's achieved GB/s "
        "sags below its tuned winner's baseline (the tune_stale health "
        "latch — README 'Live observability'), run a bounded re-sweep "
        "of that class's knob between SLO windows and hot-swap the "
        "schedule, emitting kind:'control' tune_swap records "
        "(README 'Fleet tuning'). Needs --telemetry; the re-sweep "
        "budget is --batch-deadline (else --tune-budget). Classes "
        "without a tune_info recipe are never re-tuned",
    )
    p.add_argument(
        "--record", default=None, metavar="PATH",
        help="capture this run's offered traffic (arrival times + class "
        "keys, chaos injections included) as a versioned portable "
        "artifact with a traffic fingerprint; replay it with --replay "
        "for identical-traffic A/B runs (README 'Latency anatomy & "
        "traffic replay')",
    )
    p.add_argument(
        "--replay", default=None, metavar="PATH",
        help="drive the loop with a recorded traffic artifact instead "
        "of a synthetic arrival process: the recorded (time, class) "
        "stream is reproduced byte-identically, --duration is pinned "
        "to the recording's horizon, and the traffic fingerprint lands "
        "in the manifest so tpumt-report --diff can refuse cross-"
        "traffic comparisons; corrupt or version-mismatched artifacts "
        "are refused with a NOTE (exit 2)",
    )
    p.add_argument(
        "--batch-deadline", type=float, default=None, metavar="S",
        help="idle-aware watchdog: hard-exit if one BATCH exceeds S "
        "seconds (armed only around active dispatch — idle gaps "
        "between arrivals never fire it); distinct from --deadline, "
        "which bounds the whole run",
    )
    args = p.parse_args(argv)
    if args.duration <= 0:
        p.error("--duration must be positive")
    if args.rate <= 0:
        p.error("--rate must be positive")
    if args.concurrency < 1:
        p.error("--concurrency must be >= 1")
    if args.max_batch < 1:
        p.error("--max-batch must be >= 1")
    if args.report_interval <= 0:
        p.error("--report-interval must be positive")
    if args.max_queue < 1:
        p.error("--max-queue must be >= 1")
    if args.quarantine_after is not None and args.quarantine_after < 1:
        p.error("--quarantine-after must be >= 1 (omit to disable)")
    if args.record and args.replay:
        p.error("--record and --replay are mutually exclusive (replaying "
                "a recording while re-recording it would fork the "
                "traffic identity)")
    if args.batch_deadline is not None and args.batch_deadline <= 0:
        # a negative Timer fires immediately: the first batch would die
        # with a bogus "hung collective" diagnosis
        p.error("--batch-deadline must be positive (omit to disable)")
    if args.arrival == "closed" and args.concurrency > args.max_queue:
        # a shed closed-loop client is never re-armed (re-arming a
        # request the full queue just rejected would spin) — the
        # population would silently decay below what the flag promised
        p.error("--concurrency must be <= --max-queue for closed-loop "
                "arrivals (shed clients leave the population for good)")
    if _table_wants_x64(args.workloads) and args.dtype != "float64":
        # float64 workload classes need the x64 software path armed
        # BEFORE the backend materializes arrays — otherwise jnp
        # silently truncates to float32 and every SLO row mislabels
        # what actually ran (the TPM3xx hazard class, serve-shaped)
        args.dtype = "float64"
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


def _table_wants_x64(spec: str) -> bool:
    """Whether any workload class in ``spec`` asks for float64 (a
    malformed spec answers False — ``run`` reports it properly)."""
    from tpu_mpi_tests.serve.workloads import parse_workload_table

    try:
        return any(
            c.dtype == "float64" for c in parse_workload_table(spec)
        )
    except ValueError:
        return False


if __name__ == "__main__":
    sys.exit(main())
