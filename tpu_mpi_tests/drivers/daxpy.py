"""Single-device DAXPY with checksum verification.

≅ ``daxpy.cu`` / ``daxpy_nvtx.cu``. The driver body lives in the
declarative workload spec (:mod:`tpu_mpi_tests.workloads.daxpy` — the
first pillar ported onto the spec subsystem, stdout byte-identical);
this module stays the compatible entry point: ``python -m
tpu_mpi_tests.drivers.daxpy`` and the ``daxpy`` serve-mode workload
class behave exactly as before the port.
"""

from __future__ import annotations

import sys

from tpu_mpi_tests.workloads.daxpy import SPEC, main  # noqa: F401

#: the serve-mode handler, re-exported for compatibility (registration
#: happens in the spec module via register_spec)
_serve_step_factory = SPEC.serve_factory


def run(args) -> int:
    """The driver body (spec runner flow) — kept so embedders that
    called ``daxpy.run`` keep working."""
    from tpu_mpi_tests.workloads.runner import run_body

    return run_body(SPEC, args)


if __name__ == "__main__":
    sys.exit(main())
