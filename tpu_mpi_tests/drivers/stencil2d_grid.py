"""2-D process-grid stencil driver: the full distributed step over PX×PY.

The reference decomposes along ONE dim at a time (``mpi_stencil2d_gt.cc``
runs dim 0 and dim 1 as separate tests); this driver runs the framework's
generalization — a 2-D device mesh with the domain ghosted and decomposed
along BOTH axes, per iteration: halo exchange on each axis (``ppermute``
rings), both-dim 5-point derivatives, and a global residual ``psum`` over
the whole mesh, compiled as ONE program (``comm/halo.step2d_fn`` — the
"training step" analog the dry-run harness exercises). Reported lines::

    GRID TEST px:<px> py:<py>; <seconds>, err_dx=<e>, err_dy=<e>
    ITER  ... (per-iteration mean/min/max past warmup)

Verification matches the reference's strategy (SURVEY §4.1): z = x³ + y²
with analytic dz/dx = 3x², dz/dy = 2y; interior ghosts start ZERO so a
broken exchange on either mesh axis explodes the error norm; physical
ghosts are filled analytically on mesh-edge shards
(``mpi_stencil2d_gt.cc:458-497``).
"""

from __future__ import annotations

import sys

import numpy as np

from tpu_mpi_tests.drivers import _common


def _init_block(dx, dy, rx: int, ry: int, px: int, py: int, fn, dtype):
    """Ghosted (rx, ry) block: interior analytic, physical ghost bands on
    mesh-edge shards, interior ghosts zero."""
    x = dx.ghosted_coords(rx, np.float64)
    y = dy.ghosted_coords(ry, np.float64)
    full = fn(x[:, None], y[None, :]).astype(dtype)
    out = np.zeros((dx.n_ghosted, dy.n_ghosted), dtype=dtype)
    nb = dx.n_bnd
    ix = slice(nb, nb + dx.n_local)
    iy = slice(nb, nb + dy.n_local)
    out[ix, iy] = full[ix, iy]
    if rx == 0:
        out[:nb, :] = full[:nb, :]
    if rx == px - 1:
        out[-nb:, :] = full[-nb:, :]
    if ry == 0:
        out[:, :nb] = full[:, :nb]
    if ry == py - 1:
        out[:, -nb:] = full[:, -nb:]
    return out


def run(args) -> int:
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_mpi_tests.arrays.domain import Domain1D
    from tpu_mpi_tests.comm import halo as H
    from tpu_mpi_tests.comm.halo import step2d_fn
    from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh, topology
    from tpu_mpi_tests.instrument import PhaseTimer
    from tpu_mpi_tests.instrument.timers import block
    from tpu_mpi_tests.kernels.stencil import N_BND, analytic_pairs

    dtype = _common.jnp_dtype(args)
    bootstrap()
    topo = topology()
    n_dev = topo.global_device_count

    grid = _common.parse_grid_mesh(args.mesh, n_dev)
    if grid is None:
        return 2
    px, py = grid
    mesh = make_mesh({"x": px, "y": py})

    rep = _common.make_reporter(args, rank=topo.process_index, size=n_dev)
    with rep:
        rep.banner(
            f"stencil2d_grid: mesh={px}x{py} nx_local={args.nx_local} "
            f"ny_local={args.ny_local} n_iter={args.n_iter} dtype={args.dtype}"
        )

        dx = Domain1D(n_global=px * args.nx_local, n_shards=px)
        dy = Domain1D(n_global=py * args.ny_local, n_shards=py)
        zf, _ = analytic_pairs()["2d_dim0"]

        gx, gy = px * dx.n_ghosted, py * dy.n_ghosted
        zg_host = np.zeros((gx, gy), dtype=dtype)
        for rx in range(px):
            for ry in range(py):
                zg_host[
                    rx * dx.n_ghosted:(rx + 1) * dx.n_ghosted,
                    ry * dy.n_ghosted:(ry + 1) * dy.n_ghosted,
                ] = _init_block(dx, dy, rx, ry, px, py, zf, dtype)
        zs = jax.device_put(zg_host, NamedSharding(mesh, P("x", "y")))

        step, kernel = _common.pick_kernel_tier(
            lambda k: step2d_fn(
                mesh, "x", "y", N_BND, float(dx.scale), float(dy.scale),
                kernel=k,
            ),
            (jax.ShapeDtypeStruct(zs.shape, zs.dtype),),
            args.kernel,
            rep,
            label="stencil2d_step",
        )

        depth = 1
        if args.overlap != "0":
            explicit = None if args.overlap == "auto" else int(args.overlap)
            depth = H.resolve_overlap_depth(
                explicit, dtype=args.dtype, n=px * args.nx_local,
                world=n_dev,
            )
            rep.banner(f"OVERLAP stencil2d_grid depth resolved -> {depth}")

        timer = PhaseTimer(skip_first=args.n_warmup)
        out = None
        runner = None
        if depth >= 2 and args.kernel == "xla":
            # host-scheduled pipeline (README "Overlap engine"): per
            # iteration, the dual-axis exchange rides in flight while
            # the core derivatives (cells touching no ghost) compute;
            # the seam completes the frame rows/cols and the residual
            # psum. The existing err gates verify the assembled fields.
            ex_fn, core_fn, seam_fn = H.grid_overlap_fns(
                mesh, "x", "y", N_BND, float(dx.scale), float(dy.scale)
            )
            nbytes = (
                H.halo_payload_bytes(zs, 0, px, N_BND, False)
                + H.halo_payload_bytes(zs, 1, py, N_BND, False)
            )
            runner = H.OverlapRunner(
                "halo_exchange2d", depth=depth, nbytes=nbytes,
                world=n_dev, timer=timer, phase="overlap_interior",
            )
            # warmups run through a throwaway runner (the step phase
            # still brackets them — skip_first keeps its accounting)
            # so the overlap record covers only the measured iters
            warm = H.OverlapRunner(
                "halo_exchange2d", depth=depth, nbytes=nbytes,
                world=n_dev,
            )
            for i in range(args.n_warmup + args.n_iter):
                r = warm if i < args.n_warmup else runner
                with timer.phase("step"):
                    ex, cores = r.step(ex_fn, core_fn, zs)
                    out = block(seam_fn(ex, *cores))
            runner.annotate(timer)
        else:
            if depth >= 2:
                rep.line("NOTE --overlap needs --kernel xla; running "
                         "the fused serial step")
                depth = 1
            for _ in range(args.n_warmup + args.n_iter):
                out = timer.timed("step", step, zs)
        dz_dx, dz_dy, residual = out
        seconds = timer.seconds["step"]
        if args.overlap != "0":
            it_per_s = (args.n_iter / seconds if seconds > 0
                        else float("inf"))
            ov_rec = (
                runner.record("stencil2d_grid", dtype=args.dtype,
                              it_per_s=it_per_s)
                if runner is not None else
                {"kind": "overlap", "op": "stencil2d_grid",
                 "depth": depth, "steps": args.n_iter,
                 "overlap_frac": 0.0, "comm_s": 0.0,
                 "compute_s": seconds, "world": n_dev,
                 "dtype": args.dtype, "it_per_s": it_per_s}
            )
            rep.line(
                f"OVERLAP stencil2d_grid depth={depth} "
                f"{it_per_s:0.1f} it/s "
                f"overlap_frac={ov_rec['overlap_frac']:0.3f}",
                ov_rec,
            )

        # err gates vs analytic derivatives over the global interior
        rc = 0
        if dz_dx.is_fully_addressable:
            xs = np.arange(dx.n_global) * dx.delta
            ys = np.arange(dy.n_global) * dy.delta
            want_dx = (3.0 * xs[:, None] ** 2) + 0.0 * ys[None, :]
            want_dy = 0.0 * xs[:, None] + 2.0 * ys[None, :]
            got_dx = np.asarray(jax.device_get(dz_dx), np.float64)
            got_dy = np.asarray(jax.device_get(dz_dy), np.float64)
            err_dx = float(np.sqrt(np.mean((got_dx - want_dx) ** 2)))
            err_dy = float(np.sqrt(np.mean((got_dy - want_dy) ** 2)))
        else:  # multi-host: residual finiteness is the (weaker) gate
            err_dx = err_dy = float("nan")
        rep.line(
            f"GRID TEST px:{px} py:{py}; {seconds:f}, "
            f"err_dx={err_dx:e}, err_dy={err_dy:e}",
            {"kind": "grid_test", "px": px, "py": py, "seconds": seconds,
             "err_dx": err_dx, "err_dy": err_dy,
             "residual": float(residual), "kernel": kernel},
        )
        rep.iter_line(0, "device", 0, "step", timer.mean("step"),
                      timer.mins.get("step", 0.0), timer.maxs.get("step", 0.0))

        if not np.isfinite(float(residual)):
            rep.line(f"RESIDUAL FAIL: {residual}")
            return 1
        tol = args.tol if args.tol is not None else _default_tol(args, dx, dy)
        if np.isfinite(err_dx) and max(err_dx, err_dy) > tol:
            rep.line(
                f"ERR_NORM FAIL grid: dx={err_dx:.8g} dy={err_dy:.8g} > "
                f"tol {tol:.8g}"
            )
            rc = 1
        return rc


def _default_tol(args, dx, dy) -> float:
    if args.dtype == "float64":
        return 1e-5
    eps = 7.8e-3 if args.dtype == "bfloat16" else 1.2e-7
    zmax = dx.length**3 + dy.length**2
    return 8 * eps * zmax * max(dx.scale, dy.scale)


def main(argv=None) -> int:
    p = _common.base_parser(__doc__)
    p.add_argument("--mesh", default=None,
                   help="process grid as 'PX,PY' (default: auto-factor)")
    p.add_argument("--nx-local", type=int, default=64,
                   help="per-shard interior rows")
    p.add_argument("--ny-local", type=int, default=64,
                   help="per-shard interior cols")
    p.add_argument("--n-iter", type=int, default=100)
    p.add_argument("--n-warmup", type=int, default=5)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument(
        "--kernel", choices=("xla", "pallas"), default="xla",
        help="per-shard pipeline tier: XLA expressions or the streamed "
        "Pallas dual-derivative kernel (one window read for both "
        "derivatives + residual)",
    )
    p.add_argument(
        "--overlap",
        default="0",
        choices=["0", "1", "2", "auto"],
        help="halo pipeline depth (README 'Overlap engine'): 0 = off "
        "(default, the fused exchange+derivative step), 1 = resolve "
        "the knob but keep the fused step, 2 = host-scheduled "
        "pipeline (dual-axis exchange in flight under the core "
        "derivatives), auto = the schedule cache's tuned depth; "
        "--kernel xla only",
    )
    args = p.parse_args(argv)
    for name in ("nx_local", "ny_local", "n_iter"):
        if getattr(args, name) < 1:
            p.error(f"--{name.replace('_', '-')} must be positive")
    if min(args.nx_local, args.ny_local) < 5:
        p.error("--nx-local/--ny-local must be >= 5 (stencil width)")
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


if __name__ == "__main__":
    sys.exit(main())
