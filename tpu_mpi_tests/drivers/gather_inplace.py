"""In-place allgather semantics probe.

≅ ``mpigatherinplace.f90``: every rank fills its own slice of a shared
global array, does ``MPI_Allgather(MPI_IN_PLACE)``, and prints its local sum
next to the global sum; the global sum must equal the sum of local sums
exactly. Reference default is 128Mi doubles per rank (``:11``); default here
is smaller for the single-chip case and flag-scalable.

Rank r's slice is filled with ``r + 1`` (``mpigatherinplace.f90:33-36``
fills with the 1-based rank), so local sums are ``(r+1)*n`` and the global
sum is ``n * world*(world+1)/2`` — integer-exact in every dtype up to large n.
"""

from __future__ import annotations

import sys

import numpy as np

from tpu_mpi_tests.drivers import _common


def run(args) -> int:
    import jax.numpy as jnp

    from tpu_mpi_tests.comm import collectives as C
    from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh, topology
    from tpu_mpi_tests.instrument.timers import block

    dtype = _common.jnp_dtype(args)
    bootstrap()
    topo = topology()
    mesh = make_mesh()
    world = topo.global_device_count
    n = args.n_per_rank

    rep = _common.make_reporter(args, rank=topo.process_index, size=world)
    with rep:

        # fill own slice: global buffer whose shard r holds (r+1)
        fill = np.repeat(np.arange(1, world + 1, dtype=np.float64), n)
        allx = C.shard_1d(jnp.asarray(fill.astype(dtype)), mesh)
        local_sums = [(r + 1) * n for r in range(world)]

        if args.rdma:
            # hand-written RDMA ring tier (≅ hand-coding the MPI_Allgather);
            # shard rows must meet the sublane-tile alignment
            g = block(C.all_gather_rdma(allx, mesh))
        else:
            g = block(C.all_gather_inplace(allx, mesh))
        asum = float(np.asarray(g, dtype=np.float64).sum())

        for r in range(world):
            rep.line(
                f"{r}/{world} lsum={local_sums[r]:.1f} asum={asum:.1f}",
                {"kind": "gather_inplace", "rank": r, "lsum": local_sums[r],
                 "asum": asum},
            )

        expected = float(sum(local_sums))
        if asum != expected:
            rep.line(f"PARITY FAIL: asum {asum} != sum of lsums {expected}")
            return 1
        return 0


def main(argv=None) -> int:
    p = _common.base_parser(__doc__)
    p.add_argument(
        "--n-per-rank",
        type=int,
        default=1 << 20,
        help="elements per rank (reference: 128Mi doubles)",
    )
    p.add_argument(
        "--rdma",
        action="store_true",
        help="gather through the hand-written RDMA ring "
        "(collectives.all_gather_rdma) instead of lax.all_gather",
    )
    args = p.parse_args(argv)
    if args.n_per_rank < 1:
        p.error(f"--n-per-rank must be positive, got {args.n_per_rank}")
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


if __name__ == "__main__":
    sys.exit(main())
