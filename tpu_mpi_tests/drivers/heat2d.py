"""Heat-equation mini-app: explicit time integration on a 2-D process grid.

The reference is a micro-benchmark suite modeling the GENE fusion code's
communication (``mpi_stencil2d_gt.cc:1-17``): it times the exchange but
never integrates anything. This driver closes the loop into an actual
distributed PDE solve — ∂z/∂t = ν∇²z on a periodic [0,2π)² domain,
explicit Euler, 5-point Laplacian — using every framework layer end to end:
mesh bootstrap, dual-axis periodic halo exchange, device-side chained time
loop (``comm/halo.heat_step2d_fn``), sync-honest timing, and the stable
report-line formats.

Verification is roundoff-exact, not tolerance-vs-analytic: the initial
field sin(kx·x)·sin(ky·y) is an eigenvector of the discrete periodic
update, so after T steps the field must equal g^T·z0 with
g = 1 − cx(2−2cos kxΔx) − cy(2−2cos kyΔy) — any halo or kernel defect
destroys the eigenstructure immediately (a far sharper gate than the
discretization-tolerance err_norms the derivative drivers use). Reported::

    HEAT mesh:<px>x<py> n:<nx>x<ny>; steps=<T> <steps/s> steps/s
    HEAT ERR rel=<e> (gate <tol>)

Stability: ``dt`` defaults to 0.4·Δ²/(2ν)·... i.e. 80% of the explicit
limit cx+cy ≤ 1/2.
"""

from __future__ import annotations

import math
import sys
import time

import numpy as np

from tpu_mpi_tests.drivers import _common


def run(args) -> int:
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_mpi_tests.comm import halo as H
    from tpu_mpi_tests.comm.halo import heat_step2d_fn
    from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh, topology
    from tpu_mpi_tests.instrument.timers import PhaseTimer, block

    dtype = _common.jnp_dtype(args)
    bootstrap()
    topo = topology()
    n_dev = topo.global_device_count

    grid = _common.parse_grid_mesh(args.mesh, n_dev)
    if grid is None:
        return 2
    px, py = grid
    mesh = make_mesh({"x": px, "y": py})

    nx, ny = px * args.nx_local, py * args.ny_local
    dx, dy = 2.0 * math.pi / nx, 2.0 * math.pi / ny
    # 80% of the explicit-Euler stability limit cx + cy <= 1/2
    dt = args.dt if args.dt is not None else (
        0.4 / (args.nu * (1.0 / dx**2 + 1.0 / dy**2))
    )
    cx, cy = args.nu * dt / dx**2, args.nu * dt / dy**2

    rep = _common.make_reporter(args, rank=topo.process_index, size=n_dev)
    with rep:
        rep.banner(
            f"heat2d: mesh={px}x{py} n={nx}x{ny} nu={args.nu} dt={dt:.3e} "
            f"steps={args.n_steps} dtype={args.dtype}"
        )

        # ghosted-per-shard layout, interior = sin(kx x)·sin(ky y), ghosts zero
        # (the first exchange fills them — periodic, so no physical bands).
        # Ghost width = halo_steps × the 5-point Laplacian's radius (1): the
        # exchange moves exactly the bytes the fused timesteps read; at the
        # default halo_steps=1 that is the minimal per-step exchange, and
        # --halo-steps k trades k-deep ghosts for 1/k the exchanges (temporal
        # blocking, interior-identical — the eigen gate proves it at k>1)
        nb = args.halo_steps
        gxs, gys = args.nx_local + 2 * nb, args.ny_local + 2 * nb
        zg_host = np.zeros((px * gxs, py * gys), dtype=dtype)
        xs = np.arange(nx, dtype=np.float64) * dx
        ys = np.arange(ny, dtype=np.float64) * dy
        z0 = np.sin(args.kx * xs)[:, None] * np.sin(args.ky * ys)[None, :]
        for rx in range(px):
            for ry in range(py):
                blk = z0[
                    rx * args.nx_local:(rx + 1) * args.nx_local,
                    ry * args.ny_local:(ry + 1) * args.ny_local,
                ]
                zg_host[
                    rx * gxs + nb:rx * gxs + nb + args.nx_local,
                    ry * gys + nb:ry * gys + nb + args.ny_local,
                ] = blk.astype(dtype)
        zs = jax.device_put(zg_host, NamedSharding(mesh, P("x", "y")))

        kernel_arg = args.kernel
        if kernel_arg == "auto":
            # heat has no RDMA exchange — the chained/fused tiers'
            # exchange half deliberately does not transfer, only their
            # pallas update body does (README "Kernel tiers")
            kernel_arg = _common.resolve_kernel_auto(
                args.dtype, nx, n_dev, rep
            )
        step, kernel = _common.pick_kernel_tier(
            lambda k: heat_step2d_fn(
                mesh, "x", "y", nb, float(cx), float(cy),
                steps=args.halo_steps, kernel=k,
            ),
            (jax.ShapeDtypeStruct(zs.shape, zs.dtype), 1),
            kernel_arg,
            rep,
            label="heat2d_step",
        )
        depth = 1
        if args.overlap != "0":
            explicit = None if args.overlap == "auto" else int(args.overlap)
            depth = H.resolve_overlap_depth(
                explicit, dtype=args.dtype, n=nx, world=n_dev
            )
            rep.banner(f"OVERLAP heat2d depth resolved -> {depth}")

        outer_total = args.n_steps // args.halo_steps
        runner = None
        if depth >= 2:
            # host-scheduled pipeline (README "Overlap engine"): per
            # Euler step, the dual-axis exchange rides in flight while
            # the core (cells touching no fresh ghost) computes; the
            # seam patches the 1-wide boundary frame from the arrivals.
            # Verified end-to-end by the same eigen gate as the fused
            # loop — a broken seam destroys the eigenstructure.
            ex_fn, core_fn, seam_fn = H.heat_overlap_fns(
                mesh, "x", "y", float(cx), float(cy)
            )
            nbytes = (
                H.halo_payload_bytes(zs, 0, px, nb, True)
                + H.halo_payload_bytes(zs, 1, py, nb, True)
            )
            timer = PhaseTimer()

            def pipe_steps(r, z, n):
                for _ in range(n):
                    ex, zc = r.step(ex_fn, core_fn, z)
                    z = block(seam_fn(ex, zc))
                return z

            # compile + warm through a throwaway runner so the record's
            # comm/compute/drain seconds cover only the timed steps
            zs = pipe_steps(
                H.OverlapRunner("halo_exchange2d", depth=depth,
                                nbytes=nbytes, world=n_dev),
                zs, 1,
            )
            runner = H.OverlapRunner(
                "halo_exchange2d", depth=depth, nbytes=nbytes,
                world=n_dev, timer=timer, phase="overlap_interior",
            )
            t0 = time.perf_counter()
            zs = pipe_steps(runner, zs, outer_total - 1)
            seconds = time.perf_counter() - t0
            runner.annotate(timer)
            rep.time_lines(timer, stats=True)
        else:
            # compile + warm: 1 outer body = halo_steps timesteps, counted
            zs = block(step(zs, 1))

            t0 = time.perf_counter()
            zs = block(step(zs, outer_total - 1))
            seconds = time.perf_counter() - t0
        timed_steps = (outer_total - 1) * args.halo_steps
        steps_per_s = timed_steps / seconds if seconds > 0 else float("inf")
        if args.overlap != "0":
            ov_rec = (
                runner.record("heat2d", dtype=args.dtype,
                              steps_per_s=steps_per_s)
                if runner is not None else
                {"kind": "overlap", "op": "heat2d", "depth": depth,
                 "steps": outer_total - 1, "overlap_frac": 0.0,
                 "comm_s": 0.0, "compute_s": seconds, "world": n_dev,
                 "dtype": args.dtype, "steps_per_s": steps_per_s}
            )
            rep.line(
                f"OVERLAP heat2d depth={depth} "
                f"overlap_frac={ov_rec['overlap_frac']:0.3f}",
                ov_rec,
            )
        rep.line(
            f"HEAT mesh:{px}x{py} n:{nx}x{ny}; steps={args.n_steps} "
            f"{steps_per_s:0.1f} steps/s",
            {"kind": "heat", "px": px, "py": py, "nx": nx, "ny": ny,
             "steps": args.n_steps, "steps_per_s": steps_per_s,
             "nu": args.nu, "dt": dt, "kernel": kernel,
             "overlap": depth},
        )

        rc = 0
        if zs.is_fully_addressable:
            # eigenvalue gate: field == g^T · z0 to roundoff
            g = (
                1.0
                - cx * (2.0 - 2.0 * math.cos(args.kx * dx))
                - cy * (2.0 - 2.0 * math.cos(args.ky * dy))
            )
            want = (g**args.n_steps) * z0
            got = np.zeros((nx, ny), dtype=np.float64)
            zg_out = np.asarray(jax.device_get(zs), np.float64)
            for rx in range(px):
                for ry in range(py):
                    got[
                        rx * args.nx_local:(rx + 1) * args.nx_local,
                        ry * args.ny_local:(ry + 1) * args.ny_local,
                    ] = zg_out[
                        rx * gxs + nb:rx * gxs + nb + args.nx_local,
                        ry * gys + nb:ry * gys + nb + args.ny_local,
                    ]
            denom = float(np.sqrt(np.mean(want**2)))
            with np.errstate(over="ignore"):  # unstable dt overflows by design;
                # the gate reports it as inf > tol, not as a warning
                rel = (
                    float(np.sqrt(np.mean((got - want) ** 2)))
                    / max(denom, 1e-300)
                )
            tol = args.tol if args.tol is not None else _default_tol(args)
            rep.line(
                f"HEAT ERR rel={rel:e} (gate {tol:e})",
                {"kind": "heat_err", "rel": rel, "tol": tol, "g": g},
            )
            if not np.isfinite(rel) or rel > tol:
                rep.line(f"HEAT FAIL rel={rel:.8g} > tol {tol:.8g}")
                rc = 1
        else:
            rep.line("HEAT NOTE multi-host: eigen gate skipped "
                     "(shards not addressable); finiteness only")
            if not np.isfinite(float(np.asarray(
                    zs.addressable_shards[0].data).sum())):
                rc = 1
        return rc


def _default_tol(args) -> float:
    # per-step relative roundoff growth ~eps; the eigen gate is exact up
    # to accumulated rounding in T steps. Capped at 0.5 so the gate can
    # never go vacuous (bf16 at hundreds of steps accumulates real ~10%
    # rounding, but a broken exchange lands at rel ≈ 1)
    eps = {"float64": 2.3e-16, "float32": 1.2e-7, "bfloat16": 7.8e-3}[
        args.dtype
    ]
    return min(0.5, 50.0 * eps * max(args.n_steps, 1) ** 0.5 + 10.0 * eps)


def main(argv=None) -> int:
    p = _common.base_parser(__doc__)
    p.add_argument("--mesh", default=None,
                   help="process grid as 'PX,PY' (default: auto-factor)")
    p.add_argument("--nx-local", type=int, default=64)
    p.add_argument("--ny-local", type=int, default=64)
    p.add_argument("--n-steps", type=int, default=200)
    p.add_argument("--nu", type=float, default=0.1,
                   help="diffusivity")
    p.add_argument("--dt", type=float, default=None,
                   help="time step (default: 80%% of the explicit limit)")
    p.add_argument("--kx", type=int, default=1)
    p.add_argument("--ky", type=int, default=1)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument(
        "--halo-steps", type=int, default=1,
        help="temporal blocking: fuse this many Euler steps per dual-axis "
        "exchange over equally-deep ghosts (1/k the messages; "
        "interior-identical, gated by the same eigen check)",
    )
    p.add_argument(
        "--kernel", choices=("xla", "pallas", "auto"), default="xla",
        help="update-body tier: the XLA slice formulation, the in-place "
        "row-streaming Pallas kernel (same recurrence update-for-update, "
        "~2 HBM passes per fused call vs ~6 per step), or auto — the "
        "stencil/tier schedule cache's winner mapped onto the two bodies "
        "(README 'Kernel tiers'; --overlap still requires a literal xla)",
    )
    p.add_argument(
        "--overlap",
        default="0",
        choices=["0", "1", "2", "auto"],
        help="halo pipeline depth (README 'Overlap engine'): 0 = off "
        "(default, today's fused device-side loop), 1 = resolve the "
        "knob but keep today's loop (the serialized schedule), 2 = "
        "host-scheduled pipeline with the dual-axis exchange in flight "
        "under the core compute, auto = the schedule cache's tuned "
        "depth; requires --kernel xla and --halo-steps 1",
    )
    args = p.parse_args(argv)
    if args.overlap != "0" and (
        args.kernel != "xla" or args.halo_steps != 1
    ):
        p.error("--overlap requires --kernel xla and --halo-steps 1 "
                "(the interior/boundary split is the per-step XLA body)")
    for name in ("nx_local", "ny_local", "n_steps", "kx", "ky",
                 "halo_steps"):
        if getattr(args, name) < 1:
            p.error(f"--{name.replace('_', '-')} must be positive")
    if args.n_steps % args.halo_steps:
        p.error("--n-steps must be a multiple of --halo-steps")
    if min(args.nx_local, args.ny_local) < 2 * args.halo_steps + 1:
        p.error("--nx-local/--ny-local must exceed 2x the fused halo depth")
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


if __name__ == "__main__":
    sys.exit(main())
