"""Benchmark drivers — one per reference binary (SURVEY.md §2.1).

| driver              | reference binary        |
|---------------------|-------------------------|
| daxpy               | daxpy.cu / daxpy_nvtx.cu|
| mpi_daxpy           | mpi_daxpy.cc / mpi_daxpy_gt.cc |
| mpi_daxpy_nvtx      | mpi_daxpy_nvtx.cc (flagship DAXPY) |
| stencil1d           | mpi_stencil_gt.cc       |
| stencil2d           | mpi_stencil2d_gt.cc (flagship stencil) + *_sycl variants |
| gather_inplace      | mpigatherinplace.f90    |
| envprobe            | mpienv.f90              |
| serve               | — (beyond parity: steady-state serving loop) |

All drivers run unchanged on the fake-device CPU mesh (``--fake-devices N``)
and on real TPU slices; the same shard_map code path executes in both.
"""
