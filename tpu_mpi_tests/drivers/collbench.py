"""Collective micro-benchmark sweep: per-collective bandwidth vs message size.

The reference is, at heart, an MPI collective/neighbor-exchange
micro-benchmark suite (Allgather ``mpi_daxpy_nvtx.cc:282-291``, in-place
Allreduce ``mpi_stencil2d_gt.cc:609-648``, Isend/Irecv neighbor exchange
``mpi_stencil_gt.cc:83-122``) at a handful of fixed sizes. This driver
generalizes that into the OSU/nccl-tests-shaped sweep the reference never
had: every mesh collective × a geometric ladder of message sizes, measured
with device-side chained loops (``instrument.timers.chain_rate``) so the
numbers survive shared-chip contention and async dispatch.

Output per (collective, size)::

    COLL <name> bytes=<per-shard-bytes> <us> us/iter  busbw=<GB/s>

``busbw`` uses the standard ring-algorithm accounting (nccl-tests
conventions) so numbers are comparable across collectives and world sizes:

* ``allgather`` / ``alltoall``: moved = (w−1)/w · gathered_bytes
* ``allreduce``: moved = 2·(w−1)/w · shard_bytes
* ``reducescatter``: moved = (w−1)/w · shard_bytes
* ``ppermute``: moved = shard_bytes (pure neighbor shift, the halo pattern)
* ``allgather_rdma`` / ``allreduce_rdma`` (hand ring twins, opt-in): same
  bytes as their XLA counterparts — the ring schedule moves exactly the
  accounted volume
* ``allgather_oneshot`` / ``allreduce_oneshot`` (one-shot in-kernel tier,
  ISSUE 19): accounted with the SAME per-collective formula even though
  the one-shot schedule physically ships (w−1)·shard per rank — busbw is
  the nccl-tests algorithm-normalized convention precisely so tiers are
  comparable per row; the one-shot tier trades wire bytes for a single
  fixed-cost hop and is expected to win only at the small end

On a 1-device world the collectives execute (XLA degenerate lowering) but
move nothing; busbw is reported as 0 — the sweep is meaningful on ≥2
devices (CPU fake-device meshes or real slices, where it rides ICI).
"""

from __future__ import annotations

import functools
import sys

from tpu_mpi_tests.drivers import _common
from tpu_mpi_tests.tune import priors as _priors
from tpu_mpi_tests.tune.registry import declare_space

COLLECTIVES = (
    "allgather", "allreduce", "reducescatter", "ppermute", "alltoall"
)
# hand-tier explicit-RDMA ring twins (kernels/pallas_kernels.py) — opt-in
# rather than default because their lane-alignment rules skip the smallest
# ladder sizes (the skip is reported, not silent)
COLLECTIVES_RDMA = ("allgather_rdma", "allreduce_rdma")
# one-shot in-kernel tier (kernels/collectives_pallas.py, ISSUE 19): one
# launch, one DMA hop, pad-to-tile — no alignment skip, reaches every
# ladder size including the decode payloads the ring floors reject
COLLECTIVES_ONESHOT = ("allgather_oneshot", "allreduce_oneshot")

#: collectives with hand-written twins: the variant (XLA lowering vs
#: explicit-RDMA ring vs one-shot in-kernel burst) is a tunable
#: schedule — ``--collectives auto`` resolves each through the cache
#: (prior: xla), ``--tune`` sweeps all three on a miss. Declared here
#: because the variant choice lives here.
COLL_VARIANT_SPACES = {
    base: declare_space(
        f"coll_variant/{base}",
        (_priors.COLL_VARIANT, "rdma", "oneshot"),
        describe="XLA collective vs hand-written RDMA ring twin vs "
                 "one-shot in-kernel burst",
    )
    for base in ("allgather", "allreduce")
}

# the COLL line's parse pattern lives NEXT TO its format string (below) so
# a format change is a one-site edit; both test files import this
COLL_LINE_RE = (
    r"COLL (\w+) bytes=(\d+) ([\d.e+-]+|nan) us/iter  "
    r"busbw=([\d.e+-]+|nan) GB/s  n=(\d+)(?: credits=(\d+))?"
)


def _loop_fn(mesh, axis_name: str, name: str, world: int,
             rdma_credits: int = 1):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_mpi_tests.compat import shard_map
    from jax.sharding import PartitionSpec as P

    def consume_neighbor(gathered, x):
        # consume the NEIGHBOR's slice: slicing one's own shard is
        # exactly what XLA's AllGatherDynamicSliceSimplifier cancels
        # back to x, which would delete the collective and benchmark
        # an empty loop (shared by both allgather tiers so the
        # CSE-defeat trick cannot drift between them)
        r = lax.axis_index(axis_name)
        n = x.shape[0]
        nbr = lax.rem(r + 1, jnp.int32(world))
        return lax.dynamic_slice_in_dim(gathered, nbr * n, n) * 0.999 + 1e-7

    def body_of(name):
        if name == "allgather":
            def body(_, x):
                g = lax.all_gather(x, axis_name, axis=0, tiled=True)
                return consume_neighbor(g, x)
        elif name == "allreduce":
            def body(_, x):
                return lax.psum(x, axis_name) * (1.0 / world)
        elif name == "reducescatter":
            def body(_, x):
                rs = lax.psum_scatter(
                    x, axis_name, scatter_dimension=0, tiled=True
                )
                # re-expand so the chain stays shape-stable; the tile adds
                # one local HBM write per iter on top of the collective
                # (small next to the (w-1)/w network bytes it measures)
                return jnp.tile(rs, world) * (1.0 / world)
        elif name == "ppermute":
            perm = [(i, (i + 1) % world) for i in range(world)]
            def body(_, x):
                return lax.ppermute(x, axis_name, perm)
        elif name == "allgather_rdma":
            from tpu_mpi_tests.kernels.pallas_kernels import (
                ring_allgather_pallas,
            )

            def body(_, x):
                g = ring_allgather_pallas(x, axis_name=axis_name)
                return consume_neighbor(g, x)
        elif name == "allreduce_rdma":
            from tpu_mpi_tests.kernels.pallas_kernels import (
                ring_allreduce_pallas,
            )

            def body(_, x):
                return ring_allreduce_pallas(
                    x, axis_name=axis_name, credits=rdma_credits
                ) * (1.0 / world)
        elif name == "allgather_oneshot":
            from tpu_mpi_tests.kernels.collectives_pallas import (
                oneshot_allgather_pallas,
            )

            def body(_, x):
                g = oneshot_allgather_pallas(x, axis_name=axis_name)
                return consume_neighbor(g, x)
        elif name == "allreduce_oneshot":
            from tpu_mpi_tests.kernels.collectives_pallas import (
                oneshot_allreduce_pallas,
            )

            def body(_, x):
                return oneshot_allreduce_pallas(
                    x, axis_name=axis_name
                ) * (1.0 / world)
        else:  # alltoall
            def body(_, x):
                y = x.reshape(world, x.shape[0] // world)
                y = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                                   tiled=False)
                return y.reshape(x.shape) * 0.999 + 1e-7
        return body

    @functools.partial(jax.jit, donate_argnums=0)
    def run(x, n_iter):
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(axis_name), P()),
            out_specs=P(axis_name), check_vma=False,
        )
        def go(x, n):
            return lax.fori_loop(0, n[0], body_of(name), x)

        return go(x, jnp.asarray([n_iter], jnp.int32))

    return run


def _resolve_variant(base, args, mesh, axis_name, world, n, dtype,
                     shard_bytes) -> str:
    """The collective name to actually run for an ``auto`` entry:
    explicit names never reach here; the variant knob resolves cached >
    prior, and with ``--tune`` a miss prices ALL tiers on-device at
    this payload size (the rdma twin's lane-alignment floor surfaces as
    a recorded error candidate; the one-shot tier pads to tile and so
    always prices)."""
    import jax
    import jax.numpy as jnp

    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.instrument.timers import chain_rate
    from tpu_mpi_tests.tune.sweep import ensure_tuned

    def eff_of(variant: str) -> str:
        return base if variant == "xla" else f"{base}_{variant}"

    def measure(variant):
        eff = eff_of(variant)
        fn = _loop_fn(mesh, axis_name, eff, world,
                      rdma_credits=args.rdma_credits)
        if eff in COLLECTIVES_RDMA:
            # trace-time feasibility probe: below the ring kernel's
            # lane-alignment floor this raises, and the sweep records
            # the candidate as errored instead of crashing
            jax.eval_shape(
                fn, jax.ShapeDtypeStruct((n * world,), dtype), 1
            )
        x = shard_1d(jnp.ones((n * world,), dtype), mesh, axis_name)
        n_meas = max(10, args.n_iter // 10)
        sec, x = chain_rate(
            fn, x, n_short=n_meas // 10 or 1, n_long=n_meas
        )
        del x
        return sec

    variant = ensure_tuned(
        f"coll_variant/{base}", measure,
        # payload-size-sensitive: the 16 MiB winner must not decide the
        # 4 KiB row through the device-only slot
        device_fallback=False,
        dtype=args.dtype, bytes=shard_bytes, world=world,
    )
    if variant not in ("xla", "rdma", "oneshot"):
        variant = "xla"  # malformed cache value degrades to the prior
    return eff_of(variant)


def _tune_dispatch_depth(args, mesh, axis_name: str, world: int) -> None:
    """Sweep the ``coll/dispatch_depth`` knob (ISSUE 7 tentpole c) on a
    cache miss: a host-chained run of small allreduces dispatched
    through a :class:`~tpu_mpi_tests.comm.collectives.DispatchWindow`
    at each candidate depth — the latency-bound chaining pattern the
    window exists for. The winner persists under the full AND
    device-only fingerprints, so every chained site on this machine
    (e.g. the serve-mode halo handler) resolves it."""
    import time

    import jax.numpy as jnp

    from tpu_mpi_tests.comm import collectives as C
    from tpu_mpi_tests.instrument.timers import block
    from tpu_mpi_tests.tune.sweep import ensure_tuned

    dtype = _common.jnp_dtype(args)
    shard_bytes = min(
        int(s) for s in args.sizes_kib.split(",")
    ) * 1024  # smallest ladder size: fixed dispatch cost dominates there
    n = shard_bytes // jnp.dtype(dtype).itemsize
    run_fn = _loop_fn(mesh, axis_name, "allreduce", world)
    chain = max(16, args.n_iter // 10)
    nbytes = int(2 * (world - 1) / world * shard_bytes)

    def measure(cand):
        x = C.shard_1d(jnp.ones((n * world,), dtype), mesh, axis_name)
        block(run_fn(x + 0, 1))  # compile + warm (run_fn donates)
        win = C.DispatchWindow(int(cand))
        t0 = time.perf_counter()
        for _ in range(chain):
            x = win.call(
                "allreduce", run_fn, x, 1,
                nbytes=nbytes, axis_name=axis_name, world=world,
            )
        win.drain()
        block(x)
        sec = time.perf_counter() - t0
        del x
        return sec

    ensure_tuned(
        "coll/dispatch_depth", measure,
        dtype=args.dtype, bytes=shard_bytes, world=world,
    )


def _busbw_bytes(name: str, shard_bytes: int, world: int) -> float:
    # tiers are accounted with the base collective's formula (nccl-tests
    # algorithm-normalized convention): the ring twins move exactly the
    # accounted volume; the one-shot tier ships more bytes by design and
    # is normalized anyway so rows stay comparable across tiers
    name = name.removesuffix("_rdma").removesuffix("_oneshot")
    if world < 2:
        return 0.0
    if name == "allgather":
        return (world - 1) * shard_bytes  # (w-1)/w of gathered = (w-1)*shard
    if name == "allreduce":
        return 2 * (world - 1) / world * shard_bytes
    if name == "reducescatter":
        return (world - 1) / world * shard_bytes
    if name == "ppermute":
        return float(shard_bytes)
    return (world - 1) / world * shard_bytes  # alltoall


def run(args) -> int:
    import jax.numpy as jnp
    import numpy as np

    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh, topology
    from tpu_mpi_tests.instrument.timers import chain_rate
    from tpu_mpi_tests.utils import check_divisible

    bootstrap()
    topo = topology()
    mesh = make_mesh()
    world = topo.global_device_count
    axis_name = mesh.axis_names[0]

    rep = _common.make_reporter(args, rank=topo.process_index, size=world)
    with rep:
        rep.banner(
            f"collbench: world={world} sizes_kib={args.sizes_kib} "
            f"collectives={args.collectives} n_iter={args.n_iter} "
            f"rdma_credits={args.rdma_credits}"
        )

        names = _common.parse_choice_list(
            args.collectives,
            COLLECTIVES + COLLECTIVES_RDMA + COLLECTIVES_ONESHOT
            + ("auto",),
            "collective",
        )
        if names is None:
            return 2
        # "auto" expands to the twin-backed collectives with per-size
        # variant resolution (explicit names never re-resolve)
        names = [
            m
            for n in names
            for m in (
                [f"{b}:auto" for b in COLL_VARIANT_SPACES]
                if n == "auto" else [n]
            )
        ]

        if args.tune:
            # dispatch-depth sweep (on-miss inside ensure_tuned): the
            # window knob is priced here, where the chained-collective
            # pattern lives, and consumed wherever chains dispatch
            _tune_dispatch_depth(args, mesh, axis_name, world)

        dtype = _common.jnp_dtype(args)
        itemsize = jnp.dtype(dtype).itemsize
        for spec_name in names:
            base, _, mode = spec_name.partition(":")
            auto = mode == "auto"
            for kib in (int(s) for s in args.sizes_kib.split(",")):
                shard_bytes = kib * 1024
                n = shard_bytes // itemsize
                name = base
                if auto:
                    name = _resolve_variant(
                        base, args, mesh, axis_name, world, n, dtype,
                        shard_bytes,
                    )
                if name in ("alltoall", "reducescatter"):
                    # the alltoall reshape and the psum_scatter chunking both
                    # split the shard w ways
                    check_divisible(n, world, f"{name} elements per shard")
                run_fn = _loop_fn(mesh, axis_name, name, world,
                                  rdma_credits=args.rdma_credits)
                if name in COLLECTIVES_RDMA:
                    # ring kernels have lane-alignment floors (e.g. w·128·
                    # sublane elements for the 1-D allreduce); probe at trace
                    # time (no execution, no donation) and report the skip
                    # instead of failing the sweep or hiding the row
                    import jax

                    try:
                        jax.eval_shape(
                            run_fn,
                            jax.ShapeDtypeStruct((n * world,), dtype),
                            1,
                        )
                    except ValueError as e:
                        rep.line(
                            f"COLL-SKIP {name} bytes={shard_bytes} ({e})"
                        )
                        continue
                x = shard_1d(jnp.ones((n * world,), dtype), mesh, axis_name)
                # compile-cost probe (telemetry runs only): compile wall
                # time + cost model per collective×size, fingerprinted
                # like the variant knob (lower/compile never execute, so
                # the donated buffer is untouched)
                from tpu_mpi_tests.instrument import costs

                costs.compile_probe(
                    run_fn, (x, 1), label=f"coll_{name}",
                    dtype=args.dtype, bytes=shard_bytes, world=world,
                )
                # scale the chain length inversely with payload so small
                # messages accumulate enough device time to clear host-timer
                # noise (a fixed count yields NaN/garbage under ~ms jitter:
                # 500 x 15 us is invisible next to a 100 ms tunnel round-trip);
                # the actual count is reported per row (no silent config drift)
                n_eff = min(
                    max(args.n_iter, 100_000),
                    max(args.n_iter, args.n_iter * (1 << 20)
                        // max(shard_bytes, 1)),
                )
                sec, x = chain_rate(
                    run_fn, x, n_short=n_eff // 10 or 1, n_long=n_eff
                )
                moved = _busbw_bytes(name, shard_bytes, world)
                busbw = moved / sec / 1e9
                # rdma rows record their credit depth, or the pod A/B the
                # --rdma-credits flag exists for cannot be reconstructed
                # from merged jsonl results
                cred_txt = (f" credits={args.rdma_credits}"
                            if name == "allreduce_rdma" else "")
                cred_rec = ({"rdma_credits": args.rdma_credits}
                            if name == "allreduce_rdma" else {})
                rep.line(
                    # %.4g, not %.2f: a loaded host can push busbw below
                    # 0.005 GB/s, which fixed-point floors to a misleading
                    # "0.00" (a positive measurement must print positive)
                    f"COLL {name} bytes={shard_bytes} {sec * 1e6:0.2f} us/iter"
                    f"  busbw={busbw:0.4g} GB/s  n={n_eff}{cred_txt}",
                    {"kind": "coll", "collective": name, "dtype": args.dtype,
                     "shard_bytes": shard_bytes, "us_per_iter": sec * 1e6,
                     "busbw_gbps": busbw, "world": world, "n_iter": n_eff,
                     # auto rows record the resolution so merged results
                     # distinguish a tuned pick from an explicit request
                     **({"auto": True} if auto else {}),
                     **cred_rec},
                )
                del x
        return 0


def _serve_step_factory(mesh, shape, dtype):
    """Serve-mode handler: ``step_fn(n)`` runs ``n`` device-chained
    small-payload allreduces (the decode-step collective class: fixed
    per-op cost dominates, which is exactly what tail latency under
    mixed traffic stresses). ``shape`` is elements *per shard*; reuses
    the benchmark's own chained loop (:func:`_loop_fn`) so serve mode
    measures the same program ``COLL allreduce`` rows do."""
    import jax.numpy as jnp

    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.instrument.timers import block

    if len(shape) != 1:
        raise ValueError(f"allreduce wants a 1-d shape, got {shape}")
    (n,) = shape
    world = mesh.devices.size
    axis_name = mesh.axis_names[0]
    dt = jnp.dtype(dtype)
    run_fn = _loop_fn(mesh, axis_name, "allreduce", world)

    def init():
        return shard_1d(jnp.ones((n * world,), dt), mesh, axis_name)

    state = {"x": init()}

    def step(k: int):
        try:
            state["x"] = block(run_fn(state["x"], k))
        except Exception:
            # run_fn donates its input: a failed batch may have
            # consumed the held buffer — rebuild so the NEXT batch of
            # this class serves instead of failing buffer-deleted
            # forever (the loop counts this batch's error either way)
            state["x"] = init()
            raise

    step(1)  # compile + warm before traffic opens
    return step


_common.register_workload("allreduce", _serve_step_factory)


def main(argv=None) -> int:
    p = _common.base_parser(__doc__)
    p.add_argument(
        "--collectives",
        default=",".join(COLLECTIVES),
        help="comma list of collectives to sweep; beyond the default XLA "
        f"tier, {'/'.join(COLLECTIVES_RDMA)} select the hand-written "
        "RDMA ring twins (sizes below their lane-alignment floor are "
        f"reported as COLL-SKIP) and {'/'.join(COLLECTIVES_ONESHOT)} "
        "the one-shot in-kernel tier (pad-to-tile, every size); 'auto' "
        "runs the twin-backed collectives with each size's variant "
        "resolved from the schedule cache (with --tune, a cache miss "
        "prices all tiers on-device first)",
    )
    p.add_argument(
        "--rdma-credits", type=int, default=1, choices=(1, 2),
        help="receiver-credit depth for the allreduce_rdma ring's "
        "reduce-scatter phase: 2 = the double-buffered pod-latency "
        "variant (overlaps send s+1 with the right neighbor's fold of "
        "s; simulated-race-free, wall-clock benefit needs multi-chip "
        "skew — this flag is the one-command pod experiment)",
    )
    p.add_argument(
        "--sizes-kib",
        default="4,64,1024,16384",
        help="comma list of per-shard payload sizes in KiB",
    )
    p.add_argument(
        "--n-iter", type=int, default=500,
        help="chained iterations per measurement at 1 MiB payloads; "
        "smaller payloads scale the count up inversely (capped at 100k) "
        "so device time clears host-timer noise — the actual count is "
        "reported per row as n=",
    )
    args = p.parse_args(argv)
    if args.n_iter < 10:
        p.error("--n-iter must be >= 10")
    _common.setup_platform(args)
    return _common.run_guarded(run, args)


if __name__ == "__main__":
    sys.exit(main())
