"""Shared fail-fast helpers used by every layer (≅ cuda_error.h's CHECK).

Kept dependency-free so the array layer does not import the comm layer
(layer order in the package docstring: comm/ sits above arrays/).
"""

from __future__ import annotations


class TpuMtError(ValueError):
    """Base error for invalid configurations (fail-fast, SURVEY §5.3)."""


def check_divisible(n: int, by: int, what: str = "size") -> int:
    """Fail-fast divisibility precondition.

    The reference exits early when the global size does not divide evenly
    across ranks (``mpi_stencil_gt.cc:141-145``, ``mpi_daxpy.cc:43-48``); the
    framework raises instead so tests can assert on it.

    Returns ``n // by``.
    """
    if by <= 0:
        raise TpuMtError(f"{what}: divisor must be positive, got {by}")
    if n % by != 0:
        raise TpuMtError(f"{what}: {n} not evenly divisible by {by}")
    return n // by
