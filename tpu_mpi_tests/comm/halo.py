"""Halo exchange over a mesh axis: the framework's ring-communication core.

TPU-native replacement for the reference's nonblocking neighbor exchange
(``boundary_exchange`` ``mpi_stencil_gt.cc:83-122``, ``boundary_exchange_x/y``
``mpi_stencil2d_gt.cc:136-373``, SYCL variants): ``lax.ppermute`` shifts ±1
inside ``shard_map``, which XLA compiles to async ICI DMA — giving the
send/compute overlap the reference codes by hand with Irecv/Isend/Waitall.

This is deliberately the ring-attention-shaped primitive (SURVEY.md §5.7): a
1-D process ring exchanging edge blocks with neighbors ±1; sequence/context
parallelism reuses exactly this component.

Staging modes (SURVEY §7 hard part 3) keep the reference's benchmark matrix:

* ``DIRECT`` ≅ passing device view pointers straight to CUDA-aware MPI
  (``boundary_exchange_y`` unstaged path): plain ``ppermute`` on edge
  slices; XLA packs as needed.
* ``DEVICE_STAGED`` ≅ explicit pack into contiguous device buffers first
  (``boundary_exchange_x`` mandatory staging, ``stage_device`` option):
  pack kernels materialize the buffers (optimization_barrier pins them),
  then ``ppermute``.
* ``HOST_STAGED`` ≅ the non-GPU-aware-MPI fallback (``stage_host`` paths,
  ``mpi_stencil2d_gt.cc:148-156,167-174,236-249``): edge blocks take an
  explicit device→host→device round-trip outside the compiled program.
  Single-controller measurement mode (requires fully-addressable arrays).

Non-periodic boundaries follow the reference: edge ranks keep their
analytically-filled physical ghosts (``mpi_stencil_gt.cc:185-196``).
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_mpi_tests.compat import axis_size, shard_map
from tpu_mpi_tests.comm.topology import mesh_partner_links
from tpu_mpi_tests.instrument.telemetry import span_call
from tpu_mpi_tests.kernels.pack import pack_edges, unpack_ghosts
from tpu_mpi_tests.tune import priors as _priors
from tpu_mpi_tests.tune.registry import (
    declare_space,
    resolve as _tune_resolve,
)


class Staging(enum.Enum):
    DIRECT = "direct"
    DEVICE_STAGED = "device"
    HOST_STAGED = "host"
    PALLAS_RDMA = "pallas"
    #: resolve through the schedule cache (tuned winner for this
    #: topology/shape, else the DIRECT prior) — README "Autotuning"
    AUTO = "auto"

    @classmethod
    def parse(cls, s: "str | Staging") -> "Staging":
        if isinstance(s, Staging):
            return s
        try:
            return cls(s.lower())
        except ValueError:
            from tpu_mpi_tests.utils import TpuMtError

            raise TpuMtError(
                f"unknown staging mode {s!r}; valid: "
                f"{[m.value for m in cls]}"
            ) from None


#: the halo exchange schedule space: staging strategy AND exchange
#: flavor in one knob — direct/device ride ppermute, pallas is the
#: hand-written RDMA ring (HOST_STAGED is a measurement mode, never a
#: candidate). Declared here because the knob lives here.
HALO_STAGING_SPACE = declare_space(
    "halo/staging",
    (_priors.HALO_STAGING, "device", "pallas"),
    describe="halo staging strategy + ppermute-vs-RDMA exchange flavor",
)

#: resident-block schedule spaces for the k-step stencil hot loop
#: (``iterate_pallas_blocks_fn``): temporal block count (0 = dim-1
#: single-buffer schedule) and fused-timestep depth. Priors are the
#: BASELINE measured-best (f32; bench.py resolves the bf16 prior from
#: the same table).
STENCIL_BLOCKS_SPACE = declare_space(
    "stencil/blocks",
    (_priors.BENCH_BLOCKS["float32"], 0, 4),
    describe="resident row-block count per shard (0 = single buffer)",
)
STENCIL_STEPS_SPACE = declare_space(
    "stencil/steps",
    (_priors.BENCH_STEPS, 2, 8, 1),
    describe="temporal-blocking depth (timesteps fused per HBM pass)",
)

#: the kernel tier of the headline stencil iterate (ISSUE 15): which
#: per-iteration pipeline runs the exchange+update hot loop. "blocks" =
#: the ppermute hand tier (parameterized by stencil/blocks: 0 = dim-1
#: single buffer, S>=2 = resident blocks); "rdma-chained" = the
#: hand-written RDMA ring feeding the in-place kernel as two chained
#: launches (``iterate_pallas_fn(rdma=True)``); "rdma-fused" = the
#: one-launch fused halo+stencil kernel (in-kernel RDMA overlapped with
#: interior compute — :func:`iterate_fused_rdma_fn`); "xla" = the XLA
#: formulation. Declared here because every tier's runner lives here.
STENCIL_TIER_SPACE = declare_space(
    "stencil/tier",
    (_priors.STENCIL_TIER, "rdma-chained", "rdma-fused", "xla"),
    describe="kernel tier of the stencil iterate hot loop: ppermute "
             "blocks / chained RDMA / one-launch fused RDMA / XLA",
)

#: every value ``stencil/tier`` may resolve to (and the bench schedule
#: string may name) — shared by the resolvers and their malformed-cache
#: degrade paths
STENCIL_TIERS = ("blocks", "rdma-chained", "rdma-fused", "xla")


def resolve_stencil_tier(explicit=None, **ctx) -> str:
    """The kernel tier the stencil iterate should run: explicit >
    cached winner > shipped prior ("blocks" — the pre-ISSUE-15
    schedule). Context-sensitive (``device_fallback=False``): a tier
    won at one dtype/shape must not leak to another through the
    device-only slot. Malformed cache values degrade to the prior."""
    val = _tune_resolve(
        "stencil/tier", explicit=explicit, prior=_priors.STENCIL_TIER,
        device_fallback=False, **ctx,
    )
    return val if val in STENCIL_TIERS else _priors.STENCIL_TIER


#: the halo pipeline depth (ISSUE 7 tentpole a): 1 = today's serialized
#: exchange-then-update schedule (the prior, so untuned resolution is
#: byte-identical to the pre-overlap era); 2 = double-buffered — the
#: ghost exchange rides in flight while the interior/boundary-split
#: update computes its core (the reference's Irecv/compute/Waitall
#: pattern, host-scheduled; README "Overlap engine"). Deeper than 2
#: would need temporally-blocked ghosts — not a candidate here.
HALO_OVERLAP_SPACE = declare_space(
    "halo/overlap",
    (_priors.HALO_OVERLAP_DEPTH, 2),
    describe="halo pipeline depth: 1 = serialized, 2 = exchange in "
             "flight under the interior compute",
)


def resolve_overlap_depth(explicit=None, **ctx) -> int:
    """The halo pipeline depth to run: explicit > cached winner >
    shipped prior (1 — the serialized schedule). Context-sensitive
    (``device_fallback=False``): an overlap win measured at one
    shape/dtype must not leak to another through the device-only
    slot. Malformed cache values degrade to the prior."""
    val = _tune_resolve(
        "halo/overlap", explicit=explicit,
        prior=_priors.HALO_OVERLAP_DEPTH, device_fallback=False, **ctx,
    )
    try:
        depth = int(val)
    except (TypeError, ValueError):
        depth = _priors.HALO_OVERLAP_DEPTH
    return max(1, min(depth, 2))


def _staging_context(zg, axis: int, world: int) -> dict:
    """Cache context for the halo/staging knob: what moves the optimum
    — dtype, decomposed extent (bucketed), ring size. Shared by
    ``halo_exchange``'s AUTO resolution and the drivers' sweep sites so
    the stored winner and the lookup always compose the same key."""
    return {
        "dtype": str(np.dtype(zg.dtype)),
        "extent": int(zg.shape[axis]),
        "world": int(world),
    }


def resolve_staging(staging: "Staging | str", zg, axis: int,
                    world: int) -> Staging:
    """``Staging.AUTO`` → the tuned winner for this configuration (or
    the DIRECT prior); concrete modes pass through (explicit > cached >
    prior — the explicit arm is simply not asking for AUTO)."""
    staging = Staging.parse(staging)
    if staging is not Staging.AUTO:
        return staging
    from tpu_mpi_tests.utils import TpuMtError

    val = _tune_resolve(
        "halo/staging",
        prior=_priors.HALO_STAGING,
        # context-sensitive: a winner tuned at one extent/dtype/ring
        # size must not leak to another via the device-only slot
        device_fallback=False,
        **_staging_context(zg, axis, world),
    )
    try:
        resolved = Staging.parse(val)
    except TpuMtError:
        resolved = Staging.DIRECT  # malformed cache value → prior
    if resolved in (Staging.AUTO, Staging.HOST_STAGED):
        # AUTO can't resolve to itself, and HOST_STAGED is a measurement
        # mode a cache must never silently select
        resolved = Staging.DIRECT
    return resolved


def halo_payload_bytes(zg, axis: int, world: int, n_bnd: int,
                       periodic: bool) -> int:
    """Telemetry payload convention for one halo exchange: 2 directions ×
    one ghost band per neighbor pair (``world`` pairs on a periodic ring,
    ``world−1`` otherwise); band = ``n_bnd`` slabs of the non-decomposed
    extent. Shared by the per-call spans and the overlap engine's
    dispatch-window spans so both account the same bytes."""
    pairs = world if periodic else world - 1
    band_bytes = n_bnd * (zg.size // zg.shape[axis]) * zg.dtype.itemsize
    return 2 * pairs * band_bytes


def _ring_rotate(lo_edge, hi_edge, cur_lo, cur_hi, *, axis_name: str,
                 periodic: bool):
    """Rotate packed interior edges one step around the mesh-axis ring:
    hi edges travel right (my lo ghost receives the left neighbor's hi
    edge), lo edges travel left. Non-periodic edge ranks get their
    CURRENT physical ghosts (``cur_lo``/``cur_hi``) back, since the
    partial permutation leaves non-receivers with zeros. The subtle ring
    logic (partial permutation pairs, edge-rank masking) exists ONCE,
    shared by ``_receive_neighbors`` and the resident-block schedule."""
    n = axis_size(axis_name)
    pairs = n if periodic else n - 1
    fwd = [(i, (i + 1) % n) for i in range(pairs)]
    bwd = [((i + 1) % n, i) for i in range(pairs)]
    from_left = lax.ppermute(hi_edge, axis_name, fwd)
    from_right = lax.ppermute(lo_edge, axis_name, bwd)
    if not periodic:
        idx = lax.axis_index(axis_name)
        from_left = jnp.where(idx == 0, cur_lo, from_left)
        from_right = jnp.where(idx == n - 1, cur_hi, from_right)
    return from_left, from_right


def _receive_neighbors(
    z,
    *,
    axis_name: str,
    axis: int,
    n_bnd: int,
    periodic: bool,
    staged: bool = False,
):
    """Ring-receive half of the halo exchange: pack interior edges, rotate
    them ±1 (:func:`_ring_rotate`), and return ``(from_left, from_right)``
    — what belongs in this shard's ghost bands. Non-periodic edge ranks
    get their CURRENT (physical) ghosts back. Returns ``(None, None)`` on
    a 1-shard non-periodic ring, where nothing moves. Shared by
    ``exchange_shard`` and ``iterate_overlap_fn``."""
    n = axis_size(axis_name)
    lo_edge, hi_edge = pack_edges(z, axis=axis, n_bnd=n_bnd)
    if staged:
        # materialize contiguous staging buffers (≅ sbuf_l/sbuf_r device
        # buffers, mpi_stencil2d_gt.cc:141-145) — the barrier stops XLA from
        # fusing the pack into the transfer, mirroring the explicit copy
        lo_edge, hi_edge = lax.optimization_barrier((lo_edge, hi_edge))

    if n == 1:
        if periodic:
            return hi_edge, lo_edge
        return None, None

    cur_lo = lax.slice_in_dim(z, 0, n_bnd, axis=axis)
    cur_hi = lax.slice_in_dim(
        z, z.shape[axis] - n_bnd, z.shape[axis], axis=axis
    )
    return _ring_rotate(
        lo_edge, hi_edge, cur_lo, cur_hi,
        axis_name=axis_name, periodic=periodic,
    )


def exchange_shard(
    z,
    *,
    axis_name: str,
    axis: int = 0,
    n_bnd: int = 2,
    periodic: bool = False,
    staged: bool = False,
):
    """Per-shard halo exchange, for use *inside* ``shard_map``.

    ``z`` is one ghosted local block. Sends the interior edge slices to
    neighbors ±1 on the ring and writes received blocks into the ghost
    regions. On non-periodic edge ranks the existing (physical) ghosts are
    kept. Returns the updated block.
    """
    from_left, from_right = _receive_neighbors(
        z, axis_name=axis_name, axis=axis, n_bnd=n_bnd, periodic=periodic,
        staged=staged,
    )
    if from_left is None:  # 1-shard non-periodic: nothing moves
        return z
    return unpack_ghosts(z, from_left, from_right, axis=axis, n_bnd=n_bnd)


@functools.lru_cache(maxsize=None)
def _exchange_fn(
    mesh: Mesh,
    axis_name: str,
    axis: int,
    ndim: int,
    n_bnd: int,
    periodic: bool,
    staged: bool,
):
    spec = [None] * ndim
    spec[axis] = axis_name

    @functools.partial(jax.jit, donate_argnums=0)
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(*spec)
    )
    def exchange(z):
        return exchange_shard(
            z,
            axis_name=axis_name,
            axis=axis,
            n_bnd=n_bnd,
            periodic=periodic,
            staged=staged,
        )

    return exchange


@functools.lru_cache(maxsize=None)
def _exchange_pallas_fn(
    mesh: Mesh,
    axis_name: str,
    axis: int,
    ndim: int,
    n_bnd: int,
    periodic: bool,
    interpret: bool | None = None,
):
    """Hand-tuned exchange: explicit inter-chip RDMA instead of ppermute
    (≅ the reference's manual CUDA-aware-MPI staging path, SURVEY §5.8)."""
    from tpu_mpi_tests.kernels.pallas_kernels import ring_halo_pallas

    spec = [None] * ndim
    spec[axis] = axis_name

    @functools.partial(jax.jit, donate_argnums=0)
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(*spec),
        check_vma=False,
    )
    def exchange(z):
        # world=1 non-periodic still launches the kernel (no sends fire;
        # ghosts ride the aliases) so single-chip runs exercise the real path
        return ring_halo_pallas(
            z,
            axis_name=axis_name,
            axis=axis,
            n_bnd=n_bnd,
            periodic=periodic,
            interpret=interpret,
        )

    return exchange


def halo_exchange(
    zg,
    mesh: Mesh,
    axis_name: str | None = None,
    axis: int = 0,
    n_bnd: int = 2,
    periodic: bool = False,
    staging: Staging | str = Staging.DIRECT,
    interpret: bool | None = None,
    window=None,
):
    """Exchange halos of a ghosted-global sharded array (see arrays/domain.py
    for the layout: each shard holds its ghosted block along ``axis``).

    Functional and donated: returns the array with interior ghosts filled
    from neighbors; the input buffer may be reused by XLA
    (≅ in-place ghost updates of the reference).

    ``interpret`` applies to the PALLAS_RDMA tier only (bool, or a
    ``pltpu.InterpretParams`` for the simulated multi-device interpreter —
    the mode ``tests/test_ring_sync.py`` uses to execute the ring's
    barrier under race detection).

    ``window`` (a :class:`~tpu_mpi_tests.comm.collectives.DispatchWindow`)
    routes the DIRECT/DEVICE_STAGED dispatch through a bounded in-flight
    window instead of the per-call sync-honest span — the serve-mode
    chained-exchange path (README "Overlap engine"). ``window=None``
    (the default) is byte-identical to the pre-window behavior; the
    HOST_STAGED and PALLAS_RDMA tiers ignore the window (host staging is
    synchronous by construction, and a wedged RDMA ring must keep its
    per-call dispatch note adjacency).
    """
    axis_name = axis_name or mesh.axis_names[0]
    from tpu_mpi_tests.arrays.spaces import ensure_device

    zg = ensure_device(zg)
    world = mesh.shape[axis_name]
    staging = resolve_staging(staging, zg, axis, world)
    # telemetry payload: 2 directions × one ghost band per neighbor pair
    # (world pairs on a periodic ring, world−1 otherwise); band = n_bnd
    # slabs of the non-decomposed extent. Computed before the call — the
    # input is donated and its metadata may be gone afterwards.
    nbytes = halo_payload_bytes(zg, axis, world, n_bnd, periodic)
    # rank-pair traffic metadata (instrument/anatomy.py COMMGRAPH): each
    # rank sends one ghost band to each ring neighbor; ``partner_nbytes``
    # is the per-edge payload (total / 2·pairs directed edges), so the
    # reconstructed (src,dst) matrix sums back to ``nbytes`` and halo
    # symmetry — bytes(r→r+1) == bytes(r+1→r) — holds by construction.
    pairs = world if periodic else world - 1
    # link attribution (comm/topology.py): per-offset link classes,
    # resolved once per (mesh, axis) — {} on a flat topology, so flat
    # runs keep their span records byte-identical
    partner_meta = (
        {"partners": [-1, 1], "periodic": periodic,
         "partner_nbytes": nbytes // (2 * pairs),
         **mesh_partner_links(mesh, axis_name, (-1, 1), periodic)}
        if pairs > 0 else {}
    )
    if staging is Staging.HOST_STAGED:
        return span_call(
            "halo_exchange_host",
            _host_staged_exchange,
            zg, mesh, axis_name, axis, n_bnd, periodic,
            nbytes=nbytes, axis_name=axis_name, world=world,
            **partner_meta,
        )
    if staging is Staging.PALLAS_RDMA:
        # a wedged DMA semaphore / neighborhood barrier in the hand-written
        # ring is a silent hang; record the dispatch so the watchdog can
        # attribute it (instrument/watchdog.note_comm_op)
        from tpu_mpi_tests.instrument.watchdog import note_comm_op

        note_comm_op(
            f"ring_halo_pallas(axis={axis}, n_bnd={n_bnd}, "
            f"periodic={periodic}, world={world}, "
            f"shape={tuple(zg.shape)})"
        )
        return span_call(
            "halo_exchange_rdma",
            _exchange_pallas_fn(
                mesh, axis_name, axis, zg.ndim, n_bnd, periodic, interpret
            ),
            zg,
            nbytes=nbytes, axis_name=axis_name, world=world,
            **partner_meta,
        )
    fn = _exchange_fn(
        mesh,
        axis_name,
        axis,
        zg.ndim,
        n_bnd,
        periodic,
        staging is Staging.DEVICE_STAGED,
    )
    if window is not None:
        return window.call(
            "halo_exchange", fn, zg,
            nbytes=nbytes, axis_name=axis_name, world=world,
            staging=staging.value, **partner_meta,
        )
    return span_call(
        "halo_exchange",
        fn,
        zg,
        nbytes=nbytes, axis_name=axis_name, world=world,
        staging=staging.value, **partner_meta,
    )


@functools.partial(
    jax.jit, static_argnames=("starts", "axis"), donate_argnums=0
)
def _apply_ghost_bands(zg, bands, starts, axis):
    """Write host-staged ghost bands back into the device array — the
    ONLY device writes of the host-staged path, each O(n_bnd·W).

    Starts are pinned to int32: under x64 a Python-int start lowers to an
    s64 constant that older XLA's update-slice clamp compares against an
    s32 bound (hlo verifier rejection)."""
    for i, s in enumerate(starts):
        zg = lax.dynamic_update_slice_in_dim(
            zg, bands[i], np.int32(s), axis=axis
        )
    return zg


def _host_staged_exchange(zg, mesh, axis_name, axis, n_bnd, periodic):
    """Edge bands round-trip through host memory (≅ stage_host paths).

    Deliberately unfused and synchronous — this mode exists to measure the
    cost of losing device-direct communication, like the reference's
    non-GPU-aware-MPI fallback. Like the reference, ONLY the halo bands
    ever touch the host (``sbuf``/``rbuf`` staging buffers,
    ``mpi_stencil2d_gt.cc:167-174,236-249``): 2 edge slices per shard come
    down (``device_get``), the ring swap happens on host, and 2 ghost
    bands per shard go back up — O(n_bnd·W) host traffic per call, not
    O(H·W).
    """
    if isinstance(zg, jax.Array) and not zg.is_fully_addressable:
        raise ValueError(
            "HOST_STAGED exchange requires fully-addressable arrays "
            "(single-controller measurement mode); use DIRECT/DEVICE_STAGED "
            "on multi-host meshes"
        )
    n_shards = mesh.shape[axis_name]
    from tpu_mpi_tests.utils import check_divisible

    ng = check_divisible(
        zg.shape[axis], n_shards, "host-staged ghosted extent"
    )
    K = n_bnd

    # pull ONLY the interior edge bands down (the send-side staging copy)
    def edge(start):
        return jax.device_get(
            lax.slice_in_dim(zg, start, start + K, axis=axis)
        )

    lo_edges = [edge(r * ng + K) for r in range(n_shards)]
    hi_edges = [edge(r * ng + ng - 2 * K) for r in range(n_shards)]

    # host-side ring swap, then push ONLY the ghost bands back
    starts, bands = [], []
    for r in range(n_shards):
        if periodic or r > 0:  # lo ghost ← left neighbor's hi edge
            starts.append(r * ng)
            bands.append(hi_edges[(r - 1) % n_shards])
        if periodic or r < n_shards - 1:  # hi ghost ← right's lo edge
            starts.append(r * ng + ng - K)
            bands.append(lo_edges[(r + 1) % n_shards])
    if not starts:
        return zg
    return _apply_ghost_bands(
        zg, jnp.asarray(np.stack(bands)), tuple(starts), axis
    )


@functools.lru_cache(maxsize=None)
def stencil_fn(
    mesh: Mesh,
    axis_name: str,
    axis: int,
    ndim: int,
    scale: float,
    kernel: str = "xla",
):
    """Per-shard stencil application over the ghosted-global layout:
    each shard's ghosted block yields its interior derivative
    (out shard size = in shard size − 2·n_bnd along ``axis``).

    ``kernel="pallas"`` swaps in the hand-written strip-tiled kernel
    (≅ running the SYCL implementation of the same benchmark,
    ``mpi_stencil2d_sycl.cc``)."""
    from tpu_mpi_tests.kernels.stencil import stencil1d_5

    spec = [None] * ndim
    spec[axis] = axis_name

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(*spec),
        # pallas_call outputs carry no vma annotation
        check_vma=False,
    )
    def apply(z):
        if kernel == "pallas":
            from tpu_mpi_tests.kernels.pallas_kernels import stencil2d_pallas

            return stencil2d_pallas(z, scale, dim=axis)
        return stencil1d_5(z, scale=scale, axis=axis)

    return apply


@functools.lru_cache(maxsize=None)
def iterate_fused_fn(
    mesh: Mesh,
    axis_name: str,
    axis: int,
    ndim: int,
    n_bnd: int,
    scale: float,
    eps: float = 1e-6,
    staged: bool = False,
    split: bool = False,
    periodic: bool = False,
):
    """``n_iter`` fused exchange+stencil+update steps in ONE device-side loop.

    The reference's hot loop (``mpi_stencil2d_gt.cc:511-535``) dispatches one
    exchange + stencil per host iteration and syncs each time; over a
    high-latency controller link (the axon TPU tunnel has a ~106 ms host
    round-trip and a ``block_until_ready`` that does not wait) that measures
    the link, not the device. The honest TPU form is a ``lax.fori_loop``
    carrying the array: each iteration halo-exchanges, takes the stencil
    derivative, and writes ``interior += eps·dz`` back (a bounded Jacobi-like
    update that makes every iteration data-dependent on the last, so XLA can
    neither hoist nor skip work). Time N iterations with ONE sync at the end;
    difference two run lengths to cancel the fixed round-trip.

    ``n_iter`` is a dynamic (traced) operand — one compilation serves every
    iteration count.

    ``split=True`` places an ``optimization_barrier`` between the exchange
    and the stencil, forbidding XLA from fusing them — the split side of the
    split-vs-fused A/B (SURVEY §7 hard part 2), measured in-device where
    per-dispatch timing would drown in controller jitter. ``periodic=True``
    makes the exchange a real self-ring on a single chip (otherwise world=1
    exchanges are no-ops and the A/B measures nothing).
    """
    from tpu_mpi_tests.kernels.stencil import stencil1d_5

    spec = [None] * ndim
    spec[axis] = axis_name

    @functools.partial(jax.jit, donate_argnums=0)
    def run(z, n_iter):
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(*spec), P()),
            out_specs=P(*spec),
            check_vma=False,
        )
        def go(z, n):
            def body(_, zz):
                zz = exchange_shard(
                    zz,
                    axis_name=axis_name,
                    axis=axis,
                    n_bnd=n_bnd,
                    periodic=periodic,
                    staged=staged,
                )
                if split:
                    zz = lax.optimization_barrier(zz)
                dz = stencil1d_5(zz, scale=scale, axis=axis)
                new_int = (
                    lax.slice_in_dim(
                        zz, n_bnd, zz.shape[axis] - n_bnd, axis=axis
                    )
                    + eps * dz
                )
                return lax.dynamic_update_slice_in_dim(
                    zz, new_int, n_bnd, axis=axis
                )

            return lax.fori_loop(0, n[0], body, z)

        return go(z, jnp.asarray([n_iter], jnp.int32))

    return run


@functools.lru_cache(maxsize=None)
def iterate_pallas_fn(
    mesh: Mesh,
    axis_name: str,
    n_bnd: int,
    scale_eps: float,
    axis: int = 1,
    interpret: bool | None = None,
    steps: int = 1,
    periodic: bool = False,
    rdma: bool = False,
    stream: bool | None = None,
    tile: int = 64,
):
    """Like :func:`iterate_fused_fn` but with the hand-written in-place
    Pallas step (2 HBM passes/iter vs XLA's ~6). ``axis=1`` (default) puts
    the stencil on the lane dimension where VMEM shifts are register-cheap —
    the bench.py fast path (~1210 iter/s per-step at 8192² f32 on v5e vs
    ~260 for the XLA formulation; 2000–2180 with ``steps=4`` temporal
    blocking — BASELINE.md); ``axis=0`` runs the same
    2-pass in-place step on a dim-0 (sublane-shift) decomposition.

    ``steps=k`` enables communication-avoiding temporal blocking: the array
    must carry deep ghosts (``n_bnd = k · stencil radius``), exchanged once
    per k timesteps, and the Pallas kernel advances k steps per HBM pass —
    the interior sequence is identical to per-step exchange (tested), HBM
    traffic per timestep drops toward 2/k passes, and the exchange message
    count drops k-fold at the same total volume. ``n_iter`` then counts
    OUTER loop bodies (= n_iter·k timesteps).

    ``rdma=True`` swaps the ppermute exchange for the hand-written RDMA
    ring (``ring_halo_pallas``), making the whole hot loop 100% hand-tier
    — explicit inter-chip DMA feeding the in-place VMEM kernel, the
    reference's fully-manual pipeline (``mpi_stencil2d_sycl.cc``) chained
    device-side.

    ``stream`` forwards the dim-0 row-streaming selector of
    :func:`~tpu_mpi_tests.kernels.pallas_kernels.stencil2d_iterate_pallas`
    (None = auto: stream only when the full ghosted height exceeds VMEM)."""
    from tpu_mpi_tests.kernels.pallas_kernels import (
        ring_halo_pallas,
        stencil2d_iterate_pallas,
    )
    from tpu_mpi_tests.kernels.stencil import N_BND as RADIUS
    from tpu_mpi_tests.utils import TpuMtError

    if n_bnd != steps * RADIUS:
        raise TpuMtError(
            f"iterate_pallas_fn: ghost width n_bnd={n_bnd} must equal "
            f"steps({steps}) x stencil radius({RADIUS}) — deep halos carry "
            f"one radius per fused timestep"
        )

    spec = (axis_name, None) if axis == 0 else (None, axis_name)

    @functools.partial(jax.jit, donate_argnums=0)
    def run(z, n_iter):
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(*spec), P()),
            out_specs=P(*spec),
            check_vma=False,
        )
        def go(z, n):
            world = mesh.shape[axis_name]  # static at trace time
            # static flags compile to static update spans (no per-element
            # mask): every shard of a periodic ring, and the only shard of
            # a world=1 mesh (both sides physical) — the bench fast path
            if periodic:
                phys_kw = {"phys_static": (0, 0)}
            elif world == 1:
                phys_kw = {"phys_static": (1, 1)}
            else:
                idx = lax.axis_index(axis_name)
                phys_kw = {
                    "phys": jnp.stack(
                        [
                            (idx == 0).astype(jnp.int32),
                            (idx == world - 1).astype(jnp.int32),
                        ]
                    )
                }

            exch = (
                functools.partial(ring_halo_pallas, interpret=interpret)
                if rdma
                else exchange_shard
            )

            def body(_, zz):
                zz = exch(
                    zz,
                    axis_name=axis_name,
                    axis=axis,
                    n_bnd=n_bnd,
                    periodic=periodic,
                )
                return stencil2d_iterate_pallas(
                    zz,
                    scale_eps,
                    dim=axis,
                    interpret=interpret,
                    steps=steps,
                    stream=stream,
                    tile=tile,
                    **phys_kw,
                )

            return lax.fori_loop(0, n[0], body, z)

        return go(z, jnp.asarray([n_iter], jnp.int32))

    if not rdma:
        return run

    def run_attributed(z, n_iter):
        # a wedged DMA semaphore / neighborhood barrier in the hand ring
        # is a silent hang; record the dispatch so the watchdog can
        # attribute it (parity with halo_exchange's PALLAS_RDMA path)
        from tpu_mpi_tests.instrument.watchdog import note_comm_op

        note_comm_op(
            f"iterate_pallas_fn(rdma=True, axis={axis}, n_bnd={n_bnd}, "
            f"periodic={periodic}, steps={steps}, "
            f"world={mesh.shape[axis_name]}, n_iter={n_iter})"
        )
        return run(z, n_iter)

    return run_attributed


@functools.lru_cache(maxsize=None)
def iterate_fused_rdma_fn(
    mesh: Mesh,
    axis_name: str,
    n_bnd: int,
    scale_eps: float,
    axis: int = 0,
    interpret: bool | None = None,
    steps: int = 1,
    periodic: bool = False,
    tile_rows: int | None = None,
    local_only: bool = False,
    unsafe_no_seam_wait: bool = False,
):
    """The ONE-launch fused tier (ISSUE 15): like
    :func:`iterate_pallas_fn(rdma=True) <iterate_pallas_fn>` but each
    iteration is a single ``pl.pallas_call``
    (:func:`~tpu_mpi_tests.kernels.pallas_kernels.stencil2d_fused_rdma_pallas`)
    that starts the edge-band RDMA, streams the interior row blocks
    while the DMA flies, then waits the recv semaphores and finishes the
    seam blocks — the reference's fully-manual overlapped pipeline
    (``mpi_stencil2d_sycl.cc``) in one device-side schedule, with no
    ghost-byte HBM round-trip between an exchange kernel and a compute
    kernel.

    Dim-0 (row-streaming) decomposition only — the fused schedule IS a
    row-block stream. ``steps=k`` deep-ghost temporal blocking is
    preserved (``n_bnd = k · radius``, exchanged once per k timesteps).
    A 1-shard non-periodic ring degenerates to the pure compute pass
    (``local_only`` — no barrier, no sends); interiors are
    bitwise-identical to the chained tier (tests/test_pallas.py).

    ``local_only=True`` forces the compute-only twin on ANY ring — the
    host-bracketed baseline :func:`fused_overlap_record` prices the
    seam wait against (its ghosts are treated as fixed bands, so its
    VALUES are only meaningful on a genuinely 1-shard ring; as a timing
    baseline the schedule is what matters). ``unsafe_no_seam_wait``
    forwards the race-detector negative control."""
    from tpu_mpi_tests.kernels.pallas_kernels import (
        stencil2d_fused_rdma_pallas,
    )
    from tpu_mpi_tests.kernels.stencil import N_BND as RADIUS
    from tpu_mpi_tests.utils import TpuMtError

    if axis != 0:
        raise TpuMtError(
            "iterate_fused_rdma_fn: the fused tier streams row blocks — "
            "dim-0 decomposition only (decompose the other way or use "
            "iterate_pallas_fn)"
        )
    if n_bnd != steps * RADIUS:
        raise TpuMtError(
            f"iterate_fused_rdma_fn: ghost width n_bnd={n_bnd} must equal "
            f"steps({steps}) x stencil radius({RADIUS}) — deep halos "
            f"carry one radius per fused timestep"
        )

    world = mesh.shape[axis_name]
    pure_compute = local_only or (world == 1 and not periodic)
    spec = (axis_name, None)

    @functools.partial(jax.jit, donate_argnums=0)
    def run(z, n_iter):
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(*spec), P()),
            out_specs=P(*spec),
            check_vma=False,
        )
        def go(z, n):
            if periodic:
                phys_kw = {"phys_static": (0, 0)}
            elif world == 1:
                phys_kw = {"phys_static": (1, 1)}
            else:
                idx = lax.axis_index(axis_name)
                phys_kw = {
                    "phys": jnp.stack(
                        [
                            (idx == 0).astype(jnp.int32),
                            (idx == world - 1).astype(jnp.int32),
                        ]
                    )
                }

            def body(_, zz):
                return stencil2d_fused_rdma_pallas(
                    zz,
                    scale_eps,
                    axis_name=axis_name,
                    steps=steps,
                    periodic=periodic,
                    interpret=interpret,
                    tile_rows=tile_rows,
                    local_only=pure_compute,
                    unsafe_no_seam_wait=unsafe_no_seam_wait,
                    **phys_kw,
                )

            return lax.fori_loop(0, n[0], body, z)

        return go(z, jnp.asarray([n_iter], jnp.int32))

    if pure_compute:
        return run

    def run_attributed(z, n_iter):
        # a wedged DMA semaphore / neighborhood barrier in the fused
        # ring is a silent hang; record the dispatch so the watchdog can
        # attribute it (parity with the other RDMA tiers)
        from tpu_mpi_tests.instrument.watchdog import note_comm_op

        note_comm_op(
            f"iterate_fused_rdma_fn(n_bnd={n_bnd}, periodic={periodic}, "
            f"steps={steps}, world={world}, n_iter={n_iter})"
        )
        return run(z, n_iter)

    return run_attributed


def fused_overlap_record(op: str, *, steps: int, fused_s: float,
                         compute_s: float, world: int, **extra) -> dict:
    """The fused tier's kernel-level ``kind: "overlap"`` record (ISSUE
    15): ``fused_s`` is the host-bracketed per-window wall time of the
    one-launch fused runner, ``compute_s`` that of its compute-only twin
    (``iterate_fused_rdma_fn(local_only=True)`` — same kernel, same
    geometry, communication compiled out). Their difference is the
    SEAM-WAIT cost: barrier + sends + recv waits + whatever ghost
    arrival the interior stream failed to hide; ``overlap_frac`` =
    1 − seam_wait/total, so a fully-hidden exchange reads ≈ 1 and a
    serialized one reads the comm/total complement — feeding the
    existing OVERLAP table and ``--diff`` frac gate. ``drain_s`` carries
    the measured seam wait, mirroring the PR-7 convention (the genuinely
    measured hiding signal)."""
    seam_wait = max(0.0, float(fused_s) - float(compute_s))
    frac = (1.0 - seam_wait / fused_s) if fused_s > 0 else 0.0
    return {
        "kind": "overlap",
        "op": op,
        "depth": 2,
        "steps": steps,
        "overlap_frac": frac,
        "comm_s": float(fused_s),
        "compute_s": float(compute_s),
        "drain_s": seam_wait,
        "world": world,
        "tier": "rdma-fused",
        **extra,
    }


def iterate_pallas_blocks_fn(
    n_blocks: int,
    n_bnd: int,
    scale_eps: float,
    steps: int = 1,
    tile: int = 512,
    interpret: bool | None = None,
    mesh: Mesh | None = None,
    axis_name: str | None = None,
    periodic: bool = False,
):
    """k-step iterate over ``n_blocks`` RESIDENT row blocks per shard —
    the deep-halo schedule with the fast full-height dim-0 kernel, run
    either on one chip (``mesh=None``) or sharded over an N-device mesh
    axis (``mesh`` given): each device holds its S resident blocks,
    intra-shard ghost refresh is a narrow in-chip band copy, and the two
    OUTERMOST ghost bands per shard (block 0's top, block S−1's bottom)
    ride a ``ppermute`` ring to neighbor shards — the same per-k-group
    exchange ``iterate_pallas_fn`` does, priced over ICI.

    Rationale (measured on v5e, BASELINE.md): the dim-0 (sublane-tap)
    k-step kernel runs fastest when the full ghosted block height fits
    VMEM strips, but an 8192-tall domain exceeds that height. Splitting
    the domain into S separate buffers restores the fast full-height path
    per block, and the inter-block "exchange" is a narrow-band buffer
    update. S=2 measured 3021 iter/s at 8192² f32 k=4 vs 2087 for the
    single-buffer dim-1 kernel in the same contention window (1.45×);
    S≥4 loses to per-call launch overhead (~100 µs × S per k-group).

    Boundary flags: on a non-periodic multi-shard ring only the global
    first/last block is physical, which depends on the traced shard index
    — block 0 and block S−1 take the kernel's dynamic ``phys`` flags
    (SMEM-driven masks) while interior blocks keep the static fast path;
    world=1 (or ``mesh=None``) compiles fully static flags.

    Returns ``run(state, n_iter)`` where ``state`` is a tuple of
    ``n_blocks`` arrays, each ``(H_b + 2·n_bnd, W)`` per shard with
    ``n_bnd = steps·radius`` deep ghosts along dim 0 (use
    :func:`split_blocks` / :func:`merge_blocks`, which accept ``mesh``
    for the sharded layout). Interior semantics are identical to the
    per-step-exchange schedule (same argument as
    ``iterate_pallas_fn(steps=k)``; gated by test and dryrun check)."""
    from tpu_mpi_tests.kernels.pallas_kernels import (
        stencil2d_iterate_pallas,
    )
    from tpu_mpi_tests.kernels.stencil import N_BND as RADIUS
    from tpu_mpi_tests.utils import TpuMtError

    if n_bnd != steps * RADIUS:
        raise TpuMtError(
            f"iterate_pallas_blocks_fn: ghost width n_bnd={n_bnd} must "
            f"equal steps({steps}) x stencil radius({RADIUS})"
        )
    if n_blocks < 2:
        raise TpuMtError(
            f"iterate_pallas_blocks_fn: n_blocks={n_blocks} < 2 — use "
            f"iterate_pallas_fn for the single-buffer schedule"
        )
    S, K = n_blocks, n_bnd
    world = 1 if mesh is None else mesh.shape[
        axis_name or mesh.axis_names[0]
    ]
    if mesh is not None:
        axis_name = axis_name or mesh.axis_names[0]

    def body(_, st):
        blocks = list(st)
        hb = blocks[0].shape[0] - 2 * K
        # ghost sources, all read from the PRE-update blocks so the
        # refresh order cannot matter (≅ post-recvs-before-sends,
        # mpi_stencil_gt.cc:96-107)
        top_src = [None] * S
        bot_src = [None] * S
        for s in range(1, S):  # top ghost ← upper neighbor's last interior
            top_src[s] = blocks[s - 1][hb:hb + K]
        for s in range(S - 1):  # bottom ghost ← lower neighbor's first
            bot_src[s] = blocks[s + 1][K:2 * K]
        if world > 1:
            # outermost bands ride the inter-shard ring: shard r's top
            # ghost ← shard r−1's LAST interior (its block S−1), bottom
            # ghost ← shard r+1's FIRST interior (its block 0); edge
            # shards keep their analytic physical ghosts
            top_src[0], bot_src[S - 1] = _ring_rotate(
                blocks[0][K:2 * K],              # lo edge of the shard
                blocks[S - 1][hb:hb + K],        # hi edge of the shard
                blocks[0][0:K],                  # current physical lo ghost
                blocks[S - 1][hb + K:hb + 2 * K],  # current physical hi
                axis_name=axis_name, periodic=periodic,
            )
        elif periodic:  # world=1 self-ring: wrap across the block tuple
            top_src[0] = blocks[S - 1][hb:hb + K]
            bot_src[S - 1] = blocks[0][K:2 * K]

        def phys_kwargs(s):
            if periodic:
                return {"phys_static": (0, 0)}
            if world == 1:
                return {"phys_static": (1 if s == 0 else 0,
                                        1 if s == S - 1 else 0)}
            # multi-shard: only the global first/last block is physical —
            # a traced-index condition, so edge blocks use dynamic flags
            idx = lax.axis_index(axis_name)
            zero = jnp.zeros((), jnp.int32)
            if s == 0:
                return {"phys": jnp.stack(
                    [(idx == 0).astype(jnp.int32), zero])}
            if s == S - 1:
                return {"phys": jnp.stack(
                    [zero, (idx == world - 1).astype(jnp.int32)])}
            return {"phys_static": (0, 0)}

        out = []
        for s in range(S):
            b = blocks[s]
            if top_src[s] is not None:
                b = b.at[0:K].set(top_src[s])
            if bot_src[s] is not None:
                b = b.at[hb + K:hb + 2 * K].set(bot_src[s])
            out.append(
                stencil2d_iterate_pallas(
                    b, scale_eps, dim=0, steps=steps, tile=tile,
                    interpret=interpret, **phys_kwargs(s),
                )
            )
        return tuple(out)

    if mesh is None:

        @functools.partial(jax.jit, donate_argnums=0)
        def run(state, n_iter):
            return lax.fori_loop(0, n_iter[0], body, state)

    else:
        spec = P(axis_name, None)
        state_specs = tuple(spec for _ in range(S))

        @functools.partial(jax.jit, donate_argnums=0)
        def run(state, n_iter):
            @functools.partial(
                shard_map,
                mesh=mesh,
                in_specs=(state_specs, P()),
                out_specs=state_specs,
                check_vma=False,
            )
            def go(st, n):
                return lax.fori_loop(0, n[0], body, tuple(st))

            return go(state, n_iter)

    return lambda st, n: run(tuple(st), jnp.asarray([n], jnp.int32))


def split_blocks(z, n_blocks: int, n_bnd: int, mesh: Mesh | None = None,
                 axis_name: str | None = None):
    """Split a dim-0-ghosted domain ``(H + 2K, W)`` into ``n_blocks``
    resident blocks of ``(H/S + 2K, W)`` with overlapping ghost bands
    (the inverse of :func:`merge_blocks`).

    With ``mesh``, ``z`` is the ghosted-GLOBAL sharded array (each shard
    holds its ghosted block along dim 0, arrays/domain.py layout) and the
    split happens per shard: result ``s`` is a global array whose shard-r
    piece is shard r's s-th resident block."""
    from tpu_mpi_tests.utils import check_divisible

    K = n_bnd

    def local_split(zl):
        H = zl.shape[0] - 2 * K
        hb = check_divisible(H, n_blocks, "split_blocks interior rows")
        return tuple(
            zl[s * hb:s * hb + hb + 2 * K] for s in range(n_blocks)
        )

    if mesh is None:
        return local_split(z)
    return _split_blocks_fn(
        mesh, axis_name or mesh.axis_names[0], n_blocks, n_bnd
    )(z)


@functools.lru_cache(maxsize=None)
def _split_blocks_fn(mesh: Mesh, axis_name: str, n_blocks: int, n_bnd: int):
    spec = P(axis_name, None)
    return jax.jit(
        shard_map(
            lambda z: split_blocks(z, n_blocks, n_bnd),
            mesh=mesh, in_specs=spec,
            out_specs=tuple(spec for _ in range(n_blocks)),
        )
    )


def merge_blocks(state, n_bnd: int, mesh: Mesh | None = None,
                 axis_name: str | None = None):
    """Reassemble :func:`split_blocks` blocks into the whole ghosted
    domain (interiors concatenated, outermost ghost bands kept).
    With ``mesh``, inverts the sharded split (per-shard reassembly)."""
    K = n_bnd

    def local_merge(st):
        if len(st) == 1:
            return st[0]
        hb = st[0].shape[0] - 2 * K
        parts = [st[0][:K + hb]]
        parts += [b[K:K + hb] for b in st[1:-1]]
        parts.append(st[-1][K:])
        return jnp.concatenate(parts, axis=0)

    if mesh is None:
        return local_merge(tuple(state))
    return _merge_blocks_fn(
        mesh, axis_name or mesh.axis_names[0], len(state), n_bnd
    )(tuple(state))


@functools.lru_cache(maxsize=None)
def _merge_blocks_fn(mesh: Mesh, axis_name: str, n_blocks: int, n_bnd: int):
    spec = P(axis_name, None)
    return jax.jit(
        shard_map(
            lambda st: merge_blocks(st, n_bnd),
            mesh=mesh,
            in_specs=(tuple(spec for _ in range(n_blocks)),),
            out_specs=spec,
        )
    )


@functools.lru_cache(maxsize=None)
def step2d_fn(
    mesh: Mesh,
    axis_x: str,
    axis_y: str,
    n_bnd: int,
    scale_x: float,
    scale_y: float,
    kernel: str = "xla",
    interpret: bool | None = None,
):
    """Full 2-D-decomposed step over a 2-D mesh — the framework's "training
    step" analog: halo exchange along BOTH decomposed axes, stencil
    derivative in each dim, and a global residual ``psum`` over the whole
    mesh. This is the reference's complete per-iteration pipeline
    (``boundary_exchange_x`` + ``boundary_exchange_y`` +
    ``stencil2d_1d_5_d0/_d1`` + ``MPI_Allreduce``,
    ``mpi_stencil2d_gt.cc:136-373,84-110,615-625``) generalized to a 2-D
    process grid, compiled as ONE program so XLA overlaps the ppermute DMA
    with interior compute.

    The input is ghosted along both axes and sharded ``P(axis_x, axis_y)``;
    returns ``(dz_dx, dz_dy, residual)`` with the derivatives sharded the
    same way and the residual replicated.

    ``kernel="pallas"`` computes the per-shard pipeline with
    :func:`~tpu_mpi_tests.kernels.pallas_kernels.dual_dim_step_pallas`
    (both derivatives + residual partials from one streamed window read,
    vs the XLA tier's per-tap re-reads).
    """
    from tpu_mpi_tests.kernels.stencil import dual_dim_step

    if kernel not in ("xla", "pallas"):
        raise ValueError(f"step2d_fn: unknown kernel {kernel!r}")

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis_x, axis_y),
        out_specs=(P(axis_x, axis_y), P(axis_x, axis_y), P()),
        check_vma=False,
    )
    def step(z):
        z = exchange_shard(z, axis_name=axis_x, axis=0, n_bnd=n_bnd)
        z = exchange_shard(z, axis_name=axis_y, axis=1, n_bnd=n_bnd)
        if kernel == "pallas":
            from tpu_mpi_tests.kernels.pallas_kernels import (
                dual_dim_step_pallas,
            )

            dz_dx, dz_dy, residual = dual_dim_step_pallas(
                z, n_bnd, scale_x, scale_y, interpret=interpret
            )
        else:
            dz_dx, dz_dy, residual = dual_dim_step(
                z, n_bnd, scale_x, scale_y
            )
        return dz_dx, dz_dy, lax.psum(residual, (axis_x, axis_y))

    return step


@functools.lru_cache(maxsize=None)
def iterate_overlap_fn(
    mesh: Mesh,
    axis_name: str,
    n_bnd: int,
    scale_eps: float,
    axis: int = 1,
    interpret: bool | None = None,
    periodic: bool = False,
):
    """Per-step iterate with explicit communication/compute OVERLAP — the
    reference's hand pattern (post ``MPI_Irecv``/``Isend``, compute the
    interior, ``MPI_Waitall``, then fill boundary cells;
    ``mpi_stencil2d_gt.cc:136-255`` + stencil :529) expressed in XLA
    scheduling terms:

    1. edge slices start their ``ppermute`` flights;
    2. the core region — every cell whose stencil touches no fresh ghost —
       is updated by the in-place Pallas kernel, DEPENDING ONLY on old
       data, so XLA's latency-hiding scheduler runs it between
       collective-permute-start and -done;
    3. the two boundary strips are patched with the arrived ghosts;
    4. reassembly preserves the exchanged-ghost layout exactly like
       ``exchange_shard`` + ``stencil2d_iterate_pallas``.

    Semantically identical to the sequential form (tested). **Measured
    result: on TPU this transcription LOSES** — 1897 µs/iter vs the
    sequential form's 947 at 8192² f32 on a periodic self-ring (v5e). The
    merge step is the killer: the arrived ghosts and strips are narrow
    lane bands (2 wide) that Mosaic DMA cannot scatter in place
    (tile-alignment), so XLA merges them with a full-array copy — one
    extra HBM pass that outweighs the ~228 µs exchange it hides. The
    sequential form's exchange-writes-then-aliased-kernel chain is already
    optimal on this hardware; the reference's Irecv/compute/Waitall
    overlap is a GPU+MPI idiom that does not transfer. Kept (with its
    equivalence tests) as the measured A/B documenting exactly that.
    """
    from tpu_mpi_tests.kernels.pallas_kernels import stencil2d_iterate_pallas
    from tpu_mpi_tests.kernels.stencil import N_BND as RADIUS, stencil1d_5
    from tpu_mpi_tests.utils import TpuMtError

    if n_bnd != RADIUS:
        raise TpuMtError(
            f"iterate_overlap_fn: n_bnd={n_bnd} must equal the stencil "
            f"radius ({RADIUS}) — strip windows are 3·radius wide"
        )

    spec = (axis_name, None) if axis == 0 else (None, axis_name)

    def strip_update(window):
        """Update the middle ``n_bnd`` cells of a ``3·n_bnd``-wide window."""
        dz = stencil1d_5(window, scale=1.0, axis=axis)
        mid = lax.slice_in_dim(window, n_bnd, 2 * n_bnd, axis=axis)
        return mid + jnp.asarray(scale_eps, window.dtype) * dz

    @functools.partial(jax.jit, donate_argnums=0)
    def run(z, n_iter):
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(*spec), P()),
            out_specs=P(*spec),
            check_vma=False,
        )
        def go(z, n):
            def body(_, zz):
                N = zz.shape[axis]
                from_left, from_right = _receive_neighbors(
                    zz, axis_name=axis_name, axis=axis, n_bnd=n_bnd,
                    periodic=periodic,
                )
                if from_left is None:  # 1-shard non-periodic ring
                    from_left = lax.slice_in_dim(zz, 0, n_bnd, axis=axis)
                    from_right = lax.slice_in_dim(
                        zz, N - n_bnd, N, axis=axis
                    )

                # small old-value windows the strips need, sliced out
                # before the in-place kernel consumes the buffer
                lo_win = lax.slice_in_dim(zz, n_bnd, 3 * n_bnd, axis=axis)
                hi_win = lax.slice_in_dim(
                    zz, N - 3 * n_bnd, N - n_bnd, axis=axis
                )

                # core: the full in-place step depends only on OLD data
                # (its ghost reads are stale), so it runs while the edge
                # ppermutes fly; the 2·n_bnd boundary strips it computes
                # with stale ghosts are overwritten below — wasted work
                # O(n_bnd/N), far cheaper than slicing the core out (a
                # lane-offset slice of the whole array costs full extra
                # HBM passes: measured 4204 vs 947 µs/iter, 4.4× slower)
                out = stencil2d_iterate_pallas(
                    zz, scale_eps, dim=axis, interpret=interpret
                )

                # patch: arrived ghosts + correctly-computed strips, as
                # small in-place updates on the kernel's aliased buffer
                lo_strip = strip_update(
                    jnp.concatenate([from_left, lo_win], axis=axis)
                )
                hi_strip = strip_update(
                    jnp.concatenate([hi_win, from_right], axis=axis)
                )
                out = unpack_ghosts(
                    out, from_left.astype(out.dtype),
                    from_right.astype(out.dtype), axis=axis, n_bnd=n_bnd,
                )
                for patch, pos in (
                    (lo_strip, n_bnd),
                    (hi_strip, N - 2 * n_bnd),
                ):
                    out = lax.dynamic_update_slice_in_dim(
                        out, patch.astype(out.dtype), pos, axis=axis
                    )
                return out

            return lax.fori_loop(0, n[0], body, z)

        return go(z, jnp.asarray([n_iter], jnp.int32))

    return run


@functools.lru_cache(maxsize=None)
def heat_step2d_fn(
    mesh: Mesh,
    axis_x: str,
    axis_y: str,
    n_bnd: int,
    cx: float,
    cy: float,
    steps: int = 1,
    kernel: str = "xla",
    interpret: bool | None = None,
):
    """``n_steps`` outer bodies of explicit-Euler heat-equation integration
    on a periodic 2-D process grid, chained device-side: per body, halo
    exchange along both mesh axes then ``steps`` updates of
    ``interior += cx·δ²x + cy·δ²y`` (the 5-point discrete Laplacian;
    ``c = ν·dt/Δ²``). Shape-preserving and donated, so the time loop is one
    ``lax.fori_loop`` — the mini-app analog of the reference's hot loop
    (``mpi_stencil2d_gt.cc:511-535``) integrating an actual PDE instead of
    re-timing one exchange.

    ``steps=k`` is temporal blocking on the 2-D update: ghost width must be
    ``k`` (one Laplacian radius per fused timestep), BOTH axes exchange
    once per k steps (1/k the messages at the same volume), and each
    in-between update covers the maximal span — stale values creep inward
    one cell per step but only within the ghost band, which the next deep
    exchange overwrites, so the true interior is update-for-update
    identical to per-step exchange (same validity argument as the 1-D
    k-step kernel; proved by the heat2d eigen gate at k>1).

    On a periodic grid, ``sin(kx·x)·sin(ky·y)`` is an exact eigenvector of
    this update with factor ``g = 1 − cx·(2−2cos kxΔx) − cy·(2−2cos kyΔy)``
    per step, which the heat2d driver uses as a roundoff-exact gate: a
    broken exchange or kernel destroys the eigenstructure immediately.

    ``kernel="pallas"`` swaps the XLA update body for the in-place
    row-streaming Pallas kernel
    (:func:`~tpu_mpi_tests.kernels.pallas_kernels.heat2d_pallas`) — the
    same recurrence update-for-update, at ~2 HBM passes per k-step call
    instead of ~6 per step.
    """
    if n_bnd < steps:
        raise ValueError(
            f"heat_step2d_fn: ghost width n_bnd={n_bnd} must be >= "
            f"steps={steps} (one Laplacian radius per fused timestep)"
        )
    if kernel not in ("xla", "pallas"):
        raise ValueError(f"heat_step2d_fn: unknown kernel {kernel!r}")

    @functools.partial(jax.jit, donate_argnums=0)
    def run(z, n_steps):
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(axis_x, axis_y), P()),
            out_specs=P(axis_x, axis_y),
            check_vma=False,
        )
        def go(z, n):
            def body(_, zz):
                zz = exchange_shard(
                    zz, axis_name=axis_x, axis=0, n_bnd=n_bnd, periodic=True
                )
                zz = exchange_shard(
                    zz, axis_name=axis_y, axis=1, n_bnd=n_bnd, periodic=True
                )
                if kernel == "pallas":
                    from tpu_mpi_tests.kernels.pallas_kernels import (
                        heat2d_pallas,
                    )

                    return heat2d_pallas(
                        zz, cx, cy, steps=steps, n_bnd=n_bnd,
                        interpret=interpret,
                    )
                nx, ny = zz.shape
                for _ in range(steps):
                    ix = slice(1, nx - 1)
                    iy = slice(1, ny - 1)
                    mid = zz[ix, iy]
                    d2x = zz[2:nx, iy] + zz[0:nx - 2, iy] - 2.0 * mid
                    d2y = zz[ix, 2:ny] + zz[ix, 0:ny - 2] - 2.0 * mid
                    new = (
                        mid
                        + zz.dtype.type(cx) * d2x
                        + zz.dtype.type(cy) * d2y
                    )
                    zz = lax.dynamic_update_slice(zz, new, (1, 1))
                return zz

            return lax.fori_loop(0, n[0], body, z)

        return go(z, jnp.asarray([n_steps], jnp.int32))

    return run


@functools.lru_cache(maxsize=None)
def exchange_stencil_fused_fn(
    mesh: Mesh,
    axis_name: str,
    axis: int,
    ndim: int,
    n_bnd: int,
    scale: float,
    staged: bool = False,
):
    """Halo exchange + stencil in ONE compiled program — the idiomatic TPU
    form (XLA overlaps the ppermute DMA with interior compute). This is the
    fused A-side of the split-vs-fused measurement (SURVEY §7 hard part 2)."""
    from tpu_mpi_tests.kernels.stencil import stencil1d_5

    spec = [None] * ndim
    spec[axis] = axis_name

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(*spec)
    )
    def step(z):
        z = exchange_shard(
            z, axis_name=axis_name, axis=axis, n_bnd=n_bnd, staged=staged
        )
        return stencil1d_5(z, scale=scale, axis=axis)

    return step


# ---------------------------------------------------------------------------
# Overlap engine (ISSUE 7 tentpole a): host-scheduled double-buffered halos
# with an explicit interior/boundary seam — README "Overlap engine"
# ---------------------------------------------------------------------------


class OverlapRunner:
    """Host-level comm/compute overlap engine for one pipelined phase.

    Depth 1 (:meth:`serial_step`) is today's schedule: a sync-honest
    blocking exchange, then the timed compute phase — byte-identical to
    the unpipelined driver loop. Depth ≥ 2 (:meth:`overlap_step`)
    dispatches the exchange, computes the update's CORE (every cell
    whose stencil touches no fresh ghost — it depends only on old data)
    while the ghost bands fly, then drains the exchange and lets the
    caller patch the boundary seam. The reference's Irecv / compute
    interior / Waitall / fill boundary pattern
    (``mpi_stencil2d_gt.cc:136-255``), scheduled from the host so the
    span timeline can *prove* the overlap.

    Accounting: per step, the measured wall overlap between the
    exchange's dispatch-window span (its own recorded mono clock —
    :class:`~tpu_mpi_tests.instrument.telemetry.AsyncSpan`, the PR-2
    span-timeline data) and the interior-compute window.
    ``overlap_frac`` = overlapped seconds / compute seconds. Be precise
    about what this measures: it is SCHEDULE overlap — the comm was in
    flight across the compute window — so a healthy depth-2 pipeline
    reads ≈ 1.0 *by construction* (the span opens before and drains
    after the phase), while any reversion to serialized scheduling
    (depth resolving to 1, a restructured loop) reads exactly 0; that
    reversion is what the ``--diff`` frac gate catches. A sync smuggled
    INSIDE the region would not move this number — that hazard is rule
    TPM801's (static) job. The genuinely *measured* hiding signal is
    ``drain_s`` (accumulated from ``AsyncSpan.done``): ~0 means the
    exchange completed under the compute; large means the compute
    finished first and the pipeline waited — comm was NOT hidden.
    ``comm_s`` is the dispatch-window width (dispatch → drain), not
    device DMA time; ``roofline_frac`` (PR 5) stays the arbiter of
    whether overlap bought real bandwidth.
    """

    def __init__(self, op: str, *, depth: int, nbytes: int = 0,
                 axis_name: str | None = None, world: int = 1,
                 timer=None, phase: str = "overlap_interior", **meta):
        self.op = op
        self.depth = max(1, int(depth))
        self.nbytes = int(nbytes)
        self.axis_name = axis_name
        self.world = world
        self.timer = timer
        self.phase = phase
        self.meta = meta
        self.comm_s = 0.0
        self.compute_s = 0.0
        self.overlap_s = 0.0
        self.drain_s = 0.0
        self.steps = 0

    def _phase_ctx(self):
        if self.timer is not None:
            return self.timer.phase(self.phase)
        import contextlib

        return contextlib.nullcontext()

    def step(self, exchange_fn, core_fn, z):
        """One pipeline step: returns ``(ex, core_out)``; the caller
        applies the boundary seam from both.

        Depth 1 — the serialized schedule: the exchange is dispatched
        and drained under a sync-honest span, THEN the core computes
        (from ``ex``; bit-identical to computing from ``z`` since the
        core taps no ghost and the exchange writes only ghosts). Depth
        ≥ 2: the exchange rides an open dispatch-window span while the
        core computes from the pre-exchange buffer. Both depths run
        the SAME compiled programs on bit-identical inputs, which is
        what makes the depth-independence claim structural rather than
        hopeful (XLA fuses different program shapes differently — even
        per-cell-identical arithmetic can differ in final bits across
        programs, so equality is engineered by sharing programs, not
        asserted across formulations)."""
        import time as _time

        from tpu_mpi_tests.instrument import telemetry as _T
        from tpu_mpi_tests.instrument.timers import block

        if self.depth <= 1:
            ex = _T.span_call(
                self.op, exchange_fn, z, nbytes=self.nbytes,
                axis_name=self.axis_name, world=self.world, **self.meta,
            )
            ex = block(ex)
            t0 = _time.perf_counter()
            with self._phase_ctx():
                out = block(core_fn(ex))
            self.compute_s += _time.perf_counter() - t0
            self.steps += 1
            return ex, out

        h = _T.async_span(
            self.op, nbytes=self.nbytes, axis_name=self.axis_name,
            world=self.world, overlap_depth=self.depth, **self.meta,
        )
        ex = exchange_fn(z)
        t0 = _time.perf_counter()
        with self._phase_ctx():
            # deliberate sync INSIDE the overlap region: the overlapped
            # interior compute must block here — that IS the measured
            # phase the exchange hides under; only syncs on the
            # in-flight exchange itself would re-serialize
            out = block(core_fn(z))  # tpumt: ignore[TPM801]
        t1 = _time.perf_counter()
        h.done(ex)
        self.compute_s += t1 - t0
        self.comm_s += h.mono_end - h.mono_start
        self.drain_s += h.drain_s
        self.overlap_s += max(
            0.0, min(h.mono_end, t1) - max(h.mono_start, t0)
        )
        self.steps += 1
        return ex, out

    @property
    def overlap_frac(self) -> float:
        return self.overlap_s / self.compute_s if self.compute_s else 0.0

    def annotate(self, timer=None) -> None:
        """Attach the measured overlap to the compute phase's record
        (``PhaseTimer.annotate`` → the JSONL ``time`` record), so the
        OVERLAP table and ``--diff`` can gate it."""
        t = timer if timer is not None else self.timer
        if t is not None and hasattr(t, "annotate"):
            t.annotate(
                self.phase,
                overlap_frac=self.overlap_frac,
                comm_overlap_s=self.overlap_s,
                overlap_depth=self.depth,
            )

    def record(self, op: str | None = None, **extra) -> dict:
        """The ``kind: "overlap"`` JSONL record for this run — one per
        pipelined phase, rendered by tpumt-report's OVERLAP table and
        gated by ``--diff`` (``overlap:<op>:frac``)."""
        return {
            "kind": "overlap",
            "op": op or self.op,
            "depth": self.depth,
            "steps": self.steps,
            "overlap_frac": self.overlap_frac,
            "comm_s": self.comm_s,
            "compute_s": self.compute_s,
            "drain_s": self.drain_s,
            "world": self.world,
            **extra,
        }


@functools.lru_cache(maxsize=None)
def overlap_jacobi_fns(
    mesh: Mesh,
    axis_name: str,
    axis: int,
    ndim: int,
    n_bnd: int,
    scale: float,
    eps: float,
    periodic: bool = False,
    staged: bool = False,
):
    """Split-step programs for the 1-D Jacobi pipeline (the
    ``iterate_fused_fn`` body, exchange-then-update, as three compiled
    pieces): ``(exchange_nod, core, seam)``.

    * ``exchange_nod(z)``: the ppermute ghost exchange WITHOUT input
      donation — in the pipelined schedule the core still reads the
      pre-exchange buffer while the bands fly, so the buffer must
      survive the dispatch.
    * ``core(z)``: the per-step update (``interior += eps·dz``)
      restricted to cells whose stencil touches NO ghost
      (``[2·n_bnd, N−2·n_bnd)`` along ``axis``) — depends only on old
      data, so it runs while the exchange flies. Depth 1 feeds it the
      exchanged array instead; the core's taps are ghost-free, so the
      two inputs are bit-identical where it reads.
    * ``seam(ex, zc)``: the boundary patch — recompute the two
      ``n_bnd``-wide strips from the arrived ghosts (windows of ``ex``)
      and write strips + ghost bands into the core-updated array.

    Per-cell the split computes the serial taps with the serial
    arithmetic; the depth-1 and depth≥2 schedules run these SAME
    programs, so their results are bit-identical by construction
    (gated by ``tests/test_overlap.py``; vs the device-chained
    ``iterate_fused_fn`` the agreement is exact-to-roundoff — XLA may
    fuse the one-program formulation with different FMA boundaries)."""
    from tpu_mpi_tests.kernels.stencil import stencil1d_5
    from tpu_mpi_tests.utils import TpuMtError

    spec = [None] * ndim
    spec[axis] = axis_name
    smap = functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(*spec),
        check_vma=False,
    )

    @jax.jit
    @smap
    def exchange_nod(z):
        return exchange_shard(
            z, axis_name=axis_name, axis=axis, n_bnd=n_bnd,
            periodic=periodic, staged=staged,
        )

    @jax.jit
    @smap
    def core(z):
        N = z.shape[axis]
        if N < 4 * n_bnd + 1:
            raise TpuMtError(
                f"overlap_jacobi_fns: local ghosted extent {N} too small "
                f"for the interior/boundary split (need > {4 * n_bnd})"
            )
        # core cells [2nb, N-2nb) tap [nb, N-nb) — no ghosts
        window = lax.slice_in_dim(z, n_bnd, N - n_bnd, axis=axis)
        dz = stencil1d_5(window, scale=scale, axis=axis)
        new_core = (
            lax.slice_in_dim(z, 2 * n_bnd, N - 2 * n_bnd, axis=axis)
            + eps * dz
        )
        return lax.dynamic_update_slice_in_dim(
            z, new_core, 2 * n_bnd, axis=axis
        )

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(*spec), P(*spec)),
        out_specs=P(*spec), check_vma=False,
    )
    def seam(ex, zc):
        N = ex.shape[axis]
        # lo strip [nb, 2nb) taps ex[0, 3nb); hi strip mirrors
        lo_win = lax.slice_in_dim(ex, 0, 3 * n_bnd, axis=axis)
        new_lo = (
            lax.slice_in_dim(ex, n_bnd, 2 * n_bnd, axis=axis)
            + eps * stencil1d_5(lo_win, scale=scale, axis=axis)
        )
        hi_win = lax.slice_in_dim(ex, N - 3 * n_bnd, N, axis=axis)
        new_hi = (
            lax.slice_in_dim(ex, N - 2 * n_bnd, N - n_bnd, axis=axis)
            + eps * stencil1d_5(hi_win, scale=scale, axis=axis)
        )
        out = lax.dynamic_update_slice_in_dim(zc, new_lo, n_bnd, axis=axis)
        out = lax.dynamic_update_slice_in_dim(
            out, new_hi, N - 2 * n_bnd, axis=axis
        )
        # ghost bands: exactly the exchange's arrivals (serial keeps them)
        out = lax.dynamic_update_slice_in_dim(
            out, lax.slice_in_dim(ex, 0, n_bnd, axis=axis), 0, axis=axis
        )
        return lax.dynamic_update_slice_in_dim(
            out, lax.slice_in_dim(ex, N - n_bnd, N, axis=axis),
            N - n_bnd, axis=axis,
        )

    return exchange_nod, core, seam


@functools.lru_cache(maxsize=None)
def heat_overlap_fns(
    mesh: Mesh,
    axis_x: str,
    axis_y: str,
    cx: float,
    cy: float,
):
    """Split-step programs for the heat2d pipeline (periodic dual-axis,
    ``n_bnd=1``, one Euler step per exchange — the ``heat_step2d_fn``
    XLA body): ``(exchange_nod, core, seam)``.

    ``exchange_nod(z)`` chains both axes' periodic exchanges without
    donation; ``core(z)`` updates the cells at distance ≥ 2 from every
    shard edge (no ghost taps); ``seam(ex, zc)`` recomputes the 1-wide
    boundary frame from the arrived ghosts and copies the ghost
    rows/columns. The driver's ``--overlap 1`` resolution keeps
    today's fused device-side loop untouched (byte-identical
    schedules); the engine's own depth-1/depth-2 runs share these
    programs and are bit-identical to each other, exact-to-roundoff
    vs the fused body (gated by ``tests/test_overlap.py`` and
    end-to-end by the driver's eigen check)."""

    def _exchange_body(z):
        z = exchange_shard(z, axis_name=axis_x, axis=0, n_bnd=1,
                           periodic=True)
        return exchange_shard(z, axis_name=axis_y, axis=1, n_bnd=1,
                              periodic=True)

    spec = P(axis_x, axis_y)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=spec, out_specs=spec,
        check_vma=False,
    )
    def exchange_nod(z):
        return _exchange_body(z)

    def _lap(zz, ix, iy, jx, jy):
        """One Euler update of the window ``[ix:jx) × [iy:jy)`` from its
        ±1 neighbors — the exact ``heat_step2d_fn`` arithmetic on a
        sub-slab (per-cell identical taps and casts)."""
        mid = zz[ix:jx, iy:jy]
        d2x = zz[ix + 1:jx + 1, iy:jy] + zz[ix - 1:jx - 1, iy:jy] \
            - 2.0 * mid
        d2y = zz[ix:jx, iy + 1:jy + 1] + zz[ix:jx, iy - 1:jy - 1] \
            - 2.0 * mid
        return mid + zz.dtype.type(cx) * d2x + zz.dtype.type(cy) * d2y

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=spec, out_specs=spec,
        check_vma=False,
    )
    def core(z):
        nx, ny = z.shape
        new = _lap(z, 2, 2, nx - 2, ny - 2)
        return lax.dynamic_update_slice(z, new, (2, 2))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec), out_specs=spec,
        check_vma=False,
    )
    def seam(ex, zc):
        nx, ny = ex.shape
        out = zc
        # boundary frame from the arrived ghosts: two full-width rows,
        # two columns excluding the rows already written
        out = lax.dynamic_update_slice(
            out, _lap(ex, 1, 1, 2, ny - 1), (1, 1)
        )
        out = lax.dynamic_update_slice(
            out, _lap(ex, nx - 2, 1, nx - 1, ny - 1), (nx - 2, 1)
        )
        out = lax.dynamic_update_slice(
            out, _lap(ex, 2, 1, nx - 2, 2), (2, 1)
        )
        out = lax.dynamic_update_slice(
            out, _lap(ex, 2, ny - 2, nx - 2, ny - 1), (2, ny - 2)
        )
        # ghost rows/columns exactly as the exchange left them (the
        # serial update never touches ghosts)
        out = lax.dynamic_update_slice(out, ex[0:1, :], (0, 0))
        out = lax.dynamic_update_slice(out, ex[nx - 1:nx, :], (nx - 1, 0))
        out = lax.dynamic_update_slice(out, ex[:, 0:1], (0, 0))
        return lax.dynamic_update_slice(out, ex[:, ny - 1:ny], (0, ny - 1))

    return exchange_nod, core, seam


@functools.lru_cache(maxsize=None)
def grid_overlap_fns(
    mesh: Mesh,
    axis_x: str,
    axis_y: str,
    n_bnd: int,
    scale_x: float,
    scale_y: float,
):
    """Split-step programs for the 2-D-grid derivative pipeline (the
    ``step2d_fn`` XLA pipeline): ``(exchange_nod, core, seam)``.

    ``core(z)`` computes both derivatives' interiors from old data only
    — ``dz_dx`` rows ``[nb, nxi−nb)`` never tap a row ghost (and never
    tap column ghosts at all; the dual slab is pre-sliced to interior
    columns), symmetrically for ``dz_dy``. ``seam(ex, cores)``
    completes the ``nb``-wide frame rows/columns from the exchanged
    array, reassembles the full derivative fields, and reduces the
    global residual (``psum`` over both mesh axes) — per-cell identical
    to the fused serial program; the residual's reduction order may
    differ in the last bits (tolerance-gated like every residual)."""
    from tpu_mpi_tests.kernels.stencil import stencil1d_5

    spec = P(axis_x, axis_y)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=spec, out_specs=spec,
        check_vma=False,
    )
    def exchange_nod(z):
        z = exchange_shard(z, axis_name=axis_x, axis=0, n_bnd=n_bnd)
        return exchange_shard(z, axis_name=axis_y, axis=1, n_bnd=n_bnd)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=spec, out_specs=(spec, spec),
        check_vma=False,
    )
    def core(z):
        nb = n_bnd
        nxg, nyg = z.shape
        slab = z[nb:nxg - nb, nb:nyg - nb]  # interior both dims
        dx_core = stencil1d_5(slab, scale=scale_x, axis=0)
        dy_core = stencil1d_5(slab, scale=scale_y, axis=1)
        return dx_core, dy_core

    @functools.partial(jax.jit, donate_argnums=0)
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, spec, P()),
        check_vma=False,
    )
    def seam(ex, dx_core, dy_core):
        nb = n_bnd
        nxg, nyg = ex.shape
        dx_top = stencil1d_5(
            ex[0:3 * nb, nb:nyg - nb], scale=scale_x, axis=0
        )
        dx_bot = stencil1d_5(
            ex[nxg - 3 * nb:nxg, nb:nyg - nb], scale=scale_x, axis=0
        )
        dz_dx = jnp.concatenate([dx_top, dx_core, dx_bot], axis=0)
        dy_lo = stencil1d_5(
            ex[nb:nxg - nb, 0:3 * nb], scale=scale_y, axis=1
        )
        dy_hi = stencil1d_5(
            ex[nb:nxg - nb, nyg - 3 * nb:nyg], scale=scale_y, axis=1
        )
        dz_dy = jnp.concatenate([dy_lo, dy_core, dy_hi], axis=1)
        residual = jnp.sum(jnp.square(dz_dx)) + jnp.sum(jnp.square(dz_dy))
        return dz_dx, dz_dy, lax.psum(residual, (axis_x, axis_y))

    return exchange_nod, core, seam
