"""MoE token routing: capacity-bucketed all-to-all dispatch/combine.

The serving-era shape of the all-to-all pillar (ROADMAP item 4): in a
mixture-of-experts layer every rank holds a shard of the token stream,
each token names a destination expert (one expert per mesh rank here),
and the layer is two variable-occupancy ``lax.all_to_all`` hops —
dispatch tokens to their experts, combine the processed tokens back to
their source positions. Occupancy varies per (source, expert) pair, but
the collective's buffers cannot: every pair gets a fixed ``capacity``
slot bucket, tokens beyond it are DROPPED (the standard MoE overflow
rule), and the drop accounting — occupancy, overflow %, per-expert
imbalance — is a first-class measurement (``kind: "route"`` records,
the ``tpumt-report`` ROUTE table), because in production it is the
routing distribution, not the link bandwidth, that decides whether an
MoE layer keeps its SLO.

Semantics (verified against :func:`route_reference` in
``tests/test_moe.py``):

* token ``t`` on source rank ``r`` with destination ``e`` is routed iff
  fewer than ``capacity`` earlier tokens of shard ``r`` (local order)
  named ``e``; routed tokens return as ``f_e(x_t)`` (the analytic
  per-expert function ``(e+1)·x`` when ``scale=True``), dropped tokens
  return zeros — exact in every dtype for integer-valued inputs;
* the dispatch buffer is ``(world, capacity, D)`` per rank; empty slots
  carry zeros and survive the expert function (``f_e(0) = 0``).

The combine hop is a tunable schedule (``moe/combine``): the inverse
``all_to_all`` (prior — moves the same bytes as the dispatch) vs an
``all_gather`` of the processed buffers with a local slot select (moves
``world``× the bytes but collapses the second variable-occupancy hop
into the gather pattern some topologies prefer for tiny payloads).
Resolution is explicit > cached > prior like every knob since PR 4.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_mpi_tests.comm.collectives import host_value
from tpu_mpi_tests.comm.topology import mesh_link_meta
from tpu_mpi_tests.compat import shard_map
from tpu_mpi_tests.instrument import telemetry
from tpu_mpi_tests.instrument.telemetry import span_call
from tpu_mpi_tests.tune import priors as _priors
from tpu_mpi_tests.tune.registry import (
    declare_space,
    resolve as _tune_resolve,
)
from tpu_mpi_tests.utils import check_divisible

#: the combine-hop schedule knob — declared here because the routing
#: collective lives here; prior "alltoall" keeps untuned runs on the
#: symmetric dispatch/combine pair
MOE_COMBINE_SPACE = declare_space(
    "moe/combine",
    (_priors.MOE_COMBINE, "allgather"),
    describe="MoE combine hop: inverse all_to_all vs all_gather + "
             "local slot select",
)


def resolve_combine(explicit=None, **ctx) -> str:
    """Combine-hop variant: explicit > cached winner > prior.
    ``device_fallback=False`` — the optimum is payload-sensitive (the
    allgather variant moves world× the bytes), so a sibling shape's
    winner must not leak in. Malformed cache values degrade to the
    prior."""
    val = _tune_resolve(
        "moe/combine", explicit=explicit, prior=_priors.MOE_COMBINE,
        device_fallback=False, **ctx,
    )
    return val if val in ("alltoall", "allgather") else _priors.MOE_COMBINE


@dataclasses.dataclass(frozen=True)
class RouteStats:
    """Host-side accounting of one routed step.

    ``counts[r, e]`` is source rank ``r``'s demand for expert ``e``
    (pre-drop); ``expert_load[e]`` the tokens expert ``e`` actually
    received (post-capacity). ``occupancy_pct`` is routed tokens over
    total slot capacity (``world² · capacity``), ``imbalance`` the
    max/mean ratio of per-expert load (1.0 = perfectly balanced; the
    number capacity factors are provisioned against)."""

    world: int
    capacity: int
    counts: np.ndarray  # (world, world) int64

    @property
    def tokens(self) -> int:
        return int(self.counts.sum())

    @property
    def routed(self) -> int:
        return int(np.minimum(self.counts, self.capacity).sum())

    @property
    def dropped(self) -> int:
        return self.tokens - self.routed

    @property
    def overflow_pct(self) -> float:
        return 100.0 * self.dropped / self.tokens if self.tokens else 0.0

    @property
    def expert_load(self) -> np.ndarray:
        return np.minimum(self.counts, self.capacity).sum(axis=0)

    @property
    def occupancy_pct(self) -> float:
        cap_total = self.world * self.world * self.capacity
        return 100.0 * self.routed / cap_total if cap_total else 0.0

    @property
    def imbalance(self) -> float:
        load = self.expert_load.astype(np.float64)
        mean = load.mean() if load.size else 0.0
        return float(load.max() / mean) if mean > 0 else 1.0

    def record(self, op: str = "moe", **extra) -> dict:
        """The ``kind: "route"`` JSONL shape (ROUTE table input)."""
        return {
            "kind": "route",
            "op": op,
            "world": self.world,
            "capacity": self.capacity,
            "tokens": self.tokens,
            "routed": self.routed,
            "dropped": self.dropped,
            "overflow_pct": self.overflow_pct,
            "occupancy_pct": self.occupancy_pct,
            "imbalance": self.imbalance,
            "expert_load": [int(v) for v in self.expert_load],
            **extra,
        }


@functools.lru_cache(maxsize=None)
def moe_route_fn(mesh: Mesh, axis_name: str, capacity: int,
                 combine: str = "alltoall", scale: bool = True):
    """Jitted routed step over a token-sharded ``(T_global, D)`` array
    plus an int32 destination vector sharded alike. Returns
    ``(y, counts)``: the routed-and-processed tokens (dropped positions
    zero) and the per-(source, dest) demand matrix (``(world, world)``,
    replicated so every process can read the accounting host-side —
    multi-host runs cannot ``np.asarray`` a sharded output)."""
    w = mesh.shape[axis_name]

    def route(x, dest):
        # (T_local, D) tokens, (T_local,) int32 destinations
        d_model = x.shape[1]
        dest = dest.astype(jnp.int32)
        oh = (dest[:, None] == jnp.arange(w, dtype=jnp.int32)[None, :])
        oh = oh.astype(jnp.int32)  # (T, w)
        # position of each token within its destination group (exclusive
        # running count) — the capacity cutoff is per (source, dest)
        cum = jnp.cumsum(oh, axis=0) - oh
        pos = jnp.take_along_axis(cum, dest[:, None], axis=1)[:, 0]
        counts = oh.sum(axis=0)  # (w,) this source's per-dest demand
        keep = pos < capacity
        # slot layout: dest-major buckets of `capacity` slots; overflow
        # tokens scatter to the out-of-range index and are dropped by
        # the scatter mode (never silently wrapped)
        slot = jnp.where(keep, dest * capacity + pos, w * capacity)
        send = jnp.zeros((w * capacity, d_model), x.dtype)
        send = send.at[slot].set(x, mode="drop").reshape(w, capacity,
                                                        d_model)
        recv = lax.all_to_all(send, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)
        # expert compute on this rank (= expert axis_index): analytic
        # (e+1)·x so verification is exact and f_e(0) = 0 keeps empty
        # slots inert
        proc = recv
        if scale:
            e = lax.axis_index(axis_name)
            proc = recv * (e + 1).astype(x.dtype)
        if combine == "allgather":
            # gather every expert's processed buffer, select my source
            # slot locally: g[e, r] = expert e's tokens from source r
            g = lax.all_gather(proc, axis_name, axis=0, tiled=False)
            back = g[:, lax.axis_index(axis_name)]
        else:
            back = lax.all_to_all(proc, axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)
        flat = back.reshape(w * capacity, d_model)
        y = flat[jnp.where(keep, slot, 0)] * keep[:, None].astype(x.dtype)
        # replicate the (w, w) demand matrix (row = source rank) — a
        # w² int32 all_gather, negligible next to the token hops
        counts_all = lax.all_gather(counts, axis_name, axis=0,
                                    tiled=False)
        return y, counts_all

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name)),
        out_specs=(P(axis_name, None), P()),
        check_vma=False,
    )
    def routed(x, dest):
        return route(x, dest)

    return routed


def route_payload_bytes(x, world: int, capacity: int,
                        combine: str = "alltoall") -> int:
    """Telemetry payload model (aggregate across ranks, busbw
    convention): each a2a hop moves ``(w−1)/w`` of every rank's
    ``(w, capacity, D)`` buffer; the allgather combine receives the
    ``w−1`` foreign buffers whole."""
    d_model = int(x.shape[-1])
    item = int(x.dtype.itemsize) if hasattr(x, "dtype") else 4
    buf = world * capacity * d_model * item  # per-rank dispatch buffer
    dispatch = (world - 1) * buf  # w ranks × (w−1)/w × buf
    if combine == "allgather":
        return dispatch + world * (world - 1) * buf
    return 2 * dispatch


def route_tokens(x, dest, mesh: Mesh, capacity: int,
                 axis_name: str | None = None, combine: str | None = None,
                 scale: bool = True, op: str = "moe"):
    """One routed MoE step with accounting: dispatch → expert → combine.

    ``x`` is ``(T_global, D)`` sharded on axis 0 over the mesh axis,
    ``dest`` the matching int32 destination vector (values in
    ``[0, world)``), ``capacity`` the per-(source, expert) slot count.
    Returns ``(y, RouteStats)`` — ``y`` sharded like ``x`` with dropped
    positions zeroed. The call is bracketed in a sync-honest span
    (``op``) with the dispatch+combine payload model, and the
    accounting is mirrored to the telemetry sink as a ``kind: "route"``
    record when telemetry is on (the ROUTE table's input)."""
    axis_name = axis_name or mesh.axis_names[0]
    world = mesh.shape[axis_name]
    check_divisible(x.shape[0], world, "moe tokens over mesh axis")
    if capacity < 1:
        raise ValueError(f"capacity must be positive, got {capacity}")
    combine = resolve_combine(
        combine, dtype=str(x.dtype), n=x.shape[0], world=world,
    )
    fn = moe_route_fn(mesh, axis_name, int(capacity), combine, scale)
    y, counts = span_call(
        op, fn, x, dest,
        nbytes=route_payload_bytes(x, world, capacity, combine),
        axis_name=axis_name, world=world, combine=combine,
        capacity=int(capacity),
        **mesh_link_meta(mesh, axis_name),
    )
    stats = RouteStats(
        world=world, capacity=int(capacity),
        counts=np.asarray(host_value(counts), np.int64),
    )
    telemetry.emit(stats.record(op=op, combine=combine))
    return y, stats


def route_reference(x, dest, world: int, capacity: int,
                    scale: bool = True) -> np.ndarray:
    """Dense host-side reference of the routed step (numpy, no jax):
    the same first-``capacity``-per-(source, dest) drop rule applied in
    local shard order, dropped tokens zero, routed tokens ``(e+1)·x``.
    The analytic gate the device path is verified against."""
    x = np.asarray(x)
    dest = np.asarray(dest)
    t_local = x.shape[0] // world
    y = np.zeros_like(x)
    for r in range(world):
        taken = np.zeros(world, np.int64)
        for t in range(r * t_local, (r + 1) * t_local):
            e = int(dest[t])
            if taken[e] < capacity:
                taken[e] += 1
                y[t] = x[t] * (e + 1) if scale else x[t]
    return y
