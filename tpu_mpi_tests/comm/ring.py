"""Ring communication primitives + ring attention: sequence/context
parallelism as a first-class component.

SURVEY.md §5.7: the reference's entire stencil pillar is the communication
skeleton of ring attention — a 1-D process ring exchanging blocks with
neighbors ±1, nonblocking sends overlapped with local compute
(``mpi_stencil_gt.cc:83-122``). This module makes that explicit: the same
``lax.ppermute`` ring that fills stencil ghosts (comm/halo.py) here rotates
K/V blocks around the mesh axis while each shard accumulates its queries'
attention online — long sequences scale across chips with O(L_local) memory
per chip.

Components:

* :func:`ring_pass` — rotate a block one step around the ring (the
  ``Isend/Irecv`` to rank±1 analog, periodic).
* :func:`ring_scan` — fold a function over every rank's block as it rotates
  (generic ring-reduce; the stencil halo is the 1-step special case).
* :func:`ring_attention` / :func:`ring_attention_fn` — blockwise
  numerically-stable softmax attention over a sequence sharded along a mesh
  axis (the ring-attention primitive of Liu et al.; no attention exists in
  the reference — this is the capability its halo skeleton was built to
  carry, provided as a library component).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_mpi_tests.compat import (
    axis_size,
    pcast_varying,
    shard_map,
)
from tpu_mpi_tests.comm.topology import mesh_partner_links
from tpu_mpi_tests.instrument.telemetry import span_call


def online_softmax_update(m, l, s, keepdims: bool = False):
    """One block of the online-softmax recurrence shared by ALL attention
    tiers (ring, Ulysses, and the Pallas flash kernel): given running max
    ``m`` and denominator ``l`` (any leading batch shape; a trailing
    length-1 axis instead when ``keepdims``) and this block's scores ``s``
    (batch shape + a trailing key axis), returns ``(m_new, l_new, p, corr)``
    where ``p`` are the block's unnormalized probabilities and ``corr``
    rescales the caller's numerator: ``acc_new = acc·corr[...,None] + p @
    v_blk`` (no ``[...,None]`` under ``keepdims``).

    All-masked blocks leave ``m_new`` at -inf; the ``m_safe`` guard makes
    ``exp(s − m_safe) = exp(-inf) = 0`` with no −inf − −inf NaNs. Keeping
    this in ONE place means a numerics fix cannot silently diverge between
    the attention tiers (``keepdims=True`` exists because Mosaic prefers
    2-D (qt, 1) carries over 1-D vectors)."""
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=keepdims))
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - (m_safe if keepdims else m_safe[..., None]))
    corr = jnp.exp(m - m_safe)
    l_new = l * corr + p.sum(axis=-1, keepdims=keepdims)
    return m_new, l_new, p, corr


def to_striped(x, world: int):
    """Permute a global sequence (axis 0) into the STRIPED causal layout:
    shard ``r`` of the striped array holds tokens ``r, r+n, r+2n, …`` —
    global position of striped row ``r·L_loc + i`` is ``i·n + r``.

    Why: on the contiguous layout a causal ring is paced by the last rank
    (rank n−1 attends to every block while rank 0 attends to one); on the
    striped layout every (q shard, k shard) pair is ~half-live at every
    ring step, so all ranks do equal work (striped attention, Brandon et
    al. 2023 — the load-balancing analog of the reference's equal-sized
    halo decomposition). Positions stay AFFINE (``pos = r + n·i``), which
    is what lets the flash kernel's tile-skip logic work unchanged via
    ``pos_stride``."""
    from tpu_mpi_tests.utils import check_divisible

    lloc = check_divisible(x.shape[0], world, "to_striped sequence length")
    return (
        x.reshape((lloc, world) + x.shape[1:])
        .swapaxes(0, 1)
        .reshape(x.shape)
    )


def from_striped(x, world: int):
    """Inverse of :func:`to_striped`."""
    from tpu_mpi_tests.utils import check_divisible

    lloc = check_divisible(
        x.shape[0], world, "from_striped sequence length"
    )
    return (
        x.reshape((world, lloc) + x.shape[1:])
        .swapaxes(0, 1)
        .reshape(x.shape)
    )


def ring_pass(x, axis_name: str, shift: int = 1):
    """Rotate ``x`` ``shift`` steps around the mesh-axis ring (periodic):
    each rank receives the block of ``rank - shift``."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def ring_scan(f, init, block, axis_name: str, depth: int = 1):
    """Fold ``f(carry, block_j, j)`` over every rank's block ``j`` as blocks
    rotate around the ring; after ``n`` steps each rank has seen all blocks.

    ``f`` must keep carry shapes static. Step ``s`` on rank ``r`` sees the
    block originally owned by rank ``(r - s) % n``.

    ``depth`` is the K/V prefetch pipeline depth (ISSUE 7 tentpole b,
    knob ``ring/pipeline_depth``): 1 — the exact historical schedule —
    rotates the block AFTER consuming it; ``depth = d ≥ 2`` keeps
    ``d − 1`` rotations in flight, so the ``ppermute`` producing the
    next block was issued a full step earlier and its
    collective-permute-start precedes the current block's compute in
    program order — XLA's latency-hiding scheduler can run them
    together. The consumed values are identical at every depth (step
    ``s`` always sees ``rot^s(block)``), so results are bit-identical
    (gated by ``tests/test_overlap.py``); ``depth`` is clamped to the
    ring size. Cost: ``d − 1`` live extra block buffers and as many
    tail rotations whose results are dropped.
    """
    n = axis_size(axis_name)
    r = lax.axis_index(axis_name)
    # the folded carry becomes device-varying (it mixes in this rank's
    # blocks); mark the init accordingly or vma inference rejects the loop
    init = jax.tree.map(
        lambda x: pcast_varying(jnp.asarray(x), axis_name), init
    )
    d = max(1, min(int(depth), n))

    if d == 1:
        def body(s, state):
            carry, blk = state
            src = lax.rem(r - s + n, jnp.int32(n))
            carry = f(carry, blk, src)
            # rotate for the next step (sent even on the last step; XLA
            # drops nothing observable and the loop stays uniform)
            return carry, ring_pass(blk, axis_name)

        carry, _ = lax.fori_loop(0, n, body, (init, block))
        return carry

    # pipelined: the in-flight queue holds rot^s(block) .. rot^{s+d-1};
    # the prologue issues the first d−1 rotations before any compute
    q = (block,)
    for _ in range(d - 1):
        q = q + (ring_pass(q[-1], axis_name),)

    def body(s, state):
        carry, q = state
        src = lax.rem(r - s + n, jnp.int32(n))
        carry = f(carry, q[0], src)
        # consume the arrived head, issue the rotation d−1 steps ahead
        return carry, q[1:] + (ring_pass(q[-1], axis_name),)

    carry, _ = lax.fori_loop(0, n, body, (init, q))
    return carry


# Flash tile configuration per ring layout. The measured-best tables
# now live in tune/priors.py as the autotuner's cold-start priors
# (re-exported here under their historical names — tests and BASELINE
# cross-references pin them); ``k_tile=None`` / ``skip_tile=None``
# anywhere below resolve through the schedule cache (explicit > cached
# > prior — tune/registry.py), so a topology that ran a ``--tune``
# sweep gets ITS optimum while a cache-less run resolves byte-identical
# to the pinned era. attnbench --k-tile/--skip-tile stay the explicit
# overrides and win over any cache entry.
from tpu_mpi_tests.tune.priors import (  # noqa: E402
    MEASURED_BEST_K_TILE,
    MEASURED_BEST_SKIP_TILE,
)
from tpu_mpi_tests.tune.registry import (  # noqa: E402
    declare_space,
    resolve as _tune_resolve,
)


def _tile_space(layout: str):
    """Candidate (k_tile, skip_tile) schedules for one ring layout:
    the shipped prior first, then the grid the BASELINE round-5 sweeps
    actually priced (k widths 512..2048 × coupled/256-sub-span skip)."""
    prior = {
        "k_tile": MEASURED_BEST_K_TILE[layout],
        "skip_tile": MEASURED_BEST_SKIP_TILE[layout],
    }
    grid = [
        {"k_tile": kt, "skip_tile": st}
        for kt in (2048, 1024, 512)
        for st in (0, 256)
    ]
    return [prior] + [c for c in grid if c != prior]


#: flash-attention tile spaces, one per ring layout — declared here
#: because the layout notion (contig vs striped causal) lives here
FLASH_TILE_SPACES = {
    layout: declare_space(
        f"flash_tiles/{layout}",
        _tile_space(layout),
        describe="flash kernel k-tile width x causal skip granularity",
    )
    for layout in ("contig", "striped")
}

from tpu_mpi_tests.tune.priors import (  # noqa: E402
    RING_PIPELINE_DEPTH,
    RING_TIER,
)

#: the ring K/V prefetch pipeline depth (ISSUE 7 tentpole b) — declared
#: here because the ring schedule lives here; prior 1 keeps untuned
#: resolution byte-identical to the historical rotate-after-compute loop
RING_DEPTH_SPACE = declare_space(
    "ring/pipeline_depth",
    (RING_PIPELINE_DEPTH, 2, 4),
    describe="K/V rotations kept in flight ahead of the consuming "
             "matmul (1 = rotate after compute)",
)

#: the K/V rotation tier (ISSUE 19 tentpole b): "pipelined" — the
#: host-scheduled ppermute ring above, paced by ring/pipeline_depth —
#: is the prior; "fused" collapses the whole rotation+compute loop into
#: one Pallas launch whose kernel fires the next step's RDMA before the
#: current block's matmul (kernels/collectives_pallas.py). The fused
#: tier only admits geometries whose live working set fits VMEM
#: (``fused_ring_feasible``), so resolution degrades rather than crash
#: when a cached winner travels to an infeasible shape.
RING_TIER_SPACE = declare_space(
    "ring/tier",
    (RING_TIER, "fused"),
    describe="K/V rotation schedule: host-pipelined ppermute ring vs "
             "the one-launch fused-RDMA kernel",
)


def _resolve_pipeline_depth(depth, dtype=None, lq=None) -> int:
    """Ring pipeline depth: explicit > cached winner > prior (1).
    Context like the tile knobs (dtype + local block length); malformed
    cache values degrade to the prior — the cache is an accelerant,
    never a way to crash a run."""
    if depth is not None:
        return max(1, int(depth))
    tuned = _tune_resolve(
        "ring/pipeline_depth", prior=RING_PIPELINE_DEPTH,
        dtype=dtype, lq=lq,
    )
    try:
        return max(1, int(tuned))
    except (TypeError, ValueError):
        return RING_PIPELINE_DEPTH


def _resolve_ring_tier(tier, dtype=None, lq=None) -> str:
    """Ring K/V rotation tier: explicit > cached winner > prior
    ("pipelined"). Same context keys as the depth knob; a malformed
    cache value degrades to the prior, matching every other resolver."""
    if tier is not None:
        return str(tier)
    # geometry-keyed (feasibility depends on lq/d/dtype): a winner tuned
    # at one shape must not leak to another via the device-only slot
    tuned = _tune_resolve(
        "ring/tier", prior=RING_TIER, device_fallback=False,
        dtype=dtype, lq=lq,
    )
    if tuned not in ("pipelined", "fused"):
        return RING_TIER
    return tuned


def _resolve_tile_field(field: str, stripe: bool, dtype, lq) -> int:
    layout = "striped" if stripe else "contig"
    prior = {"k_tile": MEASURED_BEST_K_TILE[layout],
             "skip_tile": MEASURED_BEST_SKIP_TILE[layout]}
    tuned = _tune_resolve(
        f"flash_tiles/{layout}", prior=prior, dtype=dtype, lq=lq
    )
    try:
        return int(tuned[field])
    except (TypeError, KeyError, ValueError):
        # a malformed/hand-edited cache value degrades to the prior —
        # the cache is an accelerant, never a way to crash a run
        return int(prior[field])


def _resolve_k_tile(k_tile, stripe: bool, dtype=None, lq=None) -> int:
    if k_tile is not None:
        return k_tile
    return _resolve_tile_field("k_tile", stripe, dtype, lq)


def _resolve_skip_tile(skip_tile, stripe: bool, dtype=None, lq=None) -> int:
    if skip_tile is not None:
        return skip_tile
    return _resolve_tile_field("skip_tile", stripe, dtype, lq)


def ring_attention(
    q,
    k,
    v,
    axis_name: str,
    scale: float | None = None,
    causal: bool = False,
    precision=lax.Precision.HIGHEST,
    flash: bool = False,
    interpret: bool | None = None,
    q_tile: int = 256,
    k_tile: int | None = None,
    skip_tile: int | None = None,
    stripe: bool = False,
    depth: int | None = None,
    tier: str | None = None,
):
    """Blockwise ring attention for one shard (call inside ``shard_map``).

    ``q``/``k``/``v``: this rank's sequence blocks, shape (L_local, d).
    K/V blocks rotate around the ring; the online-softmax carry
    (running max ``m``, denominator ``l``, numerator ``acc``) is updated
    per block, so no rank ever materializes the full attention matrix or
    the full K/V — the long-context memory property.

    ``precision`` defaults to HIGHEST (true-f32 MXU passes): TPU matmuls
    default to bf16 accumulation (~3e-3 relative error), and this framework
    verifies against exact references. Pass ``lax.Precision.DEFAULT`` to
    trade accuracy for MXU throughput.

    ``flash=True`` swaps the per-block XLA matmul pipeline (which
    materializes an (L_local × L_local) scores block in HBM each ring step)
    for the hand-written Pallas flash kernel
    (``kernels.pallas_kernels.flash_attention_block_pallas``): scores live
    only in VMEM tiles, the carry is f32 and updated in place. Same
    recurrence, same masking — the tiers are interchangeable per test.

    ``stripe=True`` (causal only): inputs are in the STRIPED layout
    (:func:`to_striped` — shard r's row i is global token ``i·n + r``),
    which balances the causal ring: every rank does ~half a block pair of
    useful work at EVERY step instead of rank n−1 doing all n (VERDICT r2
    weak #1). Positions stay affine, so the flash kernel's fully-masked
    tile skip applies per step; outputs come back in the striped layout
    (:func:`from_striped` to undo globally). The layout choice is
    DTYPE-dependent: stripe pays at f32 (1.42-1.51x paced) but measured
    0.79-0.83x at bf16 (per-cell fixed cost dominates the halved matmul
    work) — keep the contiguous layout for bf16 workloads (BASELINE
    round-5 stripebalance dtype note).

    ``tier`` (ISSUE 19): ``None`` resolves the K/V rotation schedule
    through the cache (``ring/tier``, prior "pipelined" — today's
    host-scheduled ppermute loop, byte-identical untuned). "fused"
    dispatches the whole rotation+compute loop as ONE Pallas launch
    (``kernels.collectives_pallas.fused_ring_attention_pallas``) whose
    kernel overlaps each step's RDMA with the previous block's matmul;
    an explicitly-requested fused tier raises when the geometry's live
    set exceeds VMEM, while a cached winner degrades to "pipelined".
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d**0.5)
    if stripe and not causal:
        raise ValueError(
            "stripe=True only makes sense for causal ring attention "
            "(non-causal work is already balanced)"
        )
    # cache context: dtype + local block length (bucketed) — a tuned
    # winner from attnbench --tune at this shape/width applies here
    _dt = str(jnp.dtype(q.dtype))
    # K/V rotation tier (ISSUE 19): explicit > cached > prior. The
    # fused one-launch kernel replaces the whole ring below; an
    # explicit request propagates its feasibility ValueError (loud,
    # like every explicit knob) while a cached winner that traveled to
    # an infeasible geometry degrades to the pipelined schedule.
    _explicit_tier = tier is not None
    tier = _resolve_ring_tier(tier, dtype=_dt, lq=q.shape[0])
    if tier == "fused":
        from tpu_mpi_tests.kernels.collectives_pallas import (
            fused_ring_attention_pallas,
            fused_ring_feasible,
        )

        if _explicit_tier or fused_ring_feasible(
            q.shape[0], k.shape[0], d, q.dtype
        ):
            return fused_ring_attention_pallas(
                q, k, v, axis_name=axis_name, scale=float(scale),
                causal=causal, stripe=stripe, precision=precision,
                interpret=interpret,
            )
        tier = "pipelined"
    k_tile = _resolve_k_tile(k_tile, stripe, dtype=_dt, lq=q.shape[0])
    skip_tile = _resolve_skip_tile(
        skip_tile, stripe, dtype=_dt, lq=q.shape[0]
    )
    # pipeline depth (ISSUE 7): explicit > cached > prior (1 = the
    # historical rotate-after-compute ring; README "Overlap engine")
    depth = _resolve_pipeline_depth(depth, dtype=_dt, lq=q.shape[0])

    lq = q.shape[0]
    n = axis_size(axis_name)
    r = lax.axis_index(axis_name)

    if flash:
        from tpu_mpi_tests.kernels.pallas_kernels import (
            flash_attention_block_pallas,
        )

        m0 = jnp.full((lq, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((lq, 1), jnp.float32)
        acc0 = jnp.zeros((lq, d), jnp.float32)

        def step(carry, kv_blk, src):
            k_blk, v_blk = kv_blk
            if stripe:  # striped position of row i on shard p: i·n + p
                q_off, k_off, stride = r, src, n
            else:
                q_off, k_off, stride = r * lq, src * k_blk.shape[0], 1
            m, l, acc = flash_attention_block_pallas(
                q, k_blk, v_blk, *carry,
                q_off, k_off,
                scale=float(scale), causal=causal, interpret=interpret,
                precision=precision, q_tile=q_tile, k_tile=k_tile,
                skip_tile=skip_tile, pos_stride=stride,
            )
            return m, l, acc

        m, l, acc = ring_scan(
            step, (m0, l0, acc0), (k, v), axis_name, depth=depth
        )
        return (acc / l).astype(q.dtype)

    m0 = jnp.full(q.shape[:-1], -jnp.inf, q.dtype)
    l0 = jnp.zeros(q.shape[:-1], q.dtype)
    acc0 = jnp.zeros_like(q)

    def step(carry, kv_blk, src):
        m, l, acc = carry
        k_blk, v_blk = kv_blk
        s = jnp.matmul(q, k_blk.T, precision=precision) * scale
        if causal:
            # global positions: contiguous layout puts query i at r·lq+i;
            # striped layout at i·n + r (same form for the key block from
            # rank `src`); mask future keys
            lk = k_blk.shape[0]
            if stripe:
                q_pos = jnp.arange(lq) * n + r
                k_pos = jnp.arange(lk) * n + src
            else:
                q_pos = r * lq + jnp.arange(lq)
                k_pos = src * lk + jnp.arange(lk)
            s = jnp.where(
                q_pos[:, None] >= k_pos[None, :], s, -jnp.inf
            )
        m_new, l, p, corr = online_softmax_update(m, l, s)
        acc = acc * corr[:, None] + jnp.matmul(p, v_blk, precision=precision)
        return m_new, l, acc

    m, l, acc = ring_scan(
        step, (m0, l0, acc0), (k, v), axis_name, depth=depth
    )
    return acc / l[:, None]


@functools.lru_cache(maxsize=None)
def ring_attention_fn(
    mesh: Mesh,
    axis_name: str,
    causal: bool = False,
    flash: bool = False,
    interpret: bool | None = None,
    q_tile: int = 256,
    k_tile: int | None = None,
    skip_tile: int | None = None,
    precision=lax.Precision.HIGHEST,
    stripe: bool = False,
    depth: int | None = None,
    tier: str | None = None,
):
    """Jitted ring attention over a sequence sharded along ``axis_name``
    (inputs (L_global, d) sharded on axis 0). ``flash=True`` uses the
    Pallas flash kernel for the local blocks (tiles auto-shrink to divisors
    of the shard length; ``q_tile``/``k_tile`` set the ceilings;
    ``k_tile=None``/``skip_tile=None`` resolve through the schedule
    cache with the measured-best layout tables as priors —
    :data:`MEASURED_BEST_K_TILE` / :data:`MEASURED_BEST_SKIP_TILE`,
    VERDICT r4 #2; README "Autotuning"). ``stripe=True``
    expects/returns the striped causal layout
    (:func:`to_striped`/:func:`from_striped` convert globally).
    ``depth=None`` resolves the K/V prefetch pipeline depth through the
    schedule cache (``ring/pipeline_depth``, prior 1 — README "Overlap
    engine"); results are depth-independent bit for bit. ``tier=None``
    resolves the rotation schedule through the cache (``ring/tier``,
    prior "pipelined"; "fused" = the one-launch fused-RDMA kernel —
    README "Pallas collective tier").

    Choosing ``stripe`` is DTYPE-dependent (BASELINE round-5
    stripebalance dtype note, single-chip paced proxy at lq=4096):
    stripe at f32 (balance speedup 1.42-1.51x over contiguous) but
    keep the contiguous layout at bf16 (striped measured 0.79-0.83x —
    halved matmul work makes the per-cell fixed cost dominate, and
    striped runs w^2 live cells against contiguous's ~w^2/2). The
    measured-best tile tables record the f32 optima."""

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name, None), P(axis_name, None)),
        out_specs=P(axis_name, None),
        check_vma=False,
    )
    def attn(q, k, v):
        return ring_attention(
            q, k, v, axis_name, causal=causal, flash=flash,
            interpret=interpret, q_tile=q_tile, k_tile=k_tile,
            skip_tile=skip_tile, precision=precision, stripe=stripe,
            depth=depth, tier=tier,
        )

    world = mesh.shape[axis_name]

    def attn_recorded(q, k, v):
        # telemetry payload: every rank eventually receives all w−1
        # foreign K/V blocks as they rotate the ring
        kv_bytes = int(getattr(k, "nbytes", 0)) + int(
            getattr(v, "nbytes", 0)
        )
        # rank-pair traffic metadata: the K/V rotation is a ppermute by
        # +1 on a periodic ring — each rank sends its (w−1 rotations of)
        # kv block to exactly one neighbor, so the whole per-rank payload
        # rides the single (r → r+1 mod w) edge
        return span_call(
            "ring_attention", attn, q, k, v,
            nbytes=(world - 1) * kv_bytes,
            axis_name=axis_name, world=world,
            flash=flash, causal=causal, stripe=stripe,
            partners=[1], periodic=True,
            partner_nbytes=(world - 1) * kv_bytes,
            **mesh_partner_links(mesh, axis_name, (1,), True),
        )

    return attn_recorded
