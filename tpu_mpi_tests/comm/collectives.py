"""Collectives over mesh axes: all-gather, allreduce, reduce, barrier.

TPU-native replacement for the reference's MPI collectives (SURVEY.md §2.3):

* ``MPI_Allgather`` on device buffers (``mpi_daxpy_nvtx.cc:285-288``) →
  ``lax.all_gather`` inside ``shard_map`` — XLA compiles it to ICI DMA.
* ``MPI_Allgather(MPI_IN_PLACE, ...)`` (``mpi_daxpy_nvtx.cc:285``,
  ``mpigatherinplace.f90:39-40``) → :func:`all_gather_inplace`: the global
  buffer is already sharded with each device holding its own filled slice
  (the IN_PLACE precondition), gathered functionally with input donation to
  approximate the no-extra-copy property (SURVEY §7 hard part 4).
* in-place device ``MPI_Allreduce(MPI_SUM)`` (``mpi_stencil2d_gt.cc:615-625``)
  → ``lax.psum`` via :func:`allreduce_sum`, donated.
* ``MPI_Reduce(..., 0, ...)`` of scalar metrics (``mpi_stencil2d_gt.cc:
  562-566``) → :func:`reduce_sum` (psum; every process holds the result,
  rank 0 prints — same observable behavior).
* ``MPI_Barrier`` (``mpi_daxpy_nvtx.cc:274-280``) → :func:`barrier`, a
  completed 1-element psum.

All functions are built per-mesh and jitted once; they run identically on
fake CPU devices and TPU slices.
"""

from __future__ import annotations

import functools
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_mpi_tests.compat import shard_map
from tpu_mpi_tests.comm.topology import mesh_link_meta
from tpu_mpi_tests.instrument.telemetry import (
    async_span,
    comm_span,
    span_call,
)
from tpu_mpi_tests.tune import priors as _priors
from tpu_mpi_tests.tune.registry import (
    declare_space,
    resolve as _tune_resolve,
)
from tpu_mpi_tests.utils import TpuMtError, check_divisible

#: the collective dispatch-depth knob (ISSUE 7 tentpole c) — declared
#: here because the chained-collective dispatch pattern lives here;
#: prior 1 = today's per-call sync, byte-identical untuned
COLL_DISPATCH_SPACE = declare_space(
    "coll/dispatch_depth",
    (_priors.COLL_DISPATCH_DEPTH, 2, 4, 8),
    describe="chained collective dispatches allowed in flight before "
             "the window blocks on the oldest",
)


def resolve_dispatch_depth(explicit=None, **ctx) -> int:
    """Dispatch-window depth: explicit > cached winner > prior (1).
    The device-only fallback stays ON (unlike the shape-keyed knobs):
    dispatch depth prices host dispatch/drain latency, which is a
    device/controller property far more than a payload one, so one
    collbench sweep's winner serves every chained site on the machine.
    Malformed cache values degrade to the prior."""
    val = _tune_resolve(
        "coll/dispatch_depth", explicit=explicit,
        prior=_priors.COLL_DISPATCH_DEPTH, **ctx,
    )
    try:
        depth = int(val)
    except (TypeError, ValueError):
        depth = _priors.COLL_DISPATCH_DEPTH
    return max(1, depth)


def _any_deleted(tree) -> bool:
    """True when any jax.Array leaf was deleted (donated to a later
    dispatch) — such a result cannot be blocked on directly."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and leaf.is_deleted():
            return True
    return False


class DispatchWindow:
    """Bound the sync-honesty window of chained collective dispatches.

    Per-call sync (``span_call``) charges every collective its full
    dispatch + drain round-trip; a chained sequence (serve-mode batches,
    halo-exchange chains) can instead keep up to ``depth`` dispatches in
    flight before blocking on the oldest — bounding how stale the
    "measured" window can get instead of syncing per call. ``depth=1``
    degenerates to ``span_call`` per call, byte-identical to the
    pre-window behavior; ``depth=None`` resolves through the schedule
    cache (``coll/dispatch_depth``, prior 1).

    Spans recorded through an open window are dispatch-window spans
    (``async: true`` — :class:`~tpu_mpi_tests.instrument.telemetry.
    AsyncSpan`): their window runs dispatch → drain, NOT the op's
    sync-honest duration. Use as a context manager; exit drains every
    in-flight op so no span is left dangling.
    """

    def __init__(self, depth: int | None = None, **ctx):
        self.depth = resolve_dispatch_depth(depth, **ctx)
        self._inflight: deque = deque()

    def call(self, op: str, fn, *args, nbytes: int = 0,
             axis_name: str | None = None, world: int = 1, **meta):
        """Dispatch ``fn(*args)`` under this window. Depth 1: the
        per-call sync-honest path (``span_call``), unchanged. Depth ≥ 2:
        the op rides an open dispatch-window span; once ``depth`` ops
        are in flight the oldest is drained first."""
        if self.depth <= 1:
            return span_call(
                op, fn, *args, nbytes=nbytes, axis_name=axis_name,
                world=world, **meta,
            )
        handle = async_span(
            op, nbytes=nbytes, axis_name=axis_name, world=world,
            dispatch_depth=self.depth, **meta,
        )
        out = fn(*args)
        self._inflight.append((handle, out))
        while len(self._inflight) >= self.depth:
            self._drain_oldest()
        return out

    def _drain_oldest(self) -> None:
        """Retire the oldest in-flight op. A donating chained fn (the
        normal case: ``x = allreduce(x)``) consumes older outputs as
        later inputs, so the oldest buffer may already be deleted and
        cannot be blocked on directly; in-order dispatch means the
        first STILL-LIVE result's completion proves everything before
        it completed, so the window blocks once there and closes every
        span it vouches for. Non-donating chains degrade to the classic
        block-the-oldest; donating chains sync once per ``depth`` calls
        — the bounded-window cadence this knob exists to buy."""
        live = next(
            (i for i, (_, res) in enumerate(self._inflight)
             if not _any_deleted(res)),
            None,
        )
        if live is None:
            # every in-flight result was donated by work dispatched
            # OUTSIDE the window: nothing left to block on — close the
            # spans at the drain point without a sync (the external
            # consumer's own sync is the only remaining observation
            # point; crashing the drain would be worse than the
            # slightly-early close)
            while self._inflight:
                h, _ = self._inflight.popleft()
                h.done(None)
            return
        target = self._inflight[live][1]
        for _ in range(live + 1):
            h, res = self._inflight.popleft()
            h.done(res if not _any_deleted(res) else target)

    def drain(self) -> None:
        """Block on every in-flight op (closing its span) — the window's
        consume point; idempotent."""
        while self._inflight:
            self._drain_oldest()

    def __enter__(self) -> "DispatchWindow":
        return self

    def __exit__(self, *exc) -> None:
        self.drain()


def shard_1d(arr, mesh: Mesh, axis_name: str | None = None, axis: int = 0):
    """Place a global array sharded along ``axis`` over ``axis_name``
    (≅ each rank holding its block of the decomposed global array)."""
    axis_name = axis_name or mesh.axis_names[0]
    spec = [None] * getattr(arr, "ndim", 1)
    spec[axis] = axis_name
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def replicate(arr, mesh: Mesh):
    return jax.device_put(arr, NamedSharding(mesh, P()))


def shard_blocks(
    mesh: Mesh,
    global_shape,
    dtype,
    block_fn,
    axis_name: str | None = None,
    axis: int = 0,
    sharding=None,
):
    """Build a sharded global array from per-rank host blocks WITHOUT ever
    materializing the global array on host (≅ each MPI rank initializing
    only its local block — the reference never holds the global domain
    anywhere, e.g. ``mpi_stencil2d_gt.cc:445-456``).

    ``block_fn(rank)`` returns the numpy block owned by logical rank
    ``rank`` along ``axis``. Works multi-host: the callback runs only for
    addressable shards.
    """
    axis_name = axis_name or mesh.axis_names[0]
    if sharding is None:
        spec = [None] * len(global_shape)
        spec[axis] = axis_name
        sharding = NamedSharding(mesh, P(*spec))
    n_shards = mesh.shape[axis_name]
    # fail fast, like the reference's early divisibility exits
    # (mpi_stencil_gt.cc:141-145): a floor-divided block_len would silently
    # misattribute ranks and mis-assemble the array
    block_len = check_divisible(
        global_shape[axis], n_shards, f"shard_blocks axis {axis}"
    )

    def cb(index):
        for d, sl in enumerate(index):
            if d == axis:
                continue
            full = (sl.start or 0) == 0 and sl.stop in (None, global_shape[d])
            if not full:
                raise TpuMtError(
                    "shard_blocks: sharding partitions dim "
                    f"{d} but only the block axis {axis} may be decomposed "
                    "(rank inference would be wrong)"
                )
        start = index[axis].start or 0
        return np.asarray(block_fn(start // block_len), dtype=dtype)

    return jax.make_array_from_callback(tuple(global_shape), sharding, cb)


def device_init(
    mesh: Mesh,
    block_fn,
    axis_name: str | None = None,
    axis: int = 0,
    ndim: int = 2,
    sharding=None,
):
    """Build a sharded global array by computing each shard ON ITS DEVICE:
    ``block_fn(rank)`` is traced with the shard's logical rank index.

    The device-side twin of :func:`shard_blocks` — at multi-GB sizes
    host→device transfer dominates everything (333 s for one 2.2 GB shard
    over a tunneled controller); analytic fields belong on chip.
    """
    axis_name = axis_name or mesh.axis_names[0]
    spec = [None] * ndim
    spec[axis] = axis_name

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(), out_specs=P(*spec),
        check_vma=False,
    )
    def init():
        return block_fn(lax.axis_index(axis_name))

    out = init()
    if sharding is not None:
        out = jax.device_put(out, sharding)
    return out


@functools.lru_cache(maxsize=None)
def _per_rank_sq_diff_fn(mesh: Mesh, axis_name: str, axis: int, ndim: int):
    spec = [None] * ndim
    spec[axis] = axis_name

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(*spec), P(*spec)),
        out_specs=P(axis_name), check_vma=False,
    )
    def f(a, b):
        d = a - b
        return jnp.sum(d * d).reshape(1)

    return f


def per_rank_err_norms(
    numeric, actual, mesh: Mesh, axis_name: str | None = None, axis: int = 0
) -> np.ndarray:
    """Per-logical-rank ``sqrt(Σ(numeric − actual)²)`` computed shard-local
    on device (≅ each rank's err_norm, ``mpi_stencil_gt.cc:222``), gathered
    as one tiny vector — the global fields are never replicated."""
    axis_name = axis_name or mesh.axis_names[0]
    s = _per_rank_sq_diff_fn(mesh, axis_name, axis, numeric.ndim)(
        numeric, actual
    )
    return np.sqrt(
        host_value(all_gather(s, mesh, axis_name)).reshape(-1)
    )


@functools.lru_cache(maxsize=None)
def _all_gather_fn(mesh: Mesh, axis_name: str, axis: int, ndim: int):
    spec = [None] * ndim
    spec[axis] = axis_name

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P(*spec),
        out_specs=P(),
        # all_gather output is replicated by construction; static vma
        # inference can't prove it on Auto-typed meshes
        check_vma=False,
    )
    def gather(x):
        return lax.all_gather(x, axis_name, axis=axis, tiled=True)

    return gather


def _gather_payload_bytes(x, world: int) -> int:
    """Telemetry payload convention for gather-like collectives: total
    bytes received across ranks (each rank receives all w−1 foreign
    shards) — the aggregate the run summary turns into GB/s."""
    return (world - 1) * int(getattr(x, "nbytes", 0))


def all_gather(x_sharded, mesh: Mesh, axis_name: str | None = None,
               axis: int = 0):
    """Replicate a sharded array on every device (≅ ``MPI_Allgather`` of
    each rank's shard into a full copy per rank)."""
    axis_name = axis_name or mesh.axis_names[0]
    world = mesh.shape[axis_name]
    return span_call(
        "all_gather",
        _all_gather_fn(mesh, axis_name, axis, x_sharded.ndim),
        x_sharded,
        nbytes=_gather_payload_bytes(x_sharded, world),
        axis_name=axis_name,
        world=world,
        **mesh_link_meta(mesh, axis_name),
    )


@functools.lru_cache(maxsize=None)
def _all_gather_inplace_fn(mesh: Mesh, axis_name: str, axis: int, ndim: int):
    spec = [None] * ndim
    spec[axis] = axis_name

    @functools.partial(jax.jit, donate_argnums=0)
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(),
        check_vma=False,
    )
    def gather(x):
        return lax.all_gather(x, axis_name, axis=axis, tiled=True)

    return gather


@functools.lru_cache(maxsize=None)
def _all_gather_rdma_fn(mesh: Mesh, axis_name: str, ndim: int,
                        interpret: bool | None):
    from tpu_mpi_tests.kernels.pallas_kernels import ring_allgather_pallas

    spec = [None] * ndim
    spec[0] = axis_name

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(),
        check_vma=False,
    )
    def gather(x):
        return ring_allgather_pallas(
            x, axis_name=axis_name, interpret=interpret
        )

    return gather


def all_gather_rdma(x_sharded, mesh: Mesh, axis_name: str | None = None,
                    interpret: bool | None = None):
    """Hand-tier ``all_gather`` (axis 0, tiled): the explicit-RDMA ring
    twin of :func:`all_gather`, completing the dual-tier pattern for the
    collective pillar (≅ hand-writing the ``MPI_Allgather`` of
    ``mpi_daxpy_nvtx.cc:285-288`` as w−1 ring hops; SURVEY §5.8)."""
    axis_name = axis_name or mesh.axis_names[0]
    world = mesh.shape[axis_name]
    from tpu_mpi_tests.instrument.watchdog import note_comm_op

    note_comm_op(
        f"ring_allgather_pallas(world={world}, "
        f"shape={tuple(x_sharded.shape)})"
    )
    return span_call(
        "all_gather_rdma",
        _all_gather_rdma_fn(mesh, axis_name, x_sharded.ndim, interpret),
        x_sharded,
        nbytes=_gather_payload_bytes(x_sharded, world),
        axis_name=axis_name,
        world=world,
        **mesh_link_meta(mesh, axis_name),
    )


@functools.lru_cache(maxsize=None)
def _all_gather_oneshot_fn(mesh: Mesh, axis_name: str, ndim: int,
                           interpret: bool | None):
    from tpu_mpi_tests.kernels.collectives_pallas import (
        oneshot_allgather_pallas,
    )

    spec = [None] * ndim
    spec[0] = axis_name

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(),
        check_vma=False,
    )
    def gather(x):
        return oneshot_allgather_pallas(
            x, axis_name=axis_name, interpret=interpret
        )

    return gather


def all_gather_oneshot(x_sharded, mesh: Mesh,
                       axis_name: str | None = None,
                       interpret: bool | None = None):
    """Fixed-cost tier ``all_gather`` (axis 0, tiled): ONE in-kernel
    all-to-all DMA burst instead of the ring tier's w−1 dependent hops
    (:func:`all_gather_rdma`) or the XLA tier's dispatch
    (:func:`all_gather`) — the latency-optimal schedule for
    decode-shape payloads, where every hop is pure fixed cost
    (ISSUE 19; ``kernels/collectives_pallas.py``)."""
    axis_name = axis_name or mesh.axis_names[0]
    world = mesh.shape[axis_name]
    from tpu_mpi_tests.instrument.watchdog import note_comm_op

    note_comm_op(
        f"oneshot_allgather_pallas(world={world}, "
        f"shape={tuple(x_sharded.shape)})"
    )
    return span_call(
        "all_gather_oneshot",
        _all_gather_oneshot_fn(mesh, axis_name, x_sharded.ndim, interpret),
        x_sharded,
        nbytes=_gather_payload_bytes(x_sharded, world),
        axis_name=axis_name,
        world=world,
        **mesh_link_meta(mesh, axis_name),
    )


def all_gather_inplace(allx_sharded, mesh: Mesh, axis_name: str | None = None,
                       axis: int = 0):
    """``MPI_Allgather(MPI_IN_PLACE)`` parity: input is the full-size global
    buffer sharded so each device holds its own (already filled) slice;
    output is the replicated gathered buffer. The input is donated so XLA may
    reuse its memory — the closest functional analog of in-place semantics
    with immutable arrays."""
    axis_name = axis_name or mesh.axis_names[0]
    world = mesh.shape[axis_name]
    nbytes = _gather_payload_bytes(allx_sharded, world)
    return span_call(
        "all_gather_inplace",
        _all_gather_inplace_fn(mesh, axis_name, axis, allx_sharded.ndim),
        allx_sharded,
        nbytes=nbytes,
        axis_name=axis_name,
        world=world,
        **mesh_link_meta(mesh, axis_name),
    )


@functools.lru_cache(maxsize=None)
def _allreduce_fn(mesh: Mesh, axis_name: str, ndim: int):
    spec = [axis_name] + [None] * (ndim - 1)

    @functools.partial(jax.jit, donate_argnums=0)
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(*spec)
    )
    def reduce(x):
        return lax.psum(x, axis_name)

    return reduce


def allreduce_sum(per_rank, mesh: Mesh, axis_name: str | None = None):
    """In-place device ``MPI_Allreduce(MPI_SUM)`` parity
    (``mpi_stencil2d_gt.cc:615-625``): every logical rank holds an
    equal-length vector; afterwards every rank's buffer holds the elementwise
    sum. ``per_rank`` has shape ``(n_ranks, L)`` sharded on axis 0 (one row
    per rank); the result has the same shape/sharding with every row replaced
    by the sum — the donated input approximates the in-place reuse."""
    axis_name = axis_name or mesh.axis_names[0]
    n = mesh.shape[axis_name]
    if per_rank.shape[0] != n:
        raise ValueError(
            f"allreduce_sum: leading axis {per_rank.shape[0]} must equal "
            f"mesh axis {axis_name}={n} (one row per rank)"
        )
    # ring-allreduce payload: each rank moves 2(w−1)/w of its row,
    # aggregated over ranks = 2(w−1)·row bytes
    row_bytes = int(getattr(per_rank, "nbytes", 0)) // n
    return span_call(
        "allreduce",
        _allreduce_fn(mesh, axis_name, per_rank.ndim),
        per_rank,
        nbytes=2 * (n - 1) * row_bytes,
        axis_name=axis_name,
        world=n,
        **mesh_link_meta(mesh, axis_name),
    )


@functools.lru_cache(maxsize=None)
def _reduce_scatter_fn(mesh: Mesh, axis_name: str, ndim: int):
    spec = [axis_name] + [None] * (ndim - 1)

    @functools.partial(jax.jit, donate_argnums=0)
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(*spec),
        check_vma=False,
    )
    def scatter(x):
        return lax.psum_scatter(
            x[0], axis_name, scatter_dimension=0, tiled=True
        )[None]

    return scatter


def reduce_scatter_sum(per_rank, mesh: Mesh, axis_name: str | None = None):
    """XLA-tier reduce-scatter (``lax.psum_scatter``): rank ``r`` gets
    chunk ``r`` of the elementwise sum — the library twin of
    :func:`~tpu_mpi_tests.kernels.pallas_kernels.ring_reduce_scatter_pallas`
    and the first half of the ring-allreduce decomposition
    (≅ ``MPI_Reduce_scatter_block``, the collective MPI composes
    ``MPI_Allreduce`` from). ``per_rank`` has shape ``(n_ranks, L)``
    sharded on axis 0 with ``L % n_ranks == 0``; returns ``(n_ranks,
    L/n_ranks)`` with the same sharding, row ``r`` = chunk ``r`` of the
    sum."""
    axis_name = axis_name or mesh.axis_names[0]
    n = mesh.shape[axis_name]
    if per_rank.ndim != 2 or per_rank.shape[0] != n:
        raise ValueError(
            f"reduce_scatter_sum: need shape (n_ranks={n}, L), got "
            f"{per_rank.shape}"
        )
    check_divisible(per_rank.shape[1], n, "reduce_scatter_sum chunking")
    row_bytes = int(getattr(per_rank, "nbytes", 0)) // n
    return span_call(
        "reduce_scatter",
        _reduce_scatter_fn(mesh, axis_name, per_rank.ndim),
        per_rank,
        nbytes=(n - 1) * row_bytes,
        axis_name=axis_name,
        world=n,
        **mesh_link_meta(mesh, axis_name),
    )


@functools.lru_cache(maxsize=None)
def _allreduce_rdma_fn(mesh: Mesh, axis_name: str,
                       interpret: bool | None, credits: int = 1):
    from tpu_mpi_tests.kernels.pallas_kernels import ring_allreduce_pallas

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis_name),
        out_specs=P(axis_name), check_vma=False,
    )
    def reduce(x):
        # shard is this logical rank's (1, L) row; the ring runs on the row
        return ring_allreduce_pallas(
            x[0], axis_name=axis_name, interpret=interpret,
            credits=credits,
        )[None]

    return reduce


def allreduce_rdma(per_rank, mesh: Mesh, axis_name: str | None = None,
                   interpret: bool | None = None, credits: int = 1):
    """Hand-tier :func:`allreduce_sum`: explicit-RDMA ring reduce-scatter +
    all-gather instead of ``lax.psum`` (≅ hand-writing the in-place device
    ``MPI_Allreduce(MPI_SUM)`` of ``mpi_stencil2d_gt.cc:615-625`` as
    2(w−1) ring hops; SURVEY §5.8). Same contract as :func:`allreduce_sum`
    (``(n_ranks, L)`` sharded on axis 0 → every row the elementwise sum);
    ``L`` must satisfy the ring kernels' lane alignment
    (``L % (w·128·sublane) == 0``). ``credits=2`` selects the
    double-buffered reduce-scatter (the pod-latency variant — see
    ``ring_reduce_scatter_pallas``)."""
    axis_name = axis_name or mesh.axis_names[0]
    n = mesh.shape[axis_name]
    if per_rank.ndim != 2 or per_rank.shape[0] != n:
        raise ValueError(
            f"allreduce_rdma: need shape (n_ranks={n}, L), got "
            f"{per_rank.shape}"
        )
    from tpu_mpi_tests.instrument.watchdog import note_comm_op

    note_comm_op(
        f"ring_allreduce_pallas(world={n}, shape={tuple(per_rank.shape)}, "
        f"credits={credits})"
    )
    row_bytes = int(getattr(per_rank, "nbytes", 0)) // n
    return span_call(
        "allreduce_rdma",
        _allreduce_rdma_fn(mesh, axis_name, interpret, credits),
        per_rank,
        nbytes=2 * (n - 1) * row_bytes,
        axis_name=axis_name,
        world=n,
        **mesh_link_meta(mesh, axis_name),
    )


@functools.lru_cache(maxsize=None)
def _allreduce_oneshot_fn(mesh: Mesh, axis_name: str,
                          interpret: bool | None):
    from tpu_mpi_tests.kernels.collectives_pallas import (
        oneshot_allreduce_pallas,
    )

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis_name),
        out_specs=P(axis_name), check_vma=False,
    )
    def reduce(x):
        # shard is this logical rank's (1, L) row; the one-shot burst
        # runs on the row
        return oneshot_allreduce_pallas(
            x[0], axis_name=axis_name, interpret=interpret
        )[None]

    return reduce


def allreduce_oneshot(per_rank, mesh: Mesh, axis_name: str | None = None,
                      interpret: bool | None = None):
    """Fixed-cost tier :func:`allreduce_sum`: ONE in-kernel all-to-all
    DMA burst + a local ascending-src-order fold instead of the rdma
    ring's 2(w−1) dependent hops (:func:`allreduce_rdma`) — the
    latency-optimal small-payload schedule (ISSUE 19). Same contract
    (``(n_ranks, L)`` sharded on axis 0 → every row the elementwise
    sum); NO alignment floor — shards are zero-padded to the DMA tile
    in-kernel-wrapper (``kernels/collectives_pallas.py``), which is
    what lets this tier reach the decode payloads the ring rejects.
    The fold order is fixed and rank-independent, so the result is
    bitwise ``reduce(add, rows)`` on every rank."""
    axis_name = axis_name or mesh.axis_names[0]
    n = mesh.shape[axis_name]
    if per_rank.ndim != 2 or per_rank.shape[0] != n:
        raise ValueError(
            f"allreduce_oneshot: need shape (n_ranks={n}, L), got "
            f"{per_rank.shape}"
        )
    from tpu_mpi_tests.instrument.watchdog import note_comm_op

    note_comm_op(
        f"oneshot_allreduce_pallas(world={n}, "
        f"shape={tuple(per_rank.shape)})"
    )
    row_bytes = int(getattr(per_rank, "nbytes", 0)) // n
    return span_call(
        "allreduce_oneshot",
        _allreduce_oneshot_fn(mesh, axis_name, interpret),
        per_rank,
        # one-shot payload: each rank ships its whole row to w−1 peers
        nbytes=(n - 1) * row_bytes,
        axis_name=axis_name,
        world=n,
        **mesh_link_meta(mesh, axis_name),
    )


def host_value(x) -> np.ndarray:
    """Fetch an array to host safely on every process.

    ``np.asarray`` raises for arrays spanning non-addressable devices
    (multi-host); fully-replicated arrays are read from the local replica
    instead. Partially-sharded multi-host arrays must be gathered first
    (use :func:`all_gather`)."""
    if not isinstance(x, jax.Array) or x.is_fully_addressable:
        return np.asarray(x)
    if x.is_fully_replicated:
        return np.asarray(x.addressable_data(0))
    raise ValueError(
        "array spans non-addressable devices and is not replicated; "
        "all_gather it before reading host-side"
    )


@functools.lru_cache(maxsize=None)
def _per_rank_sums_fn(mesh: Mesh, axis_name: str, ndim: int, groups: int):
    spec = [None] * ndim
    spec[0] = axis_name

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(*spec), out_specs=P(axis_name),
        check_vma=False,
    )
    def local_sum(x):
        # `groups` logical ranks per shard (oversubscription emulation,
        # SURVEY §7 hard part 5: multiple MPI ranks per device become
        # multiple logical blocks per chip inside one program)
        return jnp.sum(x.reshape(groups, -1), axis=1)

    return local_sum


def per_rank_sums(
    x_sharded,
    mesh: Mesh,
    axis_name: str | None = None,
    groups_per_shard: int = 1,
):
    """Per-logical-rank local sums, replicated so every process can read
    them (≅ each rank computing its local checksum,
    ``mpi_daxpy_nvtx.cc:251-267``). With ``groups_per_shard = k`` each
    device carries ``k`` logical ranks (the reference's
    ``ranks_per_device`` oversubscription, ``mpi_daxpy.cc:49-51``).

    Returns a host numpy vector of length ``mesh.shape[axis_name] * k``.
    """
    axis_name = axis_name or mesh.axis_names[0]
    sums = _per_rank_sums_fn(
        mesh, axis_name, x_sharded.ndim, groups_per_shard
    )(x_sharded)
    return host_value(all_gather(sums, mesh, axis_name))


def reduce_sum(values) -> float:
    """Cross-process scalar metric reduction
    (≅ ``MPI_Reduce(..., MPI_SUM, 0, ...)``, ``mpi_stencil2d_gt.cc:562-566``).

    ``values`` are this process's host-side partial scalars (e.g. per-logical-
    rank iteration times). Single-process: a plain sum. Multi-process: summed
    across processes via a device collective; every process returns the same
    total (rank 0 is simply the one that prints).

    Full float64 end to end (the reference reduces times/errors as
    ``MPI_DOUBLE``): the cross-process hop ships the raw 8-byte pattern as
    two uint32 lanes — allgather moves bits, no arithmetic — so precision
    survives even when ``jax_enable_x64`` is off (where a float64 device
    array would silently downcast to f32)."""
    total = float(np.sum(np.asarray(values, dtype=np.float64)))
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        with comm_span(
            "reduce_sum", nbytes=8 * jax.process_count(),
            world=jax.process_count(),
        ) as span:
            bits = np.frombuffer(np.float64(total).tobytes(), np.uint32)
            gathered = multihost_utils.process_allgather(jnp.asarray(bits))
            span.result = gathered
        vals = np.ascontiguousarray(
            np.asarray(gathered, np.uint32).reshape(-1, 2)
        ).view(np.float64)
        total = float(np.sum(vals))
    return total


def barrier(mesh: Mesh):
    """≅ ``MPI_Barrier``: a completed collective across the mesh."""
    axis_name = mesh.axis_names[0]
    with comm_span(
        "barrier", axis_name=axis_name, world=mesh.shape[axis_name]
    ) as span:
        x = shard_1d(jnp.ones((len(mesh.devices.flat),), jnp.int32), mesh)
        out = _allreduce_fn(mesh, axis_name, 1)(x)
        out.block_until_ready()
        span.result = out
