"""Host/link-class topology discovery and span link attribution.

The reference probes machine structure as a first-class signal — node
count via ``MPI_Comm_split_type(MPI_COMM_TYPE_SHARED)``
(``mpi_daxpy_nvtx.cc:72-82``) — because the link a message rides
(shared memory vs network there; same-host ICI vs cross-host DCN here)
dominates its cost at scale. This module is the discovery half: read
the live device list once, group ranks into hosts (``process_index``)
and slices (``slice_index``, only when EVERY device reports one), and
classify every directed rank pair into a link class::

    self < intra_host < inter_host < inter_slice

ordered by strength — the strongest class a collective group crosses is
the link that prices it.

Degrade contract (the memwatch convention): fabricated devices and
backends that report no ``process_index`` yield a *declared* ``flat``
topology — host/slice fields ABSENT, never guessed — and every
downstream stamp helper returns ``{}`` for a flat topology, so
single-host/CPU runs keep their JSONL spans and report shape
byte-identical.

Stamping is resolved at wrapper-build time (:func:`mesh_link_meta` /
:func:`mesh_partner_links` are lru-cached per ``(mesh, axis)``), so the
per-call comm path pays zero topology cost — the same budget rule as
the telemetry spans themselves.

Pure-python core: :func:`discover` and :class:`TopologyMap` take any
device-like sequence (objects with ``process_index``), so tests drive
multi-host classification with fabricated device lists and no backend.
"""

from __future__ import annotations

import collections
import dataclasses
import functools

#: link classes, weakest to strongest — index order IS the strength
#: ordering (the strongest pair class prices a collective group)
LINK_CLASSES = ("self", "intra_host", "inter_host", "inter_slice")

_STRENGTH = {c: i for i, c in enumerate(LINK_CLASSES)}


def stronger(a: str, b: str) -> str:
    """The stronger (more expensive) of two link classes."""
    return a if _STRENGTH[a] >= _STRENGTH[b] else b


@dataclasses.dataclass(frozen=True)
class TopologyMap:
    """Discovered rank→host/slice structure for one device ordering.

    ``hosts``/``slices`` are per-rank group indices in device order
    (rank = position, the same rank space ``mpirun -np N`` ≅
    fake-devices uses everywhere else). ``None`` means the axis was not
    reported — a declared-flat degrade, not a measured single group.
    """

    world: int
    hosts: tuple[int, ...] | None
    slices: tuple[int, ...] | None
    declared: str  # "discovered" | "flat"

    @property
    def num_hosts(self) -> int:
        return len(set(self.hosts)) if self.hosts else 1

    @property
    def num_slices(self) -> int:
        return len(set(self.slices)) if self.slices else 1

    @property
    def ranks_per_host(self) -> int | None:
        """Uniform ranks-per-host, or ``None`` when ragged (a ragged
        shape has no honest single number — absent, never averaged)."""
        if not self.hosts:
            return None
        counts = set(collections.Counter(self.hosts).values())
        return counts.pop() if len(counts) == 1 else None

    @property
    def is_flat(self) -> bool:
        """One host, one slice (measured or declared): nothing to
        attribute — every stamp helper goes silent."""
        return self.num_hosts <= 1 and self.num_slices <= 1

    def link_class(self, a: int, b: int) -> str:
        """Directed-pair link class for ranks ``a``→``b``. With no
        host/slice info every cross-rank pair reads ``intra_host``
        (callers gate on :attr:`is_flat` before stamping, so the
        single-group read is only reachable by direct query)."""
        if a == b:
            return "self"
        if self.slices is not None and self.slices[a] != self.slices[b]:
            return "inter_slice"
        if self.hosts is not None and self.hosts[a] != self.hosts[b]:
            return "inter_host"
        return "intra_host"

    def classes(self) -> tuple[str, ...]:
        """Cross-rank link classes present, weakest first — computed
        from the group structure, not an O(world²) pair sweep."""
        if self.world <= 1:
            return ()
        hosts = self.hosts or (0,) * self.world
        slices = self.slices or (0,) * self.world
        groups = collections.Counter(zip(slices, hosts))
        hosts_by_slice: dict[int, set[int]] = {}
        for s, h in groups:
            hosts_by_slice.setdefault(s, set()).add(h)
        present = set()
        if any(n >= 2 for n in groups.values()):
            present.add("intra_host")
        if any(len(hs) >= 2 for hs in hosts_by_slice.values()):
            present.add("inter_host")
        if len(hosts_by_slice) >= 2:
            present.add("inter_slice")
        return tuple(c for c in LINK_CLASSES if c in present)

    def label(self) -> str:
        """Canonical shape label: ``h{hosts}x{ranks_per_host}``
        (``h2x4``), ``h{hosts}`` when ragged, ``s{slices}`` prefix when
        a multi-slice axis is reported, ``flat`` otherwise — the token
        bench schedule strings and pack provenance carry."""
        if self.is_flat:
            return "flat"
        rph = self.ranks_per_host
        lbl = f"h{self.num_hosts}" + (f"x{rph}" if rph else "")
        if self.num_slices > 1:
            lbl = f"s{self.num_slices}" + lbl
        return lbl


def discover(devices=None) -> TopologyMap:
    """Build a :class:`TopologyMap` from a device list (default: the
    live ``jax.devices()``). Any device missing an integer
    ``process_index`` declares the whole topology flat — fields absent,
    never guessed; ``slice_index`` contributes only when every device
    reports one."""
    if devices is None:
        import jax

        devices = jax.devices()
    devices = list(devices)
    world = len(devices)
    hosts: list[int] = []
    for d in devices:
        try:
            p = getattr(d, "process_index", None)
        except Exception:
            p = None
        if not isinstance(p, int) or isinstance(p, bool):
            return TopologyMap(world=world, hosts=None, slices=None,
                               declared="flat")
        hosts.append(p)
    slices: list[int] | None = []
    for d in devices:
        try:
            s = getattr(d, "slice_index", None)
        except Exception:
            s = None
        if not isinstance(s, int) or isinstance(s, bool):
            slices = None
            break
        slices.append(s)
    return TopologyMap(
        world=world,
        hosts=tuple(hosts),
        slices=tuple(slices) if slices else None,
        declared="discovered",
    )


@functools.lru_cache(maxsize=None)
def current() -> TopologyMap:
    """The live backend's topology, probed once per process (tests
    monkeypatching the device list must ``current.cache_clear()``)."""
    return discover()


def topo_record(topo: TopologyMap | None = None) -> dict:
    """The auditable ``kind:"topo"`` JSONL record (manifest-adjacent,
    emitted by ``make_reporter``): world, shape label, host/slice
    grouping, and the link classes present. Host/slice fields are
    ABSENT (not null) on a flat or declared-flat topology."""
    topo = current() if topo is None else topo
    rec = {
        "kind": "topo",
        "world": topo.world,
        "topology": topo.label(),
        "declared": topo.declared,
        "hosts": topo.num_hosts if topo.hosts is not None else None,
        "ranks_per_host": (topo.ranks_per_host
                           if topo.hosts is not None else None),
        "host_by_rank": (list(topo.hosts)
                         if topo.hosts is not None else None),
        "slices": topo.num_slices if topo.slices is not None else None,
        # a declared-flat topology MEASURED nothing — claiming
        # intra_host for its pairs would be the single-group guess the
        # degrade contract forbids
        "link_classes": (list(topo.classes()) or None
                         if topo.declared == "discovered" else None),
    }
    return {k: v for k, v in rec.items() if v is not None}


def _axis_rings(mesh, axis_name: str):
    """The device rings one mesh axis's collectives run over: every
    1-D group along ``axis_name`` (other axes fixed), as rows."""
    import numpy as np

    ax = list(mesh.axis_names).index(axis_name)
    moved = np.moveaxis(mesh.devices, ax, -1)
    return moved.reshape(-1, moved.shape[-1])


def _ring_topos(mesh, axis_name: str) -> list[TopologyMap] | None:
    """Per-ring positional topologies for one mesh axis, or ``None``
    when the mesh's own devices form a flat topology (the stamp gate)."""
    try:
        rings = _axis_rings(mesh, axis_name)
    except Exception:
        return None
    if discover([d for ring in rings for d in ring]).is_flat:
        return None
    return [discover(list(ring)) for ring in rings]


@functools.lru_cache(maxsize=None)
def mesh_link_meta(mesh, axis_name: str) -> dict:
    """``{"link": cls}`` for collective spans over ``axis_name`` — the
    strongest link class any collective group on that axis crosses —
    or ``{}`` when the mesh's devices form a flat topology (flat runs
    stamp nothing; spans stay byte-identical). Resolved once per
    ``(mesh, axis)`` at wrapper-build time."""
    topos = _ring_topos(mesh, axis_name)
    if topos is None:
        return {}
    cls = None
    for t in topos:
        present = t.classes()
        if present:
            c = present[-1]
            cls = c if cls is None else stronger(cls, c)
    return {"link": cls} if cls else {}


@functools.lru_cache(maxsize=None)
def mesh_partner_links(
    mesh, axis_name: str, partners: tuple, periodic: bool,
) -> dict:
    """Per-offset link classes for neighbor-exchange spans:
    ``{"partner_link": [cls per offset], "link": strongest}`` parallel
    to the ``partners`` ring-offset metadata (anatomy's
    ``partner_edges`` order), or ``{}`` on a flat topology. Each
    offset's class is the strongest pair class any rank's edge at that
    offset crosses — the honest scalar for a span that aggregates every
    local edge."""
    topos = _ring_topos(mesh, axis_name)
    if topos is None:
        return {}
    links = []
    for d in partners:
        cls = None
        for t in topos:
            n = t.world
            for i in range(n):
                j = i + int(d)
                if periodic:
                    j %= n
                elif not (0 <= j < n):
                    continue
                c = t.link_class(i, j)
                cls = c if cls is None else stronger(cls, c)
        links.append(cls or "self")
    strongest = links[0]
    for c in links[1:]:
        strongest = stronger(strongest, c)
    return {"partner_link": links, "link": strongest}
