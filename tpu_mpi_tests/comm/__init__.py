"""Communication layer: mesh bootstrap, collectives, halo exchange.

TPU-native replacement for the reference's MPI layer (SURVEY.md §2.3, §5.8):
`jax.distributed` + `jax.sharding.Mesh` replace `MPI_Init`/communicators,
XLA collectives (`ppermute`/`psum`/`all_gather`) over ICI replace CUDA-aware
MPI point-to-point and collective calls.
"""

from tpu_mpi_tests.comm.mesh import (  # noqa: F401
    Topology,
    bootstrap,
    make_mesh,
    topology,
)
from tpu_mpi_tests.comm.topology import (  # noqa: F401
    LINK_CLASSES,
    TopologyMap,
    discover,
)
