"""Sharded embedding gather/scatter: the table-lookup comm pillar.

The ``gather_inplace`` pillar generalized to what production inference
actually runs (ROADMAP item 4): a ``(vocab, d_model)`` table too large
to replicate lives row-sharded across the mesh, a batch of token ids
must come back as dense rows, and the training-side dual pushes
gradient rows back into the owning shards. Communication shapes:

* **lookup** — each rank resolves the ids that land in its row range
  locally (foreign ids contribute zeros) and one ``psum`` assembles the
  replicated ``(B, d_model)`` result: the allreduce-of-partials
  formulation XLA lowers sharded ``take`` to;
* **scatter-add** — ids/updates arrive batch-sharded, one
  ``all_gather`` replicates them, and each rank scatter-adds only the
  rows it owns (duplicate ids accumulate, ``.at[].add`` semantics).

The *local* gather is a tunable schedule (``embedding/lookup``):
``take`` (dynamic gather rows) vs ``onehot`` (a one-hot matmul — the
classic TPU alternative that trades FLOPs for the MXU's streaming
access pattern; measured-better for small vocab shards). The knob is
fingerprint-keyed (dtype × vocab bucket × batch bucket × world) and
resolves explicit > cached > prior like every schedule since PR 4; a
``--tune`` run prices both on this table before persisting the winner.

Verified against the dense host reference in ``tests/test_moe.py`` /
the embedding workload spec — lookups are copies and the scatter sums
integer-valued rows, so equality is exact in every dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_mpi_tests.compat import shard_map
from tpu_mpi_tests.comm.topology import mesh_link_meta
from tpu_mpi_tests.instrument.telemetry import span_call
from tpu_mpi_tests.tune import priors as _priors
from tpu_mpi_tests.tune.registry import (
    declare_space,
    resolve as _tune_resolve,
)
from tpu_mpi_tests.utils import check_divisible

#: local-gather schedule knob — declared here because the lookup lives
#: here; prior "take" (the dynamic-gather lowering)
EMBED_LOOKUP_SPACE = declare_space(
    "embedding/lookup",
    (_priors.EMBED_LOOKUP, "onehot"),
    describe="sharded embedding local gather: dynamic take vs one-hot "
             "matmul",
)


def resolve_lookup(explicit=None, **ctx) -> str:
    """Lookup variant: explicit > cached winner > prior ("take").
    ``device_fallback=False``: the optimum is shape-keyed (the one-hot
    matmul is O(B·V_local) — a small-vocab winner is measured-wrong at
    a large shard). Malformed cache values degrade to the prior."""
    val = _tune_resolve(
        "embedding/lookup", explicit=explicit, prior=_priors.EMBED_LOOKUP,
        device_fallback=False, **ctx,
    )
    return val if val in ("take", "onehot") else _priors.EMBED_LOOKUP


@functools.lru_cache(maxsize=None)
def _lookup_fn(mesh: Mesh, axis_name: str, variant: str):
    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis_name, None), P()),
        out_specs=P(),
        check_vma=False,
    )
    def lookup(table, ids):
        v_local = table.shape[0]
        base = lax.axis_index(axis_name) * v_local
        local = ids.astype(jnp.int32) - base
        ok = (local >= 0) & (local < v_local)
        if variant == "onehot":
            oh = (local[:, None] == jnp.arange(v_local,
                                               dtype=jnp.int32)[None, :])
            oh = (oh & ok[:, None]).astype(table.dtype)
            rows = oh @ table
        else:  # take
            rows = table[jnp.clip(local, 0, v_local - 1)]
            rows = rows * ok[:, None].astype(table.dtype)
        return lax.psum(rows, axis_name)

    return lookup


def embedding_lookup(table, ids, mesh: Mesh, axis_name: str | None = None,
                     variant: str | None = None):
    """Gather ``table[ids]`` from a row-sharded table: ``table`` is
    ``(V, D)`` sharded on axis 0, ``ids`` a replicated int vector;
    returns the replicated ``(B, D)`` rows. Payload model: the psum of
    partial rows, allreduce accounting (``2(w−1)·B·D`` bytes aggregate)."""
    axis_name = axis_name or mesh.axis_names[0]
    world = mesh.shape[axis_name]
    check_divisible(table.shape[0], world, "embedding rows over mesh axis")
    variant = resolve_lookup(
        variant, dtype=str(table.dtype), n=table.shape[0],
        bytes=int(ids.shape[0]), world=world,
    )
    row_bytes = int(ids.shape[0]) * int(table.shape[1]) * table.dtype.itemsize
    return span_call(
        "embedding_lookup",
        _lookup_fn(mesh, axis_name, variant),
        table, ids,
        nbytes=2 * (world - 1) * row_bytes,
        axis_name=axis_name, world=world, variant=variant,
        **mesh_link_meta(mesh, axis_name),
    )


@functools.lru_cache(maxsize=None)
def _scatter_add_fn(mesh: Mesh, axis_name: str):
    @functools.partial(jax.jit, donate_argnums=0)
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name), P(axis_name, None)),
        out_specs=P(axis_name, None),
        check_vma=False,
    )
    def scatter(table, ids, updates):
        v_local = table.shape[0]
        ids_all = lax.all_gather(ids.astype(jnp.int32), axis_name,
                                 axis=0, tiled=True)
        upd_all = lax.all_gather(updates, axis_name, axis=0, tiled=True)
        base = lax.axis_index(axis_name) * v_local
        local = ids_all - base
        ok = (local >= 0) & (local < v_local)
        # foreign rows scatter to the out-of-range index and drop —
        # never a masked write into row 0
        return table.at[jnp.where(ok, local, v_local)].add(
            upd_all, mode="drop"
        )

    return scatter


def embedding_scatter_add(table, ids, updates, mesh: Mesh,
                          axis_name: str | None = None):
    """Push batch-sharded update rows into the owning table shards:
    ``ids`` ``(B,)`` and ``updates`` ``(B, D)`` sharded on axis 0,
    ``table`` ``(V, D)`` row-sharded (donated — the in-place analog).
    Duplicate ids accumulate. Payload model: the id+update allgather
    (``(w−1)·(B·D + B·4)`` bytes aggregate)."""
    axis_name = axis_name or mesh.axis_names[0]
    world = mesh.shape[axis_name]
    check_divisible(table.shape[0], world, "embedding rows over mesh axis")
    check_divisible(ids.shape[0], world, "embedding batch over mesh axis")
    nbytes = (world - 1) * (
        int(getattr(updates, "nbytes", 0)) + int(ids.shape[0]) * 4
    )
    return span_call(
        "embedding_scatter_add",
        _scatter_add_fn(mesh, axis_name),
        table, ids, updates,
        nbytes=nbytes,
        axis_name=axis_name, world=world,
        **mesh_link_meta(mesh, axis_name),
    )
