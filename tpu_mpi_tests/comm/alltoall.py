"""All-to-all sequence parallelism (Ulysses-style head/sequence resharding).

The second canonical long-context strategy beside ring attention
(comm/ring.py): instead of rotating K/V blocks around a ring, one
``lax.all_to_all`` reshards the activations from sequence-sharded to
head-sharded — every rank then holds the FULL sequence for its subset of
heads and runs ordinary attention locally; a second all-to-all reshards
back. Nothing attention-shaped exists in the reference (SURVEY.md §5.7);
this provides the capability its communication layer was built to carry,
using the same mesh-axis machinery as the collectives layer.

Communication: 2 all-to-alls of the activations per call (vs the ring's
n−1 K/V block rotations) — the classic DeepSpeed-Ulysses trade.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_mpi_tests.compat import axis_size, shard_map
from tpu_mpi_tests.comm.ring import online_softmax_update
from tpu_mpi_tests.comm.topology import mesh_link_meta
from tpu_mpi_tests.instrument import telemetry as _telemetry
from tpu_mpi_tests.instrument.telemetry import span_call
from tpu_mpi_tests.utils import check_divisible


def seq_to_heads(x, axis_name: str):
    """Reshard (L_local, H, Dh) sequence-sharded → (L_global, H_local, Dh)
    head-sharded (call inside ``shard_map``)."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)


def heads_to_seq(x, axis_name: str):
    """Inverse of :func:`seq_to_heads`."""
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)


def _local_attention_full(q, k, v, causal: bool, precision):
    """Full attention over (L, H_local, Dh) — heads vectorized. Materializes
    the (H, L, L) score matrix; only used when L ≤ block_keys."""
    d = q.shape[-1]
    s = jnp.einsum("qhd,khd->hqk", q, k, precision=precision) / (d**0.5)
    if causal:
        L = s.shape[-1]
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask[None, :, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v, precision=precision)


def _local_attention(q, k, v, causal: bool, precision,
                     block_keys: int = 512):
    """Blockwise (flash-style) attention over (L, H_local, Dh).

    Keys/values are consumed in ``block_keys``-wide tiles under an online
    softmax (running max ``m``, denominator ``l``, numerator ``acc`` — the
    same carry as the ring flavor, comm/ring.py), so peak memory is
    O(L·block_keys·H_local) instead of the O(L²·H_local) score matrix that
    capped sequence length in round 1 (VERDICT weak #8). Ragged tails are
    handled by masking padded key positions; ``lax.scan`` keeps one compiled
    block program regardless of L.
    """
    L, H, d = q.shape
    if L <= block_keys:
        return _local_attention_full(q, k, v, causal, precision)
    scale = 1.0 / (d**0.5)
    nb = -(-L // block_keys)
    pad = nb * block_keys - L
    kb = jnp.pad(k, ((0, pad), (0, 0), (0, 0))).reshape(nb, block_keys, H, d)
    vb = jnp.pad(v, ((0, pad), (0, 0), (0, 0))).reshape(nb, block_keys, H, d)
    q_pos = jnp.arange(L)

    def step(carry, blk):
        m, l, acc = carry
        k_blk, v_blk, j0 = blk
        s = jnp.einsum("qhd,khd->hqk", q, k_blk, precision=precision) * scale
        k_pos = j0 + jnp.arange(block_keys)
        valid = k_pos[None, :] < L  # mask padded tail keys
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        s = jnp.where(valid[None, :, :], s, -jnp.inf)
        m_new, l, p, corr = online_softmax_update(m, l, s)  # (H, L) carries
        acc = acc * jnp.swapaxes(corr, 0, 1)[:, :, None] + jnp.einsum(
            "hqk,khd->qhd", p, v_blk, precision=precision
        )
        return (m_new, l, acc), None

    m0 = jnp.full((H, L), -jnp.inf, q.dtype)
    l0 = jnp.zeros((H, L), q.dtype)
    acc0 = jnp.zeros_like(q)
    starts = jnp.arange(nb) * block_keys
    (m, l, acc), _ = lax.scan(step, (m0, l0, acc0), (kb, vb, starts))
    return acc / jnp.swapaxes(l, 0, 1)[:, :, None]


def _local_attention_flash(q, k, v, causal, interpret, precision,
                           q_tile, k_tile, skip_tile=None):
    """Per-head Pallas flash local attention over (L, H_local, Dh):
    the single-head kernel vmapped over the head axis (pallas_call carries
    a batching rule, so the grid gains a head dimension)."""
    from tpu_mpi_tests.kernels.pallas_kernels import flash_attention_pallas

    f = functools.partial(
        flash_attention_pallas, causal=causal, interpret=interpret,
        precision=precision, q_tile=q_tile, k_tile=k_tile,
        skip_tile=skip_tile,
    )
    return jax.vmap(f, in_axes=1, out_axes=1)(q, k, v)


def ulysses_attention(
    q,
    k,
    v,
    axis_name: str,
    causal: bool = False,
    precision=lax.Precision.HIGHEST,
    block_keys: int = 512,
    flash: bool = False,
    interpret: bool | None = None,
    k_tile: int | None = None,
    skip_tile: int | None = None,
):
    """Per-shard Ulysses attention (call inside ``shard_map``): inputs
    (L_local, H, Dh) sequence-sharded; H must divide the mesh axis size.
    The local attention is blockwise (``block_keys``-wide key tiles), so
    sequence length is bounded by activations, not an L² score matrix.
    ``flash=True`` swaps in the Pallas flash kernel per head (same carry
    as the ring flavor's hand tier) at the kernel's tuned key-tile width
    (``k_tile=None`` resolves to the measured-best width,
    ``comm.ring.MEASURED_BEST_K_TILE`` — the per-k-tile carry rescale makes
    narrow tiles ~2× slower, BASELINE.md); pass ``k_tile`` to override;
    ``skip_tile`` sets the causal sub-span skip granularity (round 5).
    ``block_keys`` governs only the non-flash blockwise path, whose
    narrower default bounds its O(L·block·H) score memory."""
    n = axis_size(axis_name)
    check_divisible(q.shape[1], n, "ulysses heads over mesh axis")
    qh, kh, vh = (seq_to_heads(t, axis_name) for t in (q, k, v))
    if flash:
        out = _local_attention_flash(qh, kh, vh, causal, interpret,
                                     precision, q_tile=256, k_tile=k_tile,
                                     skip_tile=skip_tile)
    else:
        out = _local_attention(qh, kh, vh, causal, precision,
                               block_keys=block_keys)
    return heads_to_seq(out, axis_name)


@functools.lru_cache(maxsize=None)
def ulysses_attention_fn(mesh: Mesh, axis_name: str, causal: bool = False,
                         block_keys: int = 512, flash: bool = False,
                         interpret: bool | None = None,
                         k_tile: int | None = None,
                         skip_tile: int | None = None,
                         precision=lax.Precision.HIGHEST):
    """Jitted Ulysses attention over (L_global, H, Dh) arrays sharded along
    the sequence (axis 0). ``flash=True`` uses the Pallas flash kernel for
    the per-head local attention at its tuned ``k_tile``."""

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(axis_name, None, None),
            P(axis_name, None, None),
            P(axis_name, None, None),
        ),
        out_specs=P(axis_name, None, None),
        check_vma=False,
    )
    def attn(q, k, v):
        return ulysses_attention(q, k, v, axis_name, causal=causal,
                                 block_keys=block_keys, flash=flash,
                                 interpret=interpret, k_tile=k_tile,
                                 skip_tile=skip_tile,
                                 precision=precision)

    world = mesh.shape[axis_name]
    out_nbytes_cache: dict = {}

    def _out_nbytes(q, k, v) -> int:
        """Bytes of the head→seq all-to-all's operand — the ACTUAL
        output of the local attention, probed at trace time (no
        execution) and cached per input signature. Counting it as
        q-shaped (the pre-fix ``2*q.nbytes``) silently under/over-counts
        whenever flash/blockwise padding or an accumulation dtype makes
        the out operand differ from q."""
        key = tuple(
            (tuple(t.shape), str(getattr(t, "dtype", "?")))
            for t in (q, k, v)
        )
        nb = out_nbytes_cache.get(key)
        if nb is None:
            out = jax.eval_shape(attn, q, k, v)
            nb = out_nbytes_cache[key] = int(
                math.prod(out.shape) * out.dtype.itemsize
            )
        return nb

    def attn_recorded(q, k, v):
        # telemetry payload: two all-to-alls — q/k/v seq→head, then the
        # output (NOT necessarily q-shaped) head→seq; each moves
        # (w−1)/w of its operand. The output probe runs only on the
        # enabled path — a disabled call must stay one attribute check
        nbytes = 0
        if _telemetry.registry().enabled:
            moved = (
                int(getattr(q, "nbytes", 0))
                + int(getattr(k, "nbytes", 0))
                + int(getattr(v, "nbytes", 0))
                + _out_nbytes(q, k, v)
            )
            nbytes = (world - 1) * moved // world
        return span_call(
            "ulysses_attention", attn, q, k, v,
            nbytes=nbytes,
            axis_name=axis_name, world=world,
            flash=flash, causal=causal,
            **mesh_link_meta(mesh, axis_name),
        )

    return attn_recorded
