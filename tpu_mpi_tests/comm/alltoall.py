"""All-to-all sequence parallelism (Ulysses-style head/sequence resharding).

The second canonical long-context strategy beside ring attention
(comm/ring.py): instead of rotating K/V blocks around a ring, one
``lax.all_to_all`` reshards the activations from sequence-sharded to
head-sharded — every rank then holds the FULL sequence for its subset of
heads and runs ordinary attention locally; a second all-to-all reshards
back. Nothing attention-shaped exists in the reference (SURVEY.md §5.7);
this provides the capability its communication layer was built to carry,
using the same mesh-axis machinery as the collectives layer.

Communication: 2 all-to-alls of the activations per call (vs the ring's
n−1 K/V block rotations) — the classic DeepSpeed-Ulysses trade.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpu_mpi_tests.utils import check_divisible


def seq_to_heads(x, axis_name: str):
    """Reshard (L_local, H, Dh) sequence-sharded → (L_global, H_local, Dh)
    head-sharded (call inside ``shard_map``)."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)


def heads_to_seq(x, axis_name: str):
    """Inverse of :func:`seq_to_heads`."""
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)


def _local_attention(q, k, v, causal: bool, precision):
    """Full attention over (L, H_local, Dh) — heads vectorized."""
    d = q.shape[-1]
    s = jnp.einsum("qhd,khd->hqk", q, k, precision=precision) / (d**0.5)
    if causal:
        L = s.shape[-1]
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask[None, :, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v, precision=precision)


def ulysses_attention(
    q,
    k,
    v,
    axis_name: str,
    causal: bool = False,
    precision=lax.Precision.HIGHEST,
):
    """Per-shard Ulysses attention (call inside ``shard_map``): inputs
    (L_local, H, Dh) sequence-sharded; H must divide the mesh axis size."""
    n = lax.axis_size(axis_name)
    check_divisible(q.shape[1], n, "ulysses heads over mesh axis")
    qh, kh, vh = (seq_to_heads(t, axis_name) for t in (q, k, v))
    out = _local_attention(qh, kh, vh, causal, precision)
    return heads_to_seq(out, axis_name)


@functools.lru_cache(maxsize=None)
def ulysses_attention_fn(mesh: Mesh, axis_name: str, causal: bool = False):
    """Jitted Ulysses attention over (L_global, H, Dh) arrays sharded along
    the sequence (axis 0)."""

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(axis_name, None, None),
            P(axis_name, None, None),
            P(axis_name, None, None),
        ),
        out_specs=P(axis_name, None, None),
        check_vma=False,
    )
    def attn(q, k, v):
        return ulysses_attention(q, k, v, axis_name, causal=causal)

    return attn
