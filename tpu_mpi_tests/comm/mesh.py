"""Process bootstrap, topology discovery, and mesh construction.

This module is the TPU-native consolidation of three things the reference
re-implements in every driver (SURVEY.md §2.3):

* ``MPI_Init`` + launch-script plumbing  → :func:`bootstrap` wrapping
  ``jax.distributed.initialize`` (reference: ``mpi_daxpy_nvtx.cc:116``,
  ``summit/run.sh``).
* ``set_rank_device`` rank→device binding, copied five times in the reference
  (e.g. ``mpi_daxpy.cc:36-62``) → :func:`topology` + :func:`device_report`;
  in JAX the runtime owns the binding, so the framework's job is discovery,
  divisibility checking, and reporting.
* node-count discovery via ``MPI_Comm_split_type(MPI_COMM_TYPE_SHARED)``
  (``mpi_daxpy_nvtx.cc:72-82``) → :class:`Topology` host/process fields,
  which drive weak scaling exactly like the reference's node count.

Everything here is importable and testable on CPU with
``XLA_FLAGS=--xla_force_host_platform_device_count=N``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from tpu_mpi_tests.utils import TpuMtError, check_divisible  # noqa: F401

_DISTRIBUTED_INITIALIZED = False


class MeshError(TpuMtError):
    """Raised for invalid mesh/topology configurations (fail-fast, SURVEY §5.3)."""


def _check_divisible(n: int, by: int, what: str) -> int:
    try:
        return check_divisible(n, by, what)
    except TpuMtError as e:
        raise MeshError(str(e)) from None


def bootstrap(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize multi-process JAX if requested; no-op for single process.

    ≅ ``MPI_Init`` (``mpi_daxpy_nvtx.cc:116``). Multi-process mode activates
    when arguments are given or the standard coordinator env vars are set
    (``JAX_COORDINATOR_ADDRESS``/``TPU_WORKER_*`` or an autodetectable TPU
    environment). A plain no-arg call never latches state, so a later call
    with explicit coordinator arguments still initializes; repeated
    distributed init is a no-op.
    """
    global _DISTRIBUTED_INITIALIZED
    # Multi-host TPU slices advertise their worker set; >1 worker means
    # jax.distributed.initialize() can autodetect everything itself.
    tpu_workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    multihost_tpu = "," in tpu_workers
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
    )
    if not (explicit or multihost_tpu) or _DISTRIBUTED_INITIALIZED:
        return
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    # CPU multi-process worlds need a cross-process collectives backend
    # or every compiled collective fails with "Multiprocess computations
    # aren't implemented on the CPU backend"; gloo ships in jaxlib and
    # the knob is inert for TPU backends. Must be set before the first
    # backend touch, which is why it lives here and not in the drivers.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older jaxlib without the knob: previous behavior
    jax.distributed.initialize(
        coordinator_address=coordinator_address
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS"),
        num_processes=num_processes,
        process_id=process_id,
    )
    _DISTRIBUTED_INITIALIZED = True


@dataclasses.dataclass(frozen=True)
class Topology:
    """Discovered process/device topology.

    Field mapping to the reference:

    * ``process_count``  ≅ node count from ``get_node_count``
      (``mpi_daxpy_nvtx.cc:72-82``) — the weak-scaling unit.
    * ``global_device_count`` ≅ ``world_size`` (one rank per device).
    * ``local_device_count``  ≅ ranks-per-node from the shared-memory
      communicator split.
    """

    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int
    platform: str
    device_kinds: tuple[str, ...]

    @property
    def is_multi_host(self) -> bool:
        return self.process_count > 1


def topology() -> Topology:
    devices = jax.devices()
    return Topology(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=len(devices),
        platform=devices[0].platform,
        device_kinds=tuple(sorted({d.device_kind for d in devices})),
    )


def device_report(verbose: bool = False) -> str:
    """One-line (or per-device) binding report.

    ≅ the ``set_rank_device`` printouts (``mpi_daxpy.cc:56-59`` reports
    memory per rank; ``mpi_daxpy_gt.cc`` prints ``[device:vendor_id]``).
    """
    topo = topology()
    lines = [
        f"{topo.process_index}/{topo.process_count} processes, "
        f"{topo.local_device_count} local / {topo.global_device_count} global "
        f"devices, platform={topo.platform}, kinds={list(topo.device_kinds)}"
    ]
    if verbose:
        for d in jax.local_devices():
            stats = {}
            try:
                stats = d.memory_stats() or {}
            except (RuntimeError, NotImplementedError, AttributeError):
                pass
            mem = stats.get("bytes_limit")
            mem_s = f", mem_limit={mem / 2**30:.1f}GiB" if mem else ""
            lines.append(f"  device {d.id}: {d.device_kind}{mem_s}")
    return "\n".join(lines)


def make_mesh(
    axes: Mapping[str, int] | Sequence[tuple[str, int]] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named device mesh with fail-fast shape checking.

    ``axes`` maps axis name → size; at most one size may be ``-1`` (filled
    with the remaining devices). ``None`` means a 1-D mesh named ``"shard"``
    over all devices — the analog of ``MPI_COMM_WORLD`` for the reference's
    1-D decompositions (SURVEY §2.3 row 1).

    The mesh is the communicator abstraction: an ICI-major axis ordering is
    used so that ``ppermute``/``psum`` over the innermost axes ride ICI
    (devices enumerate local-first in JAX's default ordering).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)

    if axes is None:
        axes = {"shard": n}
    items = list(axes.items()) if isinstance(axes, Mapping) else list(axes)
    names = [k for k, _ in items]
    sizes = [v for _, v in items]
    if len(set(names)) != len(names):
        raise MeshError(f"duplicate mesh axis names: {names}")

    wildcards = [i for i, s in enumerate(sizes) if s == -1]
    if len(wildcards) > 1:
        raise MeshError("at most one mesh axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if wildcards:
        sizes[wildcards[0]] = _check_divisible(n, known, "mesh wildcard axis")
    total = int(np.prod(sizes)) if sizes else 1
    if total != n:
        raise MeshError(
            f"mesh shape {dict(zip(names, sizes))} needs {total} devices, "
            f"have {n}"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def make_mesh_2level(
    ici_name: str = "ici", dcn_name: str = "dcn"
) -> Mesh:
    """Two-level mesh mapping the physical topology: the outer axis spans
    processes (DCN / cross-host — ≅ the node axis from
    ``MPI_Comm_split_type``, ``mpi_daxpy_nvtx.cc:72-82``) and the inner
    axis spans each process's local devices (ICI). Collectives over
    ``ici_name`` stay on-chip-interconnect; over ``dcn_name`` they cross
    hosts — the layout rule that keeps bandwidth-hungry axes on ICI.
    """
    topo = topology()
    # group devices by owning process so the outer axis is really DCN
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return make_mesh(
        {dcn_name: topo.process_count, ici_name: topo.local_device_count},
        devices=devs,
    )


def ranks_per_device(world_size: int | None = None) -> int:
    """Oversubscription factor (reference ``ranks_per_device``,
    ``mpi_daxpy.cc:49-51``).

    Multiple processes per TPU chip are unsupported; the framework's analog is
    multiple logical shards per chip handled *inside* one process (SURVEY §7
    hard part 5), so this returns how many logical ranks each device carries
    for a requested world size, with the reference's divisibility rule.
    """
    n_dev = jax.device_count()
    if world_size is None or world_size <= n_dev:
        return 1
    return _check_divisible(world_size, n_dev, "world_size over devices")
