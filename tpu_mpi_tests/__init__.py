"""tpu_mpi_tests — a TPU-native re-creation of bd4/gpu-mpi-tests.

A framework for distributed TPU microbenchmarks with the capability matrix of
the reference CUDA-aware-MPI suite (see SURVEY.md): mesh bootstrap in place of
MPI_Init + set_rank_device, XLA collectives over ICI in place of CUDA-aware
MPI, jnp + Pallas kernels in place of cuBLAS/gtensor/SYCL, XProf annotations
in place of NVTX, and a real pytest suite in place of printf verification.

Layer map (mirrors SURVEY.md §1, top to bottom; tpu/ and native/ live at the
repo root beside this package):
  tpu/          launch + aggregation            (≅ summit/, jlse/, avg.sh)
  drivers/      benchmark drivers               (≅ the per-binary main()s)
  instrument/   timers, trace ranges, reporting (≅ NVTX + MPI_Wtime)
  comm/         mesh, collectives, halo, ring   (≅ MPI layer + seq-parallel)
  kernels/      daxpy, stencil, pack, pallas    (≅ cuBLAS/gtensor/SYCL kernels)
  arrays/       spaces, domain decomposition    (≅ gtensor spaces + ghost math)
  native/       C++ aggregator + timer lib      (≅ avg.sh + clock_gettime)
"""

__version__ = "0.1.0"

# mesh re-exports resolve lazily (PEP 562): comm.mesh imports jax at
# module scope, and the stdlib-only CLI tools (tpumt-report/tpumt-trace,
# advertised for login nodes without jax) import through this package
_MESH_EXPORTS = ("Topology", "bootstrap", "make_mesh", "topology")
__all__ = list(_MESH_EXPORTS)


def __getattr__(name):
    if name in _MESH_EXPORTS:
        from tpu_mpi_tests.comm import mesh

        return getattr(mesh, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
