"""Fault-spec grammar for the chaos layer (stdlib-only).

A chaos spec is a comma list of faults, each ``class[:key=value]*``::

    kill:rank=1:op=halo_exchange:after=3
    straggler:rank=1:delay_ms=40
    wedge:op=halo_exchange:after=2
    oom:step_mb=16:limit_mb=64:frac=0.8
    flood:burst=300:after=1

Classes and their trigger points (``tpu_mpi_tests/chaos/inject.py`` arms
them; README "Chaos & diagnosis" documents the conviction signals):

* ``kill`` — the target rank hard-exits at the ``after``-th matching
  trigger: entry of a telemetry span (``op=`` prefix match, so the span
  never closes — dead mid-collective from every sibling's point of
  view) or entry of a PhaseTimer phase (``phase=``).
* ``straggler`` — the target rank is artificially slowed. With ``op=``
  the delay lands at span *exit* (after the measured window closes), so
  the rank arrives late at the NEXT collective — the classic signature
  where the *siblings'* spans inflate while the culprit's stay fast.
  Without ``op=`` the delay wraps :func:`tpu_mpi_tests.instrument.
  timers.block` — the sync point every measured phase passes through —
  so every phase on the rank uniformly slows (a slow device/host).
* ``wedge`` — at the matching trigger the rank records a dispatch note
  (:func:`~tpu_mpi_tests.instrument.telemetry.note_dispatch`) and then
  never completes: the op is "in flight" forever, which is exactly what
  the hang watchdog exists to catch (run with ``--deadline``).
* ``oom`` — live-array ballast grows ``step_mb`` at every PhaseTimer
  phase boundary (optionally scoped by ``phase=``) until the pressure
  crosses ``frac`` of the limit, then the rank dies the way an
  OOM-killed allocator does. An explicit ``limit_mb`` always wins;
  only the default defers to the device's reported HBM limit (falling
  back to 256 MB where the backend reports no allocator stats —
  CPU/fake devices).
* ``flood`` — the serve loop receives a burst of ``burst`` synthetic
  arrivals at the ``after``-th SLO window boundary, driving shed and
  queue depth through the bound.

Every field is parsed once here; ``arm()`` bakes the decisions into
closures, so nothing re-reads env vars or re-parses specs per call.
"""

from __future__ import annotations

from dataclasses import dataclass, field

#: the fault classes the layer injects (and the diagnosis classes
#: ``tpumt-doctor`` convicts them as — see FINDING_FOR)
FAULT_CLASSES = ("kill", "straggler", "wedge", "oom", "flood")

#: injection class -> the ``tpumt-doctor`` finding class that convicts
#: it (the chaos-smoke contract: inject X, doctor must name
#: FINDING_FOR[X] with the injected rank)
FINDING_FOR = {
    "kill": "missing_rank",
    "straggler": "straggler",
    "wedge": "wedge",
    "oom": "oom",
    "flood": "shed_storm",
}

_INT_KEYS = ("rank", "after", "step_mb", "limit_mb", "burst", "seed")
_FLOAT_KEYS = ("delay_ms", "frac", "stall_s")
_STR_KEYS = ("op", "phase")

#: the keys each fault class actually consumes (inject.py's arm-time
#: routing). A key outside this set is rejected up front: accepting
#: ``straggler:phase=X`` while arming a uniform straggler would inject
#: something other than what the spec claims — the same silent-no-op
#: failure mode the grammar exists to prevent.
_CLASS_KEYS = {
    "kill": frozenset({"rank", "op", "phase", "after", "seed"}),
    "wedge": frozenset({"rank", "op", "phase", "after", "stall_s",
                        "seed"}),
    "straggler": frozenset({"rank", "op", "after", "delay_ms", "seed"}),
    "oom": frozenset({"rank", "phase", "after", "step_mb", "limit_mb",
                      "frac", "seed"}),
    "flood": frozenset({"rank", "after", "burst", "seed"}),
}


@dataclass
class FaultSpec:
    """One parsed fault. Defaults are deliberately mild enough for CI
    fake-device runs and documented in the grammar above."""

    fault: str
    rank: int = 0
    op: str | None = None          # span-op prefix trigger
    phase: str | None = None       # PhaseTimer phase-name trigger
    after: int = 1                 # fire on the Nth matching trigger
    delay_ms: float = 200.0        # straggler: delay per event
    step_mb: int = 16              # oom: ballast per phase boundary
    limit_mb: int = 256            # oom: limit when the backend has none
    frac: float = 0.8              # oom: die at frac * limit
    burst: int = 200               # flood: synthetic arrivals injected
    stall_s: float = 120.0         # wedge: safety cap if no watchdog
    seed: int = 0                  # reserved for randomized faults
    raw: str = field(default="", compare=False)
    #: keys the user gave explicitly — a default and an explicit value
    #: must be distinguishable where behavior branches on it (an
    #: explicit oom limit_mb overrides the device-reported limit)
    explicit: frozenset = field(default_factory=frozenset,
                                compare=False)

    def describe(self) -> str:
        parts = [self.fault, f"rank={self.rank}"]
        if self.op:
            parts.append(f"op={self.op}")
        if self.phase:
            parts.append(f"phase={self.phase}")
        parts.append(f"after={self.after}")
        if self.fault == "straggler":
            parts.append(f"delay_ms={self.delay_ms:g}")
        if self.fault == "oom":
            parts.append(f"step_mb={self.step_mb}")
            parts.append(f"limit_mb={self.limit_mb}")
            parts.append(f"frac={self.frac:g}")
        if self.fault == "flood":
            parts.append(f"burst={self.burst}")
        return ":".join(parts)


def parse_chaos_spec(text: str) -> list[FaultSpec]:
    """Parse a ``--chaos`` / ``TPU_MPI_CHAOS`` value. Raises
    :class:`ValueError` with the offending token and the grammar — a
    malformed fault spec must fail the run up front, not silently
    inject nothing."""
    specs: list[FaultSpec] = []
    for token in text.split(","):
        token = token.strip()
        if not token:
            continue
        parts = token.split(":")
        fault = parts[0].strip()
        if fault not in FAULT_CLASSES:
            raise ValueError(
                f"unknown fault class {fault!r} in {token!r}; expected "
                f"one of {','.join(FAULT_CLASSES)} "
                f"(grammar: class[:key=value]*)"
            )
        spec = FaultSpec(fault=fault, raw=token)
        seen: set[str] = set()
        for kv in parts[1:]:
            if "=" not in kv:
                raise ValueError(
                    f"malformed field {kv!r} in {token!r}; expected "
                    f"key=value"
                )
            key, val = (s.strip() for s in kv.split("=", 1))
            if key in (_INT_KEYS + _FLOAT_KEYS + _STR_KEYS) \
                    and key not in _CLASS_KEYS[fault]:
                raise ValueError(
                    f"{key!r} does not apply to {fault!r} in {token!r}; "
                    f"{fault} takes {','.join(sorted(_CLASS_KEYS[fault]))}"
                )
            if key in seen:
                raise ValueError(
                    f"duplicate key {key!r} in {token!r}: last-wins "
                    f"would arm something other than what the spec "
                    f"appears to say"
                )
            seen.add(key)
            try:
                if key in _INT_KEYS:
                    setattr(spec, key, int(val))
                elif key in _FLOAT_KEYS:
                    setattr(spec, key, float(val))
                elif key in _STR_KEYS:
                    setattr(spec, key, val)
                else:
                    raise ValueError(
                        f"unknown field {key!r} in {token!r}; valid: "
                        f"{','.join(_INT_KEYS + _FLOAT_KEYS + _STR_KEYS)}"
                    )
            except ValueError as e:
                if "unknown field" in str(e):
                    raise
                raise ValueError(
                    f"bad value {val!r} for {key!r} in {token!r}"
                ) from None
        spec.explicit = frozenset(seen)
        _validate(spec)
        specs.append(spec)
    if not specs:
        raise ValueError("empty chaos spec")
    return specs


def _validate(spec: FaultSpec) -> None:
    if spec.after < 1:
        raise ValueError(f"after must be >= 1 in {spec.raw!r}")
    if spec.rank < 0:
        raise ValueError(f"rank must be >= 0 in {spec.raw!r}")
    if spec.fault in ("kill", "wedge") and not (spec.op or spec.phase):
        raise ValueError(
            f"{spec.fault} needs an op= or phase= trigger in {spec.raw!r}"
        )
    if spec.op and spec.phase:
        raise ValueError(
            f"op= and phase= are mutually exclusive in {spec.raw!r}"
        )
    if spec.fault == "straggler" and spec.delay_ms <= 0:
        raise ValueError(f"delay_ms must be positive in {spec.raw!r}")
    if spec.fault == "oom":
        if spec.step_mb < 1 or spec.limit_mb < 1:
            raise ValueError(
                f"step_mb/limit_mb must be >= 1 in {spec.raw!r}"
            )
        if not (0.0 < spec.frac <= 1.0):
            raise ValueError(f"frac must be in (0, 1] in {spec.raw!r}")
    if spec.fault == "flood" and spec.burst < 1:
        raise ValueError(f"burst must be >= 1 in {spec.raw!r}")
    if spec.fault == "wedge" and spec.stall_s <= 0:
        # a zero/negative cap hard-exits 9 the instant the wedge
        # lands, so the watchdog under test never gets to fire
        raise ValueError(f"stall_s must be positive in {spec.raw!r}")
