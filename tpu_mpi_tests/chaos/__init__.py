"""Chaos layer: deterministic, env/flag-driven fault injection.

The observability spine (telemetry spans, flight recorder, watchdog,
memwatch, serve SLO records) exists to explain failures — and until this
package, nothing in the repo ever *caused* one on purpose. ``chaos``
closes the loop: ``--chaos <spec>`` / ``TPU_MPI_CHAOS`` arms seeded,
deterministic faults (killed rank, straggler, wedged dispatch, OOM
ramp, serve flood) inside the existing hooks, and ``tpumt-doctor``
(``instrument/diagnose.py``) must then convict the right failure class
on the right rank from the organic telemetry alone — CI enforces it
(``make chaos-smoke``; README "Chaos & diagnosis").

Containment: production code must never reach into this package. The
only sanctioned arm-point is ``drivers/_common.make_reporter`` (lint
rule TPM1001 enforces it), and a disarmed run installs zero chaos
state — the hot paths are byte-identical to a build without this
package.

Re-exports resolve lazily (PEP 562): ``spec`` is stdlib-only, but
``inject`` touches telemetry/timers at arm time and this package must
stay importable (for spec parsing) everywhere the CLIs run.
"""

from __future__ import annotations

_EXPORTS = {
    "FaultSpec": "spec",
    "FAULT_CLASSES": "spec",
    "FINDING_FOR": "spec",
    "parse_chaos_spec": "spec",
    "arm": "inject",
    "arm_from_spec": "inject",
    "armed": "inject",
    "disarm": "inject",
}
__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(
            f"tpu_mpi_tests.chaos.{_EXPORTS[name]}"
        )
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
