"""Arm-time fault injection over the existing observability hooks.

Design contract (README "Chaos & diagnosis"):

* **Zero cost when disarmed.** Arming REBINDS extension points that
  already exist — the telemetry span hook slot
  (``instrument/telemetry._CHAOS_SPAN_HOOK``), the PhaseTimer hook list
  (:func:`~tpu_mpi_tests.instrument.timers.add_phase_hook`), the serve
  loop's flood slot (``serve/loop._CHAOS_FLOOD``) and, for the uniform
  straggler, :func:`~tpu_mpi_tests.instrument.timers.block` itself. A
  disarmed run installs nothing: the hot paths run the exact same code
  as a build without this package (the disarmed-identity test pins
  stdout + record-kind byte equality).
* **Decisions resolve at arm time, not per call.** ``arm()`` parses the
  spec once and bakes rank/op/phase/threshold choices into closures;
  the per-event hook does a prefix match and a counter bump, nothing
  else. Faults whose rank does not match this process install nothing.
* **Deterministic.** Every fault fires on the Nth matching event of a
  deterministic trigger stream (span entries, phase boundaries, SLO
  window indices) — reruns of the same workload inject at the same
  point.
* **Audited.** Arming and firing emit ``kind: "chaos"`` records through
  the run's JSONL sink, so an injected failure is distinguishable from
  a real one in post-mortems. ``tpumt-doctor`` deliberately IGNORES
  these records: the diagnosis must convict from the organic telemetry
  signals alone, or the chaos-smoke proves nothing.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Callable

from tpu_mpi_tests.chaos.spec import FaultSpec, parse_chaos_spec

#: exit codes chosen to mimic the real failure's shape: kill ≅ SIGKILL
#: (137), oom ≅ SIGABRT-from-allocator (134), wedge safety-cap ≅ the
#: watchdog's own hard-exit code (9)
KILL_EXIT = 137
OOM_EXIT = 134
WEDGE_EXIT = 9

_ARMED: list[FaultSpec] = []
_EMIT: Callable[[dict], None] | None = None
#: live-array ballast the oom fault grows (jax arrays so the memwatch
#: census and live totals genuinely see the pressure)
_BALLAST: list = []
_ORIG_BLOCK = None
_PHASE_HOOK = None


def armed() -> list[FaultSpec]:
    """The faults armed in this process (empty when disarmed)."""
    return list(_ARMED)


def _emit_record(rec: dict) -> None:
    """Best-effort chaos audit record: the JSONL sink when the caller
    gave one, else the telemetry registry's sink. Never raises — a
    bookkeeping failure must not mask (or cause) the injected fault."""
    try:
        if _EMIT is not None:
            _EMIT(rec)
        else:
            from tpu_mpi_tests.instrument import telemetry

            telemetry.emit(rec)
    except Exception:
        pass


def _fire_record(spec: FaultSpec, **extra) -> None:
    _emit_record({
        "kind": "chaos", "event": "fire", "fault": spec.fault,
        "chaos_rank": spec.rank, "spec": spec.raw, "t": time.time(),
        **extra,
    })


def _die(spec: FaultSpec, code: int, why: str) -> None:
    _fire_record(spec, exit_code=code)
    sys.stderr.write(f"CHAOS {spec.fault}: {why} — exiting "
                     f"{code} (injected by {spec.raw!r})\n")
    sys.stderr.flush()
    os._exit(code)


# ---------------------------------------------------------------------------
# per-fault hook builders (called once, at arm time)
# ---------------------------------------------------------------------------


def _span_hook_for(spans: list[FaultSpec]):
    """One dispatcher for every span-triggered fault on this rank.
    ``when`` is "enter" (before the span's clock starts — kill/wedge
    land here so the span never closes) or "exit" (after the span
    recorded — the op-scoped straggler sleeps here, OUTSIDE the
    measured window, so the culprit's own spans stay fast while its
    late arrival inflates every sibling's next collective)."""
    counts = [0] * len(spans)
    slowed = [False] * len(spans)

    def hook(op: str, when: str) -> None:
        for i, spec in enumerate(spans):
            if spec.op and not op.startswith(spec.op):
                continue
            if spec.fault in ("kill", "wedge"):
                if when != "enter":
                    continue
                counts[i] += 1
                if counts[i] == spec.after:
                    if spec.fault == "kill":
                        _die(spec, KILL_EXIT,
                             f"killed at entry of span {op!r} "
                             f"#{counts[i]}")
                    _wedge(spec, f"span {op!r} #{counts[i]}", op=op)
            elif spec.fault == "straggler":
                if when != "exit":
                    continue
                counts[i] += 1
                if counts[i] >= spec.after:
                    if not slowed[i]:
                        slowed[i] = True
                        _fire_record(spec, op=op)
                    time.sleep(spec.delay_ms / 1e3)

    return hook


def _wedge(spec: FaultSpec, where: str, op: str | None = None) -> None:
    """Simulate a wedged dispatch: the op registers itself in the
    flight recorder (``note_dispatch`` — mirrored to JSONL as
    ``kind: "dispatch"`` when telemetry is on) and then never
    completes. The hang watchdog (``--deadline``) is what ends the
    process; ``stall_s`` is only a safety cap so a run armed without
    one cannot hang CI forever."""
    from tpu_mpi_tests.instrument import telemetry

    telemetry.note_dispatch(
        f"chaos:wedge {where}", op=op or f"chaos:{spec.phase or '?'}"
    )
    _fire_record(spec, where=where)
    sys.stderr.write(f"CHAOS wedge: stalling at {where} (injected by "
                     f"{spec.raw!r}; the watchdog should fire)\n")
    sys.stderr.flush()
    deadline = time.monotonic() + spec.stall_s
    while time.monotonic() < deadline:
        time.sleep(0.1)
    _die(spec, WEDGE_EXIT,
         f"stall cap {spec.stall_s:g}s reached with no watchdog")


def _phase_hook_for(phased: list[FaultSpec]):
    """Dispatcher for phase-triggered faults (kill/wedge on phase
    entry; oom ballast on every boundary). Runs inside
    ``timers._fire_phase_hooks`` — OUTSIDE the measured window, so the
    ballast/bookkeeping cost is never charged to the phase."""
    counts = [0] * len(phased)

    def hook(name: str, event: str) -> None:
        for i, spec in enumerate(phased):
            if spec.phase and name != spec.phase:
                continue
            if spec.fault in ("kill", "wedge"):
                if event != "begin":
                    continue
                counts[i] += 1
                if counts[i] == spec.after:
                    if spec.fault == "kill":
                        _die(spec, KILL_EXIT,
                             f"killed at entry of phase {name!r} "
                             f"#{counts[i]}")
                    _wedge(spec, f"phase {name!r} #{counts[i]}")
            elif spec.fault == "oom":
                if event != "begin":
                    continue  # one step per boundary, like kill/wedge
                counts[i] += 1
                if counts[i] >= spec.after:
                    _grow_ballast(spec, name)

    return hook


def _grow_ballast(spec: FaultSpec, phase: str) -> None:
    """One OOM-ramp step: allocate ``step_mb`` of live jax arrays (the
    census sees them; on backends with allocator stats the watermarks
    climb too), then die once the live pressure crosses ``frac`` of
    the limit — the device HBM limit where known, else ``limit_mb``."""
    try:
        import jax.numpy as jnp

        _BALLAST.append(
            jnp.ones((spec.step_mb * (1 << 20) // 4,), jnp.float32)
        )
    except Exception:
        return  # no backend (pure-host test); pressure cannot grow
    from tpu_mpi_tests.instrument import memwatch

    limit = spec.limit_mb * (1 << 20)
    if "limit_mb" not in spec.explicit:
        # only the DEFAULT defers to the device's reported limit: an
        # explicit limit_mb is a promise about how far the ramp goes,
        # and silently ramping toward 0.8x of full HBM instead would
        # be the spec/behavior mismatch the grammar rejects elsewhere
        stats = memwatch.device_memory_stats()
        hw = [s["bytes_limit"] for s in stats.values()
              if "bytes_limit" in s]
        if hw:
            limit = max(hw)
    _count, live = memwatch._live_totals()
    if live >= spec.frac * limit:
        _die(spec, OOM_EXIT,
             f"live bytes {live} crossed {spec.frac:g} of limit "
             f"{limit} during phase {phase!r}")


def _flood_hook_for(spec: FaultSpec):
    """Serve-loop flood: a one-shot burst at the ``after``-th SLO
    window boundary (deterministic in wall-clock and fake-clock runs
    alike — the window index is the trigger stream)."""
    fired = [False]

    def hook(window_index: int) -> int:
        if fired[0] or window_index != spec.after:
            return 0
        fired[0] = True
        _fire_record(spec, window_index=window_index)
        return spec.burst

    return hook


def _wrap_block(spec: FaultSpec):
    """Uniform straggler: wrap ``timers.block`` — the sync point every
    measured phase already passes through — so the delay lands INSIDE
    the measured windows and the rank reads as a uniformly slow
    device. Restored by :func:`disarm`."""
    global _ORIG_BLOCK
    from tpu_mpi_tests.instrument import timers

    if _ORIG_BLOCK is not None:
        return  # already wrapped (one uniform straggler is enough)
    _ORIG_BLOCK = timers.block
    orig = _ORIG_BLOCK
    count = [0]
    slowed = [False]

    def slow_block(*pytrees):
        count[0] += 1
        if count[0] >= spec.after:
            if not slowed[0]:
                slowed[0] = True
                _fire_record(spec)
            time.sleep(spec.delay_ms / 1e3)
        return orig(*pytrees)

    timers.block = slow_block


# ---------------------------------------------------------------------------
# arm / disarm
# ---------------------------------------------------------------------------


def arm(specs: list[FaultSpec], rank: int,
        emit: Callable[[dict], None] | None = None) -> list[FaultSpec]:
    """Install the faults of ``specs`` that target ``rank``. Returns
    the installed subset (empty when nothing targets this rank — the
    process then runs with zero chaos state installed). Re-arming
    disarms first, so tests and repeated ``make_reporter`` calls are
    idempotent."""
    global _EMIT, _PHASE_HOOK
    disarm()
    mine = [s for s in specs if s.rank == rank]
    if not mine:
        return []
    _EMIT = emit
    _ARMED.extend(mine)

    # kill/wedge/straggler with op= — span-triggered
    span_faults = [s for s in mine
                   if s.op and s.fault in ("kill", "wedge", "straggler")]
    if span_faults:
        from tpu_mpi_tests.instrument import telemetry

        telemetry._CHAOS_SPAN_HOOK = _span_hook_for(span_faults)

    phase_faults = [s for s in mine
                    if (s.fault in ("kill", "wedge") and s.phase)
                    or s.fault == "oom"]
    if phase_faults:
        from tpu_mpi_tests.instrument import timers

        _PHASE_HOOK = _phase_hook_for(phase_faults)
        timers.add_phase_hook(_PHASE_HOOK)

    for s in mine:
        if s.fault == "straggler" and not s.op:
            _wrap_block(s)
        elif s.fault == "flood":
            from tpu_mpi_tests.serve import loop as serve_loop

            serve_loop._CHAOS_FLOOD = _flood_hook_for(s)

    for s in mine:
        _emit_record({
            "kind": "chaos", "event": "armed", "fault": s.fault,
            "chaos_rank": s.rank, "spec": s.raw, "t": time.time(),
        })
    return mine


def arm_from_spec(text: str, rank: int,
                  emit: Callable[[dict], None] | None = None
                  ) -> list[FaultSpec]:
    """Parse + arm in one step (the driver-side entry point). Raises
    :class:`ValueError` on a malformed spec."""
    return arm(parse_chaos_spec(text), rank, emit=emit)


def disarm() -> None:
    """Uninstall every hook and drop the ballast — the process is back
    to the disarmed (zero chaos state) configuration."""
    global _EMIT, _ORIG_BLOCK, _PHASE_HOOK
    _ARMED.clear()
    _BALLAST.clear()
    _EMIT = None
    try:
        from tpu_mpi_tests.instrument import telemetry

        telemetry._CHAOS_SPAN_HOOK = None
    except Exception:
        pass
    if _PHASE_HOOK is not None:
        try:
            from tpu_mpi_tests.instrument import timers

            timers.remove_phase_hook(_PHASE_HOOK)
        except Exception:
            pass
        _PHASE_HOOK = None
    if _ORIG_BLOCK is not None:
        try:
            from tpu_mpi_tests.instrument import timers

            timers.block = _ORIG_BLOCK
        except Exception:
            pass
        _ORIG_BLOCK = None
    try:
        from tpu_mpi_tests.serve import loop as serve_loop

        serve_loop._CHAOS_FLOOD = None
    except Exception:
        pass
