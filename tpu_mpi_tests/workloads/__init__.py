"""Declarative workload specs: a pillar in ~100 lines, not a driver copy.

Every pillar added before this subsystem cost a 400–600-line driver that
hand-rolled the same plumbing (arg parsing, platform setup, reporter,
phase loop, tune wiring, serve registration, bench rows — attnbench is
413 lines, ``drivers/_common.py`` 469). A workload spec is the part that
is actually *about* the pillar:

* name + CLI surface (``add_args``/``check_args`` on the shared
  ``base_parser``);
* ``build → step → verify`` hooks (mesh/sharding setup, the measured
  body, the analytic gate);
* a bytes model for the comm payload its spans claim;
* the tune spaces it consumes (declared where the knob lives, PR-4
  registry rules unchanged);
* a stable bench metric (``kind: "workload"`` JSONL row).

The generic runner (:mod:`~tpu_mpi_tests.workloads.runner`) supplies
everything else — one flow shared by every spec, so a fix to the
plumbing cannot miss a pillar. Registering a spec also registers its
serve-mode handler (``drivers/_common.py`` workload registry), so a new
pillar is a serving workload class, a tuned schedule consumer, and a
``tpumt-report``/``--diff``-gated bench series the moment it exists.

This module is stdlib-only at import (spec hooks import jax inside
their bodies), like the tune registry it mirrors.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from tpu_mpi_tests.workloads.spec import WorkloadSpec

_SPECS: dict[str, "WorkloadSpec"] = {}


def register_spec(spec: "WorkloadSpec") -> "WorkloadSpec":
    """Register a workload spec (idempotent per name — spec modules are
    re-imported under test runners). Registration is what wires the
    pillar into serve mode: a spec with a ``serve_factory`` lands in the
    driver workload registry under ``spec.serve_name`` automatically."""
    existing = _SPECS.get(spec.name)
    if existing is not None:
        return existing
    _SPECS[spec.name] = spec
    factory = spec.serve_factory
    if factory is not None:
        from tpu_mpi_tests.drivers import _common

        _common.register_workload(spec.serve_name, factory)
    return spec


def load_specs() -> None:
    """Import every spec module (their ``register_spec`` calls run now).
    Lazy — like ``tune.registry._import_knob_owners`` — so the registry
    stays importable without jax."""
    import tpu_mpi_tests.workloads.daxpy  # noqa: F401
    import tpu_mpi_tests.workloads.decode  # noqa: F401
    import tpu_mpi_tests.workloads.embedding  # noqa: F401
    import tpu_mpi_tests.workloads.moe  # noqa: F401
    import tpu_mpi_tests.workloads.stencil1d  # noqa: F401


def spec_names() -> tuple[str, ...]:
    load_specs()
    return tuple(sorted(_SPECS))


def get_spec(name: str) -> "WorkloadSpec":
    load_specs()
    try:
        return _SPECS[name]
    except KeyError:
        raise KeyError(
            f"no workload spec {name!r}; registered: "
            f"{','.join(sorted(_SPECS))}"
        ) from None
