"""Decode-step collectives: tiny latency-bound allreduce/allgather.

The second serving-era pillar (ROADMAP item 4): autoregressive decode
runs one collective per layer per *token*, at batch×head payloads of a
few KB — the regime where the per-op fixed cost the bf16-stripe verdict
exposed dominates and GB/s is the wrong axis entirely. This spec sweeps
the decode collectives over batch sizes at a fixed head count and
reports **µs/op latency rows** (device-chained ``fori_loop`` timing via
``chain_rate``, the same compiled programs collbench's COLL rows
measure), each a ``kind: "decode"`` record that ``tpumt-report``
renders and ``--diff`` gates lower-is-better — a schedule change that
adds microseconds to the decode path trips the gate even though the
bandwidth tables would never notice.

Output per (collective, batch)::

    DECODE <coll> batch=<b> heads=<h> bytes=<per-shard> <us> us/op  n=<iters>
    WORKLOAD decode: allreduce_us_per_op=<v> us

Verification: the same ``lax`` collectives the rows time are checked
exactly against host references (sum for allreduce, concatenation for
allgather) on integer-valued data.
"""

from __future__ import annotations

import sys

from tpu_mpi_tests.workloads import register_spec
from tpu_mpi_tests.workloads.spec import RunContext, WorkloadSpec

#: the decode sweep's collectives: the tensor-parallel pair a decode
#: step actually issues (row-parallel matmul → allreduce; KV/head
#: assembly → allgather)
DECODE_COLLS = ("allreduce", "allgather")

# the DECODE line's parse pattern lives NEXT TO its format string so a
# format change is a one-site edit (the collbench COLL_LINE_RE idiom).
# The [variant] token is the resolved ``coll_variant/*`` tier — the
# schedule-stamp idiom BENCH rows use (``_ov<d>_<tier>``), so a µs/op
# move is attributable to the tier that produced it
DECODE_LINE_RE = (
    r"DECODE (\w+)\[(\w+)\] batch=(\d+) heads=(\d+) bytes=(\d+) "
    r"([\d.e+-]+|nan) us/op  n=(\d+)"
)


def _effective_coll(coll, mesh, axis_name, world, n, dtype, dtype_name,
                    shard_bytes, line, explicit=None):
    """``(effective _loop_fn name, variant)`` for one payload size:
    the ``coll_variant/<base>`` schedule collbench declares and sweeps,
    resolved explicit > cached > prior (``device_fallback=False`` —
    payload-size-sensitive, like collbench's own resolution). A cached
    ``rdma`` winner below the ring kernel's lane-alignment floor at
    THIS payload degrades to the XLA tier with a visible NOTE (``line``
    is the printer — the one-shot driver passes ``rep.line``, the serve
    factory ``print``; same probe for ``oneshot``, though its
    pad-to-tile wrapper makes that tier feasible at every payload), and
    a malformed cache value degrades to the prior. Collectives without
    hand twins resolve to themselves (variant None)."""
    from tpu_mpi_tests.tune import registry as tr

    if coll not in ("allgather", "allreduce"):
        return coll, None
    variant = tr.resolve(
        f"coll_variant/{coll}", explicit=explicit, device_fallback=False,
        dtype=dtype_name, bytes=shard_bytes, world=world,
    )
    if variant not in ("xla", "rdma", "oneshot"):
        variant = "xla"  # malformed cache value degrades to the prior
    if variant in ("rdma", "oneshot"):
        import jax

        from tpu_mpi_tests.drivers.collbench import _loop_fn

        fn = _loop_fn(mesh, axis_name, f"{coll}_{variant}", world)
        try:
            jax.eval_shape(
                fn, jax.ShapeDtypeStruct((n * world,), dtype), 1
            )
        except Exception as e:
            if explicit == variant:
                # an explicitly requested candidate (a re-sweep's
                # measure) must ERROR so the sweep records it as
                # infeasible, never silently measure the other tier
                raise
            if line is not None:
                line(f"NOTE decode {coll}: cached {variant} variant "
                     f"infeasible at {shard_bytes} B ({e}); "
                     f"using xla")
            return coll, "xla"
        return f"{coll}_{variant}", variant
    return coll, "xla"


class DecodeSpec(WorkloadSpec):
    name = "decode"
    title = __doc__

    def add_args(self, p) -> None:
        p.add_argument(
            "--batches", default="1,8,32",
            help="comma list of decode batch sizes to sweep (default "
            "1,8,32 — the single-stream / small-batch / saturated "
            "decode regimes)",
        )
        p.add_argument(
            "--heads", type=int, default=16,
            help="attention heads per token step (payload elements per "
            "shard = batch x heads; default 16)",
        )
        p.add_argument(
            "--colls", default=",".join(DECODE_COLLS),
            help=f"comma list of collectives ({'/'.join(DECODE_COLLS)})",
        )
        p.add_argument(
            "--n-iter", type=int, default=2000,
            help="chained device-side iterations per measurement "
            "(default 2000; tiny ops need a long chain to clear "
            "host-timer noise)",
        )

    def check_args(self, p, args) -> None:
        if args.heads < 1:
            p.error(f"--heads must be positive, got {args.heads}")
        if args.n_iter < 10:
            p.error("--n-iter must be >= 10")
        try:
            batches = [int(b) for b in args.batches.split(",") if b]
        except ValueError:
            p.error(f"--batches must be a comma list of ints, got "
                    f"{args.batches!r}")
        if not batches or any(b < 1 for b in batches):
            p.error(f"--batches entries must be positive, got "
                    f"{args.batches!r}")

    def build(self, ctx: RunContext):
        from tpu_mpi_tests.drivers import _common
        from tpu_mpi_tests.workloads.spec import SpecError

        names = _common.parse_choice_list(
            ctx.args.colls, DECODE_COLLS, "decode collective"
        )
        if names is None:
            raise SpecError(2)  # parse_choice_list printed the ERROR
        batches = [int(b) for b in ctx.args.batches.split(",") if b]
        ctx.rep.banner(
            f"decode: world={ctx.world} batches={ctx.args.batches} "
            f"heads={ctx.args.heads} colls={','.join(names)} "
            f"n_iter={ctx.args.n_iter} dtype={ctx.args.dtype}"
        )
        return {"colls": names, "batches": batches, "rows": []}

    def step(self, ctx: RunContext, state):
        import jax.numpy as jnp

        from tpu_mpi_tests.comm.collectives import shard_1d
        from tpu_mpi_tests.drivers.collbench import _loop_fn
        from tpu_mpi_tests.instrument import costs
        from tpu_mpi_tests.instrument.timers import chain_rate

        args, mesh, world = ctx.args, ctx.mesh, ctx.world
        axis_name = ctx.axis_name
        dtype = ctx.dtype()
        itemsize = jnp.dtype(dtype).itemsize
        with ctx.phase("decode_sweep"):
            for coll in state["colls"]:
                for batch in state["batches"]:
                    n = batch * args.heads  # elements per shard
                    shard_bytes = n * itemsize
                    # the µs/op pillar consumes the SAME tuned variant
                    # schedules collbench sweeps: per payload size,
                    # cached > prior (never swept here) — the decode
                    # path must not hardcode the XLA lowering while the
                    # cache says the ring twin wins at this size
                    eff, variant = _effective_coll(
                        coll, mesh, axis_name, world, n,
                        dtype, args.dtype, shard_bytes, ctx.rep.line,
                    )
                    run_fn = _loop_fn(mesh, axis_name, eff, world)
                    x = shard_1d(jnp.ones((n * world,), dtype), mesh,
                                 axis_name)
                    costs.compile_probe(
                        run_fn, (x, 1), label=f"decode_{coll}",
                        dtype=args.dtype, bytes=shard_bytes, world=world,
                    )
                    sec, x = chain_rate(
                        run_fn, x,
                        n_short=args.n_iter // 10 or 1,
                        n_long=args.n_iter,
                    )
                    us = sec * 1e6
                    row = {
                        "kind": "decode", "collective": coll,
                        "batch": batch, "heads": args.heads,
                        "shard_bytes": shard_bytes, "us_per_op": us,
                        "world": world, "dtype": args.dtype,
                        "n_iter": args.n_iter, "variant": variant,
                    }
                    state["rows"].append(row)
                    ctx.rep.line(
                        f"DECODE {coll}[{variant}] batch={batch} "
                        f"heads={args.heads} bytes={shard_bytes} "
                        f"{us:0.3f} us/op  n={args.n_iter}",
                        row,
                    )
                    del x
        return state

    def verify(self, ctx: RunContext, state) -> int:
        """Exact host-reference check of the collectives the rows time:
        per-rank rows of small integers — allreduce must return the
        elementwise sum on every rank, allgather the concatenation."""
        import numpy as np
        import jax.numpy as jnp

        from tpu_mpi_tests.comm import collectives as C

        world, mesh = ctx.world, ctx.mesh
        dtype = ctx.dtype()
        L = max(int(ctx.args.heads), 4)
        rows = np.arange(world * L, dtype=np.float64).reshape(world, L) % 7
        per_rank = C.shard_1d(jnp.asarray(rows, dtype), mesh)
        # allreduce output stays sharded: gather before the host read
        # so a multi-process run can verify too
        got_ar = np.asarray(
            C.host_value(
                C.all_gather(C.allreduce_sum(per_rank + 0, mesh), mesh)
            ),
            np.float64,
        )
        want = np.broadcast_to(rows.sum(axis=0), (world, L))
        if not np.array_equal(got_ar, want):
            ctx.rep.line("DECODE FAIL: allreduce mismatch vs host sum")
            return 1
        flat = C.shard_1d(jnp.asarray(rows.reshape(-1), dtype), mesh)
        got_ag = np.asarray(
            C.host_value(C.all_gather(flat, mesh)), np.float64
        )
        if not np.array_equal(got_ag, rows.reshape(-1)):
            ctx.rep.line("DECODE FAIL: allgather mismatch vs host "
                         "concatenation")
            return 1
        return 0

    def bytes_model(self, ctx: RunContext, state) -> int:
        # the smallest-row payload (the headline latency row's bytes)
        import jax.numpy as jnp

        item = jnp.dtype(ctx.dtype()).itemsize
        return min(state["batches"]) * ctx.args.heads * item

    def bench(self, ctx: RunContext, state) -> dict | None:
        """Headline row: the smallest-batch allreduce latency — the
        single-stream decode step cost (the per-size rows each gate
        individually through their ``kind: "decode"`` records)."""
        ar = [r for r in state["rows"] if r["collective"] == "allreduce"]
        rows = ar or state["rows"]
        if not rows:
            return None
        head = min(rows, key=lambda r: r["batch"])
        return {
            "metric": f"{head['collective']}_us_per_op",
            "value": head["us_per_op"],
            "unit": "us",
            "higher_better": False,
            "batch": head["batch"],
            "heads": head["heads"],
            "nbytes": head["shard_bytes"],
        }

    def serve_factory(self, mesh, shape, dtype):
        """Serve-mode handler: ``step_fn(n)`` runs ``n`` device-chained
        decode-step allreduces at the class's (batch, heads) shape —
        the latency-bound class mixed traffic stresses. Reuses the
        benchmark's own chained program (collbench ``_loop_fn``), which
        donates: a failed batch rebuilds the buffer so one transient
        error cannot poison the class (the collbench handler's rule).

        The allreduce variant resolves through the same
        ``coll_variant/allreduce`` schedule the one-shot rows consume,
        and the handler carries a ``tune_info`` recipe so the serve
        loop's re-tune controller (``--retune``, tune/controller.py)
        can re-sweep and hot-swap it when the class's achieved GB/s
        goes stale."""
        import jax.numpy as jnp

        from tpu_mpi_tests.comm.collectives import shard_1d
        from tpu_mpi_tests.drivers.collbench import _loop_fn
        from tpu_mpi_tests.instrument.timers import block

        if len(shape) != 2:
            raise ValueError(f"decode wants (batch, heads), got {shape}")
        batch, heads = shape
        n = batch * heads
        world = mesh.devices.size
        axis_name = mesh.axis_names[0]
        dt = jnp.dtype(dtype)
        shard_bytes = n * dt.itemsize
        ctx = {"dtype": str(dtype), "bytes": shard_bytes,
               "world": world}

        def init():
            return shard_1d(jnp.ones((n * world,), dt), mesh, axis_name)

        def build(variant=None):
            eff, _v = _effective_coll(
                "allreduce", mesh, axis_name, world, n, dt, str(dtype),
                shard_bytes, print, explicit=variant,
            )
            run_fn = _loop_fn(mesh, axis_name, eff, world)
            state = {"x": init()}

            def step(k: int):
                try:
                    state["x"] = block(run_fn(state["x"], k))
                except Exception:
                    state["x"] = init()
                    raise

            step(1)  # compile + warm before traffic opens
            step.tune_info = {
                "knob": "coll_variant/allreduce",
                "ctx": dict(ctx),
                "candidates": ("xla", "rdma", "oneshot"),
                # the RESOLVED tier this handler is serving (schedule
                # provenance, the DECODE [variant] stamp's serve twin)
                "variant": _v,
                "rebuild": build,
            }
            return step

        return build()


SPEC = register_spec(DecodeSpec())


def main(argv=None) -> int:
    from tpu_mpi_tests.workloads.runner import make_main

    return make_main(SPEC)(argv)


if __name__ == "__main__":
    sys.exit(main())
