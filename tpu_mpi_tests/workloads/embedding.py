"""Embedding gather/scatter pillar: sharded table lookup under load.

The ``gather_inplace`` pillar generalized (ROADMAP item 4): a
``(vocab, d_model)`` table row-sharded across the mesh, batches of ids
resolved to dense rows through the psum-of-partials lookup and pushed
back through the allgather scatter-add (``comm/embedding.py``). The
local-gather schedule (``embedding/lookup``: dynamic ``take`` vs
one-hot matmul) is fingerprint-tuned — ``--lookup auto`` resolves the
cached winner, ``--tune`` prices both on this exact table first — and
both directions are verified exactly against the dense host reference
(lookups are copies; scatter sums integer-valued rows).

Output lines::

    EMBED lookup: variant=<v> us_per_op=<t>
    EMBED scatter: us_per_op=<t>
    WORKLOAD embedding: lookup_us_per_op=<t> us
"""

from __future__ import annotations

import sys

from tpu_mpi_tests.workloads import register_spec
from tpu_mpi_tests.workloads.spec import RunContext, WorkloadSpec


def _build_table(seed: int, vocab: int, d_model: int, batch: int):
    """Deterministic integer-valued table/ids/updates on host — exact
    verification in every dtype (lookups copy, scatter sums small
    ints)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    table = rng.integers(-4, 5, size=(vocab, d_model)).astype(np.float64)
    ids = rng.integers(0, vocab, size=(batch,)).astype(np.int32)
    updates = rng.integers(-3, 4, size=(batch, d_model)).astype(np.float64)
    return table, ids, updates


class EmbeddingSpec(WorkloadSpec):
    name = "embedding"
    title = __doc__

    def add_args(self, p) -> None:
        p.add_argument(
            "--vocab", type=int, default=65536,
            help="table rows (sharded over the mesh axis; must divide "
            "by the device count)",
        )
        p.add_argument(
            "--d-model", type=int, default=64,
            help="row width (default 64)",
        )
        p.add_argument(
            "--batch", type=int, default=256,
            help="ids per lookup/scatter (must divide by the device "
            "count for the scatter direction)",
        )
        p.add_argument(
            "--iters", type=int, default=32,
            help="timed lookups and scatters (default 32)",
        )
        p.add_argument(
            "--lookup", default="auto",
            choices=["auto", "take", "onehot"],
            help="local-gather schedule: 'auto' resolves the "
            "embedding/lookup knob (cached winner > prior 'take'; with "
            "--tune a miss prices both on this table first)",
        )
        p.add_argument(
            "--seed", type=int, default=0,
            help="table/id RNG seed (default 0)",
        )

    def check_args(self, p, args) -> None:
        for flag, val in (("--vocab", args.vocab),
                          ("--d-model", args.d_model),
                          ("--batch", args.batch),
                          ("--iters", args.iters)):
            if val < 1:
                p.error(f"{flag} must be positive, got {val}")

    def build(self, ctx: RunContext):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_mpi_tests.utils import check_divisible

        args, mesh, world = ctx.args, ctx.mesh, ctx.world
        check_divisible(args.vocab, world, "embedding rows over mesh axis")
        check_divisible(args.batch, world, "embedding batch over mesh axis")
        dtype = ctx.dtype()
        t_host, ids_host, upd_host = _build_table(
            args.seed, args.vocab, args.d_model, args.batch
        )
        axis = ctx.axis_name
        table = jax.device_put(
            jnp.asarray(t_host, dtype), NamedSharding(mesh, P(axis, None))
        )
        ids_rep = jax.device_put(
            jnp.asarray(ids_host), NamedSharding(mesh, P())
        )
        ids_sh = jax.device_put(
            jnp.asarray(ids_host), NamedSharding(mesh, P(axis))
        )
        upd_sh = jax.device_put(
            jnp.asarray(upd_host, dtype),
            NamedSharding(mesh, P(axis, None)),
        )
        variant = None if args.lookup == "auto" else args.lookup
        if variant is None and args.tune:
            variant = self._tune_lookup(ctx, table, ids_rep)
        ctx.rep.banner(
            f"embedding: vocab={args.vocab} d_model={args.d_model} "
            f"batch={args.batch} world={world} dtype={args.dtype} "
            f"lookup={variant or 'auto'}"
        )
        return {
            "table": table, "ids_rep": ids_rep, "ids_sh": ids_sh,
            "upd_sh": upd_sh, "t_host": t_host, "ids_host": ids_host,
            "upd_host": upd_host, "variant": variant,
        }

    def _tune_lookup(self, ctx: RunContext, table, ids_rep):
        """--tune + --lookup auto: price both local-gather schedules on
        this table (sync-honest short chains), persist the winner."""
        import time

        from tpu_mpi_tests.comm import embedding as E
        from tpu_mpi_tests.instrument.timers import block
        from tpu_mpi_tests.tune.sweep import ensure_tuned

        def measure(cand):
            block(E.embedding_lookup(table, ids_rep, ctx.mesh,
                                     variant=cand))  # compile + warm
            t0 = time.perf_counter()
            for _ in range(4):
                out = E.embedding_lookup(table, ids_rep, ctx.mesh,
                                         variant=cand)
            block(out)
            return time.perf_counter() - t0

        return ensure_tuned(
            "embedding/lookup", measure, device_fallback=False,
            dtype=ctx.args.dtype, n=ctx.args.vocab,
            bytes=ctx.args.batch, world=ctx.world,
        )

    def step(self, ctx: RunContext, state):
        import time

        from tpu_mpi_tests.comm import embedding as E
        from tpu_mpi_tests.comm.embedding import resolve_lookup
        from tpu_mpi_tests.instrument.timers import block

        args = ctx.args
        table, ids_rep = state["table"], state["ids_rep"]
        variant = resolve_lookup(
            state["variant"], dtype=args.dtype, n=args.vocab,
            bytes=args.batch, world=ctx.world,
        )
        state["variant"] = variant
        # lookup: warmup, then the timed chain
        out = E.embedding_lookup(table, ids_rep, ctx.mesh,
                                 variant=variant)
        block(out)
        with ctx.phase("lookup"):
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = E.embedding_lookup(table, ids_rep, ctx.mesh,
                                         variant=variant)
            block(out)
            lookup_s = time.perf_counter() - t0
        state["lookup_out"] = out
        state["lookup_us"] = lookup_s / args.iters * 1e6
        if ctx.topo.process_index == 0:
            ctx.rep.line(
                f"EMBED lookup: variant={variant} "
                f"us_per_op={state['lookup_us']:0.3f}",
                {"kind": "embed", "dir": "lookup", "variant": variant,
                 "us_per_op": state["lookup_us"], "vocab": args.vocab,
                 "d_model": args.d_model, "batch": args.batch,
                 "world": ctx.world, "dtype": args.dtype},
            )
        # scatter-add: donates the table — chain through the donated
        # result; warmup scatters into a throwaway copy so the timed
        # chain starts from the reference state
        warm = E.embedding_scatter_add(
            table + 0, state["ids_sh"], state["upd_sh"], ctx.mesh
        )
        block(warm)
        del warm
        tab = table  # the build-time buffer is consumed by the chain
        with ctx.phase("scatter"):
            t0 = time.perf_counter()
            for _ in range(args.iters):
                tab = E.embedding_scatter_add(
                    tab, state["ids_sh"], state["upd_sh"], ctx.mesh
                )
            block(tab)
            scatter_s = time.perf_counter() - t0
        state["table_out"] = tab
        state["scatter_us"] = scatter_s / args.iters * 1e6
        if ctx.topo.process_index == 0:
            ctx.rep.line(
                f"EMBED scatter: us_per_op={state['scatter_us']:0.3f}",
                {"kind": "embed", "dir": "scatter",
                 "us_per_op": state["scatter_us"], "vocab": args.vocab,
                 "d_model": args.d_model, "batch": args.batch,
                 "world": ctx.world, "dtype": args.dtype},
            )
        return state

    def verify(self, ctx: RunContext, state) -> int:
        import numpy as np

        from tpu_mpi_tests.comm.collectives import all_gather, host_value

        t_host, ids, upd = (state["t_host"], state["ids_host"],
                            state["upd_host"])
        # lookup_out is replicated (psum), table_out row-sharded: fetch
        # through host_value (gathering first where sharded) so a
        # multi-process run can read them
        got = np.asarray(host_value(state["lookup_out"]), np.float64)
        want = t_host[ids]
        if not np.array_equal(got, want):
            bad = np.flatnonzero((got != want).any(axis=1))
            ctx.rep.line(
                f"EMBED FAIL lookup: {bad.size}/{len(ids)} rows "
                f"mismatch the dense reference, first at [{int(bad[0])}]"
            )
            return 1
        # iters scatter-adds of the same (ids, updates) accumulate
        # linearly — duplicates included (np.add.at semantics)
        ref = t_host.copy()
        np.add.at(ref, ids, upd * ctx.args.iters)
        got_t = np.asarray(
            host_value(all_gather(state["table_out"], ctx.mesh,
                                  ctx.axis_name)),
            np.float64,
        )
        if not np.array_equal(got_t, ref):
            bad = np.flatnonzero((got_t != ref).any(axis=1))
            ctx.rep.line(
                f"EMBED FAIL scatter: {bad.size}/{ref.shape[0]} table "
                f"rows mismatch the dense reference, first at "
                f"[{int(bad[0])}]"
            )
            return 1
        return 0

    def bytes_model(self, ctx: RunContext, state) -> int:
        import jax.numpy as jnp

        item = jnp.dtype(ctx.dtype()).itemsize
        row = ctx.args.batch * ctx.args.d_model * item
        return 2 * (ctx.world - 1) * row  # the lookup psum model

    def bench(self, ctx: RunContext, state) -> dict:
        return {
            "metric": "lookup_us_per_op",
            "value": state["lookup_us"],
            "unit": "us",
            "higher_better": False,
            "variant": state["variant"],
            "scatter_us_per_op": state["scatter_us"],
            "vocab": ctx.args.vocab,
            "batch": ctx.args.batch,
            "nbytes": self.bytes_model(ctx, state),
        }

    def serve_factory(self, mesh, shape, dtype):
        """Serve-mode handler: ``step_fn(n)`` resolves ``n`` lookup
        batches against a persistent sharded table (shape = ``(vocab,
        batch, d_model)``). Lookups do not donate, so failed batches
        need no rebuild; the variant resolves through the tune cache
        like any schedule (the serve preload warms it)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_mpi_tests.comm import embedding as E
        from tpu_mpi_tests.instrument.timers import block
        from tpu_mpi_tests.utils import check_divisible

        if len(shape) != 3:
            raise ValueError(
                f"embedding wants (vocab, batch, d_model), got {shape}"
            )
        vocab, batch, d_model = shape
        world = mesh.devices.size
        axis_name = mesh.axis_names[0]
        check_divisible(vocab, world, "embedding rows over mesh axis")
        t_host, ids_host, _ = _build_table(0, vocab, d_model, batch)
        table = jax.device_put(
            jnp.asarray(t_host, jnp.dtype(dtype)),
            NamedSharding(mesh, P(axis_name, None)),
        )
        ids = jax.device_put(
            jnp.asarray(ids_host), NamedSharding(mesh, P())
        )

        def step(k: int):
            out = None
            for _ in range(k):
                out = E.embedding_lookup(table, ids, mesh)
            block(out)

        step(1)  # compile + warm before traffic opens
        return step


SPEC = register_spec(EmbeddingSpec())


def main(argv=None) -> int:
    from tpu_mpi_tests.workloads.runner import make_main

    return make_main(SPEC)(argv)


if __name__ == "__main__":
    sys.exit(main())
