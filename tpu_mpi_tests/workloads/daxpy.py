"""Single-device DAXPY with checksum verification — as a workload spec.

≅ ``daxpy.cu`` (and, with ``--profile-dir``, ``daxpy_nvtx.cu`` — the NVTX
twin is a flag here, not a second binary). Semantics preserved: n=1024
default, a=2.0, x=i+1, y=-(i+1), result y=i+1, checksum n(n+1)/2 printed as
``SUM = <v>`` (``daxpy.cu:82-88``). The copyInput/daxpy/copyOutput phase
structure of ``mpi_daxpy_nvtx.cu:72-91`` maps to trace ranges + phase
timers.

This is the first driver ported onto the declarative workload-spec
subsystem (``tpu_mpi_tests/workloads/``): the spec holds exactly the
pillar-specific body — build (host init + H2D), step (the kernel +
D2H), verify (per-element + checksum gates) — and the generic runner
supplies the parser/platform/reporter/serve plumbing the old driver
hand-rolled. Stdout is byte-identical to the pre-port driver (gated in
``tests/test_workloads.py``); ``drivers/daxpy.py`` remains the
compatible entry point.
"""

from __future__ import annotations

import sys

from tpu_mpi_tests.tune import priors as _priors
from tpu_mpi_tests.tune.registry import declare_space
from tpu_mpi_tests.workloads import register_spec
from tpu_mpi_tests.workloads.spec import RunContext, WorkloadSpec

#: host-dispatch chunking (ISSUE 14): how many kernel applications one
#: dispatch chains device-side. The prior (1) is the reference's
#: dispatch-per-iteration loop, byte-identical; bigger chunks amortize
#: the per-dispatch fixed cost. Declared where the knob lives; a
#: LOCAL-compute space by design, so the rank-0-swept fleet protocol is
#: measurable on every backend (the fleet-smoke candidate knob).
CHUNK_SPACE = declare_space(
    "daxpy/chunk", (_priors.DAXPY_CHUNK, 8, 32),
    describe="device-chained kernel applications per host dispatch",
)


class DaxpySpec(WorkloadSpec):
    name = "daxpy"
    title = __doc__
    needs_mesh = False

    def add_args(self, p) -> None:
        p.add_argument("--n", type=int, default=1024, help="vector length")
        p.add_argument(
            "--a", type=float, default=2.0, help="scalar multiplier"
        )
        p.add_argument(
            "--print-elements",
            action="store_true",
            help="print every y element (the reference always does; "
            "daxpy.cu:84)",
        )
        p.add_argument(
            "--iters",
            type=int,
            default=1,
            metavar="K",
            help="re-run the identical kernel K times (same inputs each "
            "time, so the result and every verification gate are "
            "unchanged; the kernel phase is re-entered K times) — the "
            "steady-state repetition knob for memwatch/chaos "
            "observation runs. Default 1 = the reference's one-shot "
            "semantics, stdout byte-identical",
        )

    def check_args(self, p, args) -> None:
        if args.n < 1:
            p.error(f"--n must be positive, got {args.n}")
        if args.iters < 1:
            p.error(f"--iters must be positive, got {args.iters}")

    def build(self, ctx: RunContext):
        import tpu_mpi_tests.kernels.daxpy as kd
        from tpu_mpi_tests.arrays.spaces import Space, place, to_device
        from tpu_mpi_tests.instrument.timers import block

        dtype = ctx.dtype()
        # initializeArrays on host, then copyInput H2D (daxpy_nvtx.cu:72-79)
        h_x, h_y = kd.init_xy_np(ctx.args.n, dtype)
        with ctx.phase("copyInput"):
            d_x = block(to_device(place(h_x, Space.HOST)))
            d_y = block(to_device(place(h_y, Space.HOST)))
        return {"d_x": d_x, "d_y": d_y, "dtype": dtype}

    def step(self, ctx: RunContext, state):
        import jax.numpy as jnp
        import numpy as np

        import tpu_mpi_tests.kernels.daxpy as kd
        from tpu_mpi_tests.instrument import costs
        from tpu_mpi_tests.instrument.timers import block

        # compile-cost probe (telemetry runs only): AOT-compiles the
        # kernel once, recording compile wall time + the compiler's
        # flops/bytes model as a kind:"compile" record; phase="kernel"
        # lets tpumt-report join it against the measured phase time
        # for the roofline column (instrument/costs.py)
        a_dev = jnp.asarray(ctx.args.a, state["dtype"])
        costs.compile_probe(
            kd.daxpy, (a_dev, state["d_x"], state["d_y"]), label="daxpy",
            phase="kernel", n=ctx.args.n, dtype=ctx.args.dtype,
        )
        # --iters re-runs the IDENTICAL call (original y each time):
        # the result and every gate below stay those of one
        # application, while the phase re-enters K times — repeated
        # boundaries for the memwatch hooks and chaos triggers.
        # The daxpy/chunk schedule (explicit-free: cached > prior, a
        # --tune miss sweeps — multi-process runs take the rank-0-swept
        # broadcast-applied fleet path) chains applications device-side:
        # every iteration recomputes from the same operands, so any
        # chunk yields the bitwise single-application result and the
        # gates below are unchanged. chunk == 1 (the prior) runs the
        # reference's dispatch-per-iteration loop verbatim.
        import time as _time

        from tpu_mpi_tests.tune.sweep import ensure_tuned

        chain = self._chunk_fn(ctx, state, a_dev)

        def measure(cand):
            c = max(1, int(cand))
            block(chain(c))  # compile + warm
            reps = max(2, 16 // c)
            t0 = _time.perf_counter()
            for _ in range(reps):
                block(chain(c))
            return (_time.perf_counter() - t0) / (reps * c)

        chunk = ensure_tuned(
            "daxpy/chunk", measure, n=ctx.args.n, dtype=ctx.args.dtype,
        )
        try:
            chunk = max(1, int(chunk))
        except (TypeError, ValueError):
            chunk = 1  # malformed cache value degrades to the prior

        if chunk > 1:
            left = ctx.args.iters
            while left > 0:
                k = min(chunk, left)
                with ctx.phase("kernel"):
                    d_y = block(chain(k))
                left -= k
        else:
            for _ in range(ctx.args.iters):
                with ctx.phase("kernel"):
                    d_y = block(
                        kd.daxpy(a_dev, state["d_x"], state["d_y"])
                    )

        with ctx.phase("copyOutput"):
            state["y"] = np.asarray(d_y)
        return state

    def _chunk_fn(self, ctx: RunContext, state, a_dev):
        """One jitted dispatch of ``k`` chained kernel applications.
        The fori_loop body ignores its carry and recomputes from the
        original operands, so the chain's result is bitwise the
        single-application result at every ``k`` — chunking changes
        dispatch count, never numerics. Building it is free (jit is
        lazy); the default chunk==1 path never calls it."""
        import jax
        from jax import lax

        import tpu_mpi_tests.kernels.daxpy as kd

        d_x, d_y = state["d_x"], state["d_y"]

        @jax.jit
        def chain(k):
            return lax.fori_loop(
                0, k, lambda _i, _y: kd.daxpy(a_dev, d_x, d_y), d_y
            )

        return chain

    def verify(self, ctx: RunContext, state) -> int:
        import numpy as np

        import tpu_mpi_tests.kernels.daxpy as kd

        args, rep, y = ctx.args, ctx.rep, state["y"]
        n, dtype = args.n, state["dtype"]
        if args.print_elements:
            for v in y:
                rep.line(f"{v:f}")
        total = float(y.sum(dtype=np.float64))
        rep.sum_line(total)
        # --verbose appends count/mean/min/max per phase on the TIME lines;
        # the JSONL time records always carry the distribution
        rep.time_lines(ctx.timer, stats=args.verbose)

        # per-element verification (≅ the reference's per-element loop,
        # daxpy.cu:82-87): a compensating-error bug passes a checksum, so
        # with the reference's a=2 every element is asserted exactly. This
        # holds for ANY n and dtype: x is stored as x̂ = dtype(i+1), the
        # multiply by 2 is exact (power of two), and 2x̂ − x̂ = x̂ exactly
        # (Sterbenz lemma), so the device result must bit-equal dtype(i+1)
        # even where i+1 itself rounds. Other a values fall back to the
        # checksum alone — matching the reference, whose check is
        # hardwired to its init (daxpy.cu:85).
        if args.a == 2.0:
            h_want = np.arange(1, n + 1, dtype=np.float64).astype(dtype)
            bad = np.flatnonzero(y != np.asarray(h_want))
            if bad.size:
                i = int(bad[0])
                rep.line(
                    f"ELEMENT FAIL: {bad.size}/{n} mismatches, first at "
                    f"[{i}]: got {y[i]}, expected {np.asarray(h_want)[i]}"
                )
                return 1

        expected = kd.expected_checksum(n)
        # float32 accumulates rounding over large n; scale tolerance with n
        tol = 0 if args.dtype == "float64" else max(1e-6 * expected, 1.0)
        if abs(total - expected) > tol:
            rep.line(f"CHECKSUM FAIL: got {total}, expected {expected}")
            return 1
        return 0

    def serve_factory(self, mesh, shape, dtype):
        """Serve-mode handler (``drivers/_common.py`` workload registry):
        ``step_fn(n)`` runs ``n`` device-chained DAXPY steps against
        persistent buffers. The recurrence ``y ← a·x + y/2`` keeps the
        iterate bounded (fixed point 2·a·x) so an hours-long serve run
        can never overflow the state the way the raw accumulating kernel
        would. ``mesh`` is unused — DAXPY is the single-device workload
        class."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from tpu_mpi_tests.instrument.timers import block

        if len(shape) != 1:
            raise ValueError(f"daxpy wants a 1-d shape, got {shape}")
        (n,) = shape
        dt = jnp.dtype(dtype)
        x = jnp.arange(1, n + 1, dtype=dt)
        a = jnp.asarray(2.0, dt)
        half = jnp.asarray(0.5, dt)

        @jax.jit
        def run(y, k):
            return lax.fori_loop(0, k, lambda _, yy: a * x + yy * half, y)

        state = {"y": jnp.zeros((n,), dt)}

        def step(k: int):
            state["y"] = block(run(state["y"], k))

        step(1)  # compile + warm before traffic opens
        return step


SPEC = register_spec(DaxpySpec())


def main(argv=None) -> int:
    from tpu_mpi_tests.workloads.runner import make_main

    return make_main(SPEC)(argv)


if __name__ == "__main__":
    sys.exit(main())
