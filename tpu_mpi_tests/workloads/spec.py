"""The workload-spec contract and the per-run context it executes in.

A :class:`WorkloadSpec` is the declarative unit the generic runner
(:mod:`~tpu_mpi_tests.workloads.runner`) drives: hooks for the
pillar-specific parts, attributes for the wiring decisions the runner
makes on its behalf. The contract mirrors the drivers it replaces —
``build`` is mesh/sharding/state setup, ``step`` is the measured body
(it prints the pillar's measured lines and owns its phase timing via
``ctx.phase``), ``verify`` is the analytic gate, ``bench`` the stable
row — so porting a driver is moving code, not rewriting it (gated by
the byte-identical daxpy/stencil1d ports in ``tests/test_workloads.py``).

Spec modules must stay importable without jax (the serve registry and
``tpumt-report`` import them on login nodes); hooks import jax inside
their bodies like every driver ``run`` does.
"""

from __future__ import annotations

import dataclasses
from contextlib import contextmanager
from typing import Any


class SpecError(Exception):
    """Raised by a spec hook for a user-input/configuration error the
    runner should turn into a clean nonzero exit — the hook prints its
    own ERROR line first (the driver convention: no tracebacks for bad
    flags)."""

    def __init__(self, rc: int = 2):
        super().__init__(rc)
        self.rc = rc


@dataclasses.dataclass
class RunContext:
    """Everything a spec hook may need, built once per run by the
    runner: parsed args, the Reporter (JSONL + stdout lines), the mesh
    and topology (None / trivial for ``needs_mesh=False`` specs), and a
    shared PhaseTimer whose lines/records the spec decides to emit."""

    spec: "WorkloadSpec"
    args: Any
    rep: Any
    topo: Any
    mesh: Any
    timer: Any

    @property
    def world(self) -> int:
        return self.topo.global_device_count if self.topo else 1

    @property
    def axis_name(self) -> str:
        return self.mesh.axis_names[0]

    def dtype(self):
        """The run's jnp dtype (imports jax — hook-body use only)."""
        from tpu_mpi_tests.drivers import _common

        return _common.jnp_dtype(self.args)

    @contextmanager
    def phase(self, name: str):
        """One timed phase: an XProf trace range + a PhaseTimer phase
        (sync-honest — the timer blocks at the boundary), the same
        bracketing every driver hand-rolls."""
        from tpu_mpi_tests.instrument.trace import trace_range

        with trace_range(name), self.timer.phase(name):
            yield


class WorkloadSpec:
    """Base class: override the hooks; attributes steer the runner.

    ``name`` is the spec/driver identity (``python -m
    tpu_mpi_tests.workloads.<name>``, the WORKLOAD row key);
    ``serve_name`` (default: ``name``) is the serve-mode workload-class
    name — distinct where a driver historically registered under
    another name (stencil1d serves as ``halo``). ``needs_mesh=False``
    specs run single-device with a rank-0/size-1 reporter (the daxpy
    shape); everything else gets ``bootstrap → topology → make_mesh``.
    """

    name: str = "?"
    title: str = ""
    needs_mesh: bool = True

    # -- CLI -------------------------------------------------------------
    def add_args(self, p) -> None:
        """Spec-specific flags on top of the shared ``base_parser``."""

    def check_args(self, p, args) -> None:
        """Validate; call ``p.error(...)`` on bad values (exit 2)."""

    # -- the run ---------------------------------------------------------
    def build(self, ctx: RunContext):
        """Initialize state (device buffers, resolved schedules).
        Returns the state object threaded through ``step``/``verify``."""
        raise NotImplementedError

    def step(self, ctx: RunContext, state):
        """The measured body: warmup + timed phases + the pillar's
        measured stdout lines/records. Returns the (possibly updated)
        state. Must end device-synced (``block``/``chain_rate``/span) —
        the repo's sync-honesty discipline is the spec's obligation."""
        raise NotImplementedError

    def verify(self, ctx: RunContext, state) -> int:
        """Analytic verification gate: print FAIL lines and return a
        nonzero rc on mismatch, 0 on pass."""
        raise NotImplementedError

    # -- models / rows ---------------------------------------------------
    def bytes_model(self, ctx: RunContext, state) -> int | None:
        """Nominal comm payload bytes of one step — the span/bench
        annotation, not a bandwidth claim. None when the comm wrappers
        the spec calls already annotate their own spans (the ported
        pillars) — the model then lives next to the collective."""
        return None

    def bench(self, ctx: RunContext, state) -> dict | None:
        """The stable bench row: ``{"metric", "value", "unit",
        "higher_better", ...extras}`` or None for no row (the ported
        drivers keep their historical lines instead). The runner prints
        it as ``WORKLOAD <name>: <metric>=<value> <unit>`` and emits a
        ``kind: "workload"`` record that ``tpumt-report`` renders and
        ``--diff`` gates."""
        return None

    # -- serve mode ------------------------------------------------------
    @property
    def serve_name(self) -> str:
        return self.name

    #: ``(mesh, shape, dtype) -> step_fn(n)`` or None; registered into
    #: the drivers/_common.py workload registry by ``register_spec``
    serve_factory = None
