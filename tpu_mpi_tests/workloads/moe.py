"""MoE token-routing pillar: capacity-bucketed all-to-all under load.

≅ nothing in the reference — this is the serving-era shape of its
all-to-all pattern (ROADMAP item 4): tokens sharded across the mesh,
each naming a destination expert (one per rank), dispatched and
combined through two ``lax.all_to_all`` hops with a fixed per-pair
``capacity`` and standard MoE overflow-drop semantics
(``comm/moe.py``). The measurement is the routing distribution as much
as the time: every routed step's occupancy, overflow %, and per-expert
imbalance land as ``kind: "route"`` records (``tpumt-report`` ROUTE
table; ``--diff`` gates overflow) next to the ``us_per_step`` bench
row. Verification is exact against the dense host reference
(``route_reference``) — integer-valued tokens, analytic ``(e+1)·x``
experts.

Output lines::

    ROUTE moe: world=<w> capacity=<c> tokens=<t> routed=<n> \
dropped=<d> overflow=<f>% occupancy=<o>% imbalance=<i>
    WORKLOAD moe: us_per_step=<v> us
"""

from __future__ import annotations

import sys

from tpu_mpi_tests.workloads import register_spec
from tpu_mpi_tests.workloads.spec import RunContext, WorkloadSpec


def _capacity(tokens: int, world: int, factor: float) -> int:
    """Per-(source, expert) slot count: the uniform expectation
    ``tokens/world²`` scaled by the provisioning factor, floored at 1."""
    expect = tokens / (world * world)
    return max(1, int(expect * factor + 0.999999))


def _build_tokens(seed: int, tokens: int, d_model: int, skew: float,
                  world: int):
    """Deterministic integer-valued tokens + skewed destinations on
    host: weights ∝ (e+1)^−skew so imbalance (and, at factor ≈ 1,
    overflow) is real, not a degenerate zero."""
    import numpy as np

    rng = np.random.default_rng(seed)
    x = rng.integers(1, 8, size=(tokens, d_model))
    w_e = (np.arange(1, world + 1, dtype=np.float64)) ** (-skew)
    dest = rng.choice(world, size=tokens, p=w_e / w_e.sum())
    return x.astype(np.float64), dest.astype(np.int32)


class MoESpec(WorkloadSpec):
    name = "moe"
    title = __doc__

    def add_args(self, p) -> None:
        p.add_argument(
            "--tokens", type=int, default=4096,
            help="global token count (sharded over the mesh axis; must "
            "divide by the device count)",
        )
        p.add_argument(
            "--d-model", type=int, default=64,
            help="token width (default 64)",
        )
        p.add_argument(
            "--capacity-factor", type=float, default=1.25,
            help="per-(source, expert) slots as a multiple of the "
            "uniform expectation tokens/world^2 (default 1.25; <= 1 "
            "guarantees overflow under any skew)",
        )
        p.add_argument(
            "--route-skew", type=float, default=0.5,
            help="destination skew: expert e drawn with weight "
            "(e+1)^-skew (0 = uniform; default 0.5)",
        )
        p.add_argument(
            "--iters", type=int, default=32,
            help="timed routed steps (default 32)",
        )
        p.add_argument(
            "--combine", default="auto",
            choices=["auto", "alltoall", "allgather"],
            help="combine-hop schedule: 'auto' resolves the moe/combine "
            "knob (cached winner > prior; with --tune a miss prices "
            "both on this shape first)",
        )
        p.add_argument(
            "--seed", type=int, default=0,
            help="token/destination RNG seed (deterministic routing and "
            "drop accounting across runs)",
        )

    def check_args(self, p, args) -> None:
        for flag, val in (("--tokens", args.tokens),
                          ("--d-model", args.d_model),
                          ("--iters", args.iters)):
            if val < 1:
                p.error(f"{flag} must be positive, got {val}")
        if args.capacity_factor <= 0:
            p.error("--capacity-factor must be positive")
        if args.route_skew < 0:
            p.error("--route-skew must be >= 0")

    def build(self, ctx: RunContext):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_mpi_tests.utils import check_divisible

        args, mesh, world = ctx.args, ctx.mesh, ctx.world
        check_divisible(args.tokens, world, "moe tokens over mesh axis")
        dtype = ctx.dtype()
        capacity = _capacity(args.tokens, world, args.capacity_factor)
        x_host, dest_host = _build_tokens(
            args.seed, args.tokens, args.d_model, args.route_skew, world
        )
        xs = jax.device_put(
            jnp.asarray(x_host, dtype),
            NamedSharding(mesh, P(ctx.axis_name, None)),
        )
        ds = jax.device_put(
            jnp.asarray(dest_host),
            NamedSharding(mesh, P(ctx.axis_name)),
        )
        combine = None if args.combine == "auto" else args.combine
        if combine is None and args.tune:
            combine = self._tune_combine(ctx, xs, ds, capacity)
        if combine is None:
            # resolve the cached winner (same fingerprint context as
            # route_tokens') so the banner/bytes_model/bench row report
            # the variant that actually runs, not the prior
            from tpu_mpi_tests.comm.moe import resolve_combine

            combine = resolve_combine(
                dtype=str(xs.dtype), n=args.tokens, world=world,
            )
        ctx.rep.banner(
            f"moe: tokens={args.tokens} d_model={args.d_model} "
            f"world={world} capacity={capacity} "
            f"(factor={args.capacity_factor:g}) skew={args.route_skew:g} "
            f"dtype={args.dtype} combine={combine}"
        )
        return {
            "x": xs, "dest": ds, "x_host": x_host,
            "dest_host": dest_host, "capacity": capacity,
            "combine": combine,
        }

    def _tune_combine(self, ctx: RunContext, xs, ds, capacity):
        """--tune + --combine auto: price both combine schedules on
        this exact shape (sync-honest short routed chains), persist the
        winner, return it (a warmed cache is a pure hit)."""
        from tpu_mpi_tests.comm import moe as M
        from tpu_mpi_tests.instrument.timers import block
        from tpu_mpi_tests.tune.sweep import ensure_tuned
        import time

        def measure(cand):
            y, _ = M.route_tokens(xs, ds, ctx.mesh, capacity,
                                  combine=cand)  # compile + warm
            block(y)
            t0 = time.perf_counter()
            for _ in range(4):
                y, _ = M.route_tokens(xs, ds, ctx.mesh, capacity,
                                      combine=cand)
            block(y)
            return time.perf_counter() - t0

        return ensure_tuned(
            "moe/combine", measure, device_fallback=False,
            dtype=ctx.args.dtype, n=ctx.args.tokens, world=ctx.world,
        )

    def step(self, ctx: RunContext, state):
        import time

        from tpu_mpi_tests.comm import moe as M
        from tpu_mpi_tests.instrument.timers import block

        args = ctx.args
        xs, ds = state["x"], state["dest"]
        capacity, combine = state["capacity"], state["combine"]
        # untimed warmup: compile + first-touch outside the window
        y, stats = M.route_tokens(xs, ds, ctx.mesh, capacity,
                                  combine=combine)
        block(y)
        with ctx.phase("route"):
            t0 = time.perf_counter()
            for _ in range(args.iters):
                y, stats = M.route_tokens(xs, ds, ctx.mesh, capacity,
                                          combine=combine)
                block(y)
            seconds = time.perf_counter() - t0
        state["y"], state["stats"] = y, stats
        state["us_per_step"] = seconds / args.iters * 1e6
        if ctx.topo.process_index == 0:
            ctx.rep.line(
                f"ROUTE moe: world={stats.world} "
                f"capacity={stats.capacity} tokens={stats.tokens} "
                f"routed={stats.routed} dropped={stats.dropped} "
                f"overflow={stats.overflow_pct:.2f}% "
                f"occupancy={stats.occupancy_pct:.1f}% "
                f"imbalance={stats.imbalance:.3f}",
                stats.record(op="moe", dtype=args.dtype),
            )
        return state

    def verify(self, ctx: RunContext, state) -> int:
        import numpy as np

        from tpu_mpi_tests.comm.collectives import all_gather, host_value
        from tpu_mpi_tests.comm.moe import route_reference

        # gather the token-sharded result before the host read — a
        # multi-process run cannot np.asarray a sharded array
        got = host_value(all_gather(state["y"], ctx.mesh, ctx.axis_name))
        ref = route_reference(
            state["x_host"], state["dest_host"], ctx.world,
            state["capacity"],
        ).astype(got.dtype)
        if not np.array_equal(got, ref):
            bad = np.flatnonzero((got != ref).any(axis=1))
            i = int(bad[0])
            ctx.rep.line(
                f"ROUTE FAIL: {bad.size}/{got.shape[0]} token rows "
                f"mismatch the dense reference, first at [{i}]: got "
                f"{got[i][:4]}, expected {ref[i][:4]}"
            )
            return 1
        # the drop accounting must agree with the reference's drop rule
        ref_dropped = int((ref.sum(axis=1) == 0).sum()
                          - (state["x_host"].sum(axis=1) == 0).sum())
        if state["stats"].dropped != ref_dropped:
            ctx.rep.line(
                f"ROUTE FAIL: recorded dropped={state['stats'].dropped} "
                f"!= reference {ref_dropped}"
            )
            return 1
        return 0

    def bytes_model(self, ctx: RunContext, state) -> int:
        from tpu_mpi_tests.comm.moe import route_payload_bytes

        return route_payload_bytes(
            state["x"], ctx.world, state["capacity"], state["combine"],
        )

    def bench(self, ctx: RunContext, state) -> dict:
        stats = state["stats"]
        return {
            "metric": "us_per_step",
            "value": state["us_per_step"],
            "unit": "us",
            "higher_better": False,
            "tokens": ctx.args.tokens,
            "capacity": stats.capacity,
            "overflow_pct": stats.overflow_pct,
            "occupancy_pct": stats.occupancy_pct,
            "imbalance": stats.imbalance,
            "nbytes": self.bytes_model(ctx, state),
        }

    def serve_factory(self, mesh, shape, dtype):
        """Serve-mode handler: ``step_fn(n)`` runs ``n`` routed steps on
        a persistent token set (shape = ``(tokens, d_model)``; experts =
        mesh ranks; capacity factor 1.25, seed 0 — deterministic drop
        accounting per class). Routing does not donate its inputs, so a
        failed batch needs no state rebuild; with ``--telemetry`` every
        request batch lands its route record on the JSONL stream."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_mpi_tests.comm import moe as M
        from tpu_mpi_tests.instrument.timers import block
        from tpu_mpi_tests.utils import check_divisible

        if len(shape) != 2:
            raise ValueError(f"moe wants (tokens, d_model), got {shape}")
        tokens, d_model = shape
        world = mesh.devices.size
        axis_name = mesh.axis_names[0]
        check_divisible(tokens, world, "moe tokens over mesh axis")
        capacity = _capacity(tokens, world, 1.25)
        x_host, dest_host = _build_tokens(0, tokens, d_model, 0.5, world)
        xs = jax.device_put(
            jnp.asarray(x_host, jnp.dtype(dtype)),
            NamedSharding(mesh, P(axis_name, None)),
        )
        ds = jax.device_put(
            jnp.asarray(dest_host), NamedSharding(mesh, P(axis_name)),
        )

        def step(k: int):
            y = None
            for _ in range(k):
                y, _ = M.route_tokens(xs, ds, mesh, capacity)
            block(y)

        step(1)  # compile + warm before traffic opens
        return step


SPEC = register_spec(MoESpec())


def main(argv=None) -> int:
    from tpu_mpi_tests.workloads.runner import make_main

    return make_main(SPEC)(argv)


if __name__ == "__main__":
    sys.exit(main())
