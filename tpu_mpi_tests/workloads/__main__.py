"""``python -m tpu_mpi_tests.workloads <spec> [args...]`` — the
umbrella workload CLI (also installed as ``tpumt-workload``)."""

import sys

from tpu_mpi_tests.workloads.runner import main

if __name__ == "__main__":
    sys.exit(main())
