"""The generic workload runner: one flow for every spec.

Everything the hand-written drivers each re-implemented happens here
exactly once:

* arg parsing through the shared ``base_parser`` (``--fake-devices``,
  ``--dtype``, ``--jsonl``, ``--telemetry``, ``--memwatch``, ``--tune``
  and friends all work for every spec by construction);
* platform/dtype/tune-cache setup + the hang watchdog
  (``run_guarded``);
* ``bootstrap → topology → make_mesh`` and the full observability
  wiring via ``make_reporter`` (manifest, clock sync, telemetry sink,
  memwatch, tune-record sink, ``--trace-out`` merge);
* the ``build → step → verify`` hook sequence under a ProfilerGate,
  with a shared PhaseTimer the spec brackets via ``ctx.phase``;
* the stable bench row: a spec returning ``bench(...)`` gets a
  ``WORKLOAD <name>: <metric>=<value> <unit>`` line plus a
  ``kind: "workload"`` JSONL record — rendered by ``tpumt-report`` and
  gated by ``--diff`` with no per-spec aggregation code.

``main(argv)`` is the umbrella CLI (``python -m
tpu_mpi_tests.workloads <name> ...`` / ``tpumt-workload``); each spec
module also exposes its own ``make_main``-built entry point so
``python -m tpu_mpi_tests.workloads.moe`` behaves like any driver.
"""

from __future__ import annotations

import functools

from tpu_mpi_tests.drivers import _common
from tpu_mpi_tests.workloads.spec import (
    RunContext,
    SpecError,
    WorkloadSpec,
)


def run_body(spec: WorkloadSpec, args) -> int:
    """The guarded driver body: reporter + hook sequence + bench row."""
    from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh, topology
    from tpu_mpi_tests.instrument import PhaseTimer, ProfilerGate

    bootstrap()
    topo = topology()
    mesh = None
    rank, size = 0, 1
    if spec.needs_mesh:
        mesh = make_mesh()
        rank, size = topo.process_index, topo.global_device_count
    rep = _common.make_reporter(args, rank=rank, size=size)
    with rep:
        ctx = RunContext(
            spec=spec, args=args, rep=rep, topo=topo, mesh=mesh,
            timer=PhaseTimer(),
        )
        try:
            with ProfilerGate(args.profile_dir):
                state = spec.build(ctx)
                state = spec.step(ctx, state)
            rc = int(spec.verify(ctx, state) or 0)
        except SpecError as e:
            return e.rc  # the hook printed its ERROR line already
        # no bench row on a failed verify: a correctness-broken run
        # must not seed the --diff-gated metric series with a
        # valid-looking headline number
        if rc == 0:
            row = spec.bench(ctx, state)
            if row:
                _emit_bench_row(ctx, row)
        return rc


def _emit_bench_row(ctx: RunContext, row: dict) -> None:
    """One stable bench line + ``kind: "workload"`` record per run.
    The record carries ``higher_better`` so the ``--diff`` gate knows
    the regression direction without a hard-coded metric table."""
    metric = row["metric"]
    value = float(row["value"])
    unit = row.get("unit", "")
    rec = {
        "kind": "workload",
        "workload": ctx.spec.name,
        "metric": metric,
        "value": value,
        "unit": unit,
        "higher_better": bool(row.get("higher_better", True)),
        "dtype": ctx.args.dtype,
        "world": ctx.world,
    }
    for k, v in row.items():
        if k not in ("metric", "value", "unit", "higher_better"):
            rec[k] = v
    ctx.rep.line(
        f"WORKLOAD {ctx.spec.name}: {metric}={value:.6g}"
        f"{' ' + unit if unit else ''}",
        rec,
    )


def make_main(spec: WorkloadSpec):
    """Build a driver-shaped ``main(argv) -> int`` for one spec."""

    def main(argv=None) -> int:
        p = _common.base_parser(spec.title or spec.name)
        spec.add_args(p)
        args = p.parse_args(argv)
        spec.check_args(p, args)
        _common.setup_platform(args)
        return _common.run_guarded(functools.partial(run_body, spec), args)

    main.__doc__ = spec.title
    return main


def main(argv=None) -> int:
    """Umbrella CLI: ``tpumt-workload <spec> [spec args...]`` (or
    ``--list``). The spec name routes to its own ``make_main`` parser,
    so ``tpumt-workload moe --help`` shows the moe surface."""
    import sys

    from tpu_mpi_tests import workloads

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("--list", "-l"):
        for name in workloads.spec_names():
            print(name)
        return 0
    if argv[0] in ("--help", "-h"):
        print("usage: tpumt-workload <spec> [args...] | --list")
        print("specs:", ", ".join(workloads.spec_names()))
        return 0
    name, rest = argv[0], argv[1:]
    try:
        spec = workloads.get_spec(name)
    except KeyError as e:
        print(f"ERROR {e.args[0]}", file=sys.stderr)
        return 2
    return make_main(spec)(rest)


if __name__ == "__main__":
    import sys

    sys.exit(main())
