"""Distributed 1-D 5-point stencil with halo exchange — as a workload spec.

≅ ``mpi_stencil_gt.cc`` (call stack SURVEY.md §3.3): y = x³ over n_global
points (default 32Mi, ``--n-global-mi`` in Mi units like the reference
argv), decomposed across ranks with ghost width 2; one timed halo
exchange; stencil derivative; per-rank ``err_norm`` vs the analytic 3x²,
exact to rounding for a cubic. Output lines preserved::

    <rank>/<size> exchange time <s>
    <rank>/<size> [<device>] err_norm = <v>

Ported onto the declarative workload-spec subsystem
(``tpu_mpi_tests/workloads/``): build = domain init + staging-schedule
resolution, step = the warmup/timed exchange + derivative, verify = the
err-norm gate (+ the ``--overlap`` pipeline mode). Stdout is
byte-identical to the pre-port driver (``tests/test_workloads.py``);
``drivers/stencil1d.py`` remains the compatible entry point, and the
serve-mode handler still registers as the ``halo`` workload class.
"""

from __future__ import annotations

import sys
import time

from tpu_mpi_tests.workloads import register_spec
from tpu_mpi_tests.workloads.spec import RunContext, WorkloadSpec


class Stencil1dSpec(WorkloadSpec):
    name = "stencil1d"
    title = __doc__

    @property
    def serve_name(self) -> str:
        return "halo"

    def add_args(self, p) -> None:
        p.add_argument(
            "--n-global-mi",
            type=int,
            default=None,
            help="global size in Mi elements (reference argv unit; "
            "default 32)",
        )
        p.add_argument(
            "--n-global",
            type=int,
            default=32 * 1024 * 1024,
            help="global size in elements (exact; overridden by "
            "--n-global-mi)",
        )
        p.add_argument(
            "--staging",
            default="direct",
            choices=["direct", "device", "host", "pallas", "auto"],
            help="halo staging mode (≅ reference stage_host/device "
            "variants; 'pallas' = hand-written inter-chip RDMA ring "
            "kernel; 'auto' = the schedule cache's tuned winner for "
            "this topology — with --tune a cache miss runs the "
            "measured sweep first)",
        )
        p.add_argument(
            "--tol",
            type=float,
            default=None,
            help="err_norm gate (default: dtype-dependent)",
        )
        p.add_argument(
            "--overlap",
            default="0",
            choices=["0", "1", "2", "auto"],
            help="run the double-buffered halo pipeline after the gate "
            "(README 'Overlap engine'): 0 = off (default), 1 = the "
            "serialized schedule, 2 = exchange in flight under the "
            "interior compute, auto = the schedule cache's tuned depth "
            "(with --tune a cache miss sweeps the candidates first); "
            "depth>=2 is verified bit-identical to depth 1",
        )
        p.add_argument(
            "--overlap-iters",
            type=int,
            default=32,
            help="pipeline steps for --overlap (default 32)",
        )

    def check_args(self, p, args) -> None:
        if args.overlap_iters < 1:
            p.error("--overlap-iters must be positive")
        if args.n_global_mi is not None:
            args.n_global = args.n_global_mi * 1024 * 1024
        if args.n_global < 1:
            p.error(f"global size must be positive, got {args.n_global}")

    def build(self, ctx: RunContext):
        from tpu_mpi_tests.arrays.domain import Domain1D
        from tpu_mpi_tests.comm import collectives as C
        from tpu_mpi_tests.comm import halo as H
        from tpu_mpi_tests.instrument.timers import block
        from tpu_mpi_tests.kernels.stencil import analytic_pairs

        args, world = ctx.args, ctx.world
        dtype = ctx.dtype()
        n_global = args.n_global
        d = Domain1D(n_global=n_global, n_shards=world, n_bnd=2)
        f, df = analytic_pairs()["1d"]

        ctx.rep.banner(
            f"stencil1d: n_global={n_global} world={world} "
            f"n_local={d.n_local} dtype={args.dtype} "
            f"staging={args.staging}"
        )

        # shards materialize on their own devices (multi-GB host→device
        # init transfer is the wrong tool at 32Mi+ scale — see
        # collectives.device_init)
        zg = block(
            C.device_init(
                ctx.mesh, lambda r: d.init_shard_jax(f, r, dtype), ndim=1
            )
        )

        staging = H.Staging.parse(args.staging)
        if staging is H.Staging.AUTO:
            if args.tune:
                # measured sweep over the halo schedule space (staging
                # strategy + ppermute-vs-RDMA flavor) on this exact
                # buffer: each candidate prices a donated feedback chain
                # (state = exchange(state)), sync-honest via block();
                # the winner persists to the schedule cache and a rerun
                # is a pure cache hit (make tune-smoke gates this)
                from tpu_mpi_tests.tune.sweep import (
                    ensure_tuned,
                    feedback_rate,
                )

                def measure(cand):
                    sec, _ = feedback_rate(
                        lambda z: H.halo_exchange(
                            z, ctx.mesh, staging=cand
                        ),
                        zg + 0,  # fresh copy: the exchange donates
                    )
                    return sec

                ensure_tuned(
                    "halo/staging", measure, device_fallback=False,
                    **H._staging_context(zg, 0, world),
                )
            staging = H.resolve_staging("auto", zg, 0, world)
            ctx.rep.banner(f"TUNE halo/staging resolved -> {staging.value}")
        return {"zg": zg, "staging": staging, "d": d, "df": df,
                "dtype": dtype}

    def step(self, ctx: RunContext, state):
        from tpu_mpi_tests.comm import halo as H
        from tpu_mpi_tests.instrument import costs
        from tpu_mpi_tests.instrument.timers import block

        args, topo, world = ctx.args, ctx.topo, ctx.world
        mesh, zg, staging = ctx.mesh, state["zg"], state["staging"]
        d = state["d"]
        # untimed warmup so the timed exchange measures communication,
        # not trace+compile (exchange is idempotent: ghosts are rewritten
        # with identical values) — async-dispatch discipline, SURVEY §7
        # part 2
        zg = block(H.halo_exchange(zg, mesh, staging=staging))
        # one timed exchange (mpi_stencil_gt.cc:200-205)
        t0 = time.perf_counter()
        zg = block(H.halo_exchange(zg, mesh, staging=staging))
        seconds = time.perf_counter() - t0
        if topo.process_index == 0:
            for r in range(world):
                ctx.rep.line(
                    f"{r}/{world} exchange time {seconds:0.8f}",
                    {"kind": "exchange1d", "rank": r, "seconds": seconds},
                )

        # compile-cost probe on the derivative kernel (the halo
        # exchange is probed automatically through span_call); the
        # fingerprint context keys the record to this layout
        deriv_fn = H.stencil_fn(mesh, ctx.axis_name, 0, 1, d.scale)
        costs.compile_probe(
            deriv_fn, (zg,), label="stencil1d_deriv",
            dtype=args.dtype, n=args.n_global, world=world,
        )
        state["deriv"] = block(deriv_fn(zg))
        state["zg"] = zg
        return state

    def verify(self, ctx: RunContext, state) -> int:
        import jax
        import numpy as np

        from tpu_mpi_tests.comm import collectives as C

        args, topo, world = ctx.args, ctx.topo, ctx.world
        d, df, dtype = state["d"], state["df"], state["dtype"]
        n_global = args.n_global
        # per-rank err norms vs analytic derivative, computed shard-local
        # on device (the full global field never moves to host)
        actual = C.device_init(
            ctx.mesh, lambda r: d.interior_shard_jax(df, r, dtype), ndim=1
        )
        per_rank_err = C.per_rank_err_norms(
            state["deriv"], actual, ctx.mesh
        )
        kind = jax.devices()[0].device_kind
        if topo.process_index == 0:
            for r in range(world):
                ctx.rep.line(
                    f"{r}/{world} [{kind}] err_norm = "
                    f"{per_rank_err[r]:.8f}",
                    {"kind": "err_norm", "rank": r,
                     "err": float(per_rank_err[r])},
                )

        if args.tol is not None:
            tol = args.tol
        elif args.dtype == "float64":
            # rounding error grows with scale·√n like the f32 case
            # (coordinate ulps amplified by 1/delta); a broken halo
            # exceeds this by >10⁴
            eps64 = 2.2e-16
            tol = max(
                128 * eps64 * d.length**3 * d.scale * np.sqrt(n_global),
                1e-6,
            )
        else:
            # f32/bf16: cancellation error ≈ eps·max|y|·scale per point
            # (SURVEY §7 hard part 1); a broken halo exceeds this by >10³
            eps = (
                float(np.finfo(np.dtype(args.dtype).newbyteorder("=")).eps)
                if args.dtype != "bfloat16" else 7.8e-3
            )
            ymax = d.length**3
            tol = 8 * eps * ymax * d.scale * np.sqrt(n_global)
        if per_rank_err.max() > tol:
            ctx.rep.line(
                f"ERR_NORM FAIL: max {per_rank_err.max():.8g} > tol "
                f"{tol:.8g}"
            )
            return 1
        if args.overlap != "0":
            return _run_overlap(
                args, ctx.rep, ctx.mesh, topo, state["zg"], d
            )
        return 0

    def serve_factory(self, mesh, shape, dtype):
        """Serve-mode handler: ``step_fn(n)`` performs ``n`` halo
        exchanges on a persistent ghosted shard set (the exchange is
        idempotent — ghosts are rewritten with identical values — so
        chained requests are exactly the driver's timed step). Each
        exchange goes through
        :func:`~tpu_mpi_tests.comm.halo.halo_exchange`, so with
        telemetry on every request also lands its own comm span, and the
        staging schedule resolves through the tune cache like any other
        run.

        The chained exchanges dispatch through a
        :class:`~tpu_mpi_tests.comm.collectives.DispatchWindow` whose
        depth resolves from the schedule cache (``coll/dispatch_depth``,
        prior 1) — so steady-state traffic exercises the tuned pipelined
        path: at depth 1 every exchange syncs per call (today's
        behavior, byte-identical), at depth ≥ 2 up to that many
        dispatches ride in flight before the window blocks on the
        oldest."""
        import jax.numpy as jnp

        from tpu_mpi_tests.arrays.domain import Domain1D
        from tpu_mpi_tests.comm import collectives as C
        from tpu_mpi_tests.comm import halo as H
        from tpu_mpi_tests.instrument.timers import block
        from tpu_mpi_tests.kernels.stencil import analytic_pairs

        if len(shape) != 1:
            raise ValueError(f"halo wants a 1-d shape, got {shape}")
        (n,) = shape
        world = mesh.devices.size
        d = Domain1D(n_global=n, n_shards=world, n_bnd=2)
        f, _ = analytic_pairs()["1d"]
        dt = jnp.dtype(dtype)
        # tuned overlap depth, resolved like any other knob (cached >
        # prior)
        depth = C.resolve_dispatch_depth(
            dtype=str(dt), n=n, world=world
        )

        def init():
            return block(C.device_init(
                mesh, lambda r: d.init_shard_jax(f, r, dt), ndim=1
            ))

        state = {"z": init()}

        def step(k: int):
            try:
                z = state["z"]
                with C.DispatchWindow(depth) as win:
                    for _ in range(k):
                        # AUTO staging: the tune cache's winner for this
                        # topology when one is warmed, the shipped prior
                        # (direct) otherwise — the schedule preload at
                        # serve start is consumed here
                        z = H.halo_exchange(
                            z, mesh, staging=H.Staging.AUTO,
                            window=win if depth > 1 else None,
                        )
                state["z"] = block(z)
            except Exception:
                # the exchange donates its input: after a mid-batch
                # failure the held buffer may already be consumed, and
                # keeping it would poison every later batch of this
                # class with buffer-deleted errors for the rest of a
                # long run — rebuild, then let the loop count the error
                state["z"] = init()
                raise

        step(1)  # compile + warm before traffic opens
        return step


def _run_overlap(args, rep, mesh, topo, zg, d) -> int:
    """The ``--overlap`` mode: run the double-buffered halo pipeline
    (README "Overlap engine") for ``--overlap-iters`` steps of the
    fused exchange+update recurrence on a copy of the verified field.

    Depth resolves explicit > cached > prior (1); with ``--tune`` and
    ``--overlap auto`` a cache miss sweeps the depth candidates first
    (each priced on a short pipeline run). Depth ≥ 2 runs are verified
    bit-identical against a depth-1 rerun — the interior/boundary seam
    correctness gate — and the measured ``overlap_frac`` (wall overlap
    of the in-flight exchange span with the interior-compute phase) is
    attached to the phase record and the ``kind:"overlap"`` row."""
    import time as _time

    import numpy as np

    from tpu_mpi_tests.comm import halo as H
    from tpu_mpi_tests.comm.topology import mesh_link_meta
    from tpu_mpi_tests.instrument.timers import PhaseTimer, block

    world = topo.global_device_count
    axis_name = mesh.axis_names[0]
    eps = 1e-6
    n_iters = args.overlap_iters
    explicit = None if args.overlap == "auto" else int(args.overlap)
    ctx = dict(dtype=args.dtype, n=args.n_global, world=world)
    fns = H.overlap_jacobi_fns(
        mesh, axis_name, 0, 1, 2, float(d.scale), eps
    )
    exchange_nod, core, seam = fns
    nbytes = H.halo_payload_bytes(zg, 0, world, 2, False)

    def pipeline(depth: int, n: int, timer=None):
        runner = H.OverlapRunner(
            "halo_exchange", depth=depth, nbytes=nbytes,
            axis_name=axis_name, world=world, timer=timer,
            phase="overlap_interior",
            **mesh_link_meta(mesh, axis_name),
        )
        z = block(zg + 0)
        for _ in range(n):
            ex, zc = runner.step(exchange_nod, core, z)
            z = block(seam(ex, zc))
        return z, runner

    if explicit is None and args.tune:
        from tpu_mpi_tests.tune.sweep import ensure_tuned

        def measure(cand):
            # compile + warm OUTSIDE the timed window: the split
            # programs are shared across depths (lru_cache), so the
            # first candidate — the prior, depth 1 — would otherwise
            # pay the one-time jit cost and bias the winner to depth 2
            z, _ = pipeline(int(cand), 1)
            del z
            t0 = _time.perf_counter()
            z, _ = pipeline(int(cand), max(4, n_iters // 4))
            del z
            return _time.perf_counter() - t0

        ensure_tuned(
            "halo/overlap", measure, device_fallback=False, **ctx
        )
    depth = H.resolve_overlap_depth(explicit, **ctx)
    rep.banner(f"OVERLAP halo depth resolved -> {depth}")

    zw, _ = pipeline(depth, 1)  # compile + warm (programs are shared
    del zw                      # across depths via the lru cache)
    timer = PhaseTimer()
    t0 = _time.perf_counter()
    z, runner = pipeline(depth, n_iters, timer=timer)
    seconds = _time.perf_counter() - t0
    it_per_s = n_iters / seconds if seconds > 0 else float("inf")

    rc = 0
    if depth > 1:
        # seam gate: the pipelined schedule must be bit-identical to
        # the serialized one (same compiled programs, reordered)
        z_ref, _ = pipeline(1, n_iters)
        if not np.array_equal(np.asarray(z), np.asarray(z_ref)):
            rep.line(
                f"OVERLAP FAIL depth={depth}: pipelined result diverges "
                f"from the depth-1 schedule (seam defect)"
            )
            rc = 1
        del z_ref
    del z

    runner.annotate(timer)
    rep.time_lines(timer, stats=True)
    rep.line(
        f"OVERLAP halo depth={depth} iters={n_iters} "
        f"{it_per_s:0.1f} it/s overlap_frac={runner.overlap_frac:0.3f}",
        runner.record(
            "halo", iters=n_iters, it_per_s=it_per_s, dtype=args.dtype,
            n=args.n_global,
        ),
    )
    return rc


SPEC = register_spec(Stencil1dSpec())


def main(argv=None) -> int:
    from tpu_mpi_tests.workloads.runner import make_main

    return make_main(SPEC)(argv)


if __name__ == "__main__":
    sys.exit(main())
