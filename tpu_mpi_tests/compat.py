"""JAX version compatibility shims.

The framework targets the current ``jax.shard_map`` API (top-level export,
``check_vma`` flag, ``lax.pcast``); older jaxlibs in the field (0.4.x) ship
the same machinery as ``jax.experimental.shard_map`` with the flag named
``check_rep`` and no ``pcast``. Every internal module imports from here so
the suite runs unmodified on both — the comm layer is the system under
test and must not be un-importable on a merely-older runtime.
"""

from __future__ import annotations

from jax import lax

try:  # current API: top-level export, check_vma
    from jax import shard_map as _shard_map

    _VMA_FLAG = "check_vma"
except ImportError:  # jax 0.4.x: experimental module, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _VMA_FLAG = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
    """``jax.shard_map`` with the ``check_vma`` spelling on every version."""
    kwargs[_VMA_FLAG] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def axis_size(axis_name: str) -> int:
    """Static mesh-axis size inside a ``shard_map`` body on every version
    (``lax.axis_size`` is a recent addition; older jax exposes the bound
    frame size through ``jax.core.axis_frame``)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    from jax import core

    return core.axis_frame(axis_name)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across renames: older jax calls it
    ``TPUCompilerParams`` and lacks some fields (e.g. ``has_side_effects``)
    — unknown fields are dropped there, which is safe for this repo's
    kernels: their outputs are always consumed through
    ``input_output_aliases``, so DCE cannot drop the calls the flag was
    protecting."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        import dataclasses

        cls = pltpu.TPUCompilerParams
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in kwargs.items() if k in known}
    return cls(**kwargs)


def pcast_varying(x, axis_name: str):
    """``lax.pcast(x, (axis_name,), to="varying")`` where it exists.

    Older jax has no varying-manual-axes tracking (the ``check_rep``
    machinery never needs the cast), so the identity is the correct
    fallback there."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    return x
