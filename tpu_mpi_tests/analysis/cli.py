"""``tpumt-lint``: the repo's JAX/TPU correctness linter (console script).

Also runnable uninstalled as ``python -m tpu_mpi_tests.analysis.cli``.
Pure stdlib like the sibling login-node CLIs (tpumt-report/tpumt-trace):
imports and runs where ``import jax`` raises.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpu_mpi_tests.analysis.core import lint_paths, rule_table

_EPILOG = """\
rule families (stable codes; see README "Static analysis" for the table):
  TPM1xx sync-honesty     timed jax dispatch without a device sync
                          (TPM102: through a helper, via the summaries)
  TPM2xx trace-purity     host side effects inside traced functions
  TPM3xx x64-safety       float64 silently canonicalized to float32
  TPM4xx import-hygiene   eager `import jax` in login-node CLI closures
  TPM5xx axis-consistency collective axis names vs shard_map/mesh
                          (TPM502: resolved program-wide, no same-file
                          skip)
  TPM6xx concurrency      unlocked cross-thread file-handle writes
  TPM7xx schedule-consts  pinned tile/schedule constants bypassing the
                          autotuner's registry/cache (tpu_mpi_tests/tune)
  TPM8xx overlap-regions  syncs inside declared overlap regions
                          (TPM802: escaped async handle, never consumed)
  TPM9xx engine           unused/malformed suppressions, parse errors
  TPM11xx collective-divergence  collective reachable from a
                          rank-dependent branch: the SPMD deadlock shape
                          (TPM1101 diverging paths; TPM1102 rank-guarded
                          early exit before a collective — both
                          flow-sensitive over the per-function CFG)
  TPM12xx donation-safety a name read after being passed in a donated
                          position and not rebound (use-after-donate)
  TPM13xx broadcast-consistency  a value bound only on a rank-guarded
                          path consumed without broadcast/
                          process_allgather — ranks silently diverge
  TPM14xx record-contract JSONL fields consumed but never produced
                          (TPM1401) / kinds consumed but never emitted
                          (TPM1402); RECORDS.md is the generated
                          schema table (`make records`)
  TPM16xx lockset races   may-happen-in-parallel lockset analysis over
                          the threading plane: TPM1601 disjoint-lockset
                          data race, TPM1602 non-reentrant-lock
                          self-deadlock through the call graph, TPM1603
                          hook-slot rebind without the arm/disarm
                          idiom. TPM601 is its single-file fallback:
                          it fires only where thread-entry discovery
                          resolved nothing.
  TPM17xx schedule-protocol  whole-program collective schedule
                          automata: TPM1701 rank-divergent composed
                          schedule (assembled across functions /
                          broadcast wrappers / rank-returning
                          helpers), TPM1702 rank-dependent loop bound
                          enclosing a collective, TPM1703 collective
                          under an exception path that skips its
                          partner op; `--conform <jsonl...>` replays
                          real seq-stamped telemetry against the
                          automaton — TPM1704 stream no static path
                          generates, TPM1705 rank stream ending with
                          a mandatory collective un-emitted.

suppress one finding on its line (unused suppressions are themselves
findings):   x = jnp.asarray(2.0)  # tpumt: ignore[TPM301]

warm runs reuse the content-hash analysis cache (default
~/.cache/tpumt/lint.json, $TPU_MPI_LINT_CACHE / --cache override,
--no-cache disables): unchanged files skip parse + summary entirely;
editing any analysis-package source invalidates every entry.
"""


def _sarif_doc(findings) -> dict:
    """SARIF 2.1.0, the minimal subset CI hosts render inline: one run,
    the full rule table as driver rules, one result per finding with a
    physical location (1-based column per the SARIF spec)."""
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpumt-lint",
                "informationUri":
                    "https://github.com/bd4/gpu-mpi-tests",
                "rules": [
                    {"id": code,
                     "shortDescription": {"text": summary}}
                    for code, summary in rule_table()
                ],
            }},
            "results": [
                {"ruleId": f.code,
                 "level": "error",
                 "message": {"text": f.message},
                 "locations": [{"physicalLocation": {
                     "artifactLocation": {"uri": f.path},
                     "region": {"startLine": f.line,
                                "startColumn": f.col + 1},
                 }}]}
                for f in findings
            ],
        }],
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpumt-lint",
        description="tpumt-lint: whole-program static analyzer for this "
        "repo's JAX/TPU correctness hazard classes (stdlib-only; runs "
        "on login nodes without jax).",
        epilog=_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*", metavar="PATH",
                    help="files or directories to lint (directories "
                    "recurse over *.py, skipping fixtures/ and "
                    "__pycache__/)")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human", help="output format")
    ap.add_argument("--select", action="append", metavar="CODES",
                    help="only these codes/families (comma list; "
                    "TPM1, TPM1xx and TPM101 all work); repeatable")
    ap.add_argument("--ignore", action="append", metavar="CODES",
                    help="drop these codes/families (comma list); "
                    "repeatable")
    ap.add_argument("--entry-module", action="append", metavar="MOD",
                    help="override the TPM4xx stdlib-only entry-module "
                    "set (default: the tpumt-* console scripts); "
                    "repeatable")
    ap.add_argument("--cache", metavar="PATH", default=None,
                    help="analysis-cache path (default "
                    "~/.cache/tpumt/lint.json or $TPU_MPI_LINT_CACHE)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the content-hash analysis cache for "
                    "this run")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="parallelize per-file fact extraction over N "
                    "worker processes (default 1; warm-cache runs "
                    "re-parse zero files regardless of N)")
    ap.add_argument("--stats", action="store_true",
                    help="print files/analyzed/cache-hit counts plus "
                    "wall time and files/proc to stderr")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every registered code and exit")
    ap.add_argument("--conform", action="store_true",
                    help="conformance mode: PATHs are telemetry JSONL "
                    "streams (`.p<i>` rank sets auto-expand), replayed "
                    "against the schedule automaton compiled from "
                    "--conform-tree; convicts TPM1704/TPM1705")
    ap.add_argument("--conform-tree", metavar="DIR", default=None,
                    help="source tree the schedule automaton is "
                    "compiled from in --conform mode (default: the "
                    "installed tpu_mpi_tests package)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, summary in rule_table():
            print(f"{code}  {summary}")
        return 0
    if not args.paths:
        ap.error("no paths given (try: tpumt-lint tpu_mpi_tests tpu "
                 "tests __graft_entry__.py bench.py)")

    entry_modules = None
    if args.entry_module:
        entry_modules = {m: m for m in args.entry_module}
    cache_path = None
    if not args.no_cache:
        if args.cache:
            cache_path = args.cache
        else:
            from tpu_mpi_tests.analysis.lintcache import (
                default_cache_path,
            )

            cache_path = default_cache_path()
    if args.jobs < 1:
        ap.error("--jobs must be >= 1")
    stats: dict = {}
    notes: list[str] = []
    if args.conform:
        from pathlib import Path

        from tpu_mpi_tests.analysis import core as _core
        from tpu_mpi_tests.analysis.core import collect_project
        from tpu_mpi_tests.analysis.protocol import conform_paths

        # the automaton is compiled from source, the stream from
        # telemetry: PATHs here are JSONL files, not code
        tree = args.conform_tree or str(Path(_core.__file__).parents[1])
        proj = collect_project(
            [tree],
            entry_modules=entry_modules,
            cache_path=cache_path,
            stats=stats,
            jobs=args.jobs,
        )
        findings, notes = conform_paths(args.paths, proj)
    else:
        findings = lint_paths(
            args.paths,
            select=args.select,
            ignore=args.ignore,
            entry_modules=entry_modules,
            cache_path=cache_path,
            stats=stats,
            jobs=args.jobs,
        )
    for note in notes:
        print(f"tpumt-lint: NOTE: {note}", file=sys.stderr)
    if args.stats:
        analyzed = stats.get("analyzed", 0)
        jobs = stats.get("jobs", 1)
        per_proc = analyzed / jobs if jobs else analyzed
        print(
            f"tpumt-lint stats: files={stats.get('files', 0)} "
            f"analyzed={analyzed} "
            f"cache_hits={stats.get('cache_hits', 0)} "
            f"seconds={stats.get('seconds', 0.0):.3f} "
            f"jobs={jobs} files_per_proc={per_proc:.1f} "
            f"cache={cache_path or 'off'}",
            file=sys.stderr,
        )

    if args.format == "json":
        print(json.dumps(
            {"version": 1, "count": len(findings),
             "findings": [f.as_dict() for f in findings]},
            indent=2,
        ))
    elif args.format == "sarif":
        print(json.dumps(_sarif_doc(findings), indent=2))
    else:
        for f in findings:
            print(f.format())
        if findings:
            print(f"tpumt-lint: {len(findings)} finding"
                  f"{'s' if len(findings) != 1 else ''}",
                  file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
