"""TPM12xx — donation safety (use-after-donate).

The bug class PR 7's DispatchWindow made pervasive: the in-place idiom
``x = allreduce(x)`` donates its operand (``donate_argnums=0`` on every
comm wrapper's jitted core), so after the call the *old* buffer is
deleted. Rebinding the result to the same name is the whole point; but
pass ``x`` in a donated position, bind the result elsewhere, and any
later read of ``x`` hits a deleted jax.Array —
``RuntimeError: Array has been deleted`` at best, and on some paths a
silent garbage read from reused HBM. The failure fires at *runtime*, on
the *device*, often only at real mesh sizes — exactly the class the
reference suite's ``MPI_IN_PLACE`` probes exist to catch after the
fact.

Detection over the per-file donation-flow facts plus the project
summaries (so it sees through one level of helper: a function that
forwards its param into a donated position of its callee effectively
donates that param too — ``span_call``/``DispatchWindow.call``
forwarding included):

* **read-after-donate** (straight line): a statement list where ``x``
  is passed in a donated position, the statement does not rebind ``x``,
  and a later statement reads ``x`` before any rebind. Anchored at the
  read — that is where the deleted buffer is touched.
* **donate-in-loop**: a donating call inside a ``for``/``while`` body
  that never rebinds the donated name anywhere in that body — the
  second iteration feeds an already-deleted buffer. Anchored at the
  call.

Conservative: any rebind anywhere in an intervening statement's subtree
stops the scan, attribute/expression arguments are ignored (only bare
names track), and unresolvable callees contribute no donations.
"""

from __future__ import annotations

from typing import Iterator

from tpu_mpi_tests.analysis.core import ProjectContext


class DonationSafety:
    name = "donation-safety"
    scope = "project"
    codes = {
        "TPM1201": "local name read after being passed in a donated "
                   "position and not rebound — the buffer is deleted "
                   "(use-after-donate)",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        idx = proj.index
        for ff in proj.facts:
            module = ff["module"]
            for lst in ff["dflow"]:
                yield from self._check_list(ff, idx, module, lst)

    def _check_list(self, ff, idx, module, lst) -> Iterator[tuple]:
        stmts = lst["stmts"]
        all_binds: set[str] = set()
        for st in stmts:
            all_binds.update(st["binds"])
        for i, st in enumerate(stmts):
            for call in st["calls"]:
                donated = idx.site_donates(call, module)
                if not donated:
                    continue
                short = call["target"].rsplit(".", 1)[-1]
                for p in sorted(donated):
                    if p >= len(call["args"]):
                        continue
                    name = call["args"][p]
                    if not name or name in st["binds"]:
                        # `x = f(x)` (or a branch that rebinds): the
                        # donated buffer is replaced — the idiom
                        continue
                    for later in stmts[i + 1:]:
                        read = next(
                            (ln for n, ln in later["reads"]
                             if n == name), None,
                        )
                        if read is not None:
                            yield (
                                ff["path"], read, 0, "TPM1201",
                                f"'{name}' is read here but was "
                                f"donated to '{short}' at line "
                                f"{call['line']} and never rebound — "
                                f"the buffer is deleted "
                                f"(use-after-donate); rebind the "
                                f"result ({name} = {short}(...)) or "
                                f"pass a copy ({name} + 0)",
                            )
                            break
                        if name in later["binds"]:
                            break
                    else:
                        if lst["loop"] and name not in all_binds:
                            yield (
                                ff["path"], call["line"], call["col"],
                                "TPM1201",
                                f"'{name}' is donated to '{short}' "
                                f"inside a loop that never rebinds it "
                                f"— the next iteration reads a "
                                f"deleted buffer (use-after-donate); "
                                f"chain the result "
                                f"({name} = {short}(...)) or pass a "
                                f"copy ({name} + 0)",
                            )
