"""TPM1301 — rank-guarded binding consumed without a broadcast (ISSUE 12).

The fleet-tuning / pod-serving hazard ROADMAP items 1(a) and 2 are about
to write, dogfooded before those PRs land: rank 0 computes something
(a tune-sweep winner, a batch plan) inside a rank guard, and then EVERY
rank acts on the name —

    if process_index() == 0:
        winner = sweep(space)       # only rank 0 has the real value
    else:
        winner = None               # placeholder, not a value
    apply_schedule(winner)          # ranks now disagree

Nothing deadlocks immediately, which makes this worse than TPM1101: the
ranks silently run different schedules (or crash later on the None),
and the divergence only surfaces as a wrong answer or a hang several
collectives downstream. The SPMD-honest shape routes the value through
a replicating collective first — ``broadcast``/``broadcast_one_to_all``
/``process_allgather``/``pbroadcast`` (the curated
:data:`tpu_mpi_tests.analysis.program.BROADCAST_CALLS` set).

Detection, over the per-function CFG facts: a name bound on exactly one
side of a rank-dependent ``if`` (a ``= None`` placeholder on the other
side does not count as a binding), not bound before the branch, whose
first read along the OTHER path is not a direct argument of a
broadcast-class call. Anchored at that read — the point where an
unreplicated value enters per-rank work.
"""

from __future__ import annotations

from typing import Iterator

from tpu_mpi_tests.analysis.core import ProjectContext
from tpu_mpi_tests.analysis.program import BROADCAST_CALLS


class BroadcastConsistency:
    name = "broadcast-consistency"
    scope = "project"
    codes = {
        "TPM1301": "value bound only on a rank-guarded path is read on "
                   "the unguarded path without passing through a "
                   "broadcast-class collective",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        for ff in proj.facts:
            for fn in ff["functions"]:
                for ri in fn["rank_ifs"]:
                    for name, line, col, call in ri["unbcast"]:
                        if call in BROADCAST_CALLS:
                            continue
                        yield (
                            ff["path"], line, col, "TPM1301",
                            f"'{name}' is bound only on the "
                            f"rank-guarded path of the branch at line "
                            f"{ri['line']} but read here on the path "
                            f"the other ranks take — they see a stale "
                            f"or placeholder value and the ranks "
                            f"diverge; replicate it first "
                            f"(broadcast/broadcast_one_to_all/"
                            f"process_allgather) or compute it on "
                            f"every rank",
                        )
