"""TPM8xx — overlap-region sync discipline.

The bug class THIS repo's overlap engine creates (ISSUE 7, README
"Overlap engine"), encoded the day it ships: a ``block_until_ready``-
class sync lexically inside a declared overlap region — between a
prefetch issue (``h = async_span(...)`` opening a dispatch-window span)
and its consume point (``h.done(...)`` / ``h.wait(...)``) — silently
re-serializes the pipeline. Nothing errors: results stay identical
(the whole point of the engine), it/s regresses, and ``overlap_frac``
quietly drops toward 0. The ``--diff`` gate catches the symptom in
benchmarks that run; this rule catches the cause at lint time,
everywhere.

One sync inside the region is DELIBERATE by design: the overlapped
interior compute must block under its phase bracket — that is the
window the exchange hides beneath. The engine
(``comm/halo.py`` ``OverlapRunner.overlap step``) carries the
sanctioned inline suppression with its why-comment; new overlap code
should either route through the engine (no region in driver code at
all) or suppress its one deliberate compute-sync the same way.

Detection (lexical, per function scope): an assignment whose value
calls ``async_span`` opens a region for that handle name; the first
``<handle>.done(...)`` or ``<handle>.wait(...)`` closes it; any call to
``block`` (``instrument.timers.block``), ``jax.block_until_ready``, or
a ``.block_until_ready()`` method at a line strictly inside an open
region is a TPM801 finding. An unconsumed handle leaves its region
open to the end of the function — a dangling dispatch-window span is
exactly when an accidental sync hides longest.

**TPM802** (project scope, ISSUE 10) is the interprocedural escape the
lexical rule cannot see: a helper *returns* its ``async_span`` handle
(the summaries track ``returns_handle`` transitively) and the caller
assigns it to a name it never reads again — nobody will ever ``done()``
it, so the dispatch-window span stays open to process exit and the
overlap accounting silently loses the op.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import (
    FileContext,
    ProjectContext,
    attr_parts,
)

#: call targets that open an overlap region when bound to a name
PREFETCH_NAMES = {"async_span"}
#: handle methods that consume (close) the region
CONSUME_ATTRS = {"done", "wait"}
#: sync call heuristics: the repo's block() helper, jax's module-level
#: sync, and the method spelling
SYNC_LAST_ATTRS = {"block_until_ready"}
SYNC_RESOLVED = {
    "tpu_mpi_tests.instrument.timers.block",
    "jax.block_until_ready",
}


def _is_prefetch(call: ast.Call, ctx: FileContext) -> bool:
    resolved = ctx.imports.resolve(call.func) or ""
    return resolved.rsplit(".", 1)[-1] in PREFETCH_NAMES


def _is_sync(call: ast.Call, ctx: FileContext) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in SYNC_LAST_ATTRS:
        return True
    resolved = ctx.imports.resolve(func) or ""
    if resolved in SYNC_RESOLVED:
        return True
    # bare `block(...)` bound from the timers module resolves above;
    # a same-file helper named block still counts (same hazard)
    return resolved.rsplit(".", 1)[-1] == "block"


class OverlapRegionSync:
    name = "overlap-regions"
    scope = "file"
    codes = {
        "TPM801": "sync call inside a declared overlap region (between "
                  "a prefetch issue and its consume point) — "
                  "re-serializes the pipeline",
    }

    def check(self, ctx: FileContext) -> Iterator[tuple]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_scope(ctx, node)

    def _check_scope(self, ctx: FileContext, fn) -> Iterator[tuple]:
        """Line-ordered event scan of ONE function body (nested defs get
        their own scan — their lines must not leak region state)."""
        events: list[tuple[int, str, object]] = []
        nested: set[int] = set()
        for sub in ast.walk(fn):
            if sub is not fn and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                for inner in ast.walk(sub):
                    nested.add(id(inner))
        for sub in ast.walk(fn):
            if id(sub) in nested:
                continue
            if isinstance(sub, ast.Assign) and isinstance(
                sub.value, ast.Call
            ) and _is_prefetch(sub.value, ctx):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        events.append((sub.lineno, "open", t.id))
            elif isinstance(sub, ast.Call):
                func = sub.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in CONSUME_ATTRS
                    and isinstance(func.value, ast.Name)
                ):
                    events.append((sub.lineno, "close", func.value.id))
                elif _is_sync(sub, ctx):
                    parts = attr_parts(func)
                    events.append(
                        (sub.lineno, "sync",
                         (sub, ".".join(parts) if parts else "sync"))
                    )
        events.sort(key=lambda e: e[0])
        open_regions: dict[str, int] = {}
        for line, kind, payload in events:
            if kind == "open":
                open_regions[payload] = line
            elif kind == "close":
                open_regions.pop(payload, None)
            elif open_regions:
                call, name = payload
                handle, at = next(iter(open_regions.items()))
                yield (
                    call.lineno, call.col_offset, "TPM801",
                    f"'{name}(...)' syncs inside the overlap region "
                    f"opened by '{handle} = async_span(...)' at line "
                    f"{at} — the in-flight comm serializes against it "
                    f"and overlap_frac silently drops to 0; move the "
                    f"sync after '{handle}.done()', or suppress with a "
                    f"why-comment if this sync IS the overlapped "
                    f"compute phase",
                )


class EscapedAsyncHandle:
    name = "overlap-regions-escape"
    scope = "project"
    codes = {
        "TPM802": "async dispatch-window handle returned to a caller "
                  "that never consumes it — the span stays open to "
                  "process exit",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        idx = proj.index
        for ff in proj.facts:
            for fn in ff["functions"]:
                for name, target, line, col in fn["handle_drops"]:
                    funcs = idx.resolve_funcs(target, ff["module"])
                    if not funcs:
                        continue
                    if any(idx.returns_handle(g) for g in funcs):
                        short = target.rsplit(".", 1)[-1]
                        yield (
                            ff["path"], line, col, "TPM802",
                            f"'{name}' holds the async_span handle "
                            f"returned by '{short}' but is never read "
                            f"again — no one will done()/wait() it, so "
                            f"the dispatch-window span stays open to "
                            f"process exit and its op drops out of the "
                            f"overlap accounting; consume the handle "
                            f"or drain it through a DispatchWindow",
                        )
