"""TPM7xx — schedule-constant hygiene.

The bug class: a hand-pinned tile/block/staging constant freezes ONE
machine's measured optimum for every topology. The repo shipped years of
that shape (``MEASURED_BEST_K_TILE``, ``TPU_MPI_BENCH_BLOCKS`` defaults,
the streaming skip-tile) until the autotuner (``tpu_mpi_tests/tune/``)
demoted them to cold-start priors behind a persistent per-fingerprint
schedule cache. This rule keeps the door shut: a numeric schedule
constant assigned at module level OUTSIDE the tuner's registry/resolver
modules is a finding — future knobs must declare their candidate space
(:func:`~tpu_mpi_tests.tune.registry.declare_space`) and resolve through
the cache (explicit > cached > prior), not re-pin.

Sanctioned homes, exempt by construction:

* modules under ``tpu_mpi_tests.tune`` (the priors tables and the
  registry itself);
* assignments whose value routes through ``declare_space(...)`` — the
  numeric candidates INSIDE a space declaration are the API working as
  designed (that is how a knob's candidates are stated where the knob
  lives).

Heuristic scope: ALL-CAPS module-level names containing a schedule
keyword (TILE/BLOCK/STEP/STAGING/SCHEDULE/CREDIT/MEASURED/K_GROUP/
DEPTH/OVERLAP) whose value carries a numeric literal. String-valued
config names and function-local values are out of scope.

Inside the workload-spec subsystem (``tpu_mpi_tests.workloads``) the
keyword set is EXTENDED with the serving-era knob vocabulary
(CAPACITY/LOOKUP/COMBINE/ROUTE/EXPERT/FANOUT): specs are exactly where
the next generation of schedule constants would accrete, so a spec's
schedule constant is exempt only by routing through ``declare_space``
— the same door the rest of the repo already has shut. The extension
is scoped to ``workloads/`` because those words are overloaded
elsewhere (``FLIGHT_CAPACITY`` is a ring-buffer bound, not a
schedule).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tpu_mpi_tests.analysis.core import FileContext, last_attr

#: module-name prefix of the sanctioned schedule-constant home
TUNE_PREFIX = "tpu_mpi_tests.tune"

#: module-name prefix that opts into the EXTENDED keyword set: workload
#: specs carry the serving-era knob vocabulary, and their schedule
#: constants are exempt only via declare_space
WORKLOADS_PREFIX = "tpu_mpi_tests.workloads"

_CONST_NAME = re.compile(r"^_?[A-Z][A-Z0-9_]*$")
_SCHEDULE_WORD = re.compile(
    r"(TILE|BLOCK|STEP|STAGING|SCHEDULE|CREDIT|MEASURED|K_GROUP|KGROUP"
    r"|DEPTH|OVERLAP)"  # the ISSUE-7 pipeline knobs are schedules too
)
_SPEC_SCHEDULE_WORD = re.compile(
    # the ISSUE-8 serving-era knob vocabulary, in scope only inside
    # tpu_mpi_tests.workloads (overloaded meanings elsewhere)
    r"(TILE|BLOCK|STEP|STAGING|SCHEDULE|CREDIT|MEASURED|K_GROUP|KGROUP"
    r"|DEPTH|OVERLAP|CAPACITY|LOOKUP|COMBINE|ROUTE|EXPERT|FANOUT)"
)


def _has_numeric_literal(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(
            sub.value, (int, float)
        ) and not isinstance(sub.value, bool):
            return True
    return False


def _routes_through_registry(node: ast.AST) -> bool:
    """True when the assigned value contains a ``declare_space`` call —
    numerics inside a space declaration are candidates being registered,
    which is exactly the sanctioned alternative to pinning."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and last_attr(sub.func) == (
            "declare_space"
        ):
            return True
    return False


class ScheduleConstants:
    name = "schedule-constants"
    scope = "file"
    codes = {
        "TPM701": "hand-pinned numeric schedule constant outside the "
                  "tuner's registry/resolver modules "
                  "(tpu_mpi_tests/tune/)",
    }

    def check(self, ctx: FileContext) -> Iterator[tuple]:
        if ctx.module.startswith(TUNE_PREFIX):
            return
        word = (_SPEC_SCHEDULE_WORD
                if ctx.module.startswith(WORKLOADS_PREFIX)
                else _SCHEDULE_WORD)
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            else:
                continue
            names = [
                t.id for t in targets
                if isinstance(t, ast.Name)
                and _CONST_NAME.match(t.id)
                and word.search(t.id)
            ]
            if not names:
                continue
            if not _has_numeric_literal(value):
                continue
            if _routes_through_registry(value):
                continue
            yield (
                stmt.lineno, stmt.col_offset, "TPM701",
                f"hand-pinned schedule constant {names[0]!r} — one "
                f"machine's optimum frozen for every topology; move the "
                f"value into tune/priors.py, declare the candidate "
                f"space with tune.declare_space where the knob lives, "
                f"and resolve through the schedule cache (explicit > "
                f"cached > prior; README 'Autotuning')",
            )
