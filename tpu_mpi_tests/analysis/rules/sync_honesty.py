"""TPM1xx — sync-honest timing.

The bug class: JAX dispatch is async, so a ``perf_counter`` pair around
a jax call times the *dispatch*, not the compute — the time lands on
whichever later operation flushes the queue (the dispatch-vs-compute
trap ``mpi_daxpy_nvtx`` exists to demonstrate; SURVEY §7 hard part 2).
The reference suite brackets every timed phase with a device sync
(``cudaDeviceSynchronize`` before ``MPI_Wtime``); this repo's analog is
``instrument.timers.block`` / ``block_until_ready`` / ``comm_span`` /
``PhaseTimer.timed`` — a monotonic-clock pair whose timed region
dispatches device work without any of them is dishonest timing.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import FileContext, last_attr
from tpu_mpi_tests.analysis.rules import _util

#: clock reads that start/stop a timing region
CLOCKS = {"time.perf_counter", "time.monotonic"}

#: call targets (final component) that synchronize device work before the
#: clock is read again — chain_rate/dispatch_rate embed the discipline
SYNC_NAMES = {
    "block", "block_until_ready", "comm_span", "span_call", "timed",
    "host_value", "device_get", "chain_rate", "dispatch_rate",
    "sync_global_devices", "barrier",
}


def _clock_assign(ctx: FileContext, stmt: ast.stmt) -> str | None:
    """``t0 = time.perf_counter()`` → ``"t0"``; else None."""
    if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Call)):
        return None
    if ctx.imports.resolve(stmt.value.func) in CLOCKS:
        return stmt.targets[0].id
    return None


def _uses_in_sub(stmt: ast.stmt, name: str) -> bool:
    """Does the statement read the clock delta (``... - t0``)?"""
    for n in ast.walk(stmt):
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
            for side in (n.left, n.right):
                if isinstance(side, ast.Name) and side.id == name:
                    return True
    return False


def _rebinds(stmt: ast.stmt, name: str) -> bool:
    if isinstance(stmt, ast.Assign):
        return any(isinstance(t, ast.Name) and t.id == name
                   for t in stmt.targets)
    return False


class SyncHonesty:
    name = "sync-honesty"
    scope = "file"
    codes = {
        "TPM101": "monotonic-clock pair times a jax dispatch with no "
                  "block()/block_until_ready/comm_span in the region",
    }

    def check(self, ctx: FileContext) -> Iterator[tuple]:
        local_device = _util.device_callables(ctx)
        for stmts in _util.stmt_lists(ctx.tree):
            yield from self._scan_list(ctx, stmts, local_device)

    def _scan_list(self, ctx, stmts, local_device):
        for i, stmt in enumerate(stmts):
            t = _clock_assign(ctx, stmt)
            if not t:
                continue
            region: list[ast.stmt] = []
            for j in range(i + 1, len(stmts)):
                region.append(stmts[j])
                if _uses_in_sub(stmts[j], t):
                    yield from self._check_region(
                        ctx, region, local_device
                    )
                    break
                if _rebinds(stmts[j], t):
                    break  # clock restarted before any delta read

    def _check_region(self, ctx, region, local_device):
        dispatches: list[ast.Call] = []
        for stmt in region:
            for call in _util.walk_calls(stmt):
                if last_attr(call.func) in SYNC_NAMES:
                    return  # region synchronizes; timing is honest
                if _util.is_device_call(ctx, call, local_device):
                    dispatches.append(call)
        for call in dispatches[:1]:
            yield (
                call.lineno, call.col_offset, "TPM101",
                f"timed region dispatches "
                f"'{_util.call_name(call.func)}' without a device sync "
                f"— async dispatch makes this a queue-flush "
                f"measurement; wrap the result in block()/"
                f"block_until_ready() or use comm_span/PhaseTimer.timed",
            )
