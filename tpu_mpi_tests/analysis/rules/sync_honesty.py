"""TPM1xx — sync-honest timing.

The bug class: JAX dispatch is async, so a ``perf_counter`` pair around
a jax call times the *dispatch*, not the compute — the time lands on
whichever later operation flushes the queue (the dispatch-vs-compute
trap ``mpi_daxpy_nvtx`` exists to demonstrate; SURVEY §7 hard part 2).
The reference suite brackets every timed phase with a device sync
(``cudaDeviceSynchronize`` before ``MPI_Wtime``); this repo's analog is
``instrument.timers.block`` / ``block_until_ready`` / ``comm_span`` /
``PhaseTimer.timed`` — a monotonic-clock pair whose timed region
dispatches device work without any of them is dishonest timing.

Two rules share the region detector
(:func:`tpu_mpi_tests.analysis.program.iter_timed_regions`):

* **TPM101** (file scope): the region itself dispatches device work.
* **TPM102** (project scope, ISSUE 10): the region dispatches *through
  a helper* — it calls a function whose whole-program summary
  dispatches jax work and never syncs. Same dishonest measurement, one
  call frame deeper; invisible to any per-file scan.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import (
    FileContext,
    ProjectContext,
    last_attr,
)
from tpu_mpi_tests.analysis.program import (  # noqa: F401 (re-export)
    CLOCKS,
    SYNC_NAMES,
    iter_timed_regions,
)
from tpu_mpi_tests.analysis.rules import _util


class SyncHonesty:
    name = "sync-honesty"
    scope = "file"
    codes = {
        "TPM101": "monotonic-clock pair times a jax dispatch with no "
                  "block()/block_until_ready/comm_span in the region",
    }

    def check(self, ctx: FileContext) -> Iterator[tuple]:
        local_device = _util.device_callables(ctx)
        for region in iter_timed_regions(ctx):
            yield from self._check_region(ctx, region, local_device)

    def _check_region(self, ctx, region, local_device):
        dispatches: list[ast.Call] = []
        for stmt in region:
            for call in _util.walk_calls(stmt):
                if last_attr(call.func) in SYNC_NAMES:
                    return  # region synchronizes; timing is honest
                if _util.is_device_call(ctx, call, local_device):
                    dispatches.append(call)
        for call in dispatches[:1]:
            yield (
                call.lineno, call.col_offset, "TPM101",
                f"timed region dispatches "
                f"'{_util.call_name(call.func)}' without a device sync "
                f"— async dispatch makes this a queue-flush "
                f"measurement; wrap the result in block()/"
                f"block_until_ready() or use comm_span/PhaseTimer.timed",
            )


class InterprocSyncHonesty:
    name = "sync-honesty-interproc"
    scope = "project"
    codes = {
        "TPM102": "timed region calls a helper whose call graph "
                  "dispatches jax work with no device sync "
                  "(interprocedural TPM101)",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        idx = proj.index
        for ff in proj.facts:
            for region in ff["timed_regions"]:
                for target, line, col in region["calls"]:
                    funcs = idx.resolve_funcs(target, ff["module"])
                    if not funcs:
                        continue
                    if any(idx.dispatches(fn) and not idx.syncs(fn)
                           for fn in funcs):
                        short = target.rsplit(".", 1)[-1]
                        yield (
                            ff["path"], line, col, "TPM102",
                            f"timed region calls '{short}' whose call "
                            f"graph dispatches jax work and never "
                            f"syncs — the clock pair measures its "
                            f"dispatch, not its compute; sync inside "
                            f"the region (block()/block_until_ready/"
                            f"comm_span) or inside the helper",
                        )
                        break  # one finding per region, like TPM101
