"""TPM10xx — chaos containment.

The bug class: a fault-injection hook left reachable from a hot path is
a shipped bug — one forgotten ``chaos.arm(...)`` or a stray
``from tpu_mpi_tests.chaos import ...`` in a driver and a production
run can kill ranks, wedge dispatches, or flood its own serve queue.
The chaos layer's whole containment story (README "Chaos & diagnosis")
is that faults arm in exactly ONE place — ``drivers/_common.
make_reporter`` resolves ``--chaos`` / ``$TPU_MPI_CHAOS`` once at
reporter construction — and that a disarmed run has zero chaos state
installed. This rule keeps that door shut: ANY import of
``tpu_mpi_tests.chaos`` (module-level or lazy — reachability is the
hazard, not import timing) or call into a chaos alias outside the
sanctioned homes is a finding.

Sanctioned homes, exempt by construction:

* modules under ``tpu_mpi_tests.chaos`` itself;
* the arm-point module ``tpu_mpi_tests.drivers._common``;
* test modules (``test_*`` / ``conftest``) — tests exist to exercise
  the faults.

Note the arm-point *slots* (``telemetry._CHAOS_SPAN_HOOK``,
``serve.loop._CHAOS_FLOOD``) never import chaos — chaos imports THEM
and rebinds the slot at arm time — so instrument/ and serve/ stay
import-clean and this rule needs no exemption for them.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import FileContext, is_test_file

CHAOS_PKG = "tpu_mpi_tests.chaos"

#: the one production module allowed to import the chaos layer
SANCTIONED_MODULES = {"tpu_mpi_tests.drivers._common"}


def _exempt(module: str) -> bool:
    if module.startswith(CHAOS_PKG):
        return True
    if module in SANCTIONED_MODULES:
        return True
    return is_test_file(module.rsplit(".", 1)[-1])


def _is_chaos(target: str) -> bool:
    return target == CHAOS_PKG or target.startswith(CHAOS_PKG + ".")


class ChaosContainment:
    name = "chaos-containment"
    scope = "file"
    codes = {
        "TPM1001": "chaos fault injection reachable outside "
                   "tpu_mpi_tests/chaos/ and the sanctioned arm-point "
                   "(drivers/_common.make_reporter)",
    }

    def check(self, ctx: FileContext) -> Iterator[tuple]:
        if _exempt(ctx.module):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if _is_chaos(a.name):
                        yield self._hit(node, f"import {a.name}")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level:
                    continue  # relative: resolved below via calls
                if _is_chaos(mod):
                    yield self._hit(node, f"from {mod} import ...")
                elif mod == "tpu_mpi_tests" and any(
                    a.name == "chaos" for a in node.names
                ):
                    yield self._hit(
                        node, "from tpu_mpi_tests import chaos"
                    )
            elif isinstance(node, ast.Call):
                resolved = ctx.imports.resolve(node.func)
                if resolved and _is_chaos(resolved):
                    yield self._hit(node, f"call to {resolved}")

    def _hit(self, node: ast.AST, what: str) -> tuple:
        return (
            node.lineno, node.col_offset, "TPM1001",
            f"{what} — a fault-injection hook reachable from "
            f"production code is a shipped bug; faults arm ONLY "
            f"through --chaos/$TPU_MPI_CHAOS in drivers/_common."
            f"make_reporter (README 'Chaos & diagnosis')",
        )
