"""TPM1102 — rank-guarded early exit before a collective (ISSUE 12).

The other half of the SPMD-deadlock family, and the documented TPM1101
false-negative class the ROADMAP carried out of PR 11's review:

    if rank != 0:
        return x            # every non-zero rank leaves here
    total = allreduce_sum(x, mesh)   # rank 0 waits forever

The lexical engine compared the two branch bodies' event sequences, and
both were collective-free — the ``return`` made the *rest of the
function* unreachable for most ranks, but statements after the branch
were not part of either branch's summary. With the CFG
(:mod:`tpu_mpi_tests.analysis.cfg`) an exit is an edge: each path's
event sequence now runs to the function exit, so the path that leaves
early is visibly missing every collective the continuing path still
dispatches (interprocedurally, through the project summaries).

Fires when exactly one side of a rank-dependent ``if`` terminates the
function (``return``/``raise``/``break``/``continue`` — no fallthrough
to the join) and the two paths' collective sequences differ.
Symmetric-exit and no-exit divergences stay TPM1101
(``rules/collective_divergence``); every divergent ``if`` carries
exactly one code.
"""

from __future__ import annotations

from typing import Iterator

from tpu_mpi_tests.analysis.core import ProjectContext


def _render(seq: list[str]) -> str:
    return "[" + (", ".join(seq) if seq else "—") + "]"


class EarlyExitDivergence:
    name = "early-exit-divergence"
    scope = "project"
    codes = {
        "TPM1102": "rank-guarded early exit skips a collective the "
                   "continuing ranks still enter — the SPMD deadlock "
                   "shape the lexical engine could not see",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        idx = proj.index
        for ff in proj.facts:
            for fn in ff["functions"]:
                for ri in fn["rank_ifs"]:
                    if ri["then_exits"] == ri["else_exits"]:
                        continue  # symmetric: TPM1101's shape
                    a = idx.collective_seq(ri["then"], ff["module"])
                    b = idx.collective_seq(ri["orelse"], ff["module"])
                    if a == b:
                        continue
                    exiting, staying = (
                        ("guarded", b) if ri["then_exits"]
                        else ("unguarded", a)
                    )
                    yield (
                        ff["path"], ri["line"], ri["col"], "TPM1102",
                        f"rank-dependent branch exits the function "
                        f"early on its {exiting} path while the "
                        f"continuing ranks dispatch "
                        f"{_render(staying)} — the ranks that left "
                        f"never enter the collective and the mesh "
                        f"deadlocks; run the collective on every rank "
                        f"before the rank-guarded exit (or suppress "
                        f"with a why-comment for a sanctioned "
                        f"single-process site)",
                    )
