"""TPM2xx — trace purity.

The bug class: a function handed to ``jax.jit`` / ``shard_map`` /
``pallas_call`` runs ONCE at trace time. Host side effects inside it —
``print``, ``time.*`` reads, Reporter lines, telemetry records — do not
happen per execution; they fabricate telemetry (a span recorded under a
trace claims ops=1 with trace-duration seconds, the exact hazard
``telemetry._under_trace`` exists to gate) or silently vanish from the
compiled loop. ``jax.debug.print`` and ``pl.debug_print`` are the
sanctioned in-trace prints and are not flagged; code guarded by an
``under_trace()``/``trace_state_clean`` check is exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import FileContext, attr_parts
from tpu_mpi_tests.analysis.rules import _util

TIME_FNS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.perf_counter_ns", "time.monotonic_ns", "time.time_ns",
}

#: Reporter record methods (instrument/report.py) — flagged when called
#: on a receiver that looks like a reporter (``rep``/``reporter``)
REPORTER_METHODS = {
    "line", "jsonl", "banner", "sum_line", "time_line", "test_line",
    "iter_line", "exchange_line", "time_lines",
}

TELEMETRY_MODULE = "tpu_mpi_tests.instrument.telemetry"

GUARD_MARKERS = ("under_trace", "trace_state_clean")


def _is_guard(test: ast.AST) -> bool:
    for n in ast.walk(test):
        name = None
        if isinstance(n, ast.Attribute):
            name = n.attr
        elif isinstance(n, ast.Name):
            name = n.id
        if name and any(m in name for m in GUARD_MARKERS):
            return True
    return False


class TracePurity:
    name = "trace-purity"
    scope = "file"
    codes = {
        "TPM201": "host side effect (print/time/Reporter/telemetry) "
                  "inside a traced function without an under_trace() "
                  "guard",
    }

    def check(self, ctx: FileContext) -> Iterator[tuple]:
        seen: set[tuple[int, int]] = set()
        for fn in _util.traced_functions(ctx):
            # an under_trace()-tested `if` exempts its whole span (both
            # branches are trace-awareness-gated by construction)
            guard_spans = [
                (n.lineno, n.end_lineno or n.lineno)
                for n in ast.walk(fn)
                if isinstance(n, ast.If) and _is_guard(n.test)
            ]
            for call in _util.walk_calls(fn):
                if any(lo <= call.lineno <= hi for lo, hi in guard_spans):
                    continue
                msg = self._effect(ctx, call)
                if msg and (call.lineno, call.col_offset) not in seen:
                    seen.add((call.lineno, call.col_offset))
                    yield (call.lineno, call.col_offset, "TPM201", msg)

    def _effect(self, ctx: FileContext, call: ast.Call) -> str | None:
        func = call.func
        if isinstance(func, ast.Name) and func.id == "print":
            return ("print() inside a traced function runs once at "
                    "trace time, not per execution — use "
                    "jax.debug.print or move it out of the traced body")
        resolved = ctx.imports.resolve(func)
        if resolved in TIME_FNS:
            return (f"{resolved}() inside a traced function reads the "
                    f"clock once at trace time — its value is a "
                    f"compile-time constant, not a per-step timestamp")
        parts = attr_parts(func)
        if parts:
            origin = ctx.imports.origin(parts[0]) or ""
            if (origin.startswith(TELEMETRY_MODULE)
                    or (origin + "." + ".".join(parts[1:])).startswith(
                        TELEMETRY_MODULE)):
                return (f"telemetry call '{'.'.join(parts)}' inside a "
                        f"traced function fabricates records (one "
                        f"trace-time event for the whole compiled "
                        f"loop) — guard with under_trace() like "
                        f"instrument/telemetry.py does")
            if (len(parts) >= 2 and parts[-1] in REPORTER_METHODS
                    and parts[-2].startswith("rep")):
                return (f"Reporter call '{'.'.join(parts)}' inside a "
                        f"traced function records once at trace time — "
                        f"report from the host side of the step loop")
        return None
