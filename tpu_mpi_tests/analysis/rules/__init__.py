"""Rule registry: one module per hazard family, aggregated here.

Each rule module documents the shipped bug its family encodes; codes are
stable (a code is never reused for a different hazard) so suppression
comments stay meaningful across releases. File-scope rules see one
parsed file; project-scope rules see the whole linted program as
serialized facts (``analysis/program.py``) — the ISSUE-10 families
(TPM11xx/TPM12xx), the interprocedural upgrades (TPM102/TPM502/
TPM802), the ISSUE-12 flow-sensitive families (TPM1102 early-exit
divergence, TPM1301 broadcast-consistency, TPM14xx record-contract),
the ISSUE-13 lockset concurrency layer (TPM16xx races/deadlocks/
hook-slot rebinds, with TPM601 demoted to its single-file fallback),
and the ISSUE-18 collective-protocol verifier (TPM17xx whole-program
schedule automata + ``--conform`` runtime conformance) all live there.
"""

from tpu_mpi_tests.analysis.rules.axis_consistency import (
    AxisConsistency,
    AxisProgramConsistency,
)
from tpu_mpi_tests.analysis.rules.broadcast_consistency import (
    BroadcastConsistency,
)
from tpu_mpi_tests.analysis.rules.chaos_containment import (
    ChaosContainment,
)
from tpu_mpi_tests.analysis.rules.collective_divergence import (
    CollectiveDivergence,
)
from tpu_mpi_tests.analysis.rules.early_exit_divergence import (
    EarlyExitDivergence,
)
from tpu_mpi_tests.analysis.rules.concurrency import UnlockedSharedWrite
from tpu_mpi_tests.analysis.rules.donation_safety import DonationSafety
from tpu_mpi_tests.analysis.rules.races import LocksetRaces
from tpu_mpi_tests.analysis.rules.import_hygiene import ImportHygiene
from tpu_mpi_tests.analysis.rules.overlap_regions import (
    EscapedAsyncHandle,
    OverlapRegionSync,
)
from tpu_mpi_tests.analysis.rules.record_contract import (
    RecordContract,
)
from tpu_mpi_tests.analysis.rules.schedule_constants import (
    ScheduleConstants,
)
from tpu_mpi_tests.analysis.rules.schedule_protocol import (
    ScheduleProtocol,
)
from tpu_mpi_tests.analysis.rules.sync_honesty import (
    InterprocSyncHonesty,
    SyncHonesty,
)
from tpu_mpi_tests.analysis.rules.trace_purity import TracePurity
from tpu_mpi_tests.analysis.rules.x64_safety import X64Safety

ALL_RULES = [
    SyncHonesty(),
    InterprocSyncHonesty(),
    TracePurity(),
    X64Safety(),
    ImportHygiene(),
    AxisConsistency(),
    AxisProgramConsistency(),
    UnlockedSharedWrite(),
    LocksetRaces(),
    ScheduleConstants(),
    OverlapRegionSync(),
    EscapedAsyncHandle(),
    ChaosContainment(),
    CollectiveDivergence(),
    EarlyExitDivergence(),
    DonationSafety(),
    BroadcastConsistency(),
    RecordContract(),
    ScheduleProtocol(),
]
