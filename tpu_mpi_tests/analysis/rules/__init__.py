"""Rule registry: one module per hazard family, aggregated here.

Each rule module documents the shipped bug its family encodes; codes are
stable (a code is never reused for a different hazard) so suppression
comments stay meaningful across releases.
"""

from tpu_mpi_tests.analysis.rules.axis_consistency import AxisConsistency
from tpu_mpi_tests.analysis.rules.chaos_containment import (
    ChaosContainment,
)
from tpu_mpi_tests.analysis.rules.concurrency import UnlockedSharedWrite
from tpu_mpi_tests.analysis.rules.import_hygiene import ImportHygiene
from tpu_mpi_tests.analysis.rules.overlap_regions import (
    OverlapRegionSync,
)
from tpu_mpi_tests.analysis.rules.schedule_constants import (
    ScheduleConstants,
)
from tpu_mpi_tests.analysis.rules.sync_honesty import SyncHonesty
from tpu_mpi_tests.analysis.rules.trace_purity import TracePurity
from tpu_mpi_tests.analysis.rules.x64_safety import X64Safety

ALL_RULES = [
    SyncHonesty(),
    TracePurity(),
    X64Safety(),
    ImportHygiene(),
    AxisConsistency(),
    UnlockedSharedWrite(),
    ScheduleConstants(),
    OverlapRegionSync(),
    ChaosContainment(),
]
