"""Shared AST helpers for the rule modules."""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import FileContext, attr_parts, last_attr

#: call targets that put a function under a jax trace — the bodies they
#: receive run ONCE at trace time, not per execution
TRACE_ENTRIES = {"jit", "shard_map", "pallas_call"}

#: origin-module prefixes whose calls dispatch device work in this repo
DEVICE_ORIGINS = ("jax", "tpu_mpi_tests.kernels", "tpu_mpi_tests.comm")

#: origins whose return values are device-dispatching callables (the
#: compiled-fn factories: halo iterate builders, pick_kernel_tier, ...)
FACTORY_ORIGINS = DEVICE_ORIGINS + ("tpu_mpi_tests.drivers",)


def walk_calls(node: ast.AST) -> Iterator[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def has_trace_entry(node: ast.AST) -> bool:
    """True when the expression mentions jit/shard_map/pallas_call —
    used on decorators (``@functools.partial(jax.jit, ...)`` included)
    and on call targets (``jax.jit(f)``)."""
    for n in ast.walk(node):
        name = None
        if isinstance(n, ast.Attribute):
            name = n.attr
        elif isinstance(n, ast.Name):
            name = n.id
        if name in TRACE_ENTRIES:
            return True
    return False


def traced_functions(ctx: FileContext) -> list[ast.AST]:
    """Function nodes (defs and lambdas) whose body runs under a jax
    trace: jit/shard_map/pallas_call decorators, or being passed as the
    first argument to such a call (``shard_map(body, mesh=...)``,
    ``pl.pallas_call(kernel, ...)``, ``jax.jit(f)``)."""
    defs_by_name: dict[str, list[ast.AST]] = {}
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(n.name, []).append(n)

    traced: list[ast.AST] = []
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(has_trace_entry(d) for d in n.decorator_list):
                traced.append(n)
        elif isinstance(n, ast.Call) and has_trace_entry(n.func) and n.args:
            first = n.args[0]
            if isinstance(first, ast.Lambda):
                traced.append(first)
            elif isinstance(first, ast.Name):
                traced.extend(defs_by_name.get(first.id, ()))
    return traced


def device_callables(ctx: FileContext) -> set[str]:
    """Local names that dispatch device work when called: functions with
    a trace-entry decorator, or names assigned from a call into jax /
    the comm / kernels layers (compiled-fn factories)."""
    out: set[str] = set()
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(has_trace_entry(d) for d in n.decorator_list):
                out.add(n.name)
        elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            resolved = ctx.imports.resolve(n.value.func) or ""
            if not (resolved.startswith(FACTORY_ORIGINS)
                    or has_trace_entry(n.value.func)):
                continue
            for t in n.targets:
                targets = t.elts if isinstance(
                    t, (ast.Tuple, ast.List)
                ) else [t]
                out.update(e.id for e in targets
                           if isinstance(e, ast.Name))
    return out


def is_device_call(ctx: FileContext, call: ast.Call,
                   local_device: set[str]) -> bool:
    """Does this call plausibly dispatch (async) device work?"""
    parts = attr_parts(call.func)
    if not parts:
        return False
    if parts[0] in local_device and len(parts) == 1:
        return True
    origin = ctx.imports.origin(parts[0])
    return bool(origin and origin.startswith(DEVICE_ORIGINS))


def stmt_lists(tree: ast.AST) -> Iterator[list[ast.stmt]]:
    """Every statement list in the tree (module/function/branch bodies)."""
    for n in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(n, field, None)
            if isinstance(stmts, list) and stmts and isinstance(
                stmts[0], ast.stmt
            ):
                yield stmts


def call_name(node: ast.AST) -> str:
    return last_attr(node) or "<call>"
