"""Shared AST helpers for the rule modules.

The implementations moved to :mod:`tpu_mpi_tests.analysis.core` when the
whole-program facts extractor (``analysis/program.py``) started needing
them — importing them from here would drag the rule registry into the
extractor's import path. This module re-exports them so rule code keeps
its ``_util.`` spelling.
"""

from tpu_mpi_tests.analysis.core import (  # noqa: F401
    DEVICE_ORIGINS,
    FACTORY_ORIGINS,
    TRACE_ENTRIES,
    call_name,
    device_callables,
    has_trace_entry,
    is_device_call,
    stmt_lists,
    traced_functions,
    walk_calls,
)
