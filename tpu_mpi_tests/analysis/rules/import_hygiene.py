"""TPM4xx — import hygiene for the login-node CLI set.

The bug class: ``tpumt-report``/``tpumt-trace``/``tpumt-lint`` are
advertised for login nodes with no jax install, but a single eager
``import jax`` anywhere in their module-level import closure breaks all
of them at once (the PR 2 review fix that made the package ``__init__``
re-exports lazy, PEP 562). This rule walks the module-level import graph
from each entry module and flags any module-level jax import in the
reachable set — imports inside functions (the lazy idiom every
jax-touching module here uses) are exempt by construction.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import FileContext, ProjectContext


def _resolve_relative(module: str, current: str, is_pkg: bool) -> str:
    """``.foo``/``..foo`` against the importing module's package."""
    level = len(module) - len(module.lstrip("."))
    name = module[level:]
    parts = current.split(".")
    if not is_pkg:
        parts = parts[:-1]
    if level > 1:
        parts = parts[: len(parts) - (level - 1)]
    return ".".join(parts + ([name] if name else []))


def _module_level_imports(
    ctx: FileContext,
) -> list[tuple[int, str, list[str]]]:
    """``(line, module, from_names)`` for every import executed at module
    import time: top-level statements plus those nested in module-level
    ``if``/``try`` (conditional imports still run), but nothing inside a
    function or class body (lazy by construction) and nothing under an
    ``if TYPE_CHECKING:`` guard (never runs)."""
    out: list[tuple[int, str, list[str]]] = []
    is_pkg = ctx.path.endswith("__init__.py")

    def scan(stmts):
        for stmt in stmts:
            if isinstance(stmt, ast.Import):
                for a in stmt.names:
                    out.append((stmt.lineno, a.name, []))
            elif isinstance(stmt, ast.ImportFrom):
                mod = ("." * stmt.level) + (stmt.module or "")
                if mod.startswith("."):
                    mod = _resolve_relative(mod, ctx.module, is_pkg)
                out.append((stmt.lineno, mod,
                            [a.name for a in stmt.names]))
            elif isinstance(stmt, ast.If):
                if any(
                    isinstance(n, (ast.Name, ast.Attribute))
                    and (getattr(n, "id", None) == "TYPE_CHECKING"
                         or getattr(n, "attr", None) == "TYPE_CHECKING")
                    for n in ast.walk(stmt.test)
                ):
                    continue
                scan(stmt.body)
                scan(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                # `try: import jax / except ImportError:` is the
                # canonical SAFE optional import — it imports fine
                # where jax is absent, so the guarded body is exempt.
                # Handler bodies are still scanned: an import there
                # runs exactly when the body already failed, so a jax
                # import in the handler does break the guarantee.
                if not _catches_import_error(stmt):
                    scan(stmt.body)
                scan(stmt.orelse)
                scan(stmt.finalbody)
                for h in stmt.handlers:
                    scan(h.body)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                scan(stmt.body)

    scan(ctx.tree.body)
    return out


def _catches_import_error(stmt: ast.Try) -> bool:
    for h in stmt.handlers:
        if h.type is None:
            return True  # bare except
        types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        for t in types:
            name = getattr(t, "id", None) or getattr(t, "attr", None)
            if name in ("ImportError", "ModuleNotFoundError",
                        "Exception", "BaseException"):
                return True
    return False


def _parents(module: str) -> list[str]:
    parts = module.split(".")
    return [".".join(parts[:i]) for i in range(1, len(parts))]


class ImportHygiene:
    name = "import-hygiene"
    scope = "project"
    codes = {
        "TPM401": "module-level `import jax` reachable from a "
                  "stdlib-only entry point (tpumt-report/tpumt-trace/"
                  "tpumt-lint must import on login nodes)",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        mods = proj.by_module  # module name -> [FileContext, ...]
        # BFS over the module-level import graph; chain[m] remembers one
        # path back to the entry point for the finding message. Every
        # context sharing a module name contributes edges and is
        # scanned: duplicate names across linted roots must widen the
        # reachable set, never silently drop a file from it.
        chain: dict[str, list[str]] = {}
        queue: list[str] = []
        for entry in proj.entry_modules:
            for m in _parents(entry) + [entry]:
                if m in mods and m not in chain:
                    chain[m] = [entry] if m == entry else [entry, m]
                    queue.append(m)
        while queue:
            cur = queue.pop(0)
            for ctx in mods[cur]:
                for _line, target, names in _module_level_imports(ctx):
                    edges = [target] + [f"{target}.{n}" for n in names]
                    for t in edges:
                        for m in _parents(t) + [t]:
                            if m in mods and m not in chain:
                                chain[m] = chain[cur] + [m]
                                queue.append(m)

        for m in sorted(chain):
            for ctx in mods[m]:
                for line, target, _names in _module_level_imports(ctx):
                    if target == "jax" or target.startswith("jax."):
                        entry = chain[m][0]
                        script = proj.entry_modules.get(entry, entry)
                        via = " -> ".join(chain[m])
                        yield (
                            ctx.path, line, 0, "TPM401",
                            f"module-level import of '{target}' breaks "
                            f"the stdlib-only guarantee of {script} "
                            f"(import chain: {via}) — import jax "
                            f"lazily inside the function that needs it",
                        )
