"""TPM4xx — import hygiene for the login-node CLI set.

The bug class: ``tpumt-report``/``tpumt-trace``/``tpumt-lint`` are
advertised for login nodes with no jax install, but a single eager
``import jax`` anywhere in their module-level import closure breaks all
of them at once (the PR 2 review fix that made the package ``__init__``
re-exports lazy, PEP 562). This rule walks the module-level import graph
from each entry module and flags any module-level jax import in the
reachable set — imports inside functions (the lazy idiom every
jax-touching module here uses) are exempt by construction, as is the
``try: import jax / except ImportError:`` optional-import shape (it
imports fine where jax is absent; imports in the *handler* still fire).

The graph edges come from the per-file facts
(``facts["mod_imports"]``, extracted by
:func:`tpu_mpi_tests.analysis.program.module_level_imports`), so a
warm-cache run walks the identical graph without re-parsing anything.
"""

from __future__ import annotations

from typing import Iterator

from tpu_mpi_tests.analysis.core import ProjectContext


def _parents(module: str) -> list[str]:
    parts = module.split(".")
    return [".".join(parts[:i]) for i in range(1, len(parts))]


class ImportHygiene:
    name = "import-hygiene"
    scope = "project"
    codes = {
        "TPM401": "module-level `import jax` reachable from a "
                  "stdlib-only entry point (tpumt-report/tpumt-trace/"
                  "tpumt-lint must import on login nodes)",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        mods = proj.by_module  # module name -> [facts, ...]
        # BFS over the module-level import graph; chain[m] remembers one
        # path back to the entry point for the finding message. Every
        # facts record sharing a module name contributes edges and is
        # scanned: duplicate names across linted roots must widen the
        # reachable set, never silently drop a file from it.
        chain: dict[str, list[str]] = {}
        queue: list[str] = []
        for entry in proj.entry_modules:
            for m in _parents(entry) + [entry]:
                if m in mods and m not in chain:
                    chain[m] = [entry] if m == entry else [entry, m]
                    queue.append(m)
        while queue:
            cur = queue.pop(0)
            for ff in mods[cur]:
                for _line, target, names in ff["mod_imports"]:
                    edges = [target] + [f"{target}.{n}" for n in names]
                    for t in edges:
                        for m in _parents(t) + [t]:
                            if m in mods and m not in chain:
                                chain[m] = chain[cur] + [m]
                                queue.append(m)

        for m in sorted(chain):
            for ff in mods[m]:
                for line, target, _names in ff["mod_imports"]:
                    if target == "jax" or target.startswith("jax."):
                        entry = chain[m][0]
                        script = proj.entry_modules.get(entry, entry)
                        via = " -> ".join(chain[m])
                        yield (
                            ff["path"], line, 0, "TPM401",
                            f"module-level import of '{target}' breaks "
                            f"the stdlib-only guarantee of {script} "
                            f"(import chain: {via}) — import jax "
                            f"lazily inside the function that needs it",
                        )
