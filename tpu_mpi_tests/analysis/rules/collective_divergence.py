"""TPM11xx — collective divergence under rank-dependent control flow.

The classic SPMD deadlock: a collective is reachable from a branch only
*some* ranks take (``if process_index() == 0: allreduce(...)``). The
ranks that enter the collective wait forever for the ranks that never
will — nothing errors, the pod just stops, and the only post-mortem is
a watchdog dump (PAPER §2's halo pillar and §3's ``MPI_IN_PLACE``
probes are instruments for catching exactly this *after* the fact; this
rule catches it at lint time). With the whole-program summaries the
check is interprocedural: a rank-guarded branch that calls a helper
whose call graph dispatches a collective diverges just the same.

Detection (conservative by design): for every ``if`` whose test is
rank-dependent — a ``process_index()`` call, a comparison against a
rank-named variable/attribute, or (ISSUE 12) a truthiness test like
``if not rank:`` / a tested local aliasing ``process_index()`` —
flatten each *path's* event sequence (computed to function exit over
the CFG, so branches that ``return`` early carry only what they
actually run) into the collective ops its execution dispatches (call
targets expanded through the project summaries) and compare. Equal
sequences (usually both empty: rank-0-only *printing* is everywhere
and fine) pass; any difference is a finding anchored at the ``if``.
Branches where exactly one side exits the function early belong to
TPM1102 (``rules/early_exit_divergence``) — this rule skips them so
every divergent ``if`` carries exactly one code.

Sanctioned rank-0-only sites (a single-process tune sweep, a rank-0
report/trace merge) carry the standard inline suppression with a
why-comment — the allowlist is explicit in the code it blesses, not
hidden in the rule.
"""

from __future__ import annotations

from typing import Iterator

from tpu_mpi_tests.analysis.core import ProjectContext


def _render(seq: list[str]) -> str:
    return "[" + (", ".join(seq) if seq else "—") + "]"


class CollectiveDivergence:
    name = "collective-divergence"
    scope = "project"
    codes = {
        "TPM1101": "collective dispatch reachable from a rank-dependent "
                   "branch whose paths dispatch different collective "
                   "sequences — the SPMD deadlock shape",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        idx = proj.index
        for ff in proj.facts:
            for fn in ff["functions"]:
                for ri in fn["rank_ifs"]:
                    if ri["then_exits"] != ri["else_exits"]:
                        continue  # the early-exit shape: TPM1102's
                    a = idx.collective_seq(ri["then"], ff["module"])
                    b = idx.collective_seq(ri["orelse"], ff["module"])
                    if a == b:
                        continue
                    yield (
                        ff["path"], ri["line"], ri["col"], "TPM1101",
                        f"rank-dependent branch dispatches diverging "
                        f"collective sequences: {_render(a)} on the "
                        f"guarded path vs {_render(b)} on the other — "
                        f"ranks that skip a collective the rest enter "
                        f"deadlock the mesh; hoist the collective out "
                        f"of the rank branch (or suppress with a "
                        f"why-comment for a sanctioned single-process "
                        f"rank-0-only site)",
                    )
