"""TPM17xx — collective-protocol verification (ISSUE 18).

The hazard this family encodes is the composed one TPM1101/TPM1102
cannot see: each rank-guarded branch can look locally symmetric while
the *whole-program* schedule — assembled across functions, broadcast
wrappers, loops, and exception paths — still diverges per rank. On a
pod that is not a crash; it is one rank parked in a collective its
partners never enter, a silent fleet-wide hang (the `MPI_Waitall`
wedge the reference suite exists to catch).

The heavy lifting lives in :mod:`tpu_mpi_tests.analysis.protocol`:
every function's ``proto`` event tree is summarized bottom-up into a
regular collective schedule, composed through the project call graph,
and checked pairwise over rank-feasible paths. This module is the thin
rule adapter: it owns the code table (TPM1704/TPM1705 are listed here
so ``--list-rules``, the README table, and SARIF metadata stay the
single source of truth, but they are only ever *emitted* by the
``tpumt-lint --conform`` replay — a static run cannot produce them).
"""

from tpu_mpi_tests.analysis.core import ProjectContext


class ScheduleProtocol:
    name = "schedule-protocol"
    scope = "project"
    codes = {
        "TPM1701": "rank-divergent whole-program collective schedule "
                   "(divergence assembled across functions or through "
                   "broadcast wrappers / rank-returning helpers)",
        "TPM1702": "rank-dependent loop bound encloses a collective "
                   "(divergent trip counts deadlock the fleet)",
        "TPM1703": "collective reachable under an exception path whose "
                   "surviving handler skips the partner op",
        "TPM1704": "runtime (op, axis) stream no static schedule path "
                   "generates (--conform only: stale model or "
                   "dynamic-dispatch blind spot)",
        "TPM1705": "rank stream ends with a statically mandatory "
                   "collective un-emitted while a sibling emitted it "
                   "(--conform only: static twin of missing_rank)",
    }

    def check_project(self, proj: ProjectContext):
        from tpu_mpi_tests.analysis.protocol import ProtocolIndex

        yield from ProtocolIndex(proj).check_all()
