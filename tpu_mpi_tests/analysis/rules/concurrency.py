"""TPM6xx — cross-thread file-handle discipline.

The bug class: the watchdog fires from a ``threading.Timer`` thread and
used to write its timeline record through the same JSONL handle the main
thread's spans stream to; an interleaved ``json.dump`` (many small
writes) corrupted both lines (fixed in PR 2 — ``Reporter.jsonl`` is now
single-write under a lock). The rule: in any file that arms a
``threading.Timer``/``Thread``, a ``.write()`` on a shared-looking
handle (an attribute, or a name bound from ``open()``) must happen
inside a ``with <lock>:`` block. ``sys.stdout``/``sys.stderr`` writes
are exempt (line-buffered streams the hang-dump path deliberately
uses).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import FileContext, attr_parts
from tpu_mpi_tests.analysis.rules import _util

THREAD_SPAWNS = {"threading.Timer", "threading.Thread"}
LOCK_FACTORIES = {"threading.Lock", "threading.RLock"}
EXEMPT_PARTS = {"stdout", "stderr", "stream", "sys"}


def _dotted(node: ast.AST) -> str | None:
    parts = attr_parts(node)
    return ".".join(parts) if parts else None


class UnlockedSharedWrite:
    name = "concurrency"
    scope = "file"
    codes = {
        "TPM601": "write() on a shared handle in a file that arms a "
                  "threading.Timer/Thread, without holding a lock",
    }

    def check(self, ctx: FileContext) -> Iterator[tuple]:
        spawns = False
        locks: set[str] = set()
        open_names: set[str] = set()
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call):
                resolved = ctx.imports.resolve(n.func) or ""
                if resolved in THREAD_SPAWNS:
                    spawns = True
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                resolved = ctx.imports.resolve(n.value.func) or ""
                for t in n.targets:
                    name = _dotted(t)
                    if not name:
                        continue
                    if resolved in LOCK_FACTORIES:
                        locks.add(name)
                    elif resolved in ("open", "io.open"):
                        open_names.add(name)
        if not spawns:
            return
        yield from self._walk(ctx, ctx.tree.body, locks, open_names,
                              held=False)

    def _is_lockish(self, expr: ast.AST, locks: set[str]) -> bool:
        name = _dotted(expr)
        if not name:
            return False
        last = name.rsplit(".", 1)[-1].lower()
        return name in locks or "lock" in last

    def _walk(self, ctx, stmts, locks, open_names, held):
        for stmt in stmts:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner_held = held or any(
                    self._is_lockish(item.context_expr, locks)
                    for item in stmt.items
                )
                yield from self._walk(ctx, stmt.body, locks, open_names,
                                      inner_held)
                continue
            # expressions directly in this statement (not nested bodies)
            for call in self._own_calls(stmt):
                yield from self._check_write(call, open_names, held)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list):
                    yield from self._walk(ctx, sub, locks, open_names,
                                          held)
            for h in getattr(stmt, "handlers", ()):
                yield from self._walk(ctx, h.body, locks, open_names,
                                      held)

    @staticmethod
    def _own_calls(stmt):
        """Calls in the statement's header/expressions, excluding nested
        statement bodies (those get their own lock context)."""
        nested: set[int] = set()
        for field in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, field, None) or ():
                for n in ast.walk(sub):
                    nested.add(id(n))
        for h in getattr(stmt, "handlers", ()):
            for sub in h.body:
                for n in ast.walk(sub):
                    nested.add(id(n))
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call) and id(n) not in nested:
                yield n

    def _check_write(self, call, open_names, held):
        func = call.func
        if not (isinstance(func, ast.Attribute) and func.attr == "write"):
            return
        recv = func.value
        parts = attr_parts(recv)
        if parts and (parts[0] == "sys"
                      or any(p in EXEMPT_PARTS for p in parts)):
            return
        shared = isinstance(recv, ast.Attribute) or (
            isinstance(recv, ast.Name) and recv.id in open_names
        )
        if shared and not held:
            name = ".".join(parts) if parts else "<handle>"
            yield (
                call.lineno, call.col_offset, "TPM601",
                f"'{name}.write()' in a module that arms a "
                f"threading.Timer/Thread — concurrent writes interleave "
                f"records (the watchdog JSONL bug class); serialize one "
                f"write per record under `with <lock>:`",
            )
