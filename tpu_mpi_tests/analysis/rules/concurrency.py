"""TPM6xx — cross-thread file-handle discipline (single-file fallback).

The bug class: the watchdog fires from a ``threading.Timer`` thread and
used to write its timeline record through the same JSONL handle the main
thread's spans stream to; an interleaved ``json.dump`` (many small
writes) corrupted both lines (fixed in PR 2 — ``Reporter.jsonl`` is now
single-write under a lock).

ISSUE 13 demoted this family: the flow- and lock-sensitive TPM16xx
analysis (``rules/races.py``) owns every file whose thread entries it
can resolve — there the lexical "a write without a lock in a file that
arms a Timer" heuristic would double-report (or contradict) the
lockset verdict. TPM601 now fires ONLY for files where thread-entry
discovery resolved *nothing* (a dynamic spawn target like
``Timer(s, callbacks[i])`` or an untyped/ambiguous bound method, no
handler classes) — the whole-program engine is blind there, and the
old heuristic is strictly better than silence. Resolution is judged at
PROJECT scope with the same machinery the race rule uses (a captured
``?meth:`` ref that no unique project method matches resolves to
nothing), and test modules always keep the lexical rule: the lockset
families exempt them, so the fallback is all the coverage they get.
The lexical detection itself lives in
:func:`tpu_mpi_tests.analysis.locks.lexical_tpm601` and is cached as a
file fact, so warm runs replay it without re-parsing.
"""

from __future__ import annotations

from typing import Iterator

from tpu_mpi_tests.analysis.core import ProjectContext


class UnlockedSharedWrite:
    name = "concurrency"
    scope = "project"
    codes = {
        "TPM601": "write() on a shared handle in a file that arms a "
                  "threading.Timer/Thread, without holding a lock "
                  "(fallback: fires only where TPM16xx thread-entry "
                  "discovery resolved nothing)",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        from tpu_mpi_tests.analysis.rules.races import _Program

        prog = _Program(proj)
        modeled: set[str] = set()
        for ff in prog.files:
            races = ff["races"]
            ok = bool(races["handlers"])
            if not ok:
                for _kind, ref, _line in races["spawns"]:
                    if ref and prog.resolve(ref, ff["module"]):
                        ok = True
                        break
            if ok:
                modeled.add(ff["path"])
        for ff in proj.facts:
            races = ff.get("races")
            if not races or ff["path"] in modeled:
                continue  # the lockset engine models this file
            for line, col, msg in races.get("tpm601", ()):
                yield (ff["path"], line, col, "TPM601", msg)
