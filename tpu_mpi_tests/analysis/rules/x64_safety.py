"""TPM3xx — x64 safety.

The bug class: with x64 off (the TPU default), jax canonicalizes python
floats and float64 arrays to float32 on the way to the device. For most
values that is intended weak typing; for wall-clock epochs it is fatal —
float32's ulp at epoch magnitude is ~128 seconds, so a raw
``time.time()`` crossing ``jnp.asarray``/``process_allgather`` comes
back as pure quantization noise (the PR 2 clock-sync bug, fixed by
``instrument/manifest._split_us``'s f32-exact base-2^24 integer
microsecond digits). Two codes:

* TPM301: a bare float literal into ``jnp.asarray``/``jnp.array`` with
  no dtype — the produced dtype silently depends on the x64 flag;
  state the intended width.
* TPM302: a ``time.time()`` epoch value lexically flowing into a device
  conversion or collective — precision is lost regardless of intent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import FileContext
from tpu_mpi_tests.analysis.rules import _util

NARROW_SINKS = {"jax.numpy.asarray", "jax.numpy.array"}

#: additional device-boundary sinks checked for epoch flow
EPOCH_SINKS = NARROW_SINKS | {
    "jax.device_put",
    "jax.experimental.multihost_utils.process_allgather",
}


def _is_float_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, float)
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd)
    ):
        return _is_float_literal(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_float_literal(node.left) and _is_float_literal(
            node.right
        )
    return False


class X64Safety:
    name = "x64-safety"
    scope = "file"
    codes = {
        "TPM301": "float literal into jnp.asarray/jnp.array without an "
                  "explicit dtype (width silently depends on the x64 "
                  "flag)",
        "TPM302": "time.time() epoch value crosses the device boundary "
                  "(f32 canonicalization quantizes it to ~128 s)",
    }

    def check(self, ctx: FileContext) -> Iterator[tuple]:
        for call in _util.walk_calls(ctx.tree):
            resolved = ctx.imports.resolve(call.func)
            if resolved in NARROW_SINKS:
                yield from self._check_narrow(call, resolved)
            if resolved in EPOCH_SINKS:
                yield from self._check_epoch(ctx, call, resolved)

    def _check_narrow(self, call: ast.Call, resolved: str):
        has_dtype = len(call.args) >= 2 or any(
            kw.arg == "dtype" for kw in call.keywords
        )
        if has_dtype or not call.args:
            return
        if _is_float_literal(call.args[0]):
            short = resolved.replace("jax.numpy", "jnp")
            yield (
                call.lineno, call.col_offset, "TPM301",
                f"float literal into {short} without an explicit dtype "
                f"— canonicalizes to float32 when x64 is off and to "
                f"float64 when on; pass dtype= to state the intended "
                f"width",
            )

    def _check_epoch(self, ctx: FileContext, call: ast.Call,
                     resolved: str):
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if self._raw_epoch(ctx, arg):
                yield (
                    call.lineno, call.col_offset, "TPM302",
                    f"time.time() epoch value into "
                    f"{resolved.rsplit('.', 1)[-1]} — float32 "
                    f"canonicalization (x64 off) quantizes epochs "
                    f"to ~128 s; encode as integer microsecond "
                    f"digits (instrument/manifest._split_us) or "
                    f"keep the timestamp on host",
                )
                return

    def _raw_epoch(self, ctx: FileContext, expr: ast.AST) -> bool:
        """``time.time()`` reaching the sink raw or through arithmetic
        only. A nested call (``_split_us(time.time())``) is assumed to
        encode the value — that wrapper is exactly the sanctioned fix,
        and an un-encoding wrapper is beyond lexical analysis."""
        if isinstance(expr, ast.Call):
            return ctx.imports.resolve(expr.func) == "time.time"
        return any(self._raw_epoch(ctx, child)
                   for child in ast.iter_child_nodes(expr))
