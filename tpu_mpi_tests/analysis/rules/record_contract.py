"""TPM14xx — the JSONL record contract between producers and consumers
(ISSUE 12).

The repo's observability spine is ~15 JSONL record kinds emitted from
a dozen ``instrument``/``serve``/``chaos``/``workloads`` producer sites
and parsed by four stdlib-only consumers (``tpumt-report`` /
``tpumt-trace`` / ``tpumt-doctor`` / ``tpumt-top``) plus the metrics
plane. Until ISSUE 12 nothing but tests held that contract together,
and PR 11's review history (progress snapshots double-counting,
``rep.rank`` vs the true process index) shows silent drift is the live
failure mode: a consumer reading a field nobody emits just takes its
``.get`` default forever, and a consumer filtering on a kind nobody
produces renders an empty table that *looks* like a quiet run.

Two codes over the extracted facts
(:mod:`tpu_mpi_tests.analysis.program`):

* **TPM1401** — a consumer reads a constant field off a record variable
  whose tested kinds it established, and NO producer of the governing
  kinds emits that field. The consumer facts are *flow-sensitive*
  (ISSUE 12): a read inside one arm of a per-kind dispatch chain is
  judged against that arm's kinds alone, a read exclusively on the
  complement side of a kind test is unjudgeable and skipped, and only
  reads in shared code fall back to the union of every tested kind.
  Groups whose producers include an *open* schema (``**spread`` /
  ``.update()`` — dynamic fields) are skipped entirely.
* **TPM1402** — a consumer tests a record variable against a kind no
  producer in the linted program ever emits.

Test modules (``test_*.py``/``conftest.py``) are exempt on BOTH sides:
tests assert on records, they are not contract parties — a kind
produced only by a test fixture must still convict its shipped
consumer. The generated ``RECORDS.md`` (``make records``,
:mod:`tpu_mpi_tests.analysis.records`) is the same facts rendered as
the authoritative schema table.
"""

from __future__ import annotations

from typing import Iterator

from tpu_mpi_tests.analysis.core import (
    ProjectContext,
    is_test_file as _is_test_file,
)


class RecordContract:
    name = "record-contract"
    scope = "project"
    codes = {
        "TPM1401": "record field consumed but never produced for any "
                   "of the kinds the consumer tested — the .get "
                   "default is served forever",
        "TPM1402": "record kind consumed but never produced anywhere "
                   "in the program — the consumer filters on records "
                   "that cannot exist",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        produced: dict[str, tuple[set, bool]] = {}
        stamped: set = set()
        for ff in proj.facts:
            if _is_test_file(ff["path"]):
                continue
            for kind, _event, fields, open_, _line in ff.get(
                "rec_produced", ()
            ):
                have, was_open = produced.get(kind, (set(), False))
                produced[kind] = (have | set(fields),
                                  was_open or bool(open_))
            for fields, _line in ff.get("rec_stamps", ()):
                # envelope fields ({**rec, "rank": ...} at a sink
                # wrapper) ride on EVERY kind that flows through
                stamped.update(fields)

        for ff in proj.facts:
            if _is_test_file(ff["path"]):
                continue
            for cons in ff.get("rec_consumed", ()):
                unknown = [k for k in cons["kinds"]
                           if k not in produced]
                for kind in unknown:
                    yield (
                        ff["path"], cons["line"], 0, "TPM1402",
                        f"'{cons['var']}' is filtered on kind "
                        f"'{kind}', which no producer in the linted "
                        f"program emits — either the kind was renamed "
                        f"out from under this consumer or the "
                        f"producer was never written; see RECORDS.md "
                        f"for the live kind set",
                    )
                if unknown:
                    continue  # field check needs a known schema union
                for group in cons["groups"]:
                    kinds = group["kinds"] or cons["kinds"]
                    if any(produced[k][1] for k in kinds):
                        continue  # an open schema produces anything
                    avail: set = set(stamped)
                    for k in kinds:
                        avail |= produced[k][0]
                    for fname, line, col in group["fields"]:
                        if fname in avail:
                            continue
                        klist = ", ".join(kinds)
                        yield (
                            ff["path"], line, col, "TPM1401",
                            f"'{cons['var']}' (kind {klist}) is read "
                            f"for field '{fname}', which no producer "
                            f"of "
                            f"{'that kind' if len(kinds) == 1 else 'those kinds'} "
                            f"emits — the read silently yields its "
                            f"default forever; fix the field name or "
                            f"emit it at the producer (RECORDS.md "
                            f"lists the live schemas)",
                        )
