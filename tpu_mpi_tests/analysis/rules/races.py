"""TPM16xx — interprocedural lockset race detection over the threading
plane (ISSUE 13 tentpole).

Three shipped-and-fixed concurrency bugs motivated this family, each
found by review rather than by the linter: the watchdog/Reporter JSONL
interleave (PR 2), the ``attach_metrics`` re-entrant-lock deadlock
shape (PR 11), and the http.server per-connection ``wfile`` false
positive of the lexical TPM601. The analysis is classic lockset
(Eraser) made commit-time practical the RacerD way: no alias analysis,
no happens-before — just thread roots, may-happen-in-parallel sides,
and per-access held-lock sets, all conservative enough to gate CI.

**The MHP model.** Every function gets a set of *sides*: the concurrent
roots whose call graph reaches it (``threading.Thread``/``Timer``
targets, hook registrations, http.server handler methods, callables
escaping into a thread-spawning class's constructor) plus ``main`` when
it is reachable from non-thread code. Two accesses may happen in
parallel when their sides contain two *distinct* roots — with two
carve-outs: hook roots (phase hooks, chaos/telemetry slot hooks) run on
the thread that fires them, so hook×main and hook×hook pairs are NOT
parallel; and a single spawned thread is not parallel with itself,
except http.server handler roots, which serve one thread per connection
and therefore are.

**The verdicts.**

* **TPM1601** (data race): a write/write or read/write pair on one
  abstract location — ``Cls.attr`` (inheritance-merged) or a module
  global — from MHP-distinct sides whose effective locksets are
  disjoint. An access's effective lockset is its lexical ``with``
  region set plus the locks held at *every* call site reaching its
  function (intersection — the Eraser discipline), so a write inside
  ``Reporter.jsonl`` knows it holds ``_jsonl_lock`` even when reached
  through a wrapper. Constructors (``__init__`` et al.) are exempt:
  they run before the object escapes.
* **TPM1602** (re-entrant self-deadlock): a call made while holding a
  non-reentrant ``threading.Lock`` whose transitive callees re-acquire
  the same lock — the exact ``attach_metrics`` observe-outside-the-lock
  shape, now enforced instead of remembered. ``RLock`` re-entry is
  clean by design.
* **TPM1603** (hook-slot rebind): a function-scope rebind of a
  module-private ALL-CAPS hook slot (``telemetry._CHAOS_SPAN_HOOK``)
  to a live callable, in a file with no matching ``= None`` disarm,
  while some reader loads the slot — the chaos arm/disarm idiom is the
  sanctioned shape (``arm()`` installs, ``disarm()`` uninstalls).

Unknown locks (an attribute of a foreign object, a lock passed as an
argument) degrade to a wildcard that is assumed to protect — a false
negative, never a false positive. Test modules are exempt end to end:
tests spawn threads to exercise these layers, they are not contract
parties.
"""

from __future__ import annotations

from typing import Iterator

from tpu_mpi_tests.analysis.core import ProjectContext, is_test_file

#: builtin-ish method names excluded from the unique-method fallback
#: resolution — `rec.get(...)`/`path.exists()` must never resolve to a
#: project class that happens to define the same name
_COMMON_METHODS = {
    "get", "items", "keys", "values", "update", "append", "pop",
    "add", "join", "split", "read", "readline", "readlines", "strip",
    "format", "copy", "setdefault", "extend", "sort", "remove",
    "clear", "close", "open", "encode", "decode", "count", "index",
    "insert", "search", "match", "group", "sub", "findall", "mkdir",
    "exists", "resolve", "unlink", "lower", "upper", "startswith",
    "endswith", "rstrip", "lstrip", "replace", "flush", "tell",
    "seek", "cancel", "start", "stop", "is_set", "set", "wait",
    "acquire", "release", "put", "send", "recv", "sum", "mean", "min",
    "max", "item", "reshape", "astype", "tolist", "touch", "rglob",
    "glob", "iterdir", "write", "main", "run", "check", "parse",
}

_INIT_METHODS = {"__init__", "__post_init__", "__new__"}

_MAX_REACH = 4000  # BFS node budget per root (runaway-graph backstop)


class _Root:
    __slots__ = ("rid", "kind", "label", "self_mhp")

    def __init__(self, rid: str, kind: str, label: str,
                 self_mhp: bool = False):
        self.rid = rid
        self.kind = kind  # "thread" | "hook"
        self.label = label
        self.self_mhp = self_mhp


def _mhp(a, b) -> bool:
    """May the two sides run in parallel? ``"main"`` or a _Root."""
    if a == "main" and b == "main":
        return False
    if a == "main" or b == "main":
        root = b if a == "main" else a
        return root.kind == "thread"  # hooks fire ON the main thread
    if a.rid == b.rid:
        return a.self_mhp  # one Timer/Thread is not parallel w/ itself
    if a.kind == "hook" and b.kind == "hook":
        return False  # two hooks still share the firing thread
    return True


class _Program:
    """The linted program's threading-plane view, built from facts."""

    def __init__(self, proj: ProjectContext):
        self.files = [ff for ff in proj.facts
                      if not is_test_file(ff["path"])
                      and "races" in ff]
        self.fn_key: dict[str, dict] = {}
        self.fn_file: dict[int, dict] = {}
        self.methods: dict[str, list[dict]] = {}  # last comp -> fns
        self.classes: dict[str, dict] = {}  # canon -> {bases, sync}
        self.lock_kind: dict[str, str] = {}
        for ff in self.files:
            mod = ff["module"]
            for cls_q, bases, sync in ff["races"]["classes"]:
                canon = f"{mod}.{cls_q}" if mod else cls_q
                self.classes[canon] = {"bases": bases, "sync": sync}
            for fn in ff["functions"]:
                if not fn.get("locks"):
                    continue
                key = f'{mod}.{fn["name"]}' if mod else fn["name"]
                self.fn_key.setdefault(key, fn)
                self.fn_file[id(fn)] = ff
                if fn["locks"].get("cls"):
                    self.methods.setdefault(
                        fn["name"].rsplit(".", 1)[-1], []
                    ).append(fn)
        for ff in self.files:
            for owner, attr, kind in ff["races"]["lock_defs"]:
                self.lock_kind[f"{self.canon_cls(owner)}::{attr}"] = kind
        self._canon_memo: dict[str, str] = {}

    # -- canonicalization ---------------------------------------------------

    def canon_cls(self, canon: str) -> str:
        """Climb to the topmost project-known ancestor so a subclass's
        ``self.phase`` and the base's are ONE abstract location."""
        seen = set()
        while canon in self.classes and canon not in seen:
            seen.add(canon)
            nxt = next((b for b in self.classes[canon]["bases"]
                        if b in self.classes), None)
            if nxt is None:
                break
            canon = nxt
        return canon

    def canon_lock(self, lid: str) -> str:
        if lid == "?" or "::" not in lid:
            return lid
        owner, attr = lid.split("::", 1)
        return f"{self.canon_cls(owner)}::{attr}"

    def sync_attrs(self, canon: str) -> set[str]:
        """Sync-object attrs merged over the (project-known) class
        chain — an Event defined by the base exempts subclass reads."""
        out: set[str] = set()
        cur, seen = canon, set()
        while cur in self.classes and cur not in seen:
            seen.add(cur)
            out.update(self.classes[cur]["sync"])
            cur = next((b for b in self.classes[cur]["bases"]
                        if b in self.classes), cur)
        return out

    # -- resolution ---------------------------------------------------------

    def resolve(self, target: str | None, module: str = "") -> list[dict]:
        if not target:
            return []
        if target.startswith("?meth:"):
            return self._unique_method(target[6:])
        fn = self.fn_key.get(target)
        if fn is not None:
            return [fn]
        if "." in target:
            owner, meth = target.rsplit(".", 1)
            # inherited method: Cls.meth defined on an ancestor
            cur, seen = owner, set()
            while cur in self.classes and cur not in seen:
                seen.add(cur)
                nxt = next((b for b in self.classes[cur]["bases"]
                            if b in self.classes), None)
                if nxt is None:
                    break
                cur = nxt
                fn = self.fn_key.get(f"{cur}.{meth}")
                if fn is not None:
                    return [fn]
            # untyped receiver (`rep.jsonl`): unique project method
            return self._unique_method(meth)
        if module:
            fn = self.fn_key.get(f"{module}.{target}")
            if fn is not None:
                return [fn]
            suffix = f".{target}"
            hits = [f for k, f in self.fn_key.items()
                    if k.startswith(module + ".") and k.endswith(suffix)]
            if len(hits) == 1:
                return hits
        return []

    def _unique_method(self, meth: str) -> list[dict]:
        if meth in _COMMON_METHODS:
            return []
        hits = self.methods.get(meth, [])
        return hits if len(hits) == 1 else []

    def module_of(self, fn: dict) -> str:
        ff = self.fn_file.get(id(fn))
        return ff["module"] if ff else ""

    def path_of(self, fn: dict) -> str:
        ff = self.fn_file.get(id(fn))
        return ff["path"] if ff else "?"


class LocksetRaces:
    name = "races"
    scope = "project"
    codes = {
        "TPM1601": "unsynchronized shared-state access from "
                   "may-happen-in-parallel threads with disjoint "
                   "locksets (data race)",
        "TPM1602": "call made while holding a non-reentrant lock "
                   "whose callees re-acquire it (self-deadlock)",
        "TPM1603": "hook-slot rebind without the arm/disarm idiom "
                   "while a reader is live",
    }

    # -- entry --------------------------------------------------------------

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        prog = _Program(proj)
        if not prog.files:
            return
        roots = self._discover_roots(prog)
        reach = self._reach(prog, roots)
        main_set = self._main_reachable(prog, reach)
        inherited = self._inherited_locks(prog, roots)
        yield from self._races(prog, roots, reach, main_set, inherited)
        yield from self._deadlocks(prog, inherited)
        yield from self._slot_rebinds(prog)

    # -- thread-entry discovery ---------------------------------------------

    def _discover_roots(
        self, prog: _Program,
    ) -> dict[str, tuple[_Root, list[dict]]]:
        """root id -> (root, entry fns)."""
        out: dict[str, tuple[_Root, list[dict]]] = {}
        seen_entries: dict[str, set[int]] = {}

        def add(rid, kind, label, entries, self_mhp=False):
            if not entries:
                return
            if rid not in out:
                out[rid] = (_Root(rid, kind, label, self_mhp), [])
                seen_entries[rid] = set()
            _root, fns = out[rid]
            ids = seen_entries[rid]
            for e in entries:
                if id(e) not in ids:
                    ids.add(id(e))
                    fns.append(e)

        threaded: dict[str, str] = {}  # class canon -> "thread"|"hook"
        for ff in prog.files:
            mod = ff["module"]
            races = ff["races"]
            for kind, ref, line in races["spawns"]:
                for fn in prog.resolve(ref, mod):
                    cls = fn["locks"].get("cls")
                    if cls:
                        owner = prog.canon_cls(
                            f"{prog.module_of(fn)}.{cls}"
                        )
                        cur = threaded.get(owner)
                        if kind == "thread" or cur is None:
                            threaded[owner] = kind
                    add(f'{ff["path"]}:{line}:{ref}', kind, ref, [fn])
            for cls_q in races["handlers"]:
                canon = f"{mod}.{cls_q}" if mod else cls_q
                threaded[prog.canon_cls(canon)] = "thread"
                entries = [
                    fn for fn in ff["functions"]
                    if fn.get("locks", {}).get("cls") == cls_q
                ]
                add(f'{ff["path"]}:handler:{cls_q}', "thread",
                    f"{cls_q} (per-connection handler)", entries,
                    self_mhp=True)
        # callables escaping into a thread-spawning class's constructor
        # run on that class's thread (the MemWatch/Heartbeat sink shape)
        for ff in prog.files:
            mod = ff["module"]
            for tgt, ref, line in ff["races"]["escapes"]:
                canon = prog.canon_cls(tgt) if tgt in prog.classes \
                    else tgt
                kind = threaded.get(canon)
                if kind is None:
                    continue
                add(f'{ff["path"]}:{line}:{ref}', kind,
                    f"{ref} (escaped into {tgt})",
                    prog.resolve(ref, mod))
        return out

    # -- reachability -------------------------------------------------------

    def _callees(self, prog: _Program, fn: dict) -> list[dict]:
        mod = prog.module_of(fn)
        out = []
        for target, _l, _c, _h in fn["locks"].get("calls", ()):
            out.extend(prog.resolve(target, mod))
        return out

    def _reach(self, prog, roots) -> dict[int, list[_Root]]:
        """fn id -> roots whose call graph reaches it."""
        reach: dict[int, list[_Root]] = {}
        for root, entries in roots.values():
            seen: set[int] = set()
            stack = list(entries)
            while stack and len(seen) < _MAX_REACH:
                fn = stack.pop()
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                tags = reach.setdefault(id(fn), [])
                if root not in tags:
                    tags.append(root)
                stack.extend(self._callees(prog, fn))
        return reach

    def _main_reachable(self, prog, reach) -> set[int]:
        """Functions reachable from non-thread code: seeded by every
        function no root reaches, closed over call edges."""
        main: set[int] = set()
        stack = []
        for ff in prog.files:
            for fn in ff["functions"]:
                if fn.get("locks") and id(fn) not in reach:
                    main.add(id(fn))
                    stack.append(fn)
        while stack:
            fn = stack.pop()
            for g in self._callees(prog, fn):
                if id(g) not in main:
                    main.add(id(g))
                    stack.append(g)
        return main

    # -- lockset inheritance ------------------------------------------------

    def _inherited_locks(self, prog, roots) -> dict[int, frozenset]:
        """Locks held at EVERY known call site of a function
        (intersection, Eraser-style), so a helper called only under a
        lock judges its accesses as protected. Thread entries and
        escaped callables are pinned to the empty set — their foreign
        call sites hold nothing we can see."""
        sites: dict[int, list[tuple[dict, frozenset]]] = {}
        for ff in prog.files:
            mod = ff["module"]
            for fn in ff["functions"]:
                if not fn.get("locks"):
                    continue
                for target, _l, _c, held in fn["locks"]["calls"]:
                    hs = frozenset(prog.canon_lock(x) for x in held)
                    for g in prog.resolve(target, mod):
                        sites.setdefault(id(g), []).append((fn, hs))
        pinned: set[int] = set()
        for _root, entries in roots.values():
            pinned.update(id(e) for e in entries)
        for ff in prog.files:
            mod = ff["module"]
            for _tgt, ref, _line in ff["races"]["escapes"]:
                pinned.update(id(g) for g in prog.resolve(ref, mod))

        TOP = None
        inh: dict[int, frozenset | None] = {}
        for ff in prog.files:
            for fn in ff["functions"]:
                if not fn.get("locks"):
                    continue
                if id(fn) in pinned or id(fn) not in sites:
                    inh[id(fn)] = frozenset()
                else:
                    inh[id(fn)] = TOP
        for _pass in range(32):
            changed = False
            for fid, val in list(inh.items()):
                if fid in pinned or fid not in sites:
                    continue
                new: frozenset | None = TOP
                for caller, hs in sites[fid]:
                    ci = inh.get(id(caller), frozenset())
                    contrib = TOP if ci is TOP else hs | ci
                    if contrib is TOP:
                        continue
                    new = contrib if new is TOP else (new & contrib)
                if new != val:
                    inh[fid] = new
                    changed = True
            if not changed:
                break
        return {fid: (v if v is not None else frozenset())
                for fid, v in inh.items()}

    # -- TPM1601 ------------------------------------------------------------

    def _races(self, prog, roots, reach, main_set,
               inherited) -> Iterator[tuple]:
        events: dict[tuple, list] = {}
        for ff in prog.files:
            mod = ff["module"]
            for fn in ff["functions"]:
                lk = fn.get("locks")
                if not lk:
                    continue
                if fn["name"].rsplit(".", 1)[-1] in _INIT_METHODS:
                    continue  # runs before the object escapes
                sides: list = list(reach.get(id(fn), ()))
                if id(fn) in main_set:
                    sides.append("main")
                if not sides:
                    continue
                for rw, owner, name, line, col, held in lk["accesses"]:
                    if owner and not owner.startswith("@"):
                        canon = prog.canon_cls(f"{mod}.{owner}")
                        if name in prog.sync_attrs(canon):
                            continue
                        loc = (canon, name)
                    elif owner.startswith("@"):
                        loc = (owner[1:], name)
                    else:
                        loc = (mod, name)
                    locks = frozenset(
                        prog.canon_lock(x) for x in held
                    ) | inherited.get(id(fn), frozenset())
                    events.setdefault(loc, []).append(
                        (rw, fn, sides, locks, line, col, ff["path"])
                    )
        for loc in sorted(events, key=lambda L: (L[0], L[1])):
            evs = events[loc]
            pair = self._racy_pair(evs)
            if pair is None:
                continue
            anchor, other = pair  # anchor is always a write
            root = next((s for s in anchor[2] if s != "main"),
                        next((s for s in other[2] if s != "main"),
                             None))
            where = "a second thread running it" if other is anchor \
                else f"'{_fn_name(other[1])}'"
            yield (
                anchor[6], anchor[4], anchor[5], "TPM1601",
                f"unsynchronized access to {loc[0]}.{loc[1]}: "
                f"'{_fn_name(anchor[1])}' "
                f"({_lockstr(anchor[3])}) races {where} "
                f"({_lockstr(other[3])}) — both run concurrently "
                f"(e.g. via {root.label if root else 'a thread root'})"
                f" with no common lock; hold one shared lock on every "
                f"access, or suppress with a why-comment if ordering "
                f"makes it benign",
            )

    @staticmethod
    def _racy_pair(evs):
        """First (write, other) MHP pair with disjoint locksets, in a
        deterministic order: UNPROTECTED writes first (the anchor is
        where the missing lock goes), thread-side as the tiebreak,
        then line order."""
        def keyfn(e):
            thread_side = any(s != "main" for s in e[2])
            return (e[0] != "w", bool(e[3]), not thread_side,
                    e[6], e[4], e[5])

        ordered = sorted(evs, key=keyfn)
        for i, e1 in enumerate(ordered):
            for e2 in ordered[i:]:
                if e1[0] != "w" and e2[0] != "w":
                    continue
                if "?" in e1[3] or "?" in e2[3]:
                    continue
                if e1[3] & e2[3]:
                    continue
                if any(
                    _mhp(a, b)
                    for a in e1[2] for b in e2[2]
                ):
                    return (e1, e2) if e1[0] == "w" else (e2, e1)
        return None

    # -- TPM1602 ------------------------------------------------------------

    def _trans_acquires(self, prog, fn, memo, stack) -> frozenset:
        out, _clean = self._trans_acquires_ex(prog, fn, memo, stack)
        return out

    def _trans_acquires_ex(self, prog, fn, memo,
                           stack) -> tuple[frozenset, bool]:
        """``(locks, clean)``: clean results (no cycle truncation
        anywhere below) are memoized; a result computed with a cut
        back-edge is complete only for the TOP of the cycle, so caching
        it for an intermediate member would bake in an order-dependent
        false negative (code-review finding)."""
        if id(fn) in memo:
            return memo[id(fn)], True
        if id(fn) in stack:
            return frozenset(), False  # back-edge: truncated here
        stack = stack | {id(fn)}
        out = {
            prog.canon_lock(lid)
            for lid, _l, _c, _h in fn["locks"].get("acquires", ())
            if lid != "?"
        }
        clean = True
        for g in self._callees(prog, fn):
            sub, sub_clean = self._trans_acquires_ex(prog, g, memo,
                                                     stack)
            out |= sub
            clean = clean and sub_clean
        result = frozenset(out)
        if clean:
            memo[id(fn)] = result
        return result, clean

    def _deadlocks(self, prog, inherited) -> Iterator[tuple]:
        memo: dict[int, frozenset] = {}
        seen: set[tuple] = set()
        for ff in prog.files:
            mod = ff["module"]
            for fn in ff["functions"]:
                lk = fn.get("locks")
                if not lk:
                    continue
                inh = inherited.get(id(fn), frozenset())
                # direct nested re-acquire: `with L:` inside `with L:`
                for lid, line, col, outer in lk["acquires"]:
                    L = prog.canon_lock(lid)
                    held = {prog.canon_lock(x) for x in outer} | inh
                    if L in held and L != "?" \
                            and prog.lock_kind.get(L) == "lock":
                        key = (ff["path"], line, L)
                        if key not in seen:
                            seen.add(key)
                            yield (ff["path"], line, col, "TPM1602",
                                   f"re-acquiring non-reentrant lock "
                                   f"{L} already held here — "
                                   f"guaranteed self-deadlock; use an "
                                   f"RLock or restructure so the lock "
                                   f"is taken once")
                for target, line, col, held in lk["calls"]:
                    hs = {prog.canon_lock(x) for x in held} | inh
                    hs.discard("?")
                    if not hs:
                        continue
                    for g in prog.resolve(target, mod):
                        re_acq = hs & self._trans_acquires(
                            prog, g, memo, frozenset()
                        )
                        for L in sorted(re_acq):
                            if prog.lock_kind.get(L) != "lock":
                                continue
                            key = (ff["path"], line, L)
                            if key in seen:
                                continue
                            seen.add(key)
                            yield (
                                ff["path"], line, col, "TPM1602",
                                f"call to '{target}' while holding "
                                f"{L}: its call graph re-acquires the "
                                f"same non-reentrant lock — "
                                f"self-deadlock (the attach_metrics "
                                f"shape); move the call outside the "
                                f"locked region or make the lock an "
                                f"RLock",
                            )

    # -- TPM1603 ------------------------------------------------------------

    def _slot_rebinds(self, prog) -> Iterator[tuple]:
        read_slots = {
            slot
            for ff in prog.files
            for slot, _line in ff["races"]["slot_reads"]
        }
        for ff in prog.files:
            writes = ff["races"]["slot_writes"]
            disarmed = {
                (mod, name)
                for mod, name, vkind, _l, _c, scope in writes
                if scope == "func" and vkind == "none"
            }
            for mod, name, vkind, line, col, scope in writes:
                if scope != "func" or vkind not in ("call", "func"):
                    continue
                if (mod, name) in disarmed:
                    continue
                if f"{mod}.{name}" not in read_slots:
                    continue
                yield (
                    ff["path"], line, col, "TPM1603",
                    f"hook slot {mod}.{name} rebound to a live "
                    f"callable with no matching `= None` disarm in "
                    f"this file — a reader thread sees the stale hook "
                    f"forever (the chaos arm()/disarm() idiom is the "
                    f"sanctioned shape: install and uninstall in the "
                    f"same layer)",
                )


def _fn_name(fn: dict) -> str:
    return fn["name"]


def _lockstr(locks: frozenset) -> str:
    if not locks:
        return "no locks held"
    short = sorted(x.split("::")[-1] if "::" in x else x
                   for x in locks)
    return "holding " + ", ".join(short)
