"""TPM5xx — mesh-axis consistency.

The bug class: collective axis names are stringly-typed; a ``lax.psum``
over an axis the enclosing ``shard_map`` never bound fails only at trace
time on a real mesh — and on a 1-device CI mesh some mismatches trace
fine and ship. The rule is same-file by design (the comm layer threads
``axis_name`` variables through, which the linter leaves alone): a
string-literal axis in a collective must appear among the axis-name
literals bound by a ``shard_map``/``Mesh``/``make_mesh``/
``PartitionSpec`` in the same file. Files with no mesh/shard_map context
are skipped — there is nothing to check against.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import (
    FileContext,
    attr_parts,
    last_attr,
)
from tpu_mpi_tests.analysis.rules import _util

#: calls whose string literals BIND axis names for the file
AXIS_DEF_CALLS = {
    "shard_map", "Mesh", "AbstractMesh", "make_mesh", "NamedSharding",
    "PartitionSpec", "P",
}

#: collective/axis-query calls checked, with the axis argument position
AXIS_USES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "ppermute": 1, "all_gather": 1, "all_to_all": 1, "pshuffle": 1,
    "pbroadcast": 1, "axis_index": 0, "axis_size": 0,
    "pcast_varying": 1, "pcast": 1,
}

#: origins whose AXIS_USES calls are real collectives (a local helper
#: coincidentally named `all_gather` is not checked)
USE_ORIGINS = ("jax", "tpu_mpi_tests.compat")


def _axis_literals(node: ast.AST) -> list[tuple[str, ast.AST]]:
    """String constants in an axis argument: ``"x"`` or ``("x", "y")``."""
    out: list[tuple[str, ast.AST]] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append((node.value, node))
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, str
            ):
                out.append((elt.value, elt))
    return out


class AxisConsistency:
    name = "axis-consistency"
    scope = "file"
    codes = {
        "TPM501": "collective axis name not bound by any shard_map/mesh "
                  "in this file",
    }

    def check(self, ctx: FileContext) -> Iterator[tuple]:
        bound: set[str] = set()
        for call in _util.walk_calls(ctx.tree):
            if last_attr(call.func) in AXIS_DEF_CALLS:
                for n in ast.walk(call):
                    if isinstance(n, ast.Constant) and isinstance(
                        n.value, str
                    ):
                        bound.add(n.value)
            # axis_name= kwargs bind too: compiled-fn factories take the
            # axis they will shard_map over (e.g. iterate_pallas_fn)
            for kw in call.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    bound.update(a for a, _ in _axis_literals(kw.value))
        if not bound:
            return

        for call in _util.walk_calls(ctx.tree):
            name = last_attr(call.func)
            if name not in AXIS_USES:
                continue
            chain = attr_parts(call.func)
            if not chain:
                continue
            origin = ctx.imports.origin(chain[0]) or ""
            if not origin.startswith(USE_ORIGINS):
                continue
            axis_arg = None
            pos = AXIS_USES[name]
            if len(call.args) > pos:
                axis_arg = call.args[pos]
            else:
                for kw in call.keywords:
                    if kw.arg == "axis_name":
                        axis_arg = kw.value
            if axis_arg is None:
                continue
            for axis, node in _axis_literals(axis_arg):
                if axis not in bound:
                    known = ", ".join(sorted(bound))
                    yield (
                        node.lineno, node.col_offset, "TPM501",
                        f"axis '{axis}' in {name}() is not bound by any "
                        f"shard_map/mesh in this file (bound here: "
                        f"{known}) — a mismatched axis fails only at "
                        f"trace time on a real mesh",
                    )
