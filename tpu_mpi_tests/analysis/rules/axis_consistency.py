"""TPM5xx — mesh-axis consistency.

The bug class: collective axis names are stringly-typed; a ``lax.psum``
over an axis the enclosing ``shard_map`` never bound fails only at trace
time on a real mesh — and on a 1-device CI mesh some mismatches trace
fine and ship.

* **TPM501** (file scope): a string-literal axis in a collective must
  appear among the axis-name literals bound by a ``shard_map``/``Mesh``/
  ``make_mesh``/``PartitionSpec`` in the same file. Files with no local
  mesh context are left to —
* **TPM502** (project scope, ISSUE 10): the same check for files the
  per-file rule used to skip entirely, resolved against the axis
  literals bound *anywhere in the linted program* (the facts carry each
  file's binding set). A helper module whose ``psum`` axis is bound by
  the driver that imports it now lints clean; an axis bound nowhere in
  the program is now a finding instead of a silent skip.

The axis vocabulary lives in :mod:`tpu_mpi_tests.analysis.program`
(``AXIS_DEF_CALLS``/``AXIS_USES``/``USE_ORIGINS``) so the facts
extractor and this rule read one definition.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis.core import (
    FileContext,
    ProjectContext,
    attr_parts,
    last_attr,
)
from tpu_mpi_tests.analysis.program import (
    AXIS_DEF_CALLS,
    AXIS_USES,
    USE_ORIGINS,
)
from tpu_mpi_tests.analysis.rules import _util


def _axis_literals(node: ast.AST) -> list[tuple[str, ast.AST]]:
    """String constants in an axis argument: ``"x"`` or ``("x", "y")``."""
    out: list[tuple[str, ast.AST]] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append((node.value, node))
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, str
            ):
                out.append((elt.value, elt))
    return out


class AxisConsistency:
    name = "axis-consistency"
    scope = "file"
    codes = {
        "TPM501": "collective axis name not bound by any shard_map/mesh "
                  "in this file",
    }

    def check(self, ctx: FileContext) -> Iterator[tuple]:
        bound: set[str] = set()
        for call in _util.walk_calls(ctx.tree):
            if last_attr(call.func) in AXIS_DEF_CALLS:
                for n in ast.walk(call):
                    if isinstance(n, ast.Constant) and isinstance(
                        n.value, str
                    ):
                        bound.add(n.value)
            # axis_name= kwargs bind too: compiled-fn factories take the
            # axis they will shard_map over (e.g. iterate_pallas_fn)
            for kw in call.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    bound.update(a for a, _ in _axis_literals(kw.value))
        if not bound:
            return

        for call in _util.walk_calls(ctx.tree):
            name = last_attr(call.func)
            if name not in AXIS_USES:
                continue
            chain = attr_parts(call.func)
            if not chain:
                continue
            origin = ctx.imports.origin(chain[0]) or ""
            if not origin.startswith(USE_ORIGINS):
                continue
            axis_arg = None
            pos = AXIS_USES[name]
            if len(call.args) > pos:
                axis_arg = call.args[pos]
            else:
                for kw in call.keywords:
                    if kw.arg == "axis_name":
                        axis_arg = kw.value
            if axis_arg is None:
                continue
            for axis, node in _axis_literals(axis_arg):
                if axis not in bound:
                    known = ", ".join(sorted(bound))
                    yield (
                        node.lineno, node.col_offset, "TPM501",
                        f"axis '{axis}' in {name}() is not bound by any "
                        f"shard_map/mesh in this file (bound here: "
                        f"{known}) — a mismatched axis fails only at "
                        f"trace time on a real mesh",
                    )


class AxisProgramConsistency:
    name = "axis-consistency-program"
    scope = "project"
    codes = {
        "TPM502": "collective axis name not bound by any shard_map/mesh "
                  "anywhere in the linted program (file has no local "
                  "mesh context)",
    }

    def check_project(self, proj: ProjectContext) -> Iterator[tuple]:
        bound: set[str] = set()
        for ff in proj.facts:
            bound.update(ff["axis_bound"])
        for ff in proj.facts:
            if ff["axis_bound"]:
                continue  # TPM501's same-file jurisdiction
            for line, col, op, axis in ff["axis_uses"]:
                if axis in bound:
                    continue
                yield (
                    ff["path"], line, col, "TPM502",
                    f"axis '{axis}' in {op}() is not bound by any "
                    f"shard_map/mesh anywhere in the linted program "
                    f"({len(bound)} program-wide binding"
                    f"{'s' if len(bound) != 1 else ''}) — this file has "
                    f"no mesh context of its own, so the per-file rule "
                    f"used to skip it; a mismatched axis fails only at "
                    f"trace time on a real mesh",
                )
