"""Threading-plane fact extraction for the TPM16xx lockset race
analysis (ISSUE 13 tentpole).

This module turns one parsed file into the JSON-serializable raw
material of a classic lockset race detector (Eraser, Savage et al.
1997, made commit-time practical by RacerD, Blackshear et al. 2018):

* **thread-entry discovery** — callables escaping into
  ``threading.Thread(target=...)`` / ``threading.Timer(..., f)``,
  ``timers.add_phase_hook(...)`` registrations, hook-slot rebinds
  (``telemetry._CHAOS_SPAN_HOOK = ...``), ``http.server`` handler
  classes, and callables escaping into the constructor of a
  thread-spawning class (the ``MemWatch(sink=lambda rec:
  rep.jsonl(...))`` wiring shape);
* **lockset computation** — ``with self._lock:`` / ``with _LOCK:``
  regions resolved over the per-function CFG's
  :class:`~tpu_mpi_tests.analysis.cfg.WithRegion` blocks, giving every
  statement (and therefore every access event and outgoing call) its
  lexically held-lock set; caller-side propagation (a helper called
  only under a lock inherits it) happens at project scope
  (``rules/races.py``) over the per-function summaries built here;
* **shared-state access events** — ``self.<attr>`` loads/stores (plus
  mutator-method calls through the attribute: ``self._f.write(...)``
  mutates the handle), module-global mutations, and cross-module
  attribute stores, each stamped with the held locks.

Everything here is *per file* and name-based. Known blind spots
(documented in README "Static analysis"): dynamic dispatch, locks
passed as arguments (they degrade to a ``"?"`` wildcard that is assumed
to protect — false negatives, never false positives), ``getattr``
dispatch, and cross-process state.

The old lexical TPM601 heuristic lives here too
(:func:`lexical_tpm601`): its findings are recorded as facts and
emitted by the project rule only for files where thread-entry
discovery resolved nothing — the single-file fallback of ISSUE 13.

Stdlib-only by contract, like the rest of the analysis package. Must
not import the rule registry (facts extraction is cache-side).
"""

from __future__ import annotations

import ast
import re
from typing import Callable, Iterator

from tpu_mpi_tests.analysis import cfg as cfg_mod
from tpu_mpi_tests.analysis.core import (
    FileContext,
    attr_parts,
    last_attr,
    own_nodes as _own_nodes,
)

# ---------------------------------------------------------------------------
# vocabularies

#: thread spawn points: the callable argument runs on a new thread
THREAD_SPAWNS = {"threading.Thread", "threading.Timer"}

#: lock factories and the lock *kind* TPM1602 needs (re-acquiring a
#: plain Lock self-deadlocks; an RLock re-enters by design)
LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "lock",
    "threading.Semaphore": "lock",
    "threading.BoundedSemaphore": "lock",
}

#: attributes assigned from these are synchronization/thread-safe
#: objects — their own method calls are internally serialized (Event,
#: Queue) or GIL-atomic by design (deque append/popleft), so they are
#: not shared-state access events
SYNC_FACTORIES = {
    "threading.Event", "queue.Queue", "queue.SimpleQueue",
    "queue.LifoQueue", "collections.deque", "deque",
}

#: http.server-style handler base classes: each request gets its own
#: thread, so every method of a subclass is a concurrent root — and a
#: SELF-concurrent one (many requests in flight at once)
HANDLER_BASES = {
    "http.server.BaseHTTPRequestHandler",
    "http.server.SimpleHTTPRequestHandler",
    "socketserver.BaseRequestHandler",
    "socketserver.StreamRequestHandler",
}

#: registrar call names whose argument becomes a hook root (invoked
#: from foreign frames — concurrent with real threads, though the
#: repo's phase hooks themselves fire on the thread running the phase)
HOOK_REGISTRARS = {"add_phase_hook"}

#: method calls through an attribute that MUTATE the receiver — the
#: ``self._f.write(...)`` access is a write on the ``_f`` slot's object
MUTATORS = {
    "write", "writelines", "flush", "close", "append", "appendleft",
    "extend", "add", "update", "pop", "popitem", "popleft", "remove",
    "discard", "clear", "insert", "setdefault", "sort", "reverse",
    "put", "put_nowait",
}

#: module-private ALL-CAPS rebind slots (the chaos/telemetry hook-slot
#: idiom): writes are judged by TPM1603's arm/disarm check, and their
#: reads/writes are excluded from the TPM1601 event stream so one
#: hazard carries one code
_SLOT_RE = re.compile(r"^_[A-Z][A-Z0-9_]*$")
_SLOT_WORDS = ("HOOK", "PROVIDER", "FLOOD", "EMIT", "SLOT", "CALLBACK")


def is_hook_slot(name: str) -> bool:
    return bool(_SLOT_RE.match(name)) and any(
        w in name for w in _SLOT_WORDS
    )


def _lockish(name: str) -> bool:
    return "lock" in name.lower() or name.lower() in ("mutex",)


# ---------------------------------------------------------------------------
# small walkers (own scope: nested def/lambda bodies excluded)


def _unit_nodes(unit: ast.AST) -> Iterator[ast.AST]:
    yield unit
    yield from _own_nodes(unit)


def _walk_classes(tree: ast.Module) -> list[tuple[str, ast.ClassDef]]:
    """``(qualname, node)`` for every class, nested ones under their
    enclosing def/class prefixes — mirrors ``program._walk_functions``
    so method quals and class quals line up."""
    out: list[tuple[str, ast.ClassDef]] = []

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                q = f"{prefix}{child.name}"
                out.append((q, child))
                visit(child, q + ".")
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


# ---------------------------------------------------------------------------
# the extractor


class _RaceFacts:
    """One file's threading-plane facts, built in two passes: a class/
    module survey, then a per-function walk that stamps lock contexts
    on calls and access events."""

    def __init__(self, ctx: FileContext,
                 functions: list[tuple[str, ast.AST, str]],
                 graphs: dict[int, cfg_mod.CFG],
                 resolve: Callable[[ast.AST], str | None]):
        self.ctx = ctx
        self.functions = functions
        self.graphs = graphs
        self.resolve = resolve
        self.module = ctx.module

        #: cls qual -> {"bases", "methods", "data", "sync"}
        self.classes: dict[str, dict] = {}
        self.lock_defs: list[list] = []      # [owner, attr, kind]
        self.spawns: list[list] = []         # [kind, ref|None, line]
        self.handlers: list[str] = []        # handler class quals
        self.escapes: list[list] = []        # [call_target, ref, line]
        self.slot_writes: list[list] = []    # [mod, name, vkind, line,
        #                                       col, scope]
        self.slot_reads: list[list] = []     # [f"{mod}.{name}", line]
        #: keyed by node identity, NOT qualname — try/except and
        #: platform-variant files legitimately define the same qual
        #: twice, and each def keeps its own lock summary
        self.fn_locks: dict[int, dict] = {}

        self._survey_classes()
        self._survey_module()
        self._survey_globals()
        for qual, node, cls in functions:
            env = dict(self.module_env)
            env.update(self._type_env(_own_nodes(node)))
            self._scan_spawn_sites(node, cls, env)
            self.fn_locks[id(node)] = self._function_locks(
                qual, node, cls, env
            )
        self._scan_spawn_sites(self.ctx.tree, "", self.module_env,
                               module_level=True)

    # -- pass 1: classes / module ------------------------------------------

    def _survey_classes(self) -> None:
        all_classes = _walk_classes(self.ctx.tree)
        for qual, node in all_classes:
            bases: list[str] = []
            for b in node.bases:
                parts = attr_parts(b)
                if not parts:
                    continue
                origin = self.ctx.imports.origin(parts[0])
                if origin:
                    bases.append(".".join([origin] + parts[1:]))
                else:
                    # same-file base (possibly nested): prefer the
                    # defined class with that final name
                    local = [q for q, _n in all_classes
                             if q.rsplit(".", 1)[-1] == parts[-1]]
                    bases.append(
                        f"{self.module}.{local[0]}" if local
                        else ".".join(parts)
                    )
            self.classes[qual] = {
                "bases": bases, "methods": set(), "data": set(),
                "sync": set(),
            }
            if any(b in HANDLER_BASES for b in bases):
                self.handlers.append(qual)
        for qual, _node, cls in self.functions:
            if cls and cls in self.classes \
                    and qual.rsplit(".", 1)[0] == cls:
                self.classes[cls]["methods"].add(
                    qual.rsplit(".", 1)[-1]
                )
        # attribute survey: stores, lock/sync factory assignments
        for qual, node, cls in self.functions:
            if not cls or cls not in self.classes:
                continue
            info = self.classes[cls]
            for n in _own_nodes(node):
                targets: list[ast.AST] = []
                value = None
                if isinstance(n, ast.Assign):
                    targets, value = list(n.targets), n.value
                elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
                    targets, value = [n.target], n.value
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    info["data"].add(t.attr)
                    canon = self.resolve(value.func) if isinstance(
                        value, ast.Call
                    ) else None
                    if canon in LOCK_FACTORIES:
                        self.lock_defs.append([
                            f"{self.module}.{cls}", t.attr,
                            LOCK_FACTORIES[canon],
                        ])
                    elif canon in SYNC_FACTORIES:
                        info["sync"].add(t.attr)

    def _survey_module(self) -> None:
        self.module_assigned: set[str] = set()
        self.module_locks: dict[str, str] = {}  # name -> kind
        self.module_env: dict[str, str] = self._type_env(
            _own_nodes(self.ctx.tree)
        )
        for n in self.ctx.tree.body:
            targets: list[ast.AST] = []
            value = None
            if isinstance(n, ast.Assign):
                targets, value = list(n.targets), n.value
            elif isinstance(n, ast.AnnAssign):
                targets, value = [n.target], n.value
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                self.module_assigned.add(t.id)
                canon = self.resolve(value.func) if isinstance(
                    value, ast.Call
                ) else None
                if canon in LOCK_FACTORIES:
                    kind = LOCK_FACTORIES[canon]
                    self.module_locks[t.id] = kind
                    # exported like the class locks, so TPM1602 can
                    # tell a module-scope Lock from an RLock
                    self.lock_defs.append([self.module, t.id, kind])
                if is_hook_slot(t.id):
                    self.slot_writes.append([
                        self.module, t.id, self._value_kind(value),
                        n.lineno, n.col_offset, "module",
                    ])

    def _survey_globals(self) -> None:
        """Names any function in the file mutates at module scope —
        the candidates whose reads become access events."""
        self.glob_written: set[str] = set()
        for _qual, node, _cls in self.functions:
            for n in _own_nodes(node):
                if isinstance(n, ast.Global):
                    self.glob_written.update(
                        x for x in n.names
                        if not is_hook_slot(x)
                    )
                elif isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute
                ) and n.func.attr in MUTATORS and isinstance(
                    n.func.value, ast.Name
                ) and n.func.value.id in self.module_assigned \
                        and not is_hook_slot(n.func.value.id):
                    self.glob_written.add(n.func.value.id)

    # -- helpers ------------------------------------------------------------

    def _type_env(self, nodes) -> dict[str, str]:
        """``x = ClassName(...)`` assignments: local-name → constructed
        class canon, so ``x.meth()`` calls and ``x.meth`` escapes
        resolve without a project-wide name hunt."""
        env: dict[str, str] = {}
        for n in nodes:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and isinstance(n.value, ast.Call):
                canon = self.resolve(n.value.func)
                if canon and canon.rsplit(".", 1)[-1][:1].isupper():
                    env[n.targets[0].id] = canon
        return env

    def _value_kind(self, value: ast.AST | None) -> str:
        if value is None:
            return "other"
        if isinstance(value, ast.Constant) and value.value is None:
            return "none"
        if isinstance(value, (ast.Call, ast.Lambda)):
            return "call"
        if isinstance(value, ast.Name) and self._local_def(value.id):
            return "func"
        return "other"

    def _local_def(self, name: str) -> str | None:
        """Same-file def whose final qual component is ``name`` (the
        deepest/first match) — how a bare ``sink`` argument resolves to
        the nested ``_arm_metrics.sink`` closure."""
        matches = [q for q, _n, _c in self.functions
                   if q.rsplit(".", 1)[-1] == name]
        return f"{self.module}.{matches[0]}" if matches else None

    def _call_target(self, func: ast.AST, cls: str,
                     env: dict[str, str]) -> str | None:
        parts = attr_parts(func)
        if not parts:
            return None
        if parts[0] == "self":
            if len(parts) == 2 and cls:
                return f"{self.module}.{cls}.{parts[1]}"
            return None
        if len(parts) == 2 and parts[0] in env:
            return f"{env[parts[0]]}.{parts[1]}"
        return self.resolve(func)

    def _callable_ref(self, v: ast.AST, cls: str,
                      env: dict[str, str]) -> list[str]:
        """Thread-target / escaped-callable references an argument can
        carry: a bound method, a local function, or (for a lambda) the
        targets its body calls."""
        if isinstance(v, ast.Lambda):
            out = []
            for n in ast.walk(v.body):
                if isinstance(n, ast.Call):
                    t = self._call_target(n.func, cls, env)
                    if t is None and isinstance(n.func, ast.Attribute):
                        t = f"?meth:{n.func.attr}"
                    if t:
                        out.append(t)
            return out
        parts = attr_parts(v)
        if parts and len(parts) == 2:
            if parts[0] == "self" and cls:
                return [f"{self.module}.{cls}.{parts[1]}"]
            if parts[0] in env:
                return [f"{env[parts[0]]}.{parts[1]}"]
            origin = self.ctx.imports.origin(parts[0])
            if origin:
                return [f"{origin}.{parts[1]}"]
            return [f"?meth:{parts[1]}"]
        if isinstance(v, ast.Name):
            local = self._local_def(v.id)
            if local:
                return [local]
        return []

    def _module_alias(self, name: str) -> str | None:
        """Local name → module canon, when the name IS a module (plain
        import alias, or a from-import of a submodule)."""
        if name in self.ctx.imports.modules:
            return self.ctx.imports.modules[name]
        if name in self.ctx.imports.names:
            mod, orig = self.ctx.imports.names[name]
            # `from pkg import mod as alias`: heuristically a module
            # when the original is lowercase (classes are CapWords,
            # functions rarely get rebound attributes)
            if orig[:1].islower():
                return f"{mod}.{orig}" if mod else orig
        return None

    # -- spawn / escape / slot discovery ------------------------------------

    def _scan_spawn_sites(self, root: ast.AST, cls: str,
                          env: dict[str, str],
                          module_level: bool = False) -> None:
        for n in _own_nodes(root):
            if isinstance(n, ast.Call):
                self._scan_call(n, cls, env)
            elif isinstance(n, ast.Assign):
                self._scan_assign_slots(n, cls,
                                        module_level=module_level)
            elif isinstance(n, ast.Attribute) and isinstance(
                n.ctx, ast.Load
            ) and isinstance(n.value, ast.Name):
                mod = self._module_alias(n.value.id)
                if mod and is_hook_slot(n.attr):
                    self.slot_reads.append([f"{mod}.{n.attr}",
                                            n.lineno])
            elif isinstance(n, ast.Name) and isinstance(
                n.ctx, ast.Load
            ) and is_hook_slot(n.id) and n.id in self.module_assigned:
                self.slot_reads.append([f"{self.module}.{n.id}",
                                        n.lineno])

    def _scan_call(self, n: ast.Call, cls: str,
                   env: dict[str, str]) -> None:
        canon = self.resolve(n.func) or ""
        # thread/timer spawns
        if canon in THREAD_SPAWNS:
            target = None
            if canon.endswith("Timer"):
                if len(n.args) > 1:
                    target = n.args[1]
            for kw in n.keywords:
                if kw.arg in ("target", "function"):
                    target = kw.value
            refs = self._callable_ref(target, cls, env) \
                if target is not None else []
            if refs:
                for r in refs:
                    self.spawns.append(["thread", r, n.lineno])
            else:
                self.spawns.append(["thread", None, n.lineno])
            return
        # hook registrations
        if (last_attr(n.func) or "") in HOOK_REGISTRARS and n.args:
            arg = n.args[0]
            if isinstance(arg, ast.Name) and arg.id == "self" and cls:
                refs = [f"{self.module}.{cls}.__call__"]
            else:
                refs = self._callable_ref(arg, cls, env)
            for r in refs or [None]:
                self.spawns.append(["hook", r, n.lineno])
            return
        # callable escapes into an arbitrary call (judged at project
        # scope: only calls landing in thread-spawning classes matter)
        tgt = self._call_target(n.func, cls, env)
        if not tgt:
            return
        for v in list(n.args) + [kw.value for kw in n.keywords]:
            for r in self._callable_ref(v, cls, env):
                self.escapes.append([tgt, r, n.lineno])

    def _scan_assign_slots(self, n: ast.Assign, cls: str,
                           module_level: bool = False) -> None:
        for t in n.targets:
            if isinstance(t, ast.Attribute) and isinstance(
                t.value, ast.Name
            ):
                mod = self._module_alias(t.value.id)
                if mod and is_hook_slot(t.attr):
                    # an import-time install is a declaration-shaped
                    # initializer, not the arm-time rebind TPM1603
                    # judges — record it as module scope
                    self.slot_writes.append([
                        mod, t.attr, self._value_kind(n.value),
                        n.lineno, n.col_offset,
                        "module" if module_level else "func",
                    ])
            elif isinstance(t, ast.Name) and is_hook_slot(t.id) \
                    and t.id in self.module_assigned and not cls \
                    and not module_level:
                # function-scope rebind of the module's own slot
                # (reached via a `global` declaration); module-scope
                # initializers were already recorded by _survey_module
                # as scope "module" — the slot's declaration, not a
                # rebind
                self.slot_writes.append([
                    self.module, t.id, self._value_kind(n.value),
                    n.lineno, n.col_offset, "func",
                ])

    # -- per-function lock facts --------------------------------------------

    def _lock_id(self, expr: ast.AST, cls: str, qual: str,
                 local_locks: set[str]) -> str | None:
        parts = attr_parts(expr)
        if not parts:
            return None
        if parts[0] == "self" and len(parts) == 2 and cls:
            attr = parts[1]
            known = any(
                o == f"{self.module}.{cls}" and a == attr
                for o, a, _k in self.lock_defs
            )
            if known or _lockish(attr):
                return f"{self.module}.{cls}::{attr}"
            return None
        if len(parts) == 1:
            name = parts[0]
            if name in self.module_locks:
                return f"{self.module}::{name}"
            if name in local_locks:
                return f"{self.module}.{qual}::{name}"
            if _lockish(name):
                return "?"
            return None
        # deeper chains / foreign receivers: a lock we cannot name —
        # the wildcard is assumed to protect (FN over FP)
        return "?" if _lockish(parts[-1]) else None

    def _function_locks(self, qual: str, node: ast.AST, cls: str,
                        env: dict[str, str]) -> dict:
        graph = self.graphs.get(id(node)) or cfg_mod.build(node)
        local_locks: set[str] = set()
        for n in _own_nodes(node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and isinstance(n.value, ast.Call):
                canon = self.resolve(n.value.func)
                if canon in LOCK_FACTORIES:
                    name = n.targets[0].id
                    local_locks.add(name)
                    self.lock_defs.append([
                        f"{self.module}.{qual}", name,
                        LOCK_FACTORIES[canon],
                    ])
        glob_decls: set[str] = set()
        local_names: set[str] = set()
        for n in _own_nodes(node):
            if isinstance(n, ast.Global):
                glob_decls.update(n.names)
            elif isinstance(n, ast.Name) and isinstance(
                n.ctx, ast.Store
            ):
                local_names.add(n.id)
        a = node.args if hasattr(node, "args") else None
        if a is not None:
            local_names.update(p.arg for p in (
                a.posonlyargs + a.args + a.kwonlyargs
            ))
            for va in (a.vararg, a.kwarg):
                if va is not None:
                    local_names.add(va.arg)
        # a name assigned locally (no `global`) shadows the module
        # global — its loads are local reads, not shared-state events
        local_names -= glob_decls

        held_by_block: dict[int, set[str]] = {}
        regions: list[tuple[cfg_mod.WithRegion, list[str]]] = []
        for region in graph.with_regions:
            ids = []
            for item in region.node.items:
                lid = self._lock_id(item.context_expr, cls, qual,
                                    local_locks)
                if lid:
                    ids.append(lid)
            if not ids:
                continue
            regions.append((region, ids))
            for b in region.blocks:
                held_by_block.setdefault(b, set()).update(ids)

        acquires: list[list] = []
        for region, ids in regions:
            outer: set[str] = set()
            for other, oids in regions:
                if other is region:
                    continue
                if region.blocks < other.blocks:
                    outer.update(oids)
            for lid in ids:
                acquires.append([lid, region.node.lineno,
                                 region.node.col_offset,
                                 sorted(outer)])

        accesses: list[list] = []
        calls: list[list] = []
        for block in graph.blocks:
            held = sorted(held_by_block.get(block.idx, ()))
            for unit in block.units:
                self._scan_unit(unit, cls, qual, env, held,
                                glob_decls, local_names, accesses,
                                calls)
        return {
            "cls": cls,
            "acquires": acquires,
            "calls": calls,
            "accesses": accesses,
        }

    def _scan_unit(self, unit: ast.AST, cls: str, qual: str,
                   env: dict[str, str], held: list[str],
                   glob_decls: set[str], local_names: set[str],
                   accesses: list[list], calls: list[list]) -> None:
        nodes = list(_unit_nodes(unit))
        skip: set[int] = set()   # attribute nodes consumed by calls
        write_ids: set[int] = set()

        info = self.classes.get(cls, {"methods": set(), "data": set(),
                                      "sync": set()})

        def is_self_attr(x) -> bool:
            return (cls and isinstance(x, ast.Attribute)
                    and isinstance(x.value, ast.Name)
                    and x.value.id == "self")

        for n in nodes:
            if isinstance(n, ast.Call):
                tgt = self._call_target(n.func, cls, env)
                if tgt:
                    calls.append([tgt, n.lineno, n.col_offset, held])
                f = n.func
                if isinstance(f, ast.Attribute):
                    recv = f.value
                    if is_self_attr(f) and f.attr in info["methods"] \
                            and f.attr not in info["data"]:
                        skip.add(id(f))  # self.meth(...): a call edge
                    if is_self_attr(recv) and f.attr in MUTATORS:
                        write_ids.add(id(recv))
                    elif isinstance(recv, ast.Name) \
                            and f.attr in MUTATORS \
                            and recv.id in self.glob_written:
                        accesses.append(["w", "", recv.id, n.lineno,
                                         n.col_offset, held])
            elif isinstance(n, ast.Subscript) and isinstance(
                n.ctx, ast.Store
            ) and is_self_attr(n.value):
                write_ids.add(id(n.value))

        for n in nodes:
            if is_self_attr(n) and id(n) not in skip:
                attr = n.attr
                if attr in info["sync"]:
                    continue
                if attr in info["methods"] and attr not in info["data"]:
                    continue  # a method reference, not shared data
                if isinstance(n.ctx, (ast.Store, ast.Del)) \
                        or id(n) in write_ids:
                    rw = "w"
                else:
                    rw = "r"
                accesses.append([rw, cls, attr, n.lineno,
                                 n.col_offset, held])
            elif isinstance(n, ast.Name):
                if is_hook_slot(n.id):
                    continue  # TPM1603's domain
                if isinstance(n.ctx, ast.Store) and n.id in glob_decls:
                    accesses.append(["w", "", n.id, n.lineno,
                                     n.col_offset, held])
                elif isinstance(n.ctx, ast.Load) \
                        and n.id in self.glob_written \
                        and n.id not in local_names:
                    accesses.append(["r", "", n.id, n.lineno,
                                     n.col_offset, held])
            elif isinstance(n, ast.Attribute) and isinstance(
                n.ctx, ast.Store
            ) and isinstance(n.value, ast.Name):
                mod = self._module_alias(n.value.id)
                if mod and not is_hook_slot(n.attr):
                    accesses.append(["w", "@" + mod, n.attr, n.lineno,
                                     n.col_offset, held])

    # -- output -------------------------------------------------------------

    def file_facts(self) -> dict:
        return {
            "classes": sorted(
                [q, sorted(i["bases"]), sorted(i["sync"])]
                for q, i in self.classes.items()
            ),
            "lock_defs": sorted(self.lock_defs),
            "spawns": sorted(self.spawns,
                             key=lambda s: (s[2], s[0], s[1] or "")),
            "handlers": sorted(self.handlers),
            "escapes": sorted(self.escapes),
            "slot_writes": sorted(self.slot_writes,
                                  key=lambda s: (s[3], s[4])),
            "slot_reads": sorted(self.slot_reads),
            "tpm601": lexical_tpm601(self.ctx),
        }


def extract_race_facts(
    ctx: FileContext,
    functions: list[tuple[str, ast.AST, str]],
    graphs: dict[int, cfg_mod.CFG],
    resolve: Callable[[ast.AST], str | None],
) -> tuple[dict, dict[int, dict]]:
    """``(file_facts, per-function lock facts keyed by ``id(node)``)``
    for one parsed file."""
    rf = _RaceFacts(ctx, functions, graphs, resolve)
    return rf.file_facts(), rf.fn_locks


# ---------------------------------------------------------------------------
# the demoted lexical TPM601 heuristic (PR-3), now a fact: emitted by
# the project concurrency rule ONLY for files where thread-entry
# discovery resolved nothing (no spawn target, no handler class) — the
# whole-program TPM1601 machinery owns every file it can model


_TPM601_EXEMPT_PARTS = {"stdout", "stderr", "stream", "sys"}


def _dotted(node: ast.AST) -> str | None:
    parts = attr_parts(node)
    return ".".join(parts) if parts else None


def _is_lockish_expr(expr: ast.AST, locks: set[str]) -> bool:
    name = _dotted(expr)
    if not name:
        return False
    last = name.rsplit(".", 1)[-1].lower()
    return name in locks or "lock" in last


def _own_stmt_calls(stmt):
    """Calls in the statement's header/expressions, excluding nested
    statement bodies (those get their own lock context)."""
    nested: set[int] = set()
    for field in ("body", "orelse", "finalbody"):
        for sub in getattr(stmt, field, None) or ():
            for n in ast.walk(sub):
                nested.add(id(n))
    for h in getattr(stmt, "handlers", ()):
        for sub in h.body:
            for n in ast.walk(sub):
                nested.add(id(n))
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call) and id(n) not in nested:
            yield n


def _tpm601_walk(stmts, locks, open_names, held) -> Iterator[list]:
    for stmt in stmts:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner_held = held or any(
                _is_lockish_expr(item.context_expr, locks)
                for item in stmt.items
            )
            yield from _tpm601_walk(stmt.body, locks, open_names,
                                    inner_held)
            continue
        for call in _own_stmt_calls(stmt):
            yield from _tpm601_check_write(call, open_names, held)
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, list):
                yield from _tpm601_walk(sub, locks, open_names, held)
        for h in getattr(stmt, "handlers", ()):
            yield from _tpm601_walk(h.body, locks, open_names, held)


def _tpm601_check_write(call, open_names, held) -> Iterator[list]:
    func = call.func
    if not (isinstance(func, ast.Attribute) and func.attr == "write"):
        return
    recv = func.value
    parts = attr_parts(recv)
    if parts and (parts[0] == "sys"
                  or any(p in _TPM601_EXEMPT_PARTS for p in parts)):
        return
    shared = isinstance(recv, ast.Attribute) or (
        isinstance(recv, ast.Name) and recv.id in open_names
    )
    if shared and not held:
        name = ".".join(parts) if parts else "<handle>"
        yield [
            call.lineno, call.col_offset,
            f"'{name}.write()' in a module that arms a "
            f"threading.Timer/Thread — concurrent writes interleave "
            f"records (the watchdog JSONL bug class); serialize one "
            f"write per record under `with <lock>:`",
        ]


def lexical_tpm601(ctx: FileContext) -> list[list]:
    """The PR-3 heuristic verbatim: ``.write()`` on a shared-looking
    handle, in a file that arms a Timer/Thread, outside ``with
    <lock>:``. Returns ``[line, col, message]`` rows."""
    spawns = False
    locks: set[str] = set()
    open_names: set[str] = set()
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call):
            resolved = ctx.imports.resolve(n.func) or ""
            if resolved in THREAD_SPAWNS:
                spawns = True
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            resolved = ctx.imports.resolve(n.value.func) or ""
            for t in n.targets:
                name = _dotted(t)
                if not name:
                    continue
                if resolved in LOCK_FACTORIES:
                    locks.add(name)
                elif resolved in ("open", "io.open"):
                    open_names.add(name)
    if not spawns:
        return []
    return list(_tpm601_walk(ctx.tree.body, locks, open_names,
                             held=False))
