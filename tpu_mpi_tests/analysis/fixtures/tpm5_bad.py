"""TPM501 bad: psum over an axis the file's shard_map never binds."""

from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_mpi_tests.compat import shard_map


def total(mesh, x):
    def body(v):
        return lax.psum(v, "ring")

    return shard_map(
        body, mesh=mesh, in_specs=P("shard"), out_specs=P()
    )(x)
