"""TPM1102 good: the collective runs on every rank BEFORE the
rank-guarded exit — both paths dispatch the same collective sequence,
so the early return only shapes what each rank does with the already-
reduced value."""

from tpu_mpi_tests.comm.collectives import allreduce_sum


def global_mean(x, mesh, rank, world):
    total = allreduce_sum(x, mesh)
    if rank != 0:
        return total
    return total / world
