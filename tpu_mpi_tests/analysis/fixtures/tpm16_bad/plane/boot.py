"""The thread entry lives here, a file away from the hazard: the
whole-program pass must see ``r.poll`` escape into the Timer and tag
``Recorder.poll`` (and everything it calls) as a concurrent root."""

import threading

from plane.recorder import Recorder


def launch(path):
    r = Recorder(path)
    threading.Timer(1.0, r.poll).start()
    return r
