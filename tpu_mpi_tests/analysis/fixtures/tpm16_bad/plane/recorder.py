"""TPM1601 bad: ``record`` writes the handle under the lock, but the
Timer thread (armed cross-file in ``boot.py``) reaches the same write
through ``poll`` with NO lock — the caller-lockset intersection is
empty, so the shared write is unprotected (the watchdog JSONL
interleave shape, one helper down)."""

import threading


class Recorder:
    def __init__(self, path):
        self._f = open(path, "a")
        self._lock = threading.Lock()

    def record(self, line):
        with self._lock:
            self._append(line)

    def _append(self, line):
        self._f.write(line + "\n")

    def poll(self):
        self._append("poll")
