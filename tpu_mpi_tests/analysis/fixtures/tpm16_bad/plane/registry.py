"""TPM1602 bad: ``bump`` calls a helper while holding the
non-reentrant lock, and the helper re-acquires it — guaranteed
self-deadlock on the first call (the attach_metrics
observe-inside-the-lock shape)."""

import threading


class Gauges:
    def __init__(self):
        self._lock = threading.Lock()
        self._vals = {}

    def bump(self, key):
        with self._lock:
            self._vals[key] = self._vals.get(key, 0) + 1
            self._flush_locked()

    def _flush_locked(self):
        with self._lock:
            self._vals.clear()
