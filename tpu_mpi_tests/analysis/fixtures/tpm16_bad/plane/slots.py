"""The hook slot and its reader: ``fire`` snapshots the slot on the
hot path, so whatever ``armer`` installs stays live until un-installed.
"""

_TRACE_HOOK = None


def fire(op):
    hook = _TRACE_HOOK
    if hook is not None:
        hook(op)
