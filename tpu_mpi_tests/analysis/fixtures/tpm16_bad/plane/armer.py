"""TPM1603 bad: the slot is rebound to a live callable with no
``= None`` disarm anywhere in this file — a reader sees the stale hook
forever (the chaos layer's arm()/disarm() pairing is the sanctioned
idiom)."""

from plane import slots


def install(tracer):
    slots._TRACE_HOOK = _make(tracer)


def _make(tracer):
    def hook(op):
        tracer.append(op)
    return hook
