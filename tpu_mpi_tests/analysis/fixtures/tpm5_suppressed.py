"""TPM501 suppressed: the axis is bound by the CALLER's mesh (a
cross-file pattern the same-file rule cannot see)."""

from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_mpi_tests.compat import shard_map


def total(mesh, x):
    def body(v):
        return lax.psum(v, "ring")  # tpumt: ignore[TPM501]

    return shard_map(
        body, mesh=mesh, in_specs=P("shard"), out_specs=P()
    )(x)
