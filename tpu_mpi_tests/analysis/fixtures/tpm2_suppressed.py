"""TPM201 suppressed: a deliberate trace-time print (compile marker)."""

import jax


@jax.jit
def step(x):
    print("TRACING step")  # tpumt: ignore[TPM201]
    return x + 1
